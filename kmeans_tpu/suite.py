"""The narrative test/benchmark suite — L4 harness parity.

Reproduces the reference's five-test ``__main__`` harness
(kmeans_spark.py:355-652: banners, sequential tests A-E, per-test PASS/FAIL
prints) as a real program with a REAL exit code — the reference swallows
failures so ``spark-submit`` always exits 0 (SURVEY.md §4); here any failed
test makes the process exit 1.

Run: ``python -m kmeans_tpu.suite`` (add ``--platform cpu --devices 8`` to
run on a simulated 8-device CPU mesh like the CI suite; default uses
whatever accelerator JAX sees).

Differences from the reference, on purpose:
* warmup (compile) excluded from timings in B/E — the reference times cold
  (kmeans_spark.py:575-579);
* B's per-iteration time divides by the TRUE iteration count (the reference
  divides by max_iter even on early convergence, :433-438);
* E sweeps data-parallel shard counts on the mesh instead of RDD partitions
  and still writes ``speedup_graph.png`` (:594-619).
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import numpy as np


def _banner(title: str) -> None:
    print("\n" + "=" * 80)
    print(title)
    print("=" * 80)


def _result(name: str, ok: bool, detail: str = "") -> bool:
    mark = "✓" if ok else "✗"
    word = "PASSED" if ok else "FAILED"
    print(f"\n{mark} {name} {word}{(': ' + detail) if detail else ''}")
    sys.stdout.flush()
    return ok


def test_a_correctness(mesh) -> bool:
    """Gold-standard parity (reference T1, kmeans_spark.py:355-399):
    1000 pts / 3 centers / 2-D, sorted centroids vs sklearn within 1e-4."""
    from sklearn.cluster import KMeans as SklearnKMeans
    from sklearn.datasets import make_blobs
    from kmeans_tpu import KMeans

    _banner("TEST A: CORRECTNESS (The 'Blob' Test)")
    X, _ = make_blobs(n_samples=1000, centers=3, n_features=2,
                      random_state=42)
    # Shared explicit init for BOTH implementations: centroid equality then
    # tests the algorithm, not init-RNG luck (see tests/test_correctness.py).
    rng = np.random.RandomState(42)
    init = X[rng.choice(len(X), size=3, replace=False)]

    print("\n[kmeans_tpu KMeans]")
    ours = KMeans(k=3, max_iter=300, tolerance=1e-12, seed=42,
                  compute_sse=True, init=init, mesh=mesh,
                  dtype=np.float64).fit(X)
    print("\n[Sklearn KMeans]")
    ref = SklearnKMeans(n_clusters=3, init=init, n_init=1, max_iter=300,
                        random_state=42, tol=1e-14).fit(X)
    a = np.array(sorted(ours.centroids.tolist()))
    b = np.array(sorted(ref.cluster_centers_.tolist()))
    print("\nkmeans_tpu centroids:\n", a)
    print("sklearn centroids:\n", b)
    ok = np.allclose(a, b, atol=1e-4)
    detail = "" if ok else f"max diff {np.max(np.abs(a - b)):.3e}"
    return _result("TEST A", ok, detail or "centroids match within 1e-4")


def test_b_performance(mesh) -> bool:
    """Stress bench (reference T2, kmeans_spark.py:402-454): 100k x 10
    standard-normal points, k=5, 20 iterations, SSE off."""
    from kmeans_tpu import KMeans
    from kmeans_tpu.data.synthetic import make_gaussian

    _banner("TEST B: SCALE & PERFORMANCE (The 'Stress' Test)")
    X = make_gaussian(100_000, 10, random_state=42, dtype=np.float32)
    print(f"\nDataset: {X.shape[0]} points, {X.shape[1]} dimensions")
    print(f"Mesh: {dict(mesh.shape)}")

    kw = dict(k=5, max_iter=20, tolerance=1e-4, seed=42, compute_sse=False,
              mesh=mesh, verbose=False)
    km_warm = KMeans(**kw)
    ds = km_warm.cache(X)
    km_warm.fit(ds)                       # compile warmup, excluded
    km = KMeans(**kw)
    start = time.perf_counter()
    km.fit(ds)
    total = time.perf_counter() - start
    iters = km.iterations_run             # TRUE count (ref bug, :436)
    print(f"\n[Performance Metrics]")
    print(f"Total Iterations: {iters}")
    print(f"Total Time: {total:.2f} seconds (warm; compile excluded)")
    print(f"Average Time per Iteration: {total / iters:.4f} seconds")
    ok = iters >= 1 and np.all(np.isfinite(km.centroids))
    return _result("TEST B", ok, "performance metrics reported")


def test_c_convergence(mesh) -> bool:
    """SSE monotonicity (reference T3, kmeans_spark.py:457-500)."""
    from sklearn.datasets import make_blobs
    from kmeans_tpu import KMeans

    _banner("TEST C: CONVERGENCE CHECK")
    X, _ = make_blobs(n_samples=5000, centers=4, n_features=5,
                      random_state=42)
    # float64 like the reference's NumPy executors (kmeans_spark.py:153):
    # the monotone-SSE invariant is a property of exact Lloyd steps, and on
    # TPU the f32 matmul-form distances run at bf16 MXU precision, whose
    # boundary-assignment flips can tick SSE up by ~1e-4 relative near
    # convergence (see README troubleshooting / docs/PERFORMANCE.md).
    km = KMeans(k=4, max_iter=30, tolerance=1e-5, seed=42,
                compute_sse=True, mesh=mesh, dtype=np.float64).fit(X)
    print("\n[SSE History]")
    for i, sse in enumerate(km.sse_history):
        print(f"Iteration {i + 1}: SSE = {sse:.4f}")
    ok = all(km.sse_history[i] <= km.sse_history[i - 1] + 1e-6
             for i in range(1, len(km.sse_history)))
    return _result("TEST C", ok,
                   "SSE is monotonically decreasing (or stable)" if ok
                   else "SSE increased during iterations")


def test_d_empty_clusters(mesh) -> bool:
    """Empty-cluster robustness (reference T4, kmeans_spark.py:503-540):
    3 tight blobs, k=6 forces empties; all centroids must stay finite."""
    from sklearn.datasets import make_blobs
    from kmeans_tpu import KMeans

    _banner("TEST D: EMPTY CLUSTER HANDLING")
    X, _ = make_blobs(n_samples=800, centers=3, n_features=2,
                      cluster_std=0.5, random_state=42)
    print(f"\nDataset: {X.shape[0]} points with 3 natural clusters")
    print("Fitting k=6 clusters (forcing empty-cluster scenario)")
    try:
        km = KMeans(k=6, max_iter=30, tolerance=1e-4, seed=42,
                    compute_sse=True, mesh=mesh).fit(X)
        ok = bool(np.all(np.isfinite(km.centroids)))
        if ok:
            print(f"Final centroids shape: {km.centroids.shape}")
            print("All centroids are finite (no NaN/Inf values)")
        return _result("TEST D", ok,
                       "empty clusters handled correctly" if ok
                       else "invalid centroids detected")
    except Exception as e:                # noqa: BLE001 — mirror T4's guard
        return _result("TEST D", False, f"exception occurred: {e}")


def test_e_speedup_graph(out_dir: Path) -> bool:
    """Strong-scaling sweep + plot artifact (reference T5,
    kmeans_spark.py:543-621), over data-parallel shard counts."""
    import jax
    from sklearn.datasets import make_blobs
    from kmeans_tpu import KMeans
    from kmeans_tpu.parallel.mesh import make_mesh
    from kmeans_tpu.utils.plotting import save_speedup_graph

    _banner("TEST E: SPEEDUP GRAPH")
    X, _ = make_blobs(n_samples=50_000, centers=5, n_features=10,
                      random_state=42)
    X = X.astype(np.float32)
    n_dev = len(jax.devices())
    shard_counts = [n for n in (1, 2, 4, 8) if n <= n_dev]
    print(f"\nDataset: {X.shape[0]} points, {X.shape[1]} dimensions")
    print(f"K-Means Parameters: k=5, max_iter=10; shard counts: "
          f"{shard_counts}")

    times = {}
    for n in shard_counts:
        mesh = make_mesh(data=n, model=1, devices=jax.devices()[:n])
        kw = dict(k=5, max_iter=10, tolerance=1e-4, seed=42,
                  compute_sse=False, mesh=mesh, verbose=False)
        km_warm = KMeans(**kw)
        ds = km_warm.cache(X)
        km_warm.fit(ds)                   # warmup, excluded (ref times cold)
        km = KMeans(**kw)
        start = time.perf_counter()
        km.fit(ds)
        times[n] = time.perf_counter() - start
        print(f"Shards: {n} | Time: {times[n]:.4f}s")

    speedups = {n: times[shard_counts[0]] / times[n] for n in shard_counts}
    print("\n[Timing Summary]")
    for n in shard_counts:
        print(f"Shards: {n:2d} | Time: {times[n]:8.4f}s | "
              f"Speedup: {speedups[n]:6.4f}x")
    out = out_dir / "speedup_graph.png"
    save_speedup_graph(shard_counts, speedups, out)
    print(f"Graph saved to: {out}")
    return _result("TEST E", out.exists(), "speedup graph generated")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="kmeans_tpu narrative test suite (reference harness "
                    "parity, kmeans_spark.py:624-652)")
    parser.add_argument("--platform", default=None,
                        help="force a JAX platform (e.g. cpu)")
    parser.add_argument("--devices", type=int, default=None,
                        help="with --platform cpu: simulate N host devices")
    parser.add_argument("--out-dir", default="artifacts",
                        help="directory for plot artifacts")
    parser.add_argument("--only", default=None,
                        help="comma-separated subset of a,b,c,d,e")
    args = parser.parse_args(argv)
    if args.devices is not None and args.devices <= 0:
        parser.error(f"--devices must be positive, got {args.devices}")

    import jax

    from kmeans_tpu.parallel.mesh import force_cpu_devices, make_mesh

    # Test A runs the parity fit in float64 (like sklearn's oracle); x64
    # must be on before any array is created or f64 silently narrows to
    # f32 on device.
    jax.config.update("jax_enable_x64", True)

    if args.platform == "cpu":
        force_cpu_devices(args.devices)       # None honors XLA_FLAGS, else 1
    elif args.platform:
        jax.config.update("jax_platforms", args.platform)
    elif args.devices is not None and len(jax.devices()) < args.devices:
        force_cpu_devices(args.devices)

    _banner("DISTRIBUTED K-MEANS (TPU) - PRODUCTION TEST SUITE")
    print(f"JAX backend: {jax.default_backend()}, "
          f"devices: {len(jax.devices())}")

    mesh = make_mesh()
    out_dir = Path(args.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    selected = set((args.only or "a,b,c,d,e").split(","))

    results = {}
    if "a" in selected:
        results["A"] = test_a_correctness(mesh)
    if "b" in selected:
        results["B"] = test_b_performance(mesh)
    if "c" in selected:
        results["C"] = test_c_convergence(mesh)
    if "d" in selected:
        results["D"] = test_d_empty_clusters(mesh)
    if "e" in selected:
        results["E"] = test_e_speedup_graph(out_dir)

    _banner("ALL TESTS COMPLETED")
    for name, ok in results.items():
        print(f"  TEST {name}: {'PASSED' if ok else 'FAILED'}")
    failed = [n for n, ok in results.items() if not ok]
    # Real exit code — the capability the reference harness lacks.
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
