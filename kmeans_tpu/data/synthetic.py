"""Synthetic dataset generators (no sklearn runtime dependency).

The reference keeps sklearn strictly test-side ("ZERO runtime dependency",
requirements.txt:25-26; README.md:13) and builds fixtures with
``make_blobs`` (kmeans_spark.py:366/468/515/555) and ``np.random.randn``
(kmeans_spark.py:415).  This module provides equivalent generators for the
framework's own benchmarks; the pytest suite still uses sklearn's
``make_blobs`` as the fixture source where oracle parity matters.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np


def make_blobs(n_samples: int, centers: Union[int, np.ndarray] = 3,
               n_features: int = 2, cluster_std: float = 1.0,
               center_box: Tuple[float, float] = (-10.0, 10.0),
               random_state: int = 0,
               dtype=np.float64) -> Tuple[np.ndarray, np.ndarray]:
    """Isotropic Gaussian blobs, API-compatible subset of sklearn's."""
    rng = np.random.default_rng(random_state)
    if isinstance(centers, (int, np.integer)):
        centers = rng.uniform(center_box[0], center_box[1],
                              size=(int(centers), n_features))
    centers = np.asarray(centers, dtype=np.float64)
    k = centers.shape[0]
    labels = rng.integers(0, k, size=n_samples)
    X = centers[labels] + rng.normal(
        scale=cluster_std, size=(n_samples, centers.shape[1]))
    return X.astype(dtype), labels.astype(np.int64)


def make_uniform(n_samples: int, n_features: int,
                 low: float = -1.0, high: float = 1.0,
                 random_state: int = 0, dtype=np.float32) -> np.ndarray:
    """Uniform cloud — the headline-bench distribution (BASELINE.json)."""
    rng = np.random.default_rng(random_state)
    return rng.uniform(low, high,
                       size=(n_samples, n_features)).astype(dtype)


def make_gaussian(n_samples: int, n_features: int, random_state: int = 0,
                  dtype=np.float32) -> np.ndarray:
    """Standard-normal cloud (the reference's stress fixture,
    kmeans_spark.py:414-415)."""
    rng = np.random.RandomState(random_state)
    return rng.randn(n_samples, n_features).astype(dtype)
