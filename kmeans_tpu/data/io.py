"""Out-of-core dataset ingestion: shard-local reads from memory-mapped files.

The reference's data-distribution story is driver-centric: the driver holds
the full array and ``sc.parallelize`` ships partitions to executors
(kmeans_spark.py:369/418/568).  That caps dataset size at driver RAM and
pays a full host->cluster copy.  The TPU-native design inverts it: the file
is memory-mapped, and **each device shard's rows are read (and padded)
lazily inside ``jax.make_array_from_callback``** — the host never
materializes more than one shard's slice at a time, and on multi-host
meshes each host touches only the bytes its local devices own (the same
pattern orbax/t5x use for checkpoint ingestion).

Supports ``.npy`` (via ``np.load(mmap_mode='r')``) and raw binary with an
explicit shape/dtype.  The returned ``ShardedDataset`` keeps the mmap as
its host handle, so seeded row sampling (Forgy init, kmeans_spark.py:72;
empty-cluster resampling, :196) reads only the k sampled rows from disk.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kmeans_tpu.parallel.mesh import DATA_AXIS, mesh_shape
from kmeans_tpu.parallel.sharding import (ShardedDataset, choose_chunk_size,
                                          to_device)
from kmeans_tpu.data.prefetch import check_prefetch, prefetch_iter


class _ReadaheadReader:
    """Read-ahead wrapper for a ``read_rows(lo, hi)`` shard callback.

    ``jax.make_array_from_callback`` pulls one shard slice at a time;
    with a slow source (cold mmap pages, network filesystems) each
    slice's disk read serializes against the device placement of the
    previous one.  This wrapper predicts the next ``depth`` contiguous
    same-sized ranges after every read and materializes them in ONE
    background thread, so the disk read of shard i+1 overlaps the
    transfer of shard i.  A mispredicted range (out-of-order callback
    invocation, which JAX does not forbid) is only a cache miss — the
    read happens synchronously, correctness is unaffected.  Memory
    cost: up to ``depth`` extra slices resident on the host.
    """

    def __init__(self, read_rows, n: int, depth: int):
        import concurrent.futures
        self._read = read_rows
        self._n = n
        self._depth = depth
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="kmeans_tpu-readahead")
        self._pending: dict = {}       # (lo, hi) -> Future

    def __call__(self, lo: int, hi: int) -> np.ndarray:
        fut = self._pending.pop((lo, hi), None)
        if fut is None and self._pending:
            # Mispredicted (out-of-order callback invocation): drop the
            # stale predictions so readahead re-anchors to the actual
            # cursor — keeping them would both pin their slices and
            # permanently disable scheduling via the depth cap.
            for stale in self._pending.values():
                stale.cancel()
            self._pending.clear()
        out = fut.result() if fut is not None else self._read(lo, hi)
        self._schedule(hi, hi - lo)
        return out

    def _schedule(self, start: int, size: int) -> None:
        for _ in range(self._depth):
            lo, hi = start, min(start + size, self._n)
            if hi <= lo or len(self._pending) >= self._depth:
                break
            if (lo, hi) not in self._pending:
                self._pending[(lo, hi)] = self._pool.submit(
                    self._read, lo, hi)
            start = hi


def _sharded_from_source(read_rows, n: int, d: int, mesh: Mesh,
                         chunk: int, dtype,
                         sample_weight: Optional[np.ndarray],
                         host_handle,
                         explicit_chunk: bool = False,
                         prefetch: int = 0) -> ShardedDataset:
    """Build a ShardedDataset whose shards pull rows via ``read_rows(lo, hi)``
    — each callback materializes only its own slice.  ``prefetch > 0``
    wraps the reader in a :class:`_ReadaheadReader` of that depth, so
    the disk read of the next shard slice overlaps the placement of the
    current one."""
    data_shards, _ = mesh_shape(mesh)
    dtype = np.dtype(dtype)
    # Readahead predicts the NEXT contiguous row range, which on a
    # multi-host mesh belongs to ANOTHER host past this host's last
    # local shard — it would read (and pin) up to ``depth`` never-
    # consumed slices and break the module's touch-only-local-bytes
    # contract, so it is single-process only.
    prefetch = check_prefetch(prefetch)
    if prefetch and jax.process_count() == 1:
        read_rows = _ReadaheadReader(read_rows, n, prefetch)
    n_pad = math.ceil(n / (data_shards * chunk)) * (data_shards * chunk)

    sw = None
    if sample_weight is not None:
        sw = np.asarray(sample_weight, dtype=dtype)
        if sw.shape != (n,):
            raise ValueError(
                f"sample_weight must have shape ({n},), got {sw.shape}")
        if np.any(sw < 0) or not np.all(np.isfinite(sw)):
            raise ValueError("sample_weight must be finite and >= 0")

    x_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
    w_sharding = NamedSharding(mesh, P(DATA_AXIS))

    def x_cb(index) -> np.ndarray:
        rows = index[0]
        lo, hi = rows.start or 0, rows.stop if rows.stop is not None else n_pad
        real_hi = min(hi, n)
        out = np.zeros((hi - lo, d), dtype=dtype)
        if real_hi > lo:
            out[: real_hi - lo] = read_rows(lo, real_hi)
        return out

    def w_cb(index) -> np.ndarray:
        rows = index[0]
        lo, hi = rows.start or 0, rows.stop if rows.stop is not None else n_pad
        real_hi = min(hi, n)
        out = np.zeros((hi - lo,), dtype=dtype)
        if real_hi > lo:
            out[: real_hi - lo] = (1.0 if sw is None
                                   else sw[lo:real_hi])
        return out

    points = jax.make_array_from_callback((n_pad, d), x_sharding, x_cb)
    weights = jax.make_array_from_callback((n_pad,), w_sharding, w_cb)
    return ShardedDataset(points, weights, n, chunk, mesh,
                          host=host_handle, host_weights=sw,
                          explicit_chunk=explicit_chunk)


def _resolve_chunk(n: int, d: int, k_hint: int, mesh: Mesh,
                   chunk_size: Optional[int],
                   budget_elems: Optional[int] = None) -> int:
    data_shards, model_shards = mesh_shape(mesh)
    # budget_elems=None IS choose_chunk_size's default contract now
    # (default budget + single-chunk shortcut eligibility).
    return chunk_size or choose_chunk_size(
        -(-n // data_shards), max(k_hint, model_shards), d,
        budget_elems=budget_elems)


def from_npy(path, mesh: Mesh, *, chunk_size: Optional[int] = None,
             dtype=np.float32, k_hint: int = 16,
             budget_elems: Optional[int] = None,
             sample_weight: Optional[np.ndarray] = None,
             prefetch: int = 2) -> ShardedDataset:
    """Shard a 2-D ``.npy`` file onto the mesh without loading it whole.

    ``k_hint`` feeds the automatic chunk-size choice (the (chunk, k)
    distance tile is the working set); pass the k you plan to fit, or set
    ``chunk_size`` explicitly.  ``budget_elems`` overrides the per-tile
    element budget — pass ``models.gmm.EM_CHUNK_BUDGET`` when the dataset
    is destined for a ``GaussianMixture`` fit (the EM pass wants smaller
    tiles than K-Means; docs/PERFORMANCE.md).  With ``mesh=None`` this falls back to a
    plain in-memory upload (single-device paths have no per-shard slicing
    to exploit).

    ``prefetch`` (default 2) reads ahead that many shard slices in a
    background thread so disk IO overlaps device placement
    (``data.prefetch``); ``prefetch=0`` restores the fully synchronous
    load.  Host memory grows by up to ``prefetch`` slices either way —
    the per-shard (not whole-file) residency contract is unchanged.
    """
    mm = np.load(path, mmap_mode="r")
    if mm.ndim != 2:
        raise ValueError(f"expected a 2-D array in {path}, got shape "
                         f"{mm.shape}")
    n, d = mm.shape
    if mesh is None:
        return to_device(np.asarray(mm, dtype=dtype), None,
                         chunk_size or choose_chunk_size(n, k_hint, d),
                         dtype, sample_weight=sample_weight,
                         explicit=chunk_size is not None)
    chunk = _resolve_chunk(n, d, k_hint, mesh, chunk_size, budget_elems)

    def read_rows(lo: int, hi: int) -> np.ndarray:
        return np.asarray(mm[lo:hi], dtype=dtype)

    return _sharded_from_source(read_rows, n, d, mesh, chunk, dtype,
                                sample_weight, host_handle=mm,
                                explicit_chunk=chunk_size is not None,
                                prefetch=prefetch)


def from_raw(path, shape: Tuple[int, int], mesh: Mesh, *,
             file_dtype=np.float32, chunk_size: Optional[int] = None,
             dtype=np.float32, k_hint: int = 16,
             budget_elems: Optional[int] = None,
             offset: int = 0,
             sample_weight: Optional[np.ndarray] = None,
             prefetch: int = 2) -> ShardedDataset:
    """Shard a headerless binary file of ``shape`` row-major ``file_dtype``
    values (e.g. exported feature matrices) onto the mesh, reading each
    shard's byte range only.  ``prefetch`` reads ahead like
    :func:`from_npy`'s."""
    n, d = shape
    mm = np.memmap(path, dtype=file_dtype, mode="r", offset=offset,
                   shape=(n, d))
    if mesh is None:
        return to_device(np.asarray(mm, dtype=dtype), None,
                         chunk_size or choose_chunk_size(n, k_hint, d),
                         dtype, sample_weight=sample_weight,
                         explicit=chunk_size is not None)
    chunk = _resolve_chunk(n, d, k_hint, mesh, chunk_size, budget_elems)

    def read_rows(lo: int, hi: int) -> np.ndarray:
        return np.asarray(mm[lo:hi], dtype=dtype)

    return _sharded_from_source(read_rows, n, d, mesh, chunk, dtype,
                                sample_weight, host_handle=mm,
                                explicit_chunk=chunk_size is not None,
                                prefetch=prefetch)


def iter_npy_blocks(path, block_rows: int, *, dtype=None,
                    prefetch: int = 0):
    """Factory for ``KMeans.fit_stream``: returns a zero-argument callable
    that yields consecutive (<= block_rows, D) slices of a 2-D ``.npy``
    via mmap — at most ``prefetch + 2`` blocks are ever resident in host
    memory (``prefetch`` queued + one in flight in the producer + the
    one being consumed; ``data.prefetch``'s memory contract), so the
    file can exceed both HBM and host RAM.

    ``prefetch`` (default 0) materializes that many blocks ahead in a
    background thread (``data.prefetch.prefetch_iter``) — useful when
    driving your OWN consumption loop over a slow source.  The model
    streaming surfaces (``fit_stream``/``predict_stream``/...) already
    prefetch decode + device placement internally, and their producer
    thread drives this generator's disk reads off the consumer thread
    too, so stacking both is redundant (harmless, but doubles the
    resident-block count).

    Usage::

        km.fit_stream(iter_npy_blocks("big.npy", 1_000_000))
    """
    if block_rows <= 0:
        raise ValueError(f"block_rows must be positive, got {block_rows}")
    prefetch = check_prefetch(prefetch)

    def iter_blocks():
        arr = np.load(path, mmap_mode="r")
        if arr.ndim != 2:
            raise ValueError(f"{path} must contain a 2-D array, "
                             f"got shape {arr.shape}")
        for start in range(0, arr.shape[0], block_rows):
            block = np.asarray(arr[start: start + block_rows])
            yield block if dtype is None else block.astype(dtype)

    def make_blocks():
        return prefetch_iter(iter_blocks(), prefetch)

    return make_blocks
