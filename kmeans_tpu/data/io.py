"""Out-of-core dataset ingestion: shard-local reads from memory-mapped files.

The reference's data-distribution story is driver-centric: the driver holds
the full array and ``sc.parallelize`` ships partitions to executors
(kmeans_spark.py:369/418/568).  That caps dataset size at driver RAM and
pays a full host->cluster copy.  The TPU-native design inverts it: the file
is memory-mapped, and **each device shard's rows are read (and padded)
lazily inside ``jax.make_array_from_callback``** — the host never
materializes more than one shard's slice at a time, and on multi-host
meshes each host touches only the bytes its local devices own (the same
pattern orbax/t5x use for checkpoint ingestion).

Supports ``.npy`` (via ``np.load(mmap_mode='r')``) and raw binary with an
explicit shape/dtype.  The returned ``ShardedDataset`` keeps the mmap as
its host handle, so seeded row sampling (Forgy init, kmeans_spark.py:72;
empty-cluster resampling, :196) reads only the k sampled rows from disk.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Iterable, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kmeans_tpu.obs import metrics_registry as _obs_metrics
from kmeans_tpu.obs import trace as _obs_trace
from kmeans_tpu.parallel.mesh import DATA_AXIS, mesh_shape
from kmeans_tpu.parallel.sharding import (ShardedDataset, choose_chunk_size,
                                          to_device)
from kmeans_tpu.data.prefetch import (check_prefetch, close_source,
                                      prefetch_iter)


# ------------------------------------------------------------ retrying IO
#
# Fault-tolerance layer (ISSUE 4): transient reader errors — flaky block
# IO on the 7-10 MB/s tunnel, network-filesystem hiccups — must not kill
# a long fit.  Retry policy: any ``OSError`` is considered transient
# (``utils.faults.TransientIOError`` is the injected subclass the tests
# raise); retries are BOUNDED and the backoff schedule is DETERMINISTIC
# (``io_backoff * 2**(attempt-1)`` seconds, no wall-clock randomness), so
# a retried fit's trajectory is bit-identical to an unretried one — the
# retry only re-reads, never reorders or drops data.

class IOStats:
    """Per-fit IO fault counters (the ``io_retries_used_`` /
    ``blocks_skipped_`` observability surface).  ``blocks_skipped`` is
    the count of the most recent COMPLETE pass over the stream (stable
    across epochs for a deterministic source — it equals the number of
    bad blocks in the dataset); ``blocks_skipped_total`` accumulates
    across passes."""

    def __init__(self):
        self.retries_used = 0
        self.blocks_skipped = 0
        self.blocks_skipped_total = 0


def check_io_knobs(io_retries, io_backoff) -> Tuple[int, float]:
    """Validate the retry knobs: retries an int >= 0, backoff a float
    >= 0 seconds (0 = retry immediately — what deterministic tests
    use)."""
    r = int(io_retries)
    if r < 0 or r != io_retries:
        raise ValueError(f"io_retries must be an int >= 0, got "
                         f"{io_retries!r}")
    b = float(io_backoff)
    if not (b >= 0.0):
        raise ValueError(f"io_backoff must be >= 0 seconds, got "
                         f"{io_backoff!r}")
    return r, b


def _interruptible_sleep(delay: float,
                         abort: Optional[threading.Event]) -> bool:
    """Sleep ``delay`` seconds; with an ``abort`` event, wake early and
    return True when it fires (the caller then gives up the retry) —
    how an abandoned prefetch consumer reaps a producer stuck in a
    backoff sleep without waiting the schedule out."""
    if delay <= 0:
        return bool(abort is not None and abort.is_set())
    if abort is None:
        time.sleep(delay)
        return False
    return abort.wait(delay)


def retry_call(fn: Callable, *, retries: int, backoff: float,
               stats: Optional[IOStats] = None,
               abort: Optional[threading.Event] = None,
               what: str = "read"):
    """Run ``fn()`` retrying transient (``OSError``) failures up to
    ``retries`` times with deterministic exponential backoff.  The
    final failure (or any non-OSError) propagates unchanged."""
    attempt = 0
    while True:
        try:
            return fn()
        except OSError:
            if attempt >= retries:
                raise
            attempt += 1
            if stats is not None:
                stats.retries_used += 1
            # Write-through (ISSUE 11): per-call IOStats stays the
            # documented surface; the registry keeps the process view.
            _obs_metrics.REGISTRY.counter("io.retries").inc()
            if _interruptible_sleep(backoff * (2.0 ** (attempt - 1)),
                                    abort):
                raise


def _retrying_reader(read_rows: Callable, retries: int, backoff: float,
                     stats: IOStats) -> Callable:
    """Wrap a ``read_rows(lo, hi)`` shard callback in the retry policy —
    slice reads from an mmap are idempotent, so a retry is a plain
    re-read."""
    def read(lo: int, hi: int) -> np.ndarray:
        return retry_call(lambda: read_rows(lo, hi), retries=retries,
                          backoff=backoff, stats=stats,
                          what=f"rows [{lo}, {hi})")
    return read


class _ResilientBlockIter:
    """One pass over a ``make_blocks`` stream with transient-error retry
    and a non-finite-block quarantine policy.

    Retry semantics exploit the streaming surfaces' existing contract
    that ``make_blocks()`` returns a FRESH, deterministic iterable on
    every call: a generator that raised is dead, so a failed ``next()``
    is retried by re-invoking the factory and fast-forwarding past the
    blocks already delivered — idempotent re-reads, identical
    trajectory.  Failures during the fast-forward consume attempts from
    the same bounded budget.

    Quarantine: every block (and its weights, for ``(block, weights)``
    items) is scanned for non-finite values — ``on_nonfinite='error'``
    raises naming the block position (instead of the late NaN-centroid
    guard), ``'skip'`` drops the block and counts it.  The scan is one
    cheap memory pass per block and runs in the producer thread under
    prefetch.

    ``abort()`` (called by ``prefetch._PrefetchIterator.close``) wakes a
    pending backoff sleep so an abandoned consumer never waits out the
    schedule.
    """

    def __init__(self, make_blocks: Callable[[], Iterable], retries: int,
                 backoff: float, on_nonfinite: str,
                 stats: Optional[IOStats]):
        self._make = make_blocks
        self._retries = retries
        self._backoff = backoff
        self._on_nonfinite = on_nonfinite
        self._stats = stats
        self._abort = threading.Event()
        self._it = iter(make_blocks())
        self._pos = 0                    # raw blocks delivered this pass
        self._skipped = 0

    def __iter__(self):
        return self

    def _next_raw(self):
        attempt = 0
        fast_forward = 0
        while True:
            try:
                for _ in range(fast_forward):
                    next(self._it)
                fast_forward = 0
                item = next(self._it)
                self._pos += 1
                return item
            except StopIteration:
                raise
            except OSError as e:
                if attempt >= self._retries:
                    raise
                attempt += 1
                if self._stats is not None:
                    self._stats.retries_used += 1
                _obs_metrics.REGISTRY.counter("io.retries").inc()
                if _interruptible_sleep(
                        self._backoff * (2.0 ** (attempt - 1)),
                        self._abort):
                    raise e
                close_source(self._it)
                self._it = iter(self._make())
                fast_forward = self._pos

    def __next__(self):
        while True:
            try:
                # 'io.block' span (ISSUE 11): one streamed block read
                # (retries included — the span measures what the epoch
                # actually waited for this block).
                with _obs_trace.span("io.block", index=self._pos):
                    item = self._next_raw()
            except StopIteration:
                if self._stats is not None:
                    self._stats.blocks_skipped = self._skipped
                raise
            block = item[0] if isinstance(item, tuple) else item
            bad = not np.all(np.isfinite(np.asarray(block)))
            if not bad and isinstance(item, tuple) \
                    and item[1] is not None:
                bad = not np.all(np.isfinite(np.asarray(item[1])))
            if not bad:
                return item
            if self._on_nonfinite == "error":
                raise ValueError(
                    f"non-finite values in streamed block "
                    f"{self._pos - 1}; pass on_nonfinite='skip' to "
                    f"quarantine bad blocks (counted in "
                    f"blocks_skipped_)")
            self._skipped += 1
            if self._stats is not None:
                self._stats.blocks_skipped_total += 1
            _obs_metrics.REGISTRY.counter("io.blocks_skipped").inc()

    def abort(self) -> None:
        self._abort.set()

    def close(self) -> None:
        close_source(self._it)


_NONFINITE_POLICIES = ("error", "skip")


def resilient_blocks(make_blocks: Callable[[], Iterable], *,
                     io_retries: int = 0, io_backoff: float = 0.05,
                     on_nonfinite: str = "error",
                     stats: Optional[IOStats] = None
                     ) -> Callable[[], Iterable]:
    """Wrap a ``make_blocks`` factory with the transient-retry +
    non-finite-quarantine policy (see :class:`_ResilientBlockIter`).
    This is the one choke point every streamed fit routes its source
    through, so ALL passes (init, scatter, EM/Lloyd epochs, scoring) see
    the same cleaned stream and the statistics stay consistent."""
    if on_nonfinite not in _NONFINITE_POLICIES:
        raise ValueError(f"on_nonfinite must be one of "
                         f"{_NONFINITE_POLICIES}, got {on_nonfinite!r}")
    io_retries, io_backoff = check_io_knobs(io_retries, io_backoff)

    def make():
        return _ResilientBlockIter(make_blocks, io_retries, io_backoff,
                                   on_nonfinite, stats)
    return make


class _ReadaheadReader:
    """Read-ahead wrapper for a ``read_rows(lo, hi)`` shard callback.

    ``jax.make_array_from_callback`` pulls one shard slice at a time;
    with a slow source (cold mmap pages, network filesystems) each
    slice's disk read serializes against the device placement of the
    previous one.  This wrapper predicts the next ``depth`` contiguous
    same-sized ranges after every read and materializes them in ONE
    background thread, so the disk read of shard i+1 overlaps the
    transfer of shard i.  A mispredicted range (out-of-order callback
    invocation, which JAX does not forbid) is only a cache miss — the
    read happens synchronously, correctness is unaffected.  Memory
    cost: up to ``depth`` extra slices resident on the host.
    """

    def __init__(self, read_rows, n: int, depth: int):
        import concurrent.futures
        self._read = read_rows
        self._n = n
        self._depth = depth
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="kmeans_tpu-readahead")
        self._pending: dict = {}       # (lo, hi) -> Future

    def __call__(self, lo: int, hi: int) -> np.ndarray:
        fut = self._pending.pop((lo, hi), None)
        if fut is None and self._pending:
            # Mispredicted (out-of-order callback invocation): drop the
            # stale predictions so readahead re-anchors to the actual
            # cursor — keeping them would both pin their slices and
            # permanently disable scheduling via the depth cap.
            for stale in self._pending.values():
                stale.cancel()
            self._pending.clear()
        out = fut.result() if fut is not None else self._read(lo, hi)
        self._schedule(hi, hi - lo)
        return out

    def _schedule(self, start: int, size: int) -> None:
        for _ in range(self._depth):
            lo, hi = start, min(start + size, self._n)
            if hi <= lo or len(self._pending) >= self._depth:
                break
            if (lo, hi) not in self._pending:
                self._pending[(lo, hi)] = self._pool.submit(
                    self._read, lo, hi)
            start = hi


def _streamed_place(read_rows, n: int, d: int, n_pad: int, dtype,
                    sw: Optional[np.ndarray], x_sharding, w_sharding,
                    prefetch: int):
    """Per-host streamed placement (ISSUE 18d): each of THIS process's
    device shards is read and placed as one slab, staged through the
    ``data.prefetch`` producer so slab i+1's disk read + host->device
    copy overlap slab i's transfer completion, and assembled via
    ``jax.make_array_from_single_device_arrays``.  Host memory
    high-water is O(slab * (prefetch + 2)) — the producer's documented
    block bound — NOT O(local rows): a slab's host buffer is released
    as soon as its transfer completes.  ``prefetch=0`` is the fully
    synchronous oracle.  Multi-host: ``addressable_devices_indices_map``
    yields only local shards, so each host touches only its own byte
    ranges (the module's touch-only-local-bytes contract)."""
    from kmeans_tpu.parallel.sharding import _shard_ranges, _w_slice
    w_devs = {}
    for lo, hi, devs in _shard_ranges(w_sharding, (n_pad,)):
        w_devs[(lo, hi)] = devs
    ranges = _shard_ranges(x_sharding, (n_pad, d))

    def stage(item):
        i, (lo, hi, devs) = item
        # Per-slab 'stage' span on the PRODUCER tid — the timeline
        # shows the reads/copies overlapping the consumer's completion
        # waits, and the TTFI table attributes ingest per slab.
        with _obs_trace.span("stage", slab=i, slabs=len(ranges),
                             rows=hi - lo,
                             bytes=(hi - lo) * (d + 1) * dtype.itemsize):
            real_hi = min(hi, n)
            if hi <= n:
                xs = np.ascontiguousarray(
                    np.asarray(read_rows(lo, real_hi), dtype=dtype))
            else:
                xs = np.zeros((hi - lo, d), dtype=dtype)
                if real_hi > lo:
                    xs[: real_hi - lo] = read_rows(lo, real_hi)
            ws = _w_slice(sw, lo, hi, n, dtype)
            parts = [("x", jax.device_put(xs, dev)) for dev in devs]
            parts += [("w", jax.device_put(ws, dev))
                      for dev in w_devs[(lo, hi)]]
        return parts

    x_parts, w_parts, pending = [], [], []
    it = prefetch_iter(list(enumerate(ranges)), prefetch, stage=stage)
    try:
        for parts in it:
            # Await the PREVIOUS slab only now, with this slab's copies
            # already in flight — the double-buffer schedule; the wait
            # is what releases the previous slab's host buffer.
            for _, arr in pending:
                arr.block_until_ready()
            pending = parts
            for tag, arr in parts:
                (x_parts if tag == "x" else w_parts).append(arr)
        for _, arr in pending:
            arr.block_until_ready()
    finally:
        close_source(it)
    _obs_metrics.REGISTRY.counter("ingest.slabs").inc(len(ranges))
    points = jax.make_array_from_single_device_arrays(
        (n_pad, d), x_sharding, x_parts)
    weights = jax.make_array_from_single_device_arrays(
        (n_pad,), w_sharding, w_parts)
    return points, weights


def _sharded_from_source(read_rows, n: int, d: int, mesh: Mesh,
                         chunk: int, dtype,
                         sample_weight: Optional[np.ndarray],
                         host_handle,
                         explicit_chunk: bool = False,
                         prefetch: int = 0,
                         io_retries: int = 0,
                         io_backoff: float = 0.05,
                         ingest: str = "auto") -> ShardedDataset:
    """Build a ShardedDataset whose shards pull rows via ``read_rows(lo, hi)``
    — each callback materializes only its own slice.  ``prefetch > 0``
    overlaps the disk read of the next shard slice with the placement
    of the current one (``ingest='slab'``: the streamed producer;
    ``'mono'``: a :class:`_ReadaheadReader` under the blocking
    per-shard assembly, the parity oracle).  ``io_retries > 0`` retries
    each (idempotent) slice read through the deterministic-backoff
    policy; the counters land on the returned dataset's ``io_stats``
    (fits surface them as ``io_retries_used_``)."""
    from kmeans_tpu.parallel.sharding import check_ingest, resolve_ingest
    data_shards, _ = mesh_shape(mesh)
    dtype = np.dtype(dtype)
    io_retries, io_backoff = check_io_knobs(io_retries, io_backoff)
    io_stats = IOStats()
    mode = resolve_ingest(check_ingest(ingest))
    if io_retries:
        # Retry INSIDE the readahead wrapper, so background-thread reads
        # recover too (a failed readahead future would otherwise only
        # surface — unretried — at the consuming callback).
        read_rows = _retrying_reader(read_rows, io_retries, io_backoff,
                                     io_stats)
    # Readahead predicts the NEXT contiguous row range, which on a
    # multi-host mesh belongs to ANOTHER host past this host's last
    # local shard — it would read (and pin) up to ``depth`` never-
    # consumed slices and break the module's touch-only-local-bytes
    # contract, so it is single-process only.  The streamed path has
    # its own producer (which walks exactly the local shards), so the
    # wrapper serves only the mono oracle.
    prefetch = check_prefetch(prefetch)
    if prefetch and mode != "slab" and jax.process_count() == 1:
        read_rows = _ReadaheadReader(read_rows, n, prefetch)
    n_pad = math.ceil(n / (data_shards * chunk)) * (data_shards * chunk)

    sw = None
    if sample_weight is not None:
        sw = np.asarray(sample_weight, dtype=dtype)
        if sw.shape != (n,):
            raise ValueError(
                f"sample_weight must have shape ({n},), got {sw.shape}")
        if np.any(sw < 0) or not np.all(np.isfinite(sw)):
            raise ValueError("sample_weight must be finite and >= 0")

    x_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
    w_sharding = NamedSharding(mesh, P(DATA_AXIS))

    def x_cb(index) -> np.ndarray:
        rows = index[0]
        lo, hi = rows.start or 0, rows.stop if rows.stop is not None else n_pad
        real_hi = min(hi, n)
        out = np.zeros((hi - lo, d), dtype=dtype)
        if real_hi > lo:
            out[: real_hi - lo] = read_rows(lo, real_hi)
        return out

    def w_cb(index) -> np.ndarray:
        rows = index[0]
        lo, hi = rows.start or 0, rows.stop if rows.stop is not None else n_pad
        real_hi = min(hi, n)
        out = np.zeros((hi - lo,), dtype=dtype)
        if real_hi > lo:
            out[: real_hi - lo] = (1.0 if sw is None
                                   else sw[lo:real_hi])
        return out

    # 'stage' span (ISSUE 18): the whole source->shards placement; the
    # streamed path nests per-slab children on the producer tid.
    with _obs_trace.span("stage", rows=n,
                         bytes=n * (d + 1) * dtype.itemsize,
                         ingest=mode):
        _obs_metrics.REGISTRY.counter("ingest.bytes").inc(
            n * (d + 1) * dtype.itemsize)
        if mode == "slab":
            points, weights = _streamed_place(
                read_rows, n, d, n_pad, dtype, sw, x_sharding,
                w_sharding, prefetch)
        else:
            _obs_metrics.REGISTRY.counter("ingest.slabs").inc()
            points = jax.make_array_from_callback(
                (n_pad, d), x_sharding, x_cb)
            weights = jax.make_array_from_callback(
                (n_pad,), w_sharding, w_cb)
    ds = ShardedDataset(points, weights, n, chunk, mesh,
                        host=host_handle, host_weights=sw,
                        explicit_chunk=explicit_chunk)
    ds.io_stats = io_stats
    return ds


def _resolve_chunk(n: int, d: int, k_hint: int, mesh: Mesh,
                   chunk_size: Optional[int],
                   budget_elems: Optional[int] = None) -> int:
    data_shards, model_shards = mesh_shape(mesh)
    # budget_elems=None IS choose_chunk_size's default contract now
    # (default budget + single-chunk shortcut eligibility).
    return chunk_size or choose_chunk_size(
        -(-n // data_shards), max(k_hint, model_shards), d,
        budget_elems=budget_elems)


def from_npy(path, mesh: Mesh, *, chunk_size: Optional[int] = None,
             dtype=np.float32, k_hint: int = 16,
             budget_elems: Optional[int] = None,
             sample_weight: Optional[np.ndarray] = None,
             prefetch: int = 2, io_retries: int = 0,
             io_backoff: float = 0.05,
             ingest: str = "auto") -> ShardedDataset:
    """Shard a 2-D ``.npy`` file onto the mesh without loading it whole.

    ``k_hint`` feeds the automatic chunk-size choice (the (chunk, k)
    distance tile is the working set); pass the k you plan to fit, or set
    ``chunk_size`` explicitly.  ``budget_elems`` overrides the per-tile
    element budget — pass ``models.gmm.EM_CHUNK_BUDGET`` when the dataset
    is destined for a ``GaussianMixture`` fit (the EM pass wants smaller
    tiles than K-Means; docs/PERFORMANCE.md).  With ``mesh=None`` this falls back to a
    plain in-memory upload (single-device paths have no per-shard slicing
    to exploit).

    ``prefetch`` (default 2) reads ahead that many shard slices in a
    background thread so disk IO overlaps device placement
    (``data.prefetch``); ``prefetch=0`` restores the fully synchronous
    load.  Host memory grows by up to ``prefetch`` slices either way —
    the per-shard (not whole-file) residency contract is unchanged.

    ``io_retries``/``io_backoff``: retry transient (``OSError``) slice
    reads up to ``io_retries`` times with deterministic exponential
    backoff (``io_backoff * 2**(attempt-1)`` seconds) — slice reads are
    idempotent, so a retried load is bit-identical.  Retry counts land
    on the returned dataset's ``io_stats.retries_used``.

    ``ingest`` (ISSUE 18d): ``'slab'`` streams each prefetched slice
    straight into the local device shards (host high-water O(slab),
    not O(local rows) — multi-host included); ``'mono'`` keeps the
    blocking per-shard-callback assembly, the bit-parity oracle;
    ``'auto'`` applies the committed BENCH_INGEST rule.
    """
    mm = np.load(path, mmap_mode="r")
    if mm.ndim != 2:
        raise ValueError(f"expected a 2-D array in {path}, got shape "
                         f"{mm.shape}")
    n, d = mm.shape
    if mesh is None:
        return to_device(np.asarray(mm, dtype=dtype), None,
                         chunk_size or choose_chunk_size(n, k_hint, d),
                         dtype, sample_weight=sample_weight,
                         explicit=chunk_size is not None)
    chunk = _resolve_chunk(n, d, k_hint, mesh, chunk_size, budget_elems)

    def read_rows(lo: int, hi: int) -> np.ndarray:
        return np.asarray(mm[lo:hi], dtype=dtype)

    return _sharded_from_source(read_rows, n, d, mesh, chunk, dtype,
                                sample_weight, host_handle=mm,
                                explicit_chunk=chunk_size is not None,
                                prefetch=prefetch, io_retries=io_retries,
                                io_backoff=io_backoff, ingest=ingest)


def from_raw(path, shape: Tuple[int, int], mesh: Mesh, *,
             file_dtype=np.float32, chunk_size: Optional[int] = None,
             dtype=np.float32, k_hint: int = 16,
             budget_elems: Optional[int] = None,
             offset: int = 0,
             sample_weight: Optional[np.ndarray] = None,
             prefetch: int = 2, io_retries: int = 0,
             io_backoff: float = 0.05,
             ingest: str = "auto") -> ShardedDataset:
    """Shard a headerless binary file of ``shape`` row-major ``file_dtype``
    values (e.g. exported feature matrices) onto the mesh, reading each
    shard's byte range only.  ``prefetch`` reads ahead,
    ``io_retries``/``io_backoff`` retry flaky slice reads, and
    ``ingest`` picks the streamed/mono placement path like
    :func:`from_npy`'s."""
    n, d = shape
    mm = np.memmap(path, dtype=file_dtype, mode="r", offset=offset,
                   shape=(n, d))
    if mesh is None:
        return to_device(np.asarray(mm, dtype=dtype), None,
                         chunk_size or choose_chunk_size(n, k_hint, d),
                         dtype, sample_weight=sample_weight,
                         explicit=chunk_size is not None)
    chunk = _resolve_chunk(n, d, k_hint, mesh, chunk_size, budget_elems)

    def read_rows(lo: int, hi: int) -> np.ndarray:
        return np.asarray(mm[lo:hi], dtype=dtype)

    return _sharded_from_source(read_rows, n, d, mesh, chunk, dtype,
                                sample_weight, host_handle=mm,
                                explicit_chunk=chunk_size is not None,
                                prefetch=prefetch, io_retries=io_retries,
                                io_backoff=io_backoff, ingest=ingest)


def iter_npy_blocks(path, block_rows: int, *, dtype=None,
                    prefetch: int = 0, io_retries: int = 0,
                    io_backoff: float = 0.05):
    """Factory for ``KMeans.fit_stream``: returns a zero-argument callable
    that yields consecutive (<= block_rows, D) slices of a 2-D ``.npy``
    via mmap — at most ``prefetch + 2`` blocks are ever resident in host
    memory (``prefetch`` queued + one in flight in the producer + the
    one being consumed; ``data.prefetch``'s memory contract), so the
    file can exceed both HBM and host RAM.

    ``prefetch`` (default 0) materializes that many blocks ahead in a
    background thread (``data.prefetch.prefetch_iter``) — useful when
    driving your OWN consumption loop over a slow source.  The model
    streaming surfaces (``fit_stream``/``predict_stream``/...) already
    prefetch decode + device placement internally, and their producer
    thread drives this generator's disk reads off the consumer thread
    too, so stacking both is redundant (harmless, but doubles the
    resident-block count).

    Usage::

        km.fit_stream(iter_npy_blocks("big.npy", 1_000_000))

    ``io_retries``/``io_backoff`` (default off): retry each block's
    (idempotent) mmap read through the deterministic-backoff policy —
    the per-read counters land on the returned callable's ``io_stats``.
    """
    if block_rows <= 0:
        raise ValueError(f"block_rows must be positive, got {block_rows}")
    prefetch = check_prefetch(prefetch)
    io_retries, io_backoff = check_io_knobs(io_retries, io_backoff)
    io_stats = IOStats()

    def iter_blocks():
        arr = np.load(path, mmap_mode="r")
        if arr.ndim != 2:
            raise ValueError(f"{path} must contain a 2-D array, "
                             f"got shape {arr.shape}")
        for start in range(0, arr.shape[0], block_rows):
            with _obs_trace.span("io.block", offset=start,
                                 rows=min(block_rows,
                                          arr.shape[0] - start)):
                block = retry_call(
                    lambda: np.asarray(arr[start: start + block_rows]),
                    retries=io_retries, backoff=io_backoff,
                    stats=io_stats,
                    what=f"block rows [{start}, {start + block_rows})")
            yield block if dtype is None else block.astype(dtype)

    def make_blocks():
        return prefetch_iter(iter_blocks(), prefetch)

    make_blocks.io_stats = io_stats
    return make_blocks
