"""Out-of-core dataset ingestion: shard-local reads from memory-mapped files.

The reference's data-distribution story is driver-centric: the driver holds
the full array and ``sc.parallelize`` ships partitions to executors
(kmeans_spark.py:369/418/568).  That caps dataset size at driver RAM and
pays a full host->cluster copy.  The TPU-native design inverts it: the file
is memory-mapped, and **each device shard's rows are read (and padded)
lazily inside ``jax.make_array_from_callback``** — the host never
materializes more than one shard's slice at a time, and on multi-host
meshes each host touches only the bytes its local devices own (the same
pattern orbax/t5x use for checkpoint ingestion).

Supports ``.npy`` (via ``np.load(mmap_mode='r')``) and raw binary with an
explicit shape/dtype.  The returned ``ShardedDataset`` keeps the mmap as
its host handle, so seeded row sampling (Forgy init, kmeans_spark.py:72;
empty-cluster resampling, :196) reads only the k sampled rows from disk.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kmeans_tpu.parallel.mesh import DATA_AXIS, mesh_shape
from kmeans_tpu.parallel.sharding import (ShardedDataset, choose_chunk_size,
                                          to_device)


def _sharded_from_source(read_rows, n: int, d: int, mesh: Mesh,
                         chunk: int, dtype,
                         sample_weight: Optional[np.ndarray],
                         host_handle,
                         explicit_chunk: bool = False) -> ShardedDataset:
    """Build a ShardedDataset whose shards pull rows via ``read_rows(lo, hi)``
    — each callback materializes only its own slice."""
    data_shards, _ = mesh_shape(mesh)
    dtype = np.dtype(dtype)
    n_pad = math.ceil(n / (data_shards * chunk)) * (data_shards * chunk)

    sw = None
    if sample_weight is not None:
        sw = np.asarray(sample_weight, dtype=dtype)
        if sw.shape != (n,):
            raise ValueError(
                f"sample_weight must have shape ({n},), got {sw.shape}")
        if np.any(sw < 0) or not np.all(np.isfinite(sw)):
            raise ValueError("sample_weight must be finite and >= 0")

    x_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
    w_sharding = NamedSharding(mesh, P(DATA_AXIS))

    def x_cb(index) -> np.ndarray:
        rows = index[0]
        lo, hi = rows.start or 0, rows.stop if rows.stop is not None else n_pad
        real_hi = min(hi, n)
        out = np.zeros((hi - lo, d), dtype=dtype)
        if real_hi > lo:
            out[: real_hi - lo] = read_rows(lo, real_hi)
        return out

    def w_cb(index) -> np.ndarray:
        rows = index[0]
        lo, hi = rows.start or 0, rows.stop if rows.stop is not None else n_pad
        real_hi = min(hi, n)
        out = np.zeros((hi - lo,), dtype=dtype)
        if real_hi > lo:
            out[: real_hi - lo] = (1.0 if sw is None
                                   else sw[lo:real_hi])
        return out

    points = jax.make_array_from_callback((n_pad, d), x_sharding, x_cb)
    weights = jax.make_array_from_callback((n_pad,), w_sharding, w_cb)
    return ShardedDataset(points, weights, n, chunk, mesh,
                          host=host_handle, host_weights=sw,
                          explicit_chunk=explicit_chunk)


def _resolve_chunk(n: int, d: int, k_hint: int, mesh: Mesh,
                   chunk_size: Optional[int],
                   budget_elems: Optional[int] = None) -> int:
    data_shards, model_shards = mesh_shape(mesh)
    # budget_elems=None IS choose_chunk_size's default contract now
    # (default budget + single-chunk shortcut eligibility).
    return chunk_size or choose_chunk_size(
        -(-n // data_shards), max(k_hint, model_shards), d,
        budget_elems=budget_elems)


def from_npy(path, mesh: Mesh, *, chunk_size: Optional[int] = None,
             dtype=np.float32, k_hint: int = 16,
             budget_elems: Optional[int] = None,
             sample_weight: Optional[np.ndarray] = None) -> ShardedDataset:
    """Shard a 2-D ``.npy`` file onto the mesh without loading it whole.

    ``k_hint`` feeds the automatic chunk-size choice (the (chunk, k)
    distance tile is the working set); pass the k you plan to fit, or set
    ``chunk_size`` explicitly.  ``budget_elems`` overrides the per-tile
    element budget — pass ``models.gmm.EM_CHUNK_BUDGET`` when the dataset
    is destined for a ``GaussianMixture`` fit (the EM pass wants smaller
    tiles than K-Means; docs/PERFORMANCE.md).  With ``mesh=None`` this falls back to a
    plain in-memory upload (single-device paths have no per-shard slicing
    to exploit).
    """
    mm = np.load(path, mmap_mode="r")
    if mm.ndim != 2:
        raise ValueError(f"expected a 2-D array in {path}, got shape "
                         f"{mm.shape}")
    n, d = mm.shape
    if mesh is None:
        return to_device(np.asarray(mm, dtype=dtype), None,
                         chunk_size or choose_chunk_size(n, k_hint, d),
                         dtype, sample_weight=sample_weight,
                         explicit=chunk_size is not None)
    chunk = _resolve_chunk(n, d, k_hint, mesh, chunk_size, budget_elems)

    def read_rows(lo: int, hi: int) -> np.ndarray:
        return np.asarray(mm[lo:hi], dtype=dtype)

    return _sharded_from_source(read_rows, n, d, mesh, chunk, dtype,
                                sample_weight, host_handle=mm,
                                explicit_chunk=chunk_size is not None)


def from_raw(path, shape: Tuple[int, int], mesh: Mesh, *,
             file_dtype=np.float32, chunk_size: Optional[int] = None,
             dtype=np.float32, k_hint: int = 16,
             budget_elems: Optional[int] = None,
             offset: int = 0,
             sample_weight: Optional[np.ndarray] = None) -> ShardedDataset:
    """Shard a headerless binary file of ``shape`` row-major ``file_dtype``
    values (e.g. exported feature matrices) onto the mesh, reading each
    shard's byte range only."""
    n, d = shape
    mm = np.memmap(path, dtype=file_dtype, mode="r", offset=offset,
                   shape=(n, d))
    if mesh is None:
        return to_device(np.asarray(mm, dtype=dtype), None,
                         chunk_size or choose_chunk_size(n, k_hint, d),
                         dtype, sample_weight=sample_weight,
                         explicit=chunk_size is not None)
    chunk = _resolve_chunk(n, d, k_hint, mesh, chunk_size, budget_elems)

    def read_rows(lo: int, hi: int) -> np.ndarray:
        return np.asarray(mm[lo:hi], dtype=dtype)

    return _sharded_from_source(read_rows, n, d, mesh, chunk, dtype,
                                sample_weight, host_handle=mm,
                                explicit_chunk=chunk_size is not None)


def iter_npy_blocks(path, block_rows: int, *, dtype=None):
    """Factory for ``KMeans.fit_stream``: returns a zero-argument callable
    that yields consecutive (<= block_rows, D) slices of a 2-D ``.npy``
    via mmap — only one block is ever resident in host memory, so the file
    can exceed both HBM and host RAM.

    Usage::

        km.fit_stream(iter_npy_blocks("big.npy", 1_000_000))
    """
    if block_rows <= 0:
        raise ValueError(f"block_rows must be positive, got {block_rows}")

    def make_blocks():
        arr = np.load(path, mmap_mode="r")
        if arr.ndim != 2:
            raise ValueError(f"{path} must contain a 2-D array, "
                             f"got shape {arr.shape}")
        for start in range(0, arr.shape[0], block_rows):
            block = np.asarray(arr[start: start + block_rows])
            yield block if dtype is None else block.astype(dtype)

    return make_blocks
