"""Double-buffered input pipeline: overlap host IO/transfer with compute.

The streaming surfaces (``KMeans.fit_stream``, ``GaussianMixture.
fit_stream``, the predict/transform/score streams) consume host blocks
one at a time.  Without prefetch, each block's disk read and
host->device transfer serializes against the device step that consumes
it — on a tunneled transport (~7-10 MB/s measured, docs/PERFORMANCE.md)
the transfer IS the whole cost of a streamed epoch.  ``prefetch_iter``
is the repo's one input-pipeline primitive: a bounded background
producer (thread + ``queue.Queue(maxsize=prefetch)``) that reads block
i+1 from the source — and runs the caller's ``stage`` callback, which
is where the consumers put their decode + ``jax.device_put`` onto the
data-mesh sharding — while block i's step computes on device.

Contract (pinned by tests/test_prefetch.py):

* **Order-preserving and semantics-free.**  Items are yielded in source
  order; ``stage`` runs once per item in that order.  Only WHERE the
  work happens moves (a thread), never WHAT is computed — so a
  ``prefetch=0`` and a ``prefetch>0`` run of the same fit are
  bit-identical (the parity oracle the streamed-fit tests pin).
* **prefetch=0 is the synchronous path** — no thread, no queue; the
  generator applies ``stage`` inline.  It is the fallback AND the
  reference behavior every prefetch>0 run must reproduce exactly.
* **Reader errors surface at the consumer.**  Any exception raised by
  the source iterable or by ``stage`` (in the producer thread) is
  re-raised from the consumer's ``next()`` at the position where the
  failing item would have appeared — stream-shape validation errors
  keep their call-site visibility.
* **No leaked threads.**  Closing the generator early (``close()``,
  ``break``, GC of a partial epoch) signals the producer, drains the
  queue so a blocked ``put`` wakes, and JOINS the thread before
  returning.  The producer never blocks forever: every ``put`` polls a
  stop event.

Memory contract: up to ``prefetch`` staged items live in the queue plus
one in flight in the producer — a streamed fit's device footprint grows
from 1 block to at most ``prefetch + 2`` blocks.  That is the standard
staging-buffer trade; size ``prefetch`` (default 2 at the call sites)
against block size accordingly.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional

from kmeans_tpu.obs import trace as _obs_trace

__all__ = ["prefetch_iter", "check_prefetch", "close_source",
           "abort_source"]

# Poll period for the producer's stop-aware queue puts.  Short enough
# that generator close() never waits noticeably, long enough to cost
# nothing while the queue has room.
_PUT_POLL_S = 0.05


def check_prefetch(prefetch) -> int:
    """Validate a ``prefetch`` knob: an int >= 0 (0 = synchronous)."""
    p = int(prefetch)
    if p < 0 or p != prefetch:
        raise ValueError(f"prefetch must be an int >= 0, got {prefetch!r}")
    return p


def prefetch_iter(source: Iterable, prefetch: int,
                  stage: Optional[Callable] = None) -> Iterator:
    """Iterate ``source`` with ``prefetch`` items staged ahead.

    ``stage(item)`` (optional) maps each raw item to what the consumer
    receives; with ``prefetch > 0`` it runs in the producer thread —
    put the expensive per-item work there (disk read materialization,
    decode, ``jax.device_put``) so it overlaps the consumer's device
    compute.  ``prefetch=0`` applies ``stage`` inline with no thread.
    """
    prefetch = check_prefetch(prefetch)
    if prefetch == 0:
        return _sync_iter(source, stage)
    return _PrefetchIterator(source, prefetch, stage)


def close_source(it) -> None:
    """Propagate close to a closeable iterator (a generator, or a nested
    _PrefetchIterator — e.g. ``iter_npy_blocks(..., prefetch=N)`` feeding
    a prefetched fit); a no-op for plain iterators.  Abandoning a
    wrapper or a peeked stream must reap the source's thread/frame
    deterministically, not wait for cyclic GC."""
    close = getattr(it, "close", None)
    if close is not None:
        close()


def abort_source(it) -> None:
    """Wake a source blocked in an interruptible wait (e.g. a
    ``data.io._ResilientBlockIter`` mid-backoff-sleep) so the thread
    driving it can exit NOW instead of waiting the retry schedule out;
    a no-op for sources without an ``abort()`` method.  Distinct from
    :func:`close_source`: abort is safe to call from ANOTHER thread
    while the source is being iterated (it only sets an event), close
    is the join-side cleanup."""
    ab = getattr(it, "abort", None)
    if ab is not None:
        ab()


def _sync_iter(source, stage):
    it = iter(source)
    try:
        for item in it:
            yield stage(item) if stage is not None else item
    finally:
        close_source(it)


class _PrefetchIterator:
    """Generator-protocol iterator backed by one producer thread.

    Implemented as a class (not a generator function) so ``close()`` is
    an explicit, idempotent join point — and so an abandoned iterator's
    ``__del__`` still reaps the thread.
    """

    def __init__(self, source, prefetch: int, stage):
        self._q: queue.Queue = queue.Queue(maxsize=prefetch)
        self._stop = threading.Event()
        self._source = iter(source)
        self._thread = threading.Thread(
            target=self._produce, args=(self._source, stage),
            name="kmeans_tpu-prefetch", daemon=True)
        self._done = False
        self._thread.start()

    # ------------------------------------------------------- producer side

    def _put(self, msg) -> bool:
        """Stop-aware put: never blocks past a close().  Returns False
        when the consumer signalled stop (the message is dropped)."""
        while not self._stop.is_set():
            try:
                self._q.put(msg, timeout=_PUT_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self, it, stage) -> None:
        try:
            for item in it:
                # The producer's staging share (decode + device_put)
                # runs under a 'stage' span from THIS thread's tid, so
                # a chrome timeline shows block i+1's transfer
                # overlapping the consumer's dispatch spans (any inner
                # shard_points 'stage' nests; self-time attribution
                # keeps totals double-count-free).
                if stage is not None:
                    with _obs_trace.span("stage", via="prefetch"):
                        staged = stage(item)
                else:
                    staged = item
                if not self._put(("item", staged)):
                    return                      # closed early
                del staged                      # queue owns the reference
            self._put(("done", None))
        except BaseException as e:              # noqa: BLE001 — re-raised
            self._put(("error", e))             # at the consumer

    # ------------------------------------------------------- consumer side

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        while True:
            try:
                kind, val = self._q.get(timeout=_PUT_POLL_S)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    # Producer died without a terminal message (should
                    # be impossible — _produce's except posts one) and
                    # the queue is drained: stop rather than hang.
                    try:
                        kind, val = self._q.get_nowait()
                        break
                    except queue.Empty:
                        self.close()
                        raise StopIteration from None
        if kind == "item":
            return val
        self.close()
        if kind == "error":
            raise val
        raise StopIteration                     # kind == "done"

    def close(self) -> None:
        """Signal the producer, drain the queue, join the thread.
        Idempotent; called on exhaustion, error, early ``close()``/
        ``break``, and GC."""
        if self._done:
            return
        self._done = True
        self._stop.set()
        # Wake the source FIRST: a producer inside a retry backoff sleep
        # (data.io._ResilientBlockIter) must abort immediately — the
        # join below would otherwise wait out the whole deterministic
        # backoff schedule (ISSUE 4 shutdown-hardening satellite).
        abort_source(self._source)
        # Drain so a producer blocked in put() sees the stop event on
        # its next poll instead of racing a full queue.
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join()
        # After the join no one is executing the source; close it too
        # (nested prefetchers/generators must not linger until GC).
        close_source(self._source)

    def __del__(self):
        try:
            self.close()
        except Exception:       # interpreter shutdown — nothing to do
            pass
