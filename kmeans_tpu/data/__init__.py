"""Dataset generation and loading for tests and benchmarks."""

from kmeans_tpu.data.synthetic import make_blobs, make_uniform

__all__ = ["make_blobs", "make_uniform"]
