"""Dataset generation and loading for tests and benchmarks."""

from kmeans_tpu.data.synthetic import make_blobs, make_uniform
from kmeans_tpu.data.io import from_npy, from_raw, iter_npy_blocks
from kmeans_tpu.data.prefetch import prefetch_iter

__all__ = ["make_blobs", "make_uniform", "from_npy", "from_raw",
           "iter_npy_blocks", "prefetch_iter"]
