"""Dataset generation and loading for tests and benchmarks."""

from kmeans_tpu.data.synthetic import make_blobs, make_uniform
from kmeans_tpu.data.io import from_npy, from_raw

__all__ = ["make_blobs", "make_uniform", "from_npy", "from_raw"]
