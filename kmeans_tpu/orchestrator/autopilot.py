"""The elastic autopilot: a supervising loop over per-host fit workers
(ISSUE 19).

Composes the instruments eight PRs built — rotating topology-portable
checkpoints (r10), fleet heartbeats + ``straggler_report`` (r13/r17),
warm AOT resume (r19) — into the controller ROADMAP item 1 said was
missing: launch the fleet, watch its heartbeats, and act on the
COMMITTED, TYPED rules in ``orchestrator.policy``:

* a worker that DIES is classified by its typed exit code and
  relaunched from the newest resumable rotating checkpoint
  (``policy.select_resume`` — the ``.prev``-aware classification), up
  to ``policy.RELAUNCH_BUDGET`` deaths per index;
* a host flagged ``stalled`` on ``policy.STALL_CONSECUTIVE_POLLS``
  consecutive polls is EVICTED, the fleet relaunches on the SHRUNK
  mesh from the last rotating checkpoint, and — after
  ``policy.GROW_HOLDOFF_POLLS`` healthy polls — GROWS back toward the
  target world when capacity returns;
* a launch failure retries under the bounded deterministic exponential
  backoff (``policy.backoff_delay_s``), and any exhausted budget
  REFUSES with :class:`policy.AutopilotGaveUpError` carrying the full
  decision log, rather than looping forever.

Every decision is a JSONL record (``<out>/autopilot.decisions.jsonl``,
appended and flushed as it happens — a crashed supervisor still leaves
its log), an ``autopilot.decision`` event through the r15 tracer, and
an ``autopilot.<action>`` counter in the metrics registry; the
evict/shrink/grow/relaunch operations run inside ``autopilot.<action>``
tracer spans so their wall-clock cost is auditable post-hoc.

Stall flags are gated per WORKER INCARNATION: a flag only counts when
the host has heartbeaten since its current launch (``ts >=
launched_wall``) — a freshly (re)launched worker warming up its jax
import must not read as stalled just because its previous incarnation's
beats are old.  A worker that hangs before its first beat is bounded by
``policy.MAX_RUN_S``.
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

from kmeans_tpu.obs import REGISTRY, fleet as obs_fleet
from kmeans_tpu.obs import trace as obs_trace
from kmeans_tpu.obs.trace import TraceReadError
from kmeans_tpu.orchestrator import launcher, policy
from kmeans_tpu.orchestrator.policy import AutopilotGaveUpError, Decision

__all__ = ["Autopilot", "AutopilotResult", "run_autopilot"]


@dataclass
class AutopilotResult:
    """What a completed (non-gave-up) supervised run looked like."""

    outcome: str                    # "converged" | "degraded"
    world_start: int
    target_world: int
    final_world: int
    decisions: List[Dict[str, Any]]
    results: Dict[int, Dict[str, Any]]   # per-index result.p<i>.json
    centroids_agree: bool
    out_dir: str

    @property
    def exit_code(self) -> int:
        """The CLI contract: 0 converged, 1 degraded-but-done (the
        gave-up path raises and maps to 2)."""
        return 0 if self.outcome == "converged" else 1

    def as_dict(self) -> Dict[str, Any]:
        return {"outcome": self.outcome, "exit_code": self.exit_code,
                "world_start": self.world_start,
                "target_world": self.target_world,
                "final_world": self.final_world,
                "centroids_agree": self.centroids_agree,
                "decisions": self.decisions,
                "results": {str(i): r for i, r in self.results.items()},
                "out_dir": self.out_dir}


class Autopilot:
    """Supervise ``world`` fit workers to completion under the
    committed policy.  ``capacity_fn`` answers "can the fleet grow back
    one host right now?" (default: always, the single-machine simulated
    fleet); ``grow=False`` pins a shrunk fleet shrunk (useful when the
    straggler cause is known to persist)."""

    def __init__(self, spec_path, out_dir, world: int, *,
                 target_world: Optional[int] = None,
                 poll_period_s: float = policy.POLL_PERIOD_S,
                 grow: bool = True,
                 max_run_s: float = policy.MAX_RUN_S,
                 capacity_fn: Optional[Callable[[], bool]] = None,
                 coordinator_address: Optional[str] = None,
                 sleep: Callable[[float], None] = time.sleep):
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        self.spec_path = Path(spec_path)
        if not self.spec_path.is_file():
            raise FileNotFoundError(
                f"worker spec not found: {self.spec_path}")
        self.out_dir = Path(out_dir)
        self.world = world
        self.world_start = world
        self.target_world = target_world if target_world is not None \
            else world
        self.poll_period_s = poll_period_s
        self.grow = grow
        self.max_run_s = max_run_s
        self.capacity_fn = capacity_fn or (lambda: True)
        self.coordinator_address = coordinator_address
        self.sleep = sleep
        self.decisions: List[Decision] = []
        self._active: Dict[int, launcher.WorkerHandle] = {}
        self._launched_wall: Dict[int, float] = {}
        self._stall_streak: Dict[int, int] = {}
        self._relaunches: Dict[int, int] = {}
        self._healthy_streak = 0
        self._t0 = 0.0
        self._log_file = None

    # ------------------------------------------------------- decisions

    def _record(self, action: str, reason: str, *, world_after=None,
                **detail) -> Decision:
        d = Decision(seq=len(self.decisions),
                     t_s=time.monotonic() - self._t0,
                     action=action, reason=reason,
                     world_before=self.world,
                     world_after=(self.world if world_after is None
                                  else world_after),
                     detail=detail)
        self.decisions.append(d)
        payload = d.as_dict()
        if self._log_file is not None:
            self._log_file.write(json.dumps(payload) + "\n")
            self._log_file.flush()
        obs_trace.event("autopilot.decision", **payload)
        REGISTRY.counter(f"autopilot.{action}").inc()
        return d

    def _record_unreadable(self, error: str) -> None:
        """Account an unreadable heartbeat scan (a worker mid-append);
        the poll simply carries no signal — counted, never silent."""
        REGISTRY.counter("autopilot.poll_unreadable").inc()

    def _give_up(self, reason: str, **detail):
        self._record("give-up", reason, **detail)
        raise AutopilotGaveUpError(reason, self.decisions)

    # --------------------------------------------------------- workers

    def _launch(self, index: int, *, resume=None, action="launch",
                reason="fleet bring-up", **detail) -> None:
        def on_backoff(attempt, delay, err):
            self._record("launch-backoff",
                         f"worker {index} attempt {attempt} failed",
                         attempt=attempt, delay_s=delay, error=err)

        try:
            with obs_trace.span(f"autopilot.{action}", index=index,
                                world=self.world):
                h = launcher.launch_with_backoff(
                    self.spec_path, index, self.world, self.out_dir,
                    resume=resume,
                    coordinator_address=self.coordinator_address,
                    on_backoff=on_backoff, sleep=self.sleep)
        except launcher.LaunchError as e:
            # Routed fault path: the committed backoff budget is spent —
            # typed give-up with the full decision log.
            self._give_up(
                f"worker {index} failed to launch after "
                f"{policy.LAUNCH_RETRY_BUDGET} attempts: {e}")
        h.relaunches = self._relaunches.get(index, 0)
        self._active[index] = h
        self._launched_wall[index] = time.time()
        self._stall_streak[index] = 0
        self._record(action, reason, index=index,
                     resume=str(resume) if resume else None, **detail)

    def _select_resume(self, indexes) -> Optional[object]:
        """The committed resume rule + its decision records."""
        path, info = policy.select_resume(self.out_dir, indexes)
        if path is None:
            if info["torn"]:
                self._record("resume-torn",
                             "no rotation classifies resumable; "
                             "handing torn state to the typed worker "
                             "failure path", torn=info["torn"])
                return Path(info["torn"][0])
            return None
        if info["source"] == "prev":
            self._record("resume-fallback-prev",
                         f"primary torn; resuming from the .prev "
                         f"last-good rotation at iteration "
                         f"{info['iteration']}", path=str(path),
                         iteration=info["iteration"])
        return path

    def _relaunch_fleet(self, new_world: int, *, action: str,
                        reason: str) -> None:
        """Kill every active worker and relaunch the fleet at
        ``new_world`` from the newest resumable checkpoint — the shrink
        / grow primitive (a real ``jax.distributed`` world cannot
        change size in place)."""
        old_indexes = set(range(max(self.world, new_world))) \
            | set(self._active)
        with obs_trace.span(f"autopilot.{action}",
                            world_before=self.world,
                            world_after=new_world):
            for h in self._active.values():
                h.terminate()
            self._active.clear()
            self._record(action, reason, world_after=new_world)
            self.world = new_world
            resume = self._select_resume(old_indexes)
            for i in range(new_world):
                self._launch(i, resume=resume, action="relaunch",
                             reason=f"{action} to world {new_world}")

    # ------------------------------------------------------------ poll

    def _reap(self) -> bool:
        """Collect exited workers; relaunch the dead under the
        committed budgets.  Returns True if any worker exited."""
        reaped = False
        for index, h in list(self._active.items()):
            rc = h.poll()
            if rc is None:
                continue
            reaped = True
            del self._active[index]
            kind = policy.classify_exit(rc)
            if kind == "done":
                self._record("finish", f"worker {index} exit 0",
                             index=index)
                continue
            self._relaunches[index] = self._relaunches.get(index, 0) + 1
            if self._relaunches[index] > policy.RELAUNCH_BUDGET:
                self._give_up(
                    f"worker {index} died {self._relaunches[index]} "
                    f"times (last: {kind}, exit {rc}) — relaunch "
                    f"budget {policy.RELAUNCH_BUDGET} exhausted",
                    index=index, exit_code=rc, kind=kind)
            resume = self._select_resume(
                set(range(self.world)) | {index})
            self._launch(index, resume=resume, action="relaunch",
                         reason=f"worker {index} {kind} (exit {rc}); "
                         f"resuming from last rotating checkpoint",
                         exit_code=rc, kind=kind,
                         death=self._relaunches[index])
        return reaped

    def _stalled_now(self) -> List[int]:
        """Active worker indexes currently flagged ``stalled`` by the
        merged-heartbeat straggler report, gated per incarnation."""
        paths = sorted(self.out_dir.glob("hb.p*.jsonl"))
        if not paths:
            return []
        try:
            records = obs_fleet.merge_heartbeats(paths)
        except TraceReadError as e:
            # Routed fault path: a torn mid-append read is an expected
            # transient — counted, retried next poll.
            self._record_unreadable(str(e))
            return []
        if not records:
            return []
        report = obs_fleet.straggler_report(records, now=time.time())
        out = []
        for row in report["hosts"]:
            idx = row.get("process_index")
            if idx not in self._active or "stalled" not in row["flags"]:
                continue
            if row.get("ts", 0.0) < self._launched_wall.get(idx, 0.0):
                continue    # no beat from THIS incarnation yet
            out.append(idx)
        return out

    # ------------------------------------------------------------- run

    def run(self) -> AutopilotResult:
        """Supervise the fleet to completion.  Returns the typed result
        (``converged`` / ``degraded``); raises
        :class:`AutopilotGaveUpError` when a committed budget is
        exhausted."""
        self.out_dir.mkdir(parents=True, exist_ok=True)
        self._t0 = time.monotonic()
        own_tracer = obs_trace.get_tracer() is None
        ctx = obs_trace.tracing(self.out_dir / "autopilot.trace.jsonl") \
            if own_tracer else contextlib.nullcontext()
        with ctx, open(self.out_dir / "autopilot.decisions.jsonl",
                       "a") as self._log_file:
            try:
                return self._run()
            finally:
                for h in self._active.values():
                    h.terminate()
                self._active.clear()
                self._log_file = None

    def _run(self) -> AutopilotResult:
        for i in range(self.world):
            self._launch(i)
        while True:
            if time.monotonic() - self._t0 > self.max_run_s:
                self._give_up(
                    f"deadline exceeded ({self.max_run_s:g} s) with "
                    f"{len(self._active)} workers still running")
            self.sleep(self.poll_period_s)
            reaped = self._reap()
            if not self._active:
                break
            stalled = self._stalled_now()
            for idx in list(self._stall_streak):
                self._stall_streak[idx] = \
                    self._stall_streak.get(idx, 0) + 1 \
                    if idx in stalled else 0
            victims = [i for i in sorted(self._active)
                       if policy.should_evict(self._stall_streak.get(i, 0))]
            if victims:
                victim = victims[0]
                self._healthy_streak = 0
                self._record(
                    "evict",
                    f"worker {victim} stalled on "
                    f"{self._stall_streak[victim]} consecutive polls",
                    index=victim,
                    streak=self._stall_streak[victim])
                if self.world - 1 < 1:
                    self._give_up("no healthy hosts left after "
                                  "evicting the last worker")
                self._active.pop(victim).terminate()
                self._relaunch_fleet(
                    self.world - 1, action="shrink",
                    reason=f"evicted stalled worker {victim}")
                continue
            if reaped or stalled:
                self._healthy_streak = 0
            else:
                self._healthy_streak += 1
            if self.grow and policy.should_grow(
                    self.world, self.target_world,
                    self._healthy_streak) and self.capacity_fn():
                self._healthy_streak = 0
                self._relaunch_fleet(
                    self.world + 1, action="grow",
                    reason=f"capacity returned after "
                    f"{policy.GROW_HOLDOFF_POLLS} healthy polls")
        return self._finish()

    def _finish(self) -> AutopilotResult:
        import numpy as np

        results: Dict[int, Dict[str, Any]] = {}
        cents = {}
        for i in range(self.world):
            rp = self.out_dir / f"result.p{i}.json"
            if rp.exists():
                results[i] = json.loads(rp.read_text())
            cp = self.out_dir / f"centroids.p{i}.npy"
            if cp.exists():
                cents[i] = np.load(cp)
        agree = len(cents) == self.world and self.world > 0 and all(
            np.array_equal(cents[i], cents[0]) for i in cents)
        outcome = "converged" if self.world == self.target_world \
            else "degraded"
        self._record("done", f"fleet of {self.world} finished "
                     f"({outcome})", centroids_agree=agree)
        return AutopilotResult(
            outcome=outcome, world_start=self.world_start,
            target_world=self.target_world, final_world=self.world,
            decisions=[d.as_dict() for d in self.decisions],
            results=results, centroids_agree=agree,
            out_dir=str(self.out_dir))


def run_autopilot(spec_path, out_dir, world: int,
                  **kwargs) -> AutopilotResult:
    """One-call convenience wrapper around :class:`Autopilot`."""
    return Autopilot(spec_path, out_dir, world, **kwargs).run()
