"""Committed, typed autopilot decision rules (ISSUE 19).

Every threshold the supervising loop acts on lives HERE, as a module
constant, committed before any chaos run — the same pre-registration
discipline as the perf harness budgets: a rule the autopilot applies is
a rule a reviewer can read, and a chaos test pins the behavior at the
committed value, never at a tuned-after-the-fact one.

The decision vocabulary (``Decision.action``):

=====================  ==================================================
``launch``             a worker process spawned (initial fleet bring-up)
``launch-backoff``     a launch attempt failed; deterministic exponential
                       delay before the retry (:func:`backoff_delay_s`)
``finish``             a worker exited 0 (its shard of the fit is done)
``relaunch``           a dead worker restarted from the selected resume
                       source (same mesh)
``resume-fallback-prev`` the selected resume source is the ``.prev``
                       last-good rotation — the primary is torn/corrupt
``resume-torn``        NOTHING classifies resumable but torn checkpoint
                       state exists on disk: the relaunch hands the torn
                       path to ``fit(resume=)`` anyway so the failure is
                       the worker's typed one, counted against the
                       relaunch budget (never a silent fresh restart
                       that would discard committed progress)
``evict``              a host flagged ``stalled`` for
                       :data:`STALL_CONSECUTIVE_POLLS` consecutive polls
                       is killed
``shrink``             the fleet relaunches on the shrunk mesh from the
                       last rotating checkpoint
``grow``               capacity returned: the fleet relaunches on the
                       grown mesh (bounded by the target world)
``give-up``            a committed budget is exhausted —
                       :class:`AutopilotGaveUpError` carries the FULL
                       decision log
``done``               the run completed (``converged`` or ``degraded``)
=====================  ==================================================

All functions here are pure (no IO, no clock): the loop in
``autopilot.py`` feeds them observations and acts on their verdicts, so
every rule is unit-testable without a fleet.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "POLL_PERIOD_S", "STALL_CONSECUTIVE_POLLS",
    "LAUNCH_RETRY_BUDGET", "LAUNCH_BACKOFF_BASE_S",
    "LAUNCH_BACKOFF_FACTOR", "LAUNCH_BACKOFF_MAX_S",
    "RELAUNCH_BUDGET", "GROW_HOLDOFF_POLLS", "MAX_RUN_S",
    "EXIT_DONE", "EXIT_PREEMPTED", "EXIT_CKPT_CORRUPT",
    "Decision", "AutopilotGaveUpError",
    "backoff_delay_s", "classify_exit", "should_evict", "should_grow",
    "checkpoint_path", "select_resume",
]

# ------------------------------------------------- committed thresholds

#: Supervising-loop poll period (heartbeat scan + reap), seconds.
POLL_PERIOD_S = 0.25

#: A host must be flagged ``stalled`` by ``obs.fleet.straggler_report``
#: on this many CONSECUTIVE polls before it is evicted — one flag can be
#: a paused disk flush; a run of them is a dead host.
STALL_CONSECUTIVE_POLLS = 2

#: Launch attempts per worker (initial spawn or relaunch) before the
#: autopilot gives up.  4 attempts = 3 backoffs.
LAUNCH_RETRY_BUDGET = 4

#: Deterministic exponential launch backoff: attempt ``i`` (0-based)
#: sleeps ``min(BASE * FACTOR**i, MAX)`` seconds.  No jitter — chaos
#: runs must replay bit-identically.
LAUNCH_BACKOFF_BASE_S = 0.05
LAUNCH_BACKOFF_FACTOR = 2.0
LAUNCH_BACKOFF_MAX_S = 2.0

#: Times ONE worker index may die (preemption, corrupt resume, crash)
#: and be relaunched before the autopilot refuses with
#: :class:`AutopilotGaveUpError` rather than looping forever.
RELAUNCH_BUDGET = 3

#: Consecutive healthy polls (no stall flags, no deaths) required
#: before a shrunk fleet grows back toward the target world.
GROW_HOLDOFF_POLLS = 8

#: Wall-clock deadline for one supervised run, seconds.
MAX_RUN_S = 600.0

# ------------------------------------------------- worker exit contract

#: Worker exit codes (``orchestrator.worker``): the ONLY channel a dead
#: process has.  75 is sysexits' EX_TEMPFAIL (transient, retry), 77 is
#: EX_NOPERM repurposed as "resume state unusable" — distinct so the
#: supervisor can tell a preemption (checkpoint valid, relaunch) from a
#: torn resume source (counted toward give-up).
EXIT_DONE = 0
EXIT_PREEMPTED = 75
EXIT_CKPT_CORRUPT = 77


def classify_exit(returncode: int) -> str:
    """Typed classification of a worker exit: ``done`` / ``preempted``
    / ``checkpoint-corrupt`` / ``crashed``."""
    if returncode == EXIT_DONE:
        return "done"
    if returncode == EXIT_PREEMPTED:
        return "preempted"
    if returncode == EXIT_CKPT_CORRUPT:
        return "checkpoint-corrupt"
    return "crashed"


def backoff_delay_s(attempt: int) -> float:
    """Delay before retrying a failed launch ``attempt`` (0-based):
    bounded deterministic exponential —
    ``min(BASE * FACTOR**attempt, MAX)``."""
    if attempt < 0:
        raise ValueError(f"attempt must be >= 0, got {attempt}")
    return min(LAUNCH_BACKOFF_BASE_S * LAUNCH_BACKOFF_FACTOR ** attempt,
               LAUNCH_BACKOFF_MAX_S)


def should_evict(consecutive_stalled_polls: int) -> bool:
    """Evict once a host has been flagged ``stalled`` on
    :data:`STALL_CONSECUTIVE_POLLS` consecutive polls."""
    return consecutive_stalled_polls >= STALL_CONSECUTIVE_POLLS


def should_grow(world: int, target_world: int,
                healthy_streak: int) -> bool:
    """Grow back toward the target once the shrunk fleet has been
    healthy for :data:`GROW_HOLDOFF_POLLS` consecutive polls."""
    return world < target_world and healthy_streak >= GROW_HOLDOFF_POLLS


# ------------------------------------------------------------ decisions

@dataclass
class Decision:
    """One autopilot decision — the JSONL record, the tracer event
    payload, and the give-up report line are all this dict."""

    seq: int
    t_s: float                  # seconds since the run started
    action: str                 # vocabulary in the module docstring
    reason: str
    world_before: int
    world_after: int
    detail: Dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> Dict[str, Any]:
        d = {"seq": self.seq, "t_s": round(self.t_s, 3),
             "action": self.action, "reason": self.reason,
             "world_before": self.world_before,
             "world_after": self.world_after}
        d.update(self.detail)
        return d


class AutopilotGaveUpError(RuntimeError):
    """A committed retry budget is exhausted: the autopilot REFUSES to
    keep looping.  Carries the complete typed decision log — the
    post-mortem is in the exception, not scattered across worker
    logs."""

    def __init__(self, reason: str, decisions: Sequence[Decision]):
        self.reason = reason
        self.decisions = list(decisions)
        super().__init__(
            f"autopilot gave up: {reason} "
            f"({len(self.decisions)} decisions logged)")

    def report(self) -> str:
        """The decision log, one line per decision, newest last."""
        lines = [f"autopilot gave up: {self.reason}"]
        for d in self.decisions:
            lines.append(
                f"  [{d.seq:3d}] t={d.t_s:8.3f}s {d.action:<22s} "
                f"world {d.world_before}->{d.world_after}  {d.reason}")
        return "\n".join(lines)


# ------------------------------------------------------- resume sources

def checkpoint_path(out_dir, index: int):
    """The per-worker rotating checkpoint path convention
    (``<out>/ckpt.p<i>.npz``) shared by the worker (writes) and the
    resume selection below (reads)."""
    from pathlib import Path
    return Path(out_dir) / f"ckpt.p{index}.npz"


def select_resume(out_dir, indexes: Sequence[int]) -> Tuple[
        Optional[object], Dict[str, Any]]:
    """Pick the resume source for a relaunch: among the fleet's rotating
    checkpoints (``ckpt.p<i>.npz`` for ``i`` in ``indexes``), the
    RESUMABLE one with the highest completed iteration — ties broken by
    lowest index, so the choice is deterministic.  Classification goes
    through ``utils.checkpoint.classify_resume`` (the ``.prev``-aware
    metadata read; no array materialization).

    Returns ``(path_or_None, info)`` where ``info`` carries ``source``
    (``primary``/``prev``/``None``), ``iteration``, and ``torn`` — the
    paths that exist on disk but classify unresumable.  ``path`` is
    None only when NO checkpoint classifies resumable; if ``torn`` is
    non-empty the caller must treat that as torn state (relaunch
    against it, bounded by the relaunch budget), never as
    start-from-scratch."""
    from kmeans_tpu.utils.checkpoint import classify_resume, prev_path

    best = None     # (iteration, index, path, cls)
    torn: List[str] = []
    for i in sorted(indexes):
        p = checkpoint_path(out_dir, i)
        if not p.exists() and not prev_path(p).exists():
            continue
        cls = classify_resume(p)
        if not cls["resumable"]:
            torn.append(str(p))
            continue
        key = (cls["iteration"] or 0, -i)
        if best is None or key > (best[0], -best[1]):
            best = (cls["iteration"] or 0, i, p, cls)
    if best is None:
        return None, {"source": None, "iteration": None, "torn": torn}
    _, i, p, cls = best
    return p, {"source": cls["source"], "iteration": cls["iteration"],
               "index": i, "torn": torn}
