"""Worker process launch for the autopilot (ISSUE 19).

One worker = one host of the fleet = one OS process running
``python -m kmeans_tpu.orchestrator.worker`` against a shared JSON spec.
Two fleet modes share this launcher:

* **Simulated fleet** (the default, and the only mode CI's CPU backend
  can run): each worker gets the ``KMEANS_TPU_PROCESS_INDEX``/``_COUNT``
  /``_HOST`` identity env (``parallel.multihost.simulated_world_env``)
  and runs an independent replica of the fit — no ``jax.distributed``
  handshake, so it works wherever a Python subprocess does.  Per-process
  heartbeat/trace sinks, host-targeted fault injection, checkpointing
  and resume all flow through exactly the production code paths.
* **Real ``jax.distributed`` fleet**: pass ``coordinator_address`` and
  the workers handshake into one SPMD world (the mode a TPU pod uses;
  gated in CI by the backend's lack of CPU cross-process collectives).

Launch failures are TYPED: every spawn attempt first fires
``utils.faults.on_launch`` (the ``inject_launch_failures`` registry —
chaos runs flake the real spawn path, no mocks), and any failure
surfaces as :class:`LaunchError` for the autopilot's committed
exponential-backoff retry (``policy.backoff_delay_s``).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Optional

from kmeans_tpu.orchestrator import policy
from kmeans_tpu.parallel.multihost import simulated_world_env
from kmeans_tpu.utils import faults

__all__ = ["LaunchError", "WorkerHandle", "launch_worker",
           "launch_with_backoff"]


class LaunchError(RuntimeError):
    """A worker spawn attempt failed (injected flake or a real
    ``OSError`` from the OS).  The typed boundary between "could not
    start a process" (retry with backoff, bounded by
    ``policy.LAUNCH_RETRY_BUDGET``) and "a started process died"
    (``policy.classify_exit``, bounded by ``policy.RELAUNCH_BUDGET``)."""


@dataclass
class WorkerHandle:
    """One live (or reaped) worker process."""

    index: int                   # fleet process_index
    world: int                   # process_count it was launched into
    proc: subprocess.Popen
    log_path: Path
    resume: Optional[str] = None  # resume source it was handed
    launch_attempts: int = 1     # spawn attempts this launch consumed
    relaunches: int = 0          # deaths this INDEX has accumulated
    detail: dict = field(default_factory=dict)

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def terminate(self, grace_s: float = 5.0) -> int:
        """SIGTERM, bounded wait, SIGKILL fallback; returns the exit
        code."""
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                # Routed fault path: escalate to SIGKILL and re-wait —
                # a stuck worker must never wedge the supervisor.
                self.proc.kill()
                self.proc.wait()
        return self.proc.returncode


def launch_worker(spec_path, index: int, world: int, out_dir, *,
                  resume: Optional[object] = None,
                  attempt: int = 0,
                  coordinator_address: Optional[str] = None,
                  python: Optional[str] = None,
                  extra_env: Optional[dict] = None) -> WorkerHandle:
    """Spawn ONE worker.  Fires the launch-attempt fault hook first
    (``faults.on_launch`` — the ``inject_launch_failures`` registry),
    then ``Popen``s ``python -m kmeans_tpu.orchestrator.worker``.  Any
    failure raises :class:`LaunchError`; the caller owns retry/backoff
    (:func:`launch_with_backoff`)."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    log_path = out_dir / f"worker.p{index}.log"
    cmd = [python or sys.executable, "-m",
           "kmeans_tpu.orchestrator.worker",
           "--spec", str(spec_path), "--index", str(index),
           "--world", str(world), "--out", str(out_dir)]
    if resume is not None:
        cmd += ["--resume", str(resume)]

    env = os.environ.copy()
    # The worker picks its own device count from the spec (XLA_FLAGS is
    # set before its jax import); the supervisor's flags must not leak.
    env.pop("XLA_FLAGS", None)
    if coordinator_address is not None:
        env["JAX_COORDINATOR_ADDRESS"] = coordinator_address
        env["JAX_NUM_PROCESSES"] = str(world)
        env["JAX_PROCESS_ID"] = str(index)
    else:
        env.update(simulated_world_env(index, world))
    env["PYTHONPATH"] = os.pathsep.join(
        [str(Path(__file__).resolve().parents[2])]
        + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    if extra_env:
        env.update(extra_env)

    try:
        faults.on_launch(index, attempt)
        log = open(log_path, "a")
        try:
            proc = subprocess.Popen(cmd, env=env, stdout=log,
                                    stderr=subprocess.STDOUT)
        finally:
            log.close()     # Popen dup'd the fd; the parent's is done
    except (faults.SimulatedLaunchFailure, OSError) as e:
        # Routed fault path: typed re-raise for the committed
        # backoff/retry policy — never swallowed, never IO-retried.
        raise LaunchError(
            f"launch of worker {index}/{world} failed on attempt "
            f"{attempt}: {e}") from e
    return WorkerHandle(index=index, world=world, proc=proc,
                        log_path=log_path,
                        resume=str(resume) if resume is not None else None,
                        launch_attempts=attempt + 1)


def launch_with_backoff(spec_path, index: int, world: int, out_dir, *,
                        resume: Optional[object] = None,
                        coordinator_address: Optional[str] = None,
                        extra_env: Optional[dict] = None,
                        on_backoff: Optional[Callable[[int, float, str],
                                                      None]] = None,
                        sleep: Callable[[float], None] = time.sleep
                        ) -> WorkerHandle:
    """Spawn a worker under the committed retry rule: up to
    ``policy.LAUNCH_RETRY_BUDGET`` attempts, sleeping the deterministic
    ``policy.backoff_delay_s(attempt)`` between failures.  Each failure
    is reported through ``on_backoff(attempt, delay_s, error)`` so the
    autopilot logs a typed ``launch-backoff`` decision; budget
    exhaustion re-raises the final :class:`LaunchError` for the
    autopilot's give-up path."""
    last: Optional[LaunchError] = None
    for attempt in range(policy.LAUNCH_RETRY_BUDGET):
        try:
            return launch_worker(
                spec_path, index, world, out_dir, resume=resume,
                attempt=attempt, coordinator_address=coordinator_address,
                extra_env=extra_env)
        except LaunchError as e:
            # Routed fault path: committed backoff between attempts,
            # typed re-raise once the budget is spent.
            last = e
            if attempt == policy.LAUNCH_RETRY_BUDGET - 1:
                raise
            delay = policy.backoff_delay_s(attempt)
            if on_backoff is not None:
                on_backoff(attempt, delay, str(e))
            sleep(delay)
    raise last  # pragma: no cover — unreachable (loop raises above)
