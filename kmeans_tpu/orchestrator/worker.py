"""One host of an autopilot-supervised fleet (ISSUE 19).

Spawned by ``orchestrator.launcher`` as
``python -m kmeans_tpu.orchestrator.worker --spec ... --index i
--world n --out dir [--resume ckpt]``.  The worker:

1. resolves its fleet identity from the env the launcher set (simulated
   ``KMEANS_TPU_*`` overrides, or a real ``jax.distributed`` handshake
   when coordinator env is present),
2. arms any DETERMINISTIC fault injections the shared spec requests
   (``utils.faults`` registry hooks — the chaos matrix flows through the
   real fit code paths, never mocks),
3. runs ``KMeans(...).fit(X, resume=..., checkpoint_every=...,
   checkpoint_path=<out>/ckpt.p<i>.npz)`` under per-process
   heartbeat/trace sinks, and
4. reports through the TYPED exit-code contract
   (``policy.EXIT_DONE/EXIT_PREEMPTED/EXIT_CKPT_CORRUPT``) plus
   ``centroids.p<i>.npy`` / ``result.p<i>.json`` artifacts.

Spec schema (JSON)::

    {"k": 4, "max_iter": 8, "tolerance": 1e-30, "seed": 0,
     "dtype": "float64",            # f64 => bit-exact resume parity
     "checkpoint_every": 1,
     "data_npy": "X.npy",           # or "synthetic": {n, d, kind, seed}
     "devices_per_host": 1,         # XLA virtual-device count
     "mesh": false,                 # build a data mesh over the devices
     "compute_sse": true,
     "faults": {                    # all optional, all deterministic
       "kill": {"process_index": 1, "after_iteration": 2,
                "tear": "none"|"primary"|"both"},
       "slow": {"process_index": 1, "after_iteration": 2,
                "seconds": 600.0}}}

Kill faults are ONE-SHOT PER INDEX across relaunches: firing drops a
latch file (``fault.kill.p<i>.latch``) in the out dir, and a relaunched
worker at the same index sees the latch and does not re-arm — a
preempted-then-resumed host must not be preempted forever.  ``tear``
models a preemption that also tore the checkpoint mid-copy: after the
(durable) kill, the primary file (and with ``"both"`` the ``.prev``
rotation too) is overwritten with garbage, so the relaunch exercises
the real ``load_state_with_fallback`` classification.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
from pathlib import Path


def _load_data(spec, np):
    if spec.get("data_npy"):
        return np.load(spec["data_npy"])
    syn = spec["synthetic"]
    from kmeans_tpu.data.synthetic import host_equivalent
    kind = syn.get("kind", "uniform")
    centers = None
    if kind == "blobs":
        # Deterministic well-separated centers from the spec alone, so
        # every incarnation of every worker regenerates the same data.
        k = int(syn.get("centers_k", spec.get("k", 3)))
        centers = np.asarray(
            np.random.default_rng(int(syn.get("seed", 0)))
            .uniform(-6.0, 6.0, size=(k, int(syn["d"]))))
    return host_equivalent(int(syn["n"]), int(syn["d"]),
                           kind=kind, seed=int(syn.get("seed", 0)),
                           centers=centers)


def _tear(path, mode: str) -> None:
    """Overwrite checkpoint file(s) with garbage — the deterministic
    stand-in for a write torn by the preemption."""
    from kmeans_tpu.utils.checkpoint import prev_path
    targets = [Path(path)]
    if mode == "both":
        targets.append(prev_path(path))
    for t in targets:
        if t.exists():
            t.write_bytes(b"torn checkpoint (injected)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kmeans_tpu.orchestrator.worker")
    ap.add_argument("--spec", required=True)
    ap.add_argument("--index", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--resume", default=None)
    args = ap.parse_args(argv)

    spec = json.loads(Path(args.spec).read_text())
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)

    # Device topology BEFORE the jax import (the only moment it binds).
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count="
        f"{int(spec.get('devices_per_host', 1))}")
    import jax

    if spec.get("dtype") == "float64":
        jax.config.update("jax_enable_x64", True)

    import numpy as np

    from kmeans_tpu import KMeans, obs
    from kmeans_tpu.orchestrator import policy
    from kmeans_tpu.utils import faults
    from kmeans_tpu.utils.checkpoint import CheckpointCorruptError

    if os.environ.get("JAX_COORDINATOR_ADDRESS"):
        from kmeans_tpu.parallel.multihost import initialize
        initialize()        # real jax.distributed fleet (TPU pods)

    X = _load_data(spec, np)
    mesh = None
    if spec.get("mesh"):
        from kmeans_tpu.parallel.mesh import make_mesh
        mesh = make_mesh()

    dtype = np.float64 if spec.get("dtype") == "float64" else None
    km = KMeans(k=int(spec["k"]), max_iter=int(spec.get("max_iter", 100)),
                tolerance=float(spec.get("tolerance", 1e-4)),
                seed=int(spec.get("seed", 0)),
                compute_sse=bool(spec.get("compute_sse", True)),
                empty_cluster=spec.get("empty_cluster", "keep"),
                dtype=dtype, mesh=mesh, host_loop=True,
                compute_labels=False, verbose=False)

    ckpt = policy.checkpoint_path(out, args.index)
    fspec = spec.get("faults") or {}
    kill = fspec.get("kill")
    slow = fspec.get("slow")
    latch = out / f"fault.kill.p{args.index}.latch"

    stack = contextlib.ExitStack()
    with stack:
        if kill and int(kill["process_index"]) == args.index \
                and not latch.exists():
            stack.enter_context(faults.inject_host_kill(
                args.index,
                after_iteration=int(kill.get("after_iteration", 0))))
        if slow and int(slow["process_index"]) == args.index:
            stack.enter_context(faults.inject_checkpoint_delay(
                float(slow.get("seconds", 600.0)),
                after_iteration=int(slow.get("after_iteration", 0))))
        stack.enter_context(obs.tracing(out / "trace.jsonl",
                                        per_process=True))
        stack.enter_context(obs.heartbeat(out / "hb.jsonl",
                                          per_process=True))
        try:
            km.fit(X, resume=args.resume or False,
                   checkpoint_every=int(spec.get("checkpoint_every", 1)),
                   checkpoint_path=ckpt)
        except faults.SimulatedPreemption:
            # Routed fault path: the typed exit code IS the route — the
            # supervisor classifies it (policy.classify_exit) against
            # the committed relaunch budget.  Latch first so a resumed
            # worker at this index is not re-preempted forever.
            latch.touch()
            if kill and kill.get("tear", "none") != "none":
                _tear(ckpt, kill["tear"])
            return policy.EXIT_PREEMPTED
        except CheckpointCorruptError:
            # Routed fault path: both rotations of the resume source
            # are torn — typed exit for the supervisor's give-up rule.
            return policy.EXIT_CKPT_CORRUPT

    np.save(out / f"centroids.p{args.index}.npy",
            np.asarray(km.centroids))
    result = {"index": args.index, "world": args.world,
              "iterations_run": int(km.iterations_run),
              "sse": (float(km.sse_history[-1])
                      if km.sse_history else None),
              "resumed_from": args.resume}
    (out / f"result.p{args.index}.json").write_text(json.dumps(result))
    print(f"worker {args.index}/{args.world} done "
          f"({km.iterations_run} iterations)", flush=True)
    return policy.EXIT_DONE


if __name__ == "__main__":
    sys.exit(main())
