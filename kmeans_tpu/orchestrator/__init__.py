"""Elastic autopilot (ISSUE 19): the supervising orchestration loop
that keeps a distributed fit running through preemption, stragglers,
torn checkpoints and launch flakes.

Layering (each importable alone):

* :mod:`~kmeans_tpu.orchestrator.policy` — the COMMITTED, typed
  decision rules: every threshold, budget and backoff schedule as a
  module constant; pure functions; :class:`AutopilotGaveUpError`.
* :mod:`~kmeans_tpu.orchestrator.launcher` — typed worker spawning
  (simulated fleet env or real ``jax.distributed`` coordinator) with
  the bounded deterministic exponential retry.
* :mod:`~kmeans_tpu.orchestrator.worker` — one host's entry point:
  ``fit(resume=)`` under per-process obs sinks and the typed exit-code
  contract.
* :mod:`~kmeans_tpu.orchestrator.autopilot` — the loop itself: launch,
  watch merged heartbeats, evict/shrink/grow/relaunch, give up on
  exhausted budgets; every decision a JSONL event through the r15
  tracer/registry.

See docs/AUTOPILOT.md for the decision-rule table and the exit-code
contract (0 converged / 1 degraded-but-done / 2 gave-up).
"""

from kmeans_tpu.orchestrator.autopilot import (Autopilot,
                                               AutopilotResult,
                                               run_autopilot)
from kmeans_tpu.orchestrator.launcher import (LaunchError, WorkerHandle,
                                              launch_with_backoff,
                                              launch_worker)
from kmeans_tpu.orchestrator.policy import (AutopilotGaveUpError,
                                            Decision, backoff_delay_s,
                                            classify_exit,
                                            select_resume)

__all__ = [
    "Autopilot", "AutopilotResult", "run_autopilot",
    "LaunchError", "WorkerHandle", "launch_worker",
    "launch_with_backoff",
    "AutopilotGaveUpError", "Decision", "backoff_delay_s",
    "classify_exit", "select_resume",
]
