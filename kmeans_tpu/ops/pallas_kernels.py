"""Pallas/Mosaic fused K-Means kernel (the framework's native-kernel tier).

The reference has zero native components (SURVEY.md §2: its only compiled
code is NumPy/BLAS and the Spark JVM), so per SURVEY.md §7 stage 6 the
Pallas kernel IS the native tier here: one hand-scheduled TPU kernel that
fuses the whole per-iteration pass — distance matmul (MXU), running
argmin over centroid tiles (VPU), one-hot scatter-sum matmul (MXU), and
count accumulation — without ever materializing an (N, k) distance matrix
in HBM.  The k-tiling keeps the working set in VMEM even for k where the
XLA scan path's (chunk, k) tile would spill (the k=3000 GloVe-class configs
in BASELINE.json).

Outputs per call: ``labels`` (N,1) int32, ``mind2`` (N,1) — min squared
distance per point (feeding SSE and the farthest-point policy on the
outside) — plus ``sums`` (k, D) and ``counts`` (1, k) accumulated across
the sequential grid.

Tie-breaking matches NumPy/the reference (kmeans_spark.py:156): within a
centroid tile ``jnp.argmin`` picks the lowest index; across tiles a strict
``<`` keeps the earlier (lower-index) tile's winner.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Sentinel for padded centroid rows: far from any real point, finite in f32.
_PAD_VALUE = 1e12


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _round_up(a: int, b: int) -> int:
    return _cdiv(a, b) * b


# k-tile loops unroll at trace time up to this bound (static python
# offsets sidestep a Pallas-tracing recursion in the int64 index
# promotion paths under jax_enable_x64, and give Mosaic static slices to
# schedule; <= 3 tiles covers every BASELINE.json config at the 1024
# default tile).  Beyond it, a fori_loop keeps trace/compile cost O(1) in
# k.  NOTE the fori index is int64 under jax_enable_x64 (interpret mode
# reaches that combination; compiled Mosaic mode rejects x64 at the
# fused_assign_reduce boundary) — hence the int32-normalizing offset below
# and the .astype on the label carry in scan_k.
_UNROLL_K_TILES = 8


def _k_tile_loop(k_tiles: int, tile_k: int, body, init):
    """Run ``body(off, carry)`` over the k tiles, where ``off`` is the tile
    row offset: a plain python int on the static-unroll path (Mosaic's
    slice lowering rejects np scalars), an int32 tracer on the fori path."""
    if k_tiles <= _UNROLL_K_TILES:
        carry = init
        for kt in range(k_tiles):
            carry = body(kt * tile_k, carry)
        return carry
    return jax.lax.fori_loop(
        np.int32(0), np.int32(k_tiles),
        lambda kt, c: body(jnp.asarray(kt, jnp.int32) * np.int32(tile_k), c),
        init)


def _argmin_over_tiles(x, c_ref, *, k_tiles: int, tile_k: int, mm_dtype):
    """Shared MXU distance + running-argmin body: (best, mind2) for one
    (tile_n, D) point block against every centroid tile in ``c_ref``."""
    tile_n = x.shape[0]
    x2 = jnp.sum(x * x, axis=1, keepdims=True)         # (tile_n, 1)

    def scan_k(off, carry):
        best, mind2 = carry
        c = c_ref[pl.ds(off, tile_k), :]               # (tile_k, D)
        c2 = jnp.sum(c * c, axis=1)[None, :]           # (1, tile_k)
        xc = jax.lax.dot_general(
            x.astype(mm_dtype), c.astype(mm_dtype),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (tile_n, tile_k) MXU
        d2 = jnp.maximum(x2 + c2 - 2.0 * xc, 0.0)
        # Explicit int32 index dtype: under jax_enable_x64 jnp.argmin
        # returns int64, which Mosaic cannot lower on TPU.
        local_best = jax.lax.argmin(d2, 1, jnp.int32)
        local_min = jnp.min(d2, axis=1)
        upd = local_min < mind2                        # strict: earlier tile
        # astype keeps the carry int32 on the interpret+x64 fori path
        # (where the loop index is int64); a no-op everywhere else.
        best = jnp.where(upd, (local_best + off).astype(jnp.int32),
                         best)                         # ties -> earlier
        return best, jnp.where(upd, local_min, mind2)  # tile wins

    return _k_tile_loop(
        k_tiles, tile_k, scan_k,
        (jnp.zeros((tile_n,), jnp.int32),
         jnp.full((tile_n,), jnp.inf, jnp.float32)))


def _kernel(x_ref, w_ref, c_ref, labels_ref, mind2_ref, sums_ref,
            counts_ref, *, k_tiles: int, tile_k: int, mm_dtype):
    i = pl.program_id(0)
    x = x_ref[:, :]                                    # (tile_n, D)
    w = w_ref[:, :]                                    # (tile_n, 1)
    best, mind2 = _argmin_over_tiles(x, c_ref, k_tiles=k_tiles,
                                     tile_k=tile_k, mm_dtype=mm_dtype)

    labels_ref[:, :] = best[:, None]
    mind2_ref[:, :] = mind2[:, None]

    # Zero the cross-grid accumulators on the first tile (TPU grids run
    # sequentially, so += across grid steps is well-defined).
    @pl.when(i == 0)
    def _():
        sums_ref[:, :] = jnp.zeros_like(sums_ref)
        counts_ref[:, :] = jnp.zeros_like(counts_ref)

    def accum_k(off, carry):
        ids = jax.lax.broadcasted_iota(
            jnp.int32, (1, tile_k), 1) + off           # (1, tile_k)
        onehot = (best[:, None] == ids).astype(jnp.float32) * w
        sums_ref[pl.ds(off, tile_k), :] += jax.lax.dot_general(
            onehot.astype(mm_dtype), x.astype(mm_dtype),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (tile_k, D) MXU
        counts_ref[:, pl.ds(off, tile_k)] += jnp.sum(
            onehot, axis=0, keepdims=True)
        return carry

    _k_tile_loop(k_tiles, tile_k, accum_k, np.int32(0))


def _assign_kernel(x_ref, c_ref, labels_ref, mind2_ref, *, k_tiles: int,
                   tile_k: int, mm_dtype):
    best, mind2 = _argmin_over_tiles(x_ref[:, :], c_ref, k_tiles=k_tiles,
                                     tile_k=tile_k, mm_dtype=mm_dtype)
    labels_ref[:, :] = best[:, None]
    mind2_ref[:, :] = mind2[:, None]


def _check_x64(interpret: bool) -> None:
    if not interpret and jax.config.jax_enable_x64:
        raise NotImplementedError(
            "Pallas TPU kernels cannot compile under jax_enable_x64 in "
            "this jax/Mosaic version (the internal grid carry lowers to "
            "i64, which Mosaic rejects — reproduced with a trivial "
            "kernel); disable x64 or use distance_mode='matmul'")


@functools.partial(jax.jit,
                   static_argnames=("tile_n", "tile_k", "bf16", "interpret"))
def pallas_assign(points: jax.Array, centroids: jax.Array, *,
                  tile_n: int = 1024, tile_k: int = 1024, bf16: bool = False,
                  interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Assignment-only variant: (labels (n,), mind2 (n,)) — no
    accumulation.  Used under centroid (model-axis) sharding, where the
    one-hot accumulation must wait for the GLOBAL argmin reconstructed
    across shards (r1 VERDICT #3); fusing it against the local block would
    accumulate points whose true winner lives in another shard's block."""
    _check_x64(interpret)
    n, d = points.shape
    k = centroids.shape[0]
    x = points.astype(jnp.float32)
    c = centroids.astype(jnp.float32)

    tile_n = min(tile_n, _round_up(max(n, 8), 8))
    n_pad = _round_up(n, tile_n)
    d_pad = _round_up(d, 128)
    tile_k = min(tile_k, _round_up(max(k, 128), 128))
    k_pad = _round_up(k, tile_k)
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
    if d_pad != d:
        x = jnp.pad(x, ((0, 0), (0, d_pad - d)))
        c = jnp.pad(c, ((0, 0), (0, d_pad - d)))
    if k_pad != k:
        c = jnp.pad(c, ((0, k_pad - k), (0, 0)),
                    constant_values=_PAD_VALUE)

    kernel = functools.partial(_assign_kernel, k_tiles=k_pad // tile_k,
                               tile_k=tile_k,
                               mm_dtype=jnp.bfloat16 if bf16 else
                               jnp.float32)
    labels, mind2 = pl.pallas_call(
        kernel,
        grid=(n_pad // tile_n,),
        in_specs=[
            pl.BlockSpec((tile_n, d_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, c)
    return labels[:n, 0], mind2[:n, 0]


@functools.partial(jax.jit,
                   static_argnames=("tile_n", "tile_k", "bf16", "interpret"))
def fused_assign_reduce(points: jax.Array, weights: jax.Array,
                        centroids: jax.Array, *, tile_n: int = 1024,
                        tile_k: int = 1024, bf16: bool = False,
                        interpret: bool = False
                        ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                   jax.Array]:
    """(labels (n,), mind2 (n,), sums (k, D), counts (k,)) in one kernel.

    Caller contract: ``points`` rows beyond the real data must carry
    ``weights == 0`` (their labels/mind2 outputs are garbage and must be
    masked by the caller, as ``assign_reduce`` padding does).  Internally
    pads D to the 128-lane boundary (zero columns change nothing) and k to
    a ``tile_k`` multiple with far-away sentinel rows (never selected).
    """
    _check_x64(interpret)
    n, d = points.shape
    k = centroids.shape[0]
    f32 = jnp.float32
    x = points.astype(f32)
    c = centroids.astype(f32)
    w = weights.astype(f32)

    tile_n = min(tile_n, _round_up(max(n, 8), 8))
    n_pad = _round_up(n, tile_n)
    d_pad = _round_up(d, 128)
    tile_k = min(tile_k, _round_up(max(k, 128), 128))
    k_pad = _round_up(k, tile_k)
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        w = jnp.pad(w, (0, n_pad - n))
    if d_pad != d:
        x = jnp.pad(x, ((0, 0), (0, d_pad - d)))
        c = jnp.pad(c, ((0, 0), (0, d_pad - d)))
    if k_pad != k:
        c = jnp.pad(c, ((0, k_pad - k), (0, 0)),
                    constant_values=_PAD_VALUE)

    grid = (n_pad // tile_n,)
    k_tiles = k_pad // tile_k
    kernel = functools.partial(_kernel, k_tiles=k_tiles, tile_k=tile_k,
                               mm_dtype=jnp.bfloat16 if bf16 else f32)
    labels, mind2, sums, counts = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_n, d_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((n_pad, 1), f32),
            jax.ShapeDtypeStruct((k_pad, d_pad), f32),
            jax.ShapeDtypeStruct((1, k_pad), f32),
        ],
        interpret=interpret,
    )(x, w[:, None], c)
    return (labels[:n, 0], mind2[:n, 0], sums[:k, :d], counts[0, :k])
