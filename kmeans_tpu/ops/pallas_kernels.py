"""Pallas/Mosaic fused K-Means kernel (the framework's native-kernel tier).

The reference has zero native components (SURVEY.md §2: its only compiled
code is NumPy/BLAS and the Spark JVM), so per SURVEY.md §7 stage 6 the
Pallas kernel IS the native tier here: one hand-scheduled TPU kernel that
fuses the whole per-iteration pass — distance matmul (MXU), running
argmin over centroid tiles (VPU), one-hot scatter-sum matmul (MXU), and
count accumulation — without ever materializing an (N, k) distance matrix
in HBM.  It replaces the reference's per-point hot loop
(kmeans_spark.py:147-159) plus its reduceByKey sum (:169-171) in a single
pass.

Design (r2 — each choice measured on a v5e, see docs/PERFORMANCE.md):

* **Argmin over ``h - x@c.T``** with ``h = 0.5*||c||^2``: the row-constant
  ``||x||^2`` term, the 2x scale, and the negativity clamp cannot change
  the argmin, so the (n, k) tile carries at most ONE elementwise op
  besides the reductions; full squared distances are reconstructed per
  ROW (O(n)) afterwards.
* **h folded into the MXU** when D leaves a free lane (d < d_pad): points
  carry a constant-1 column at lane ``d`` and the centroid block carries
  ``-h`` there, so the distance matmul emits ``x@c.T - h`` directly and
  the kernel just argmaxes it — zero elementwise ops on the (n, k) tile.
  The same ones-column makes the scatter matmul accumulate COUNTS for
  free (its lane-``d`` output column is the weighted one-hot column sum).
* **Manual argmin** (min, then min of index-where-equal): measured ~1.3x
  faster than Mosaic's ``lax.argmin`` lowering at (2048, 512) tiles.
  Tie-breaking stays NumPy's lowest-index rule (kmeans_spark.py:156):
  within a tile the index-min picks the lowest index among equal minima;
  across tiles a strict ``<`` keeps the earlier tile's winner.
* **Software pipelining**: the grid runs one extra step and each step
  accumulates the PREVIOUS n-tile's one-hot scatter (ping-pong VMEM
  scratch) while the current tile's distance matmul runs, giving Mosaic
  independent MXU/VPU chains to interleave.  Measured: 8.8 -> 7.4 ms at
  2M x 128, k=1024 (tile_k=512).
* **Zero-padded centroid rows** masked via ``+1e30`` in ``h`` (instead of
  sentinel coordinates): padding rows can never win the argmin, and the
  fold trick stays exact.

Measured v5e results (steady-state ms/iter inside the on-device fit
loop, interleaved marginal medians): 2M x 128 k=1024: 7.9 vs 10.8 for
the XLA scan path (1.37x); GloVe-shaped 400k x 100 k=3000: 4.4 vs 5.7
(1.29x); 1M x 128 k=512: parity; small-k/small-D shapes LOSE to XLA
(lane-padding waste) — ``pallas_preferred`` encodes the win region for
``distance_mode='auto'``.  See BASELINE.md for the bench-harness
numbers.

Numerics: Mosaic executes f32 dots at bf16-input rate on this platform
(one-pass bf16 multiplies, f32 accumulation — measured identical runtime
for ``bf16=False``/``True``), matching what XLA's
``--xla_allow_excess_precision`` does to the ``matmul`` path at these
shapes.  Labels therefore agree with a bf16-rounded-products oracle
(exactly, up to accumulation-tree ULP ties); interpret mode (CI) computes
true f32 and matches the NumPy oracle bit-exactly.

Outputs per call: ``labels`` (N,1) int32, ``mind2`` (N,1) — min squared
distance per point (feeding SSE and the farthest-point policy on the
outside) — plus ``sums`` (k, D) and ``counts`` (1, k) accumulated across
the sequential grid.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Added to h for padded centroid rows: no real point can beat it —
# finite, and far beyond any real h in both f32 and bf16 (it is NOT
# exactly representable in bf16: the 7-bit mantissa rounds it to
# ~1.014e30, which masks just as well; r2 ADVICE).
_PAD_H = 1e30
# Index sentinel for the manual argmin's index-min (> any real k).
_IDX_BIG = np.int32(2 ** 30)
# Mosaic scoped-VMEM budget for the kernels (v5e has 128 MB/core).
_VMEM_LIMIT = 100 * 1024 * 1024

# k-tile loops unroll at trace time up to this bound (static python
# offsets give Mosaic static slices to schedule); beyond it a fori_loop
# keeps trace/compile cost O(1) in k.
_UNROLL_K_TILES = 8


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _round_up(a: int, b: int) -> int:
    return _cdiv(a, b) * b


def choose_tiles(n: int, d_pad: int, k_pad: int,
                 fold: Optional[bool] = None) -> Tuple[int, int]:
    """Measured tile heuristic (v5e sweeps, experiments/
    exp_pallas_kernel.py + exp_glove_mfu.py).

    k-tiles narrower than 512 lanes are the failure mode (k=512 as
    2x256: 7.1 ms vs 3.1 for one 512 tile; k=1024 as 8x128: 39 ms):
    never split below 512.  Two ~512 tiles beat one 1024 tile at k=1024
    (7.4 vs 8.8 ms — the pipelined phases interleave).  Above 2048 the
    best split depends on the FOLD variant (r4 sweep at 400k rows):

    * fold path (d < d_pad — h and counts ride the matmul): a 2-way
      balanced split wins — k_pad=3072 as 2x1536 runs 3.48 ms vs 3.97
      for one 3072 tile (70% vs 61% real-FLOPs MFU, 92% padded-MXU
      utilization), k_pad=2048 as 2x1024 2.25 vs 2.75;
    * no-fold (d == d_pad): the single wide tile wins — k_pad=2048
      one-tile 2.65 vs 2.98 split, k_pad=4096 one-tile 6.79 vs 7.46 —
      so tiles stay wide up to 4096, balanced so the round-up to a
      tile_k multiple never inflates k_pad by more than one 128-lane
      register (k=4224 with a fixed 4096 tile would pad to 8192 —
      ~1.9x the MXU work).

    tile_n: 1024 rows whenever tile_k >= 1024 — every r4 variant with
    wide k-tiles ran best at 1024 rows ((1024,1536) 3.48 ms vs
    (2048,1536) 4.19 and (512,1536) 5.15) — else the ~2^22-element
    target capped at 2048 rows (the r2-measured best for 512-wide
    tiles).  ``fold`` tells the rule the data's true width is below
    ``d_pad``."""
    if fold is None:
        fold = False            # conservative: unknown true D
    if k_pad >= 2048:
        k_tiles = _cdiv(k_pad, 4096)
        if fold:
            k_tiles = max(2, k_tiles)
        tile_k = _round_up(_cdiv(k_pad, k_tiles), 128)
    elif k_pad >= 1024:
        tile_k = _round_up(k_pad // 2, 128)        # two >=512-wide tiles
    else:
        tile_k = k_pad                             # never split below 512
    if tile_k >= 1024:
        tile_n = 1024
    else:
        tile_n = max(256, min(2048, (1 << 22) // max(tile_k, d_pad)))
        tile_n = 1 << (tile_n.bit_length() - 1)    # power-of-2 floor
    return tile_n, tile_k


def pallas_preferred(n: int, d: int, k: int) -> bool:
    """Should ``distance_mode='auto'`` pick the fused Pallas kernel here?

    Measured win region (v5e, interleaved marginals vs the XLA scan path
    — BASELINE.md): 2M x 128 k=1024: 1.37x; 400k x 100 k=3000: 1.29x;
    1M x 128 k=512: parity.  Measured LOSS region: k=64 D=16: 11x slower
    (lane padding makes the kernel do 16x the MXU work); k=10 D=784:
    ~20x slower (k padded 12.8x).  Hence the two gates: enough real k
    (>= 512), and <= 1.5x combined padding waste.  Also falls back when
    the VMEM-resident centroid block would exceed the kernel budget, off
    TPU (interpret mode is for CI, not speed), and under x64 — not a
    compile limitation anymore (the kernels DO compile under
    jax_enable_x64 since r3; pass distance_mode='pallas' explicitly for
    f32-rate compute on x64 data) but a precision contract: an x64 user
    asked for float64 math and the fused kernel is an f32 engine.
    """
    try:
        on_tpu = jax.default_backend() == "tpu"
    except RuntimeError:
        on_tpu = False
    if not on_tpu or jax.config.jax_enable_x64:
        return False
    d_pad = _round_up(d, 128)
    k_pad0 = _round_up(k, 128)
    if k < 512 or d_pad * k_pad0 > 1.5 * d * k:
        return False
    tile_n, tile_k = choose_tiles(n, d_pad, k_pad0, fold=d < d_pad)
    k_pad = _round_up(k_pad0, tile_k)
    return _vmem_estimate(tile_n, tile_k, d_pad, k_pad,
                          True) <= _VMEM_LIMIT


def resolve_auto(n: int, d: int, k: int) -> str:
    """The single resolution rule behind ``distance_mode='auto'`` —
    shared by KMeans._mode and both bench harnesses so benchmark numbers
    always reflect the library default."""
    return "pallas" if pallas_preferred(n, d, k) else "matmul"


# r2's x64 guard is GONE (r2 VERDICT #5): the toolchain fixed the Mosaic
# grid-machinery x64 lowering that used to fail even trivial kernels
# (re-verified 2026-07-30 on jax 0.9.0 / v5e), and the one remaining
# in-repo blocker — index maps returning a bare Python 0, which lowers
# as i64 under the x64 flag and broke the grid with a mixed
# "func.return (i32, i64)" — is fixed in _specs (explicit np.int32).
# The kernels now compile and run under jax_enable_x64; they remain an
# f32 COMPUTE engine by design (inputs are cast, see _pad_inputs), which
# is why resolve_auto still prefers the XLA path under x64.


def _build_kernel(*, n_tiles, k_tiles, tile_n, tile_k, d, d_pad, mm_dtype,
                  fold_h, with_stats, with_mind2=True):
    """Shared kernel body builder.  Refs (in order): x, w, c, h, then outs
    labels, mind2[, sums, counts], then (pipelined) scratch xs, ws, bs.
    ``with_mind2=False`` elides the per-point min-distance reconstruction
    (the O(n*D) x2 reduce and the (n, 1) store) — callers deriving SSE
    algebraically never read it."""
    x2_corr = 1.0 if fold_h else 0.0   # ones column contributes 1 to x2

    def k_tile_loop(body, init):
        if k_tiles <= _UNROLL_K_TILES:
            carry = init
            for kt in range(k_tiles):
                carry = body(kt * tile_k, carry)
            return carry
        return lax.fori_loop(
            np.int32(0), np.int32(k_tiles),
            lambda kt, c: body(jnp.asarray(kt, jnp.int32)
                               * np.int32(tile_k), c), init)

    def argmin_tiles(x, c_ref, h_ref):
        """(best, mind2h) over all k tiles; d2h = h - x @ c.T (emitted
        directly by the MXU when fold_h)."""
        def one(off, carry):
            best, mind2h = carry
            c = c_ref[pl.ds(off, tile_k), :]
            xc = lax.dot_general(x.astype(mm_dtype), c.astype(mm_dtype),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
            ids = lax.broadcasted_iota(jnp.int32, (tile_n, tile_k), 1)
            # Manual argmin: min, then index-min over equal minima —
            # measured faster than Mosaic's lax.argmin lowering, and
            # lowest-index tie-breaking is explicit.  The fold path
            # argMAXes xc (= x@c_real.T - h) directly: negating the
            # whole (n, k) tile first would cost a full VPU pass.
            if fold_h:
                mx = jnp.max(xc, axis=1)
                lb = jnp.min(jnp.where(xc == mx[:, None], ids, _IDX_BIG),
                             axis=1)
                m = -mx
            else:
                d2h = h_ref[:, pl.ds(off, tile_k)] - xc
                m = jnp.min(d2h, axis=1)
                lb = jnp.min(jnp.where(d2h == m[:, None], ids, _IDX_BIG),
                             axis=1)
            upd = m < mind2h               # strict: earlier tile wins ties
            best = jnp.where(upd, (lb + off).astype(jnp.int32), best)
            return best, jnp.where(upd, m, mind2h)
        return k_tile_loop(
            one, (jnp.zeros((tile_n,), jnp.int32),
                  jnp.full((tile_n,), jnp.inf, jnp.float32)))

    def accum(best, x, w, sums_ref, counts_ref):
        """Scatter one tile's weighted one-hot into the accumulators.
        With fold_h the ones column in x makes the scatter matmul's
        lane-d output column the counts."""
        def one(off, _):
            ids = lax.broadcasted_iota(jnp.int32, (tile_n, tile_k), 1) + off
            ohw = jnp.where(best[:, None] == ids, w, 0.0)  # (tile_n, tile_k)
            sums_ref[pl.ds(off, tile_k), :] += lax.dot_general(
                ohw.astype(mm_dtype), x.astype(mm_dtype),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            if not fold_h:
                counts_ref[:, pl.ds(off, tile_k)] += jnp.sum(
                    ohw, axis=0, keepdims=True)
            return _
        k_tile_loop(one, np.int32(0))

    def phase1(x_ref, w_ref, c_ref, h_ref, labels_ref, mind2_ref):
        x = x_ref[:, :]
        best, mind2h = argmin_tiles(x, c_ref, h_ref)
        labels_ref[:, :] = best[:, None]
        if mind2_ref is not None:
            x2 = jnp.sum(x * x, axis=1) - x2_corr
            # Clamp: cancellation in the expanded form goes tiny-negative.
            mind2 = jnp.maximum(2.0 * mind2h + x2, 0.0)
            mind2_ref[:, :] = mind2[:, None]
        return best

    if not with_stats:
        # No weights ref: the assignment-only variant never reads w, and
        # a dead (n, 1) input still costs its HBM materialization + DMA.
        def kernel_assign(x_ref, c_ref, h_ref, labels_ref, mind2_ref):
            phase1(x_ref, None, c_ref, h_ref, labels_ref, mind2_ref)
        return kernel_assign

    # with_mind2=False removes the mind2 ref entirely: even an UNREAD
    # (n, 1) pallas output costs its HBM layout-conversion copy
    # (~1.6 ms/iter at 2M rows — XLA does not DCE custom-call outputs).
    def kernel_pipe(x_ref, w_ref, c_ref, h_ref, labels_ref, *refs):
        # Grid runs n_tiles + 1 steps; step i scatters tile i-1 (from the
        # ping-pong scratch) while tile i's distance matmul runs — the
        # two chains are independent, so Mosaic can overlap MXU and VPU.
        # NOTE: no SSE machinery in-kernel — an sse accumulator output
        # was measured at ~1 ms/iter at the GloVe shape (it chains the
        # grid steps); callers derive the SSE algebraically from
        # sums/counts instead (see parallel.distributed._sse_from_stats).
        mind2_ref = refs[0] if with_mind2 else None
        sums_ref, counts_ref = refs[-5:-3]
        xs, ws, bs = refs[-3:]
        i = pl.program_id(0)
        # np.int32 literals: under x64 interpret mode a python 2 would
        # promote the rem to int64, which lax.rem rejects against the
        # int32 program_id.
        slot = lax.rem(i, np.int32(2))
        prev = lax.rem(i + np.int32(1), np.int32(2))

        @pl.when(i == 0)
        def _():
            sums_ref[:, :] = jnp.zeros_like(sums_ref)
            counts_ref[:, :] = jnp.zeros_like(counts_ref)

        @pl.when(i > 0)
        def _():
            accum(bs[prev, :, 0], xs[prev], ws[prev, :, :], sums_ref,
                  counts_ref)

        @pl.when(i < n_tiles)
        def _():
            best = phase1(x_ref, w_ref, c_ref, h_ref, labels_ref,
                          mind2_ref)
            xs[slot] = x_ref[:, :]
            ws[slot, :, :] = w_ref[:, :]
            bs[slot, :, 0] = best

    return kernel_pipe


# Row multiple for pre-prepped inputs: every auto tile_n (power of two,
# <= 2048) divides it, so a once-per-fit prep_points satisfies any tiling.
PREP_ROW_MULTIPLE = 2048


def prep_points(points: jax.Array, weights: jax.Array):
    """Hoistable half of the kernel's input prep: pad rows to a
    PREP_ROW_MULTIPLE multiple (weights 0 there), pad D to the 128-lane
    boundary, and set the constant-1 fold column at lane ``d``.

    Returns ``(x, w, w_col)``: padded points, padded 1-D weights, and the
    (n_pad, 1) weight COLUMN in the kernel's input layout.  Calling this
    ONCE per fit (outside the training loop) instead of letting the
    kernel re-prep per pass is worth ~3 ms/iter at the GloVe-class shape
    for the pads and another ~1.6 ms/iter at 2M rows for the weight
    column's layout conversion — full-array HBM round trips XLA does not
    hoist out of the loop.  Pass ``w_col`` as the kernel's ``weights``
    argument (2-D weights are used as-is); the kernel detects prepped
    POINTS by ``points.shape[1] != centroids.shape[1]``.
    """
    n, d = points.shape
    f32 = jnp.float32
    x = points.astype(f32)
    w = weights.astype(f32)
    n_pad = _round_up(n, PREP_ROW_MULTIPLE)
    d_pad = _round_up(d, 128)
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        w = jnp.pad(w, (0, n_pad - n))
    if d_pad != d:
        x = jnp.pad(x, ((0, 0), (0, d_pad - d)))
        x = x.at[:, d].set(1.0)            # fold/counts column
    return x, w, w[:, None]


def _pad_inputs(points, weights, centroids, tile_n, tile_k):
    """Zero-pad x/w/c; build h (0.5*||c||^2 with +_PAD_H on pad rows);
    inject the fold columns when D leaves a free lane.  Accepts inputs
    already run through ``prep_points`` (detected by width mismatch
    against the centroid table) and skips the x-side work for them."""
    d = centroids.shape[1]
    k = centroids.shape[0]
    f32 = jnp.float32
    c = centroids.astype(f32)

    d_pad = _round_up(d, 128)
    fold_h = d < d_pad
    prepped = points.shape[1] != d
    if prepped and points.shape[1] != d_pad:
        raise ValueError(
            f"points width {points.shape[1]} matches neither the centroid "
            f"width {d} nor its 128-lane padding {d_pad}; pass raw points "
            f"or the output of prep_points")
    x = points.astype(f32)
    n = points.shape[0]
    n_pad = _round_up(n, tile_n)
    k_pad = _round_up(k, tile_k)
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        if weights is not None:
            pad_rows = [(0, n_pad - n)] + [(0, 0)] * (weights.ndim - 1)
            weights = jnp.pad(weights.astype(f32), pad_rows)
    if d_pad != d:
        c = jnp.pad(c, ((0, 0), (0, d_pad - d)))
        if not prepped:
            x = jnp.pad(x, ((0, 0), (0, d_pad - d)))
    if k_pad != k:
        c = jnp.pad(c, ((0, k_pad - k), (0, 0)))

    h = 0.5 * jnp.sum(c * c, axis=1)
    h = h + jnp.where(jnp.arange(k_pad) >= k, f32(_PAD_H), f32(0.0))
    if fold_h:
        if not prepped:
            x = x.at[:, d].set(1.0)        # ones column (also counts col)
        c = c.at[:, d].set(-h)             # MXU emits x@c.T - h directly
    # 2-D weights (from prep_points) are already the kernel-layout
    # column; reshaping (n,) -> (n, 1) here costs a full-array layout
    # conversion per call when not hoisted.  None (assignment-only
    # kernel) means no weights input at all.
    if weights is None:
        w = None
    elif weights.ndim == 2:
        w = weights.astype(f32)
    else:
        w = weights.astype(f32)[:, None]
    return x, w, c, h[None, :], d_pad, fold_h, n_pad, k_pad


def _specs(tile_n, tile_k, d_pad, k_pad, n_tiles, with_stats, pipelined,
           with_mind2=True):
    # Index maps return EXPLICIT int32 (np scalars — jax constants may
    # not be captured by index maps): under jax_enable_x64 a bare Python
    # 0 lowers as i64 and the mixed (i32, i64) index tuple breaks
    # Mosaic's grid lowering ("func.return (i32, i64)") — this was the
    # last x64 blocker once the toolchain fixed trivial-kernel x64
    # compilation (r2 VERDICT #5; re-tested 2026-07-30 on jax 0.9.0).
    zero = np.int32(0)
    # Pipelined grids run one flush step past the data; clamp the block
    # index so the final step re-maps the last tile (no write happens).
    if pipelined:
        def nmap(i):
            return (jnp.minimum(i, np.int32(n_tiles - 1)), zero)
    else:
        def nmap(i):
            return (i, zero)
    in_specs = [
        pl.BlockSpec((tile_n, d_pad), nmap, memory_space=pltpu.VMEM),
    ]
    if with_stats:      # the assign-only kernel never reads weights
        in_specs.append(
            pl.BlockSpec((tile_n, 1), nmap, memory_space=pltpu.VMEM))
    in_specs += [
        pl.BlockSpec((k_pad, d_pad), lambda i: (zero, zero),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, k_pad), lambda i: (zero, zero),
                     memory_space=pltpu.VMEM),
    ]
    out_specs = [
        pl.BlockSpec((tile_n, 1), nmap, memory_space=pltpu.VMEM),
    ]
    if with_mind2 or not with_stats:
        out_specs.append(
            pl.BlockSpec((tile_n, 1), nmap, memory_space=pltpu.VMEM))
    if with_stats:
        out_specs += [
            pl.BlockSpec((k_pad, d_pad), lambda i: (zero, zero),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (zero, zero),
                         memory_space=pltpu.VMEM),
        ]
    return in_specs, out_specs


def _vmem_estimate(tile_n, tile_k, d_pad, k_pad, pipelined):
    """Rough bytes of the dominant VMEM residents (intermediates + blocks)."""
    tiles = 2 * tile_n * tile_k * 4            # xc + ohw intermediates
    blocks = k_pad * d_pad * 4 * 2 + 2 * tile_n * d_pad * 4
    scratch = 2 * tile_n * (d_pad + 2) * 4 if pipelined else 0
    return tiles + blocks + scratch


def _call(points, weights, centroids, *, tile_n, tile_k, bf16, interpret,
          with_stats, with_mind2=True):
    n = points.shape[0]
    k, d = centroids.shape
    d_pad0 = _round_up(d, 128)
    k_pad0 = _round_up(k, 128)
    if tile_n is None or tile_k is None:
        auto_n, auto_k = choose_tiles(n, d_pad0, k_pad0, fold=d < d_pad0)
        tile_n = tile_n or auto_n
        tile_k = tile_k or auto_k
    tile_n = min(tile_n, _round_up(max(n, 8), 8))
    tile_k = min(tile_k, k_pad0)
    pipelined = with_stats

    x, w, c, h, d_pad, fold_h, n_pad, k_pad = _pad_inputs(
        points, weights, centroids, tile_n, tile_k)
    n_tiles = n_pad // tile_n
    k_tiles = k_pad // tile_k
    if _vmem_estimate(tile_n, tile_k, d_pad, k_pad,
                      pipelined) > _VMEM_LIMIT:
        raise NotImplementedError(
            f"Pallas kernel VMEM estimate exceeds {_VMEM_LIMIT >> 20} MB "
            f"at k={k}, D={d} (the full centroid block plus accumulators "
            f"must stay VMEM-resident); use distance_mode='matmul', which "
            f"streams centroid tiles from HBM")

    kernel = _build_kernel(
        n_tiles=n_tiles, k_tiles=k_tiles, tile_n=tile_n, tile_k=tile_k,
        d=d, d_pad=d_pad,
        mm_dtype=jnp.bfloat16 if bf16 else jnp.float32,
        fold_h=fold_h, with_stats=with_stats,
        with_mind2=with_mind2 or not with_stats)
    has_mind2 = with_mind2 or not with_stats
    in_specs, out_specs = _specs(tile_n, tile_k, d_pad, k_pad, n_tiles,
                                 with_stats, pipelined,
                                 with_mind2=has_mind2)
    out_shape = [jax.ShapeDtypeStruct((n_pad, 1), jnp.int32)]
    if has_mind2:
        out_shape.append(jax.ShapeDtypeStruct((n_pad, 1), jnp.float32))
    if with_stats:
        out_shape += [
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
        ]
    scratch = []
    if pipelined:
        scratch = [pltpu.VMEM((2, tile_n, d_pad), jnp.float32),
                   pltpu.VMEM((2, tile_n, 1), jnp.float32),
                   pltpu.VMEM((2, tile_n, 1), jnp.int32)]

    grid = (n_tiles + 1,) if pipelined else (n_tiles,)
    # CompilerParams was TPUCompilerParams before jax 0.6 — same fields.
    params_cls = getattr(pltpu, "CompilerParams", None) or \
        pltpu.TPUCompilerParams
    outs = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, scratch_shapes=scratch,
        compiler_params=params_cls(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(*((x, w, c, h) if with_stats else (x, c, h)))
    if not with_stats:
        labels, mind2 = outs
        return labels[:n, 0], mind2[:n, 0]
    if has_mind2:
        labels, mind2, sums, counts = outs
        mind2 = mind2[:n, 0]
    else:
        # No mind2 output AT ALL: even an unread (n, 1) output costs its
        # HBM layout-conversion copy (~1.6 ms/iter at 2M rows).  None
        # makes an accidental consumer fail loudly.
        (labels, sums, counts), mind2 = outs, None
    counts = sums[:, d] if fold_h else counts[0]
    return labels[:n, 0], mind2, sums[:k, :d], counts[:k]


@functools.partial(jax.jit,
                   static_argnames=("tile_n", "tile_k", "bf16", "interpret"))
def pallas_assign(points: jax.Array, centroids: jax.Array, *,
                  tile_n: Optional[int] = None,
                  tile_k: Optional[int] = None, bf16: bool = False,
                  interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Assignment-only variant: (labels (n,), mind2 (n,)) — no
    accumulation.  Used under centroid (model-axis) sharding, where the
    one-hot accumulation must wait for the GLOBAL argmin reconstructed
    across shards (r1 VERDICT #3); fusing it against the local block would
    accumulate points whose true winner lives in another shard's block."""
    return _call(points, None, centroids, tile_n=tile_n, tile_k=tile_k,
                 bf16=bf16, interpret=interpret, with_stats=False)


@functools.partial(jax.jit,
                   static_argnames=("tile_n", "tile_k", "bf16", "interpret",
                                    "with_mind2"))
def fused_assign_reduce(points: jax.Array, weights: jax.Array,
                        centroids: jax.Array, *,
                        tile_n: Optional[int] = None,
                        tile_k: Optional[int] = None, bf16: bool = False,
                        interpret: bool = False, with_mind2: bool = True
                        ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                   jax.Array]:
    """(labels (n,), mind2 (n,), sums (k, D), counts (k,)) in one kernel.

    Caller contract: ``points`` rows beyond the real data must carry
    ``weights == 0`` (their labels/mind2 outputs are garbage and must be
    masked by the caller, as ``assign_reduce`` padding does).  Internally
    pads D to the 128-lane boundary (zero columns change nothing) and k to
    a ``tile_k`` multiple with zero rows masked via ``h`` (never
    selected).  Callers needing the SSE without touching the per-point
    ``mind2`` output should derive it from sums/counts (see
    parallel.distributed._sse_from_stats).
    """
    return _call(points, weights, centroids, tile_n=tile_n, tile_k=tile_k,
                 bf16=bf16, interpret=interpret, with_stats=True,
                 with_mind2=with_mind2)
