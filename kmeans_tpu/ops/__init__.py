"""Compute kernels: pairwise distances, fused assign+reduce, SSE.

This package replaces the reference's L1 layer — the per-point NumPy closures
shipped to Spark executors (``kmeans_spark.py:147-159`` assign,
``kmeans_spark.py:224-235`` SSE, ``kmeans_spark.py:103-119`` farthest-point) —
with fully vectorized, jit-compiled TPU kernels that batch over points AND
centroids, feed the MXU via the matmul distance form, and fuse the SSE /
farthest-point statistics into the same data pass (the reference pays a second
full pass for SSE, ``kmeans_spark.py:237``).
"""

from kmeans_tpu.ops.assign import (
    StepStats,
    assign_chunk,
    assign_labels,
    assign_reduce,
    pairwise_sq_dists,
)

__all__ = [
    "StepStats",
    "assign_chunk",
    "assign_labels",
    "assign_reduce",
    "pairwise_sq_dists",
]
