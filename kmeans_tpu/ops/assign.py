"""Fused assignment + reduction kernels (the K-Means "hot loop") for TPU.

Reference behavior being reproduced (see ``/root/reference/kmeans_spark.py``):

* ``assign_partition`` (kmeans_spark.py:147-159): per point, distances to all
  centroids via ``np.linalg.norm(centroids - point, axis=1)`` then
  ``np.argmin`` — O(N*k*D) executed point-at-a-time from Python.
* ``reduceByKey(lambda a,b: (a[0]+b[0], a[1]+b[1]))`` (kmeans_spark.py:169-171):
  per-cluster sums of point vectors and counts.
* ``compute_partition_sse`` (kmeans_spark.py:224-235): a SECOND full pass
  accumulating ``min_distance**2``.
* ``find_farthest_point`` (kmeans_spark.py:103-119): max-over-points of the
  min-distance (used by the farthest-point empty-cluster policy).

TPU-first redesign: one pass, fully batched.  Squared distances use the
``||x||^2 + ||c||^2 - 2 x @ c.T`` matmul form so the O(N*k*D) FLOPs land on
the MXU; cluster sums use a one-hot (chunk,k) @ (chunk,D) matmul (again MXU)
instead of a shuffle; SSE and the farthest point are accumulated in the SAME
pass at ~zero marginal cost (the reference pays a second data pass,
kmeans_spark.py:237).  Points are processed in fixed-size chunks under
``lax.scan`` so the (chunk, k) distance tile stays small enough for VMEM-
friendly fusion at any N — no data-dependent shapes anywhere, everything
jit-compiles once.

Tie-breaking: ``jnp.argmin`` returns the lowest index on ties, matching
NumPy's rule used by the reference (kmeans_spark.py:156) — required for
trajectory-level sklearn parity (SURVEY.md §7 hard part b).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


class StepStats(NamedTuple):
    """Globally-reducible statistics of one assignment pass.

    This is the TPU-native replacement for everything the reference's driver
    collects per iteration: the ``reduceByKey`` output (sums + counts,
    kmeans_spark.py:169-173), the SSE scalar (kmeans_spark.py:237), and the
    farthest-point candidate (kmeans_spark.py:122-129).  Every field is a
    dense, fixed-shape array, so combining shards is a plain ``psum`` /
    ``all_gather`` instead of a keyed shuffle.
    """

    sums: jax.Array            # (k, D) per-cluster coordinate sums
    counts: jax.Array          # (k,)  per-cluster point counts
    sse: jax.Array             # ()    sum of min squared distances
    farthest_dist: jax.Array   # ()    max over points of min distance^2
    farthest_point: jax.Array  # (D,)  the point achieving farthest_dist
    sse_per_cluster: jax.Array  # (k,) per-cluster sum of min sq distances


def _accum_dtype(dtype) -> jnp.dtype:
    """Accumulate in at least float32 (float64 stays float64 under x64)."""
    return jnp.promote_types(dtype, jnp.float32)


def pairwise_sq_dists(x: jax.Array, centroids: jax.Array,
                      mode: str = "matmul",
                      precision=None) -> jax.Array:
    """Squared Euclidean distances, (n, k) for x:(n, D), centroids:(k, D).

    ``mode='matmul'`` uses the expanded form — one (n,D)@(D,k) matmul, the
    MXU-friendly shape (do NOT translate the reference's per-point
    ``norm(centroids - point)``, kmeans_spark.py:153).  ``mode='direct'``
    materializes (n,k,D) differences — numerically exact (no cancellation),
    used for small problems / parity testing.

    ``precision`` feeds the cross-term ``dot_general`` (matmul mode only).
    The default (TPU: bf16-rounded products) is right for ASSIGNMENT —
    only boundary ties can flip — but callers whose answer is the
    distance VALUE near zero (the kmeans|| D² fold: a covered point must
    read ~0, not |x||c|·2^-8) should pass ``lax.Precision.HIGHEST``.
    """
    acc = _accum_dtype(x.dtype)
    if mode == "direct":
        diff = x[:, None, :].astype(acc) - centroids[None, :, :].astype(acc)
        return jnp.sum(diff * diff, axis=-1)
    if mode == "matmul_bf16":
        # Cross-term in bfloat16 (2-4x MXU rate), norms + accumulation in
        # float32.  Distances carry ~2^-8 relative input-rounding error —
        # only boundary-tied assignments can flip; opt-in for throughput.
        mm = jnp.bfloat16
    elif mode == "matmul":
        mm = acc
    else:
        raise ValueError(f"unknown distance mode: {mode!r}")
    x = x.astype(acc)
    c = centroids.astype(acc)
    x2 = jnp.sum(x * x, axis=-1, keepdims=True)            # (n, 1)
    c2 = jnp.sum(c * c, axis=-1)[None, :]                  # (1, k)
    xc = jax.lax.dot_general(
        x.astype(mm), c.astype(mm), (((1,), (1,)), ((), ())),
        preferred_element_type=acc,
        precision=precision)                               # (n, k) on the MXU
    # Clamp: cancellation in the expanded form can produce tiny negatives.
    return jnp.maximum(x2 + c2 - 2.0 * xc, 0.0)


def assign_chunk(x: jax.Array, centroids: jax.Array, mode: str = "matmul",
                 need_min: bool = True):
    """Nearest centroid per point: (labels int32 (n,), min sq-dist (n,)).

    ``need_min=False`` skips the min-distance reduction (None returned) —
    the analogue of the reference's ``compute_sse=False`` fast path
    (kmeans_spark.py:34: SSE off avoids extra work per iteration)."""
    d2 = pairwise_sq_dists(x, centroids, mode=mode)
    best = jnp.argmin(d2, axis=1).astype(jnp.int32)   # lowest index on ties
    mind2 = jnp.min(d2, axis=1) if need_min else None
    return best, mind2


def _scan_chunks(points: jax.Array, weights: jax.Array, chunk_size: int):
    """Reshape (n, D) -> (n_chunks, chunk, D); n must be pre-padded."""
    n, d = points.shape
    if n % chunk_size != 0:
        raise ValueError(
            f"points length {n} not a multiple of chunk_size {chunk_size}; "
            "pad first (kmeans_tpu.parallel.sharding.pad_points)")
    n_chunks = n // chunk_size
    return (points.reshape(n_chunks, chunk_size, d),
            weights.reshape(n_chunks, chunk_size))


def init_stats(k: int, d: int, acc) -> StepStats:
    """Zeroed accumulator (farthest seeded at -1.0, kmeans_spark.py:106)."""
    return StepStats(
        sums=jnp.zeros((k, d), acc),
        counts=jnp.zeros((k,), acc),
        sse=jnp.zeros((), acc),
        farthest_dist=jnp.full((), -1.0, acc),
        farthest_point=jnp.zeros((d,), acc),
        sse_per_cluster=jnp.zeros((k,), acc),
    )


def accumulate_chunk(carry: StepStats, xc: jax.Array, wc: jax.Array,
                     centroids: jax.Array, *, mode: str = "matmul",
                     select_fn=None, need_sse: bool = True,
                     need_farthest: bool = True,
                     need_sse_pc: bool = True) -> StepStats:
    """Fold one (chunk, D) tile of points into the running StepStats.

    The single shared accumulation body for BOTH the single-device kernel
    (``assign_reduce``) and the SPMD step (parallel.distributed): distances
    on the MXU, one-hot matmul sums/counts (the dense replacement for the
    reference's keyed shuffle, kmeans_spark.py:169-171), fused SSE (the
    reference's second pass, :237) and fused farthest-point tracking (the
    dead ``_reinitialize_empty_cluster`` policy, :84-129, live and free).

    ``select_fn(best_local, mind2_local) -> (mine_mask, mind2_global)`` is
    the hook the centroid-sharded (model-axis) path uses to reconstruct the
    global argmin across shards; None means this device owns every centroid.

    The ``need_*`` flags skip the optional statistics' VPU work entirely
    (the corresponding StepStats fields stay at their init values) — the
    TPU analogue of the reference's ``compute_sse=False`` fast path
    (kmeans_spark.py:34).  With all three off and no select_fn, even the
    min-distance reduction over the (chunk, k) tile is elided.
    """
    acc = carry.sums.dtype
    k = centroids.shape[0]
    need_min = (need_sse or need_farthest or need_sse_pc
                or select_fn is not None)
    best, mind2 = assign_chunk(xc, centroids, mode=mode, need_min=need_min)
    if select_fn is None:
        mine = jnp.ones_like(wc)
        mind2_g = mind2
    else:
        mine, mind2_g = select_fn(best, mind2)
        mine = mine.astype(acc)
    onehot = (best[:, None] == jnp.arange(k, dtype=jnp.int32)[None, :])
    onehot = onehot.astype(acc) * (wc * mine)[:, None]     # (c, k), padded=0
    # bf16 mode also runs the scatter-sum matmul at bf16 input rate (one-hot
    # weights are exact in bf16; only the point coordinates get rounded).
    mm = jnp.bfloat16 if mode == "matmul_bf16" else acc
    sums = carry.sums + jax.lax.dot_general(
        onehot.astype(mm), xc.astype(mm), (((0,), (0,)), ((), ())),
        preferred_element_type=acc)                        # (k, D) on the MXU
    counts = carry.counts + jnp.sum(onehot, axis=0)
    sse = carry.sse + jnp.sum(mind2_g * wc) if need_sse else carry.sse
    # Per-cluster SSE: the same one-hot (already weight- and ownership-
    # scaled) contracted against the min distances — a (k, c) matvec, ~free
    # next to the two matmuls above.  Feeds BisectingKMeans' split criterion.
    sse_pc = carry.sse_per_cluster + jnp.einsum(
        "ck,c->k", onehot, mind2_g.astype(acc)) if need_sse_pc \
        else carry.sse_per_cluster
    if need_farthest:
        masked = jnp.where(wc > 0, mind2_g, -jnp.inf)
        i = jnp.argmax(masked)
        far_d, far_p = masked[i], xc[i].astype(acc)
        better = far_d > carry.farthest_dist
        far_d = jnp.where(better, far_d, carry.farthest_dist)
        far_p = jnp.where(better, far_p, carry.farthest_point)
    else:
        far_d, far_p = carry.farthest_dist, carry.farthest_point
    return StepStats(sums, counts, sse, far_d, far_p, sse_pc)


@functools.partial(jax.jit, static_argnames=("chunk_size", "mode"))
def assign_reduce(points: jax.Array, weights: jax.Array,
                  centroids: jax.Array, *, chunk_size: int,
                  mode: str = "matmul") -> StepStats:
    """One fused pass: assign every point, reduce all per-iteration stats.

    ``weights`` is 1.0 for real points and 0.0 for padding rows (padding keeps
    shapes static across shards/chunks); padded rows contribute nothing to any
    statistic.  See ``accumulate_chunk`` for the accumulation semantics.
    """
    k, d = centroids.shape
    acc = _accum_dtype(points.dtype)
    xs = _scan_chunks(points, weights.astype(acc), chunk_size)

    def body(carry, chunk):
        xc, wc = chunk
        return accumulate_chunk(carry, xc, wc, centroids, mode=mode), None

    stats, _ = lax.scan(body, init_stats(k, d, acc), xs)
    return stats


@functools.partial(jax.jit, static_argnames=("chunk_size", "mode"))
def assign_labels(points: jax.Array, centroids: jax.Array, *,
                  chunk_size: int, mode: str = "matmul") -> jax.Array:
    """Labels only — the kernel behind ``predict`` (kmeans_spark.py:343-348)."""
    n, d = points.shape
    pad = (-n) % chunk_size
    pts = jnp.pad(points, ((0, pad), (0, 0)))
    xs = pts.reshape(-1, chunk_size, d)
    labels = lax.map(lambda xc: assign_chunk(xc, centroids, mode=mode)[0], xs)
    return labels.reshape(-1)[:n]
