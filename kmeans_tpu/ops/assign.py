"""Fused assignment + reduction kernels (the K-Means "hot loop") for TPU.

Reference behavior being reproduced (see ``/root/reference/kmeans_spark.py``):

* ``assign_partition`` (kmeans_spark.py:147-159): per point, distances to all
  centroids via ``np.linalg.norm(centroids - point, axis=1)`` then
  ``np.argmin`` — O(N*k*D) executed point-at-a-time from Python.
* ``reduceByKey(lambda a,b: (a[0]+b[0], a[1]+b[1]))`` (kmeans_spark.py:169-171):
  per-cluster sums of point vectors and counts.
* ``compute_partition_sse`` (kmeans_spark.py:224-235): a SECOND full pass
  accumulating ``min_distance**2``.
* ``find_farthest_point`` (kmeans_spark.py:103-119): max-over-points of the
  min-distance (used by the farthest-point empty-cluster policy).

TPU-first redesign: one pass, fully batched.  Squared distances use the
``||x||^2 + ||c||^2 - 2 x @ c.T`` matmul form so the O(N*k*D) FLOPs land on
the MXU; cluster sums use a one-hot (chunk,k) @ (chunk,D) matmul (again MXU)
instead of a shuffle; SSE and the farthest point are accumulated in the SAME
pass at ~zero marginal cost (the reference pays a second data pass,
kmeans_spark.py:237).  Points are processed in fixed-size chunks under
``lax.scan`` so the (chunk, k) distance tile stays small enough for VMEM-
friendly fusion at any N — no data-dependent shapes anywhere, everything
jit-compiles once.

Tie-breaking: ``jnp.argmin`` returns the lowest index on ties, matching
NumPy's rule used by the reference (kmeans_spark.py:156) — required for
trajectory-level sklearn parity (SURVEY.md §7 hard part b).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


class StepStats(NamedTuple):
    """Globally-reducible statistics of one assignment pass.

    This is the TPU-native replacement for everything the reference's driver
    collects per iteration: the ``reduceByKey`` output (sums + counts,
    kmeans_spark.py:169-173), the SSE scalar (kmeans_spark.py:237), and the
    farthest-point candidate (kmeans_spark.py:122-129).  Every field is a
    dense, fixed-shape array, so combining shards is a plain ``psum`` /
    ``all_gather`` instead of a keyed shuffle.
    """

    sums: jax.Array            # (k, D) per-cluster coordinate sums
    counts: jax.Array          # (k,)  per-cluster point counts
    sse: jax.Array             # ()    sum of min squared distances
    farthest_dist: jax.Array   # ()    max over points of min distance^2
    farthest_point: jax.Array  # (D,)  the point achieving farthest_dist
    sse_per_cluster: jax.Array  # (k,) per-cluster sum of min sq distances


def _accum_dtype(dtype) -> jnp.dtype:
    """Accumulate in at least float32 (float64 stays float64 under x64)."""
    return jnp.promote_types(dtype, jnp.float32)


def pairwise_sq_dists(x: jax.Array, centroids: jax.Array,
                      mode: str = "matmul",
                      precision=None) -> jax.Array:
    """Squared Euclidean distances, (n, k) for x:(n, D), centroids:(k, D).

    ``mode='matmul'`` uses the expanded form — one (n,D)@(D,k) matmul, the
    MXU-friendly shape (do NOT translate the reference's per-point
    ``norm(centroids - point)``, kmeans_spark.py:153).  ``mode='direct'``
    materializes (n,k,D) differences — numerically exact (no cancellation),
    used for small problems / parity testing.

    ``precision`` feeds the cross-term ``dot_general`` (matmul mode only).
    The default (TPU: bf16-rounded products) is right for ASSIGNMENT —
    only boundary ties can flip — but callers whose answer is the
    distance VALUE near zero (the kmeans|| D² fold: a covered point must
    read ~0, not |x||c|·2^-8) should pass ``lax.Precision.HIGHEST``.
    """
    acc = _accum_dtype(x.dtype)
    if mode == "direct":
        diff = x[:, None, :].astype(acc) - centroids[None, :, :].astype(acc)
        return jnp.sum(diff * diff, axis=-1)
    if mode == "matmul_bf16":
        # Cross-term in bfloat16 (2-4x MXU rate), norms + accumulation in
        # float32.  Distances carry ~2^-8 relative input-rounding error —
        # only boundary-tied assignments can flip; opt-in for throughput.
        mm = jnp.bfloat16
    elif mode == "matmul":
        mm = acc
    else:
        raise ValueError(f"unknown distance mode: {mode!r}")
    x = x.astype(acc)
    c = centroids.astype(acc)
    x2 = jnp.sum(x * x, axis=-1, keepdims=True)            # (n, 1)
    c2 = jnp.sum(c * c, axis=-1)[None, :]                  # (1, k)
    xc = jax.lax.dot_general(
        x.astype(mm), c.astype(mm), (((1,), (1,)), ((), ())),
        preferred_element_type=acc,
        precision=precision)                               # (n, k) on the MXU
    # Clamp: cancellation in the expanded form can produce tiny negatives.
    return jnp.maximum(x2 + c2 - 2.0 * xc, 0.0)


def assign_chunk(x: jax.Array, centroids: jax.Array, mode: str = "matmul",
                 need_min: bool = True):
    """Nearest centroid per point: (labels int32 (n,), min sq-dist (n,)).

    ``need_min=False`` skips the min-distance reduction (None returned) —
    the analogue of the reference's ``compute_sse=False`` fast path
    (kmeans_spark.py:34: SSE off avoids extra work per iteration)."""
    d2 = pairwise_sq_dists(x, centroids, mode=mode)
    best = jnp.argmin(d2, axis=1).astype(jnp.int32)   # lowest index on ties
    mind2 = jnp.min(d2, axis=1) if need_min else None
    return best, mind2


# ------------------------------------------------- guarded bf16 rung
# Training twin of the serving bf16 fast path (ISSUE 8, reusing the
# ISSUE 6 near-tie machinery): the dominant (chunk, k) distance matmul
# runs with bf16 inputs, and a label is KEPT only when its argmin margin
# (second-best minus best distance) clears ``BF16_GUARD_RTOL`` of the
# row's distance scale ``|x|^2 + max_k |c_k|^2``.  bf16 inputs round at
# ~2^-8, so a distance DIFFERENCE carries ~2^-6 * scale of error and two
# distances can swap order only inside that band; the guard bound is
# that doubled (2^-5) — flagged rows re-resolve their argmin against a
# full-precision distance pass, which makes guarded labels bit-equal to
# the f32-class argmin BY CONSTRUCTION, not just on separated data.
# This constant is the canonical home of the bound; the serving engine's
# ``BF16_TIE_RTOL`` re-exports it (one error model, two call sites).
BF16_GUARD_RTOL = 2.0 ** -5

#: The training distance-mode string of the guarded rung.  It is NOT a
#: ``pairwise_sq_dists`` mode — the guard acts on the argmin, so it is
#: resolved at the chunk-consume level (``consume_chunk`` /
#: ``distance_stage``): the tile itself computes at 'matmul_bf16' rate.
GUARDED_MODE = "matmul_bf16_guarded"


def value_mode(mode: str) -> str:
    """The mode that computes a mode's distance-VALUE surface.  The
    guarded rung protects the ARGMIN; where distance values are the
    output (transform, score, packed multi-predict), its value surface
    IS the f32 class — the single shared rule every value-surface call
    site applies (distributed builders, kmeans.py transform, the
    serving engine's tmode map)."""
    return "matmul" if mode == GUARDED_MODE else mode


def margin_chunk(x: jax.Array, d2: jax.Array, c2max: jax.Array):
    """Per-row argmin safety data from a precomputed (n, k) distance
    tile: ``(best, margin, scale)`` with ``margin`` = second-best minus
    best distance and ``scale`` = ``|x|^2 + max_k |c_k|^2`` (the
    magnitude the bf16 cross-term error is relative to).  Shared by the
    serving margin pass (``distributed.make_assign_margin_fn``) and the
    training guard (``guarded_assign_chunk``) — one error model."""
    acc = jnp.promote_types(x.dtype, jnp.float32)
    k = d2.shape[1]
    best = jnp.argmin(d2, axis=1).astype(jnp.int32)
    d1 = jnp.min(d2, axis=1)
    masked = jnp.where(jax.nn.one_hot(best, k, dtype=bool),
                       jnp.asarray(jnp.inf, d2.dtype), d2)
    d2nd = jnp.min(masked, axis=1)
    scale = jnp.sum(x.astype(acc) ** 2, axis=1) + c2max
    return best, (d2nd - d1).astype(acc), scale


def guarded_assign_chunk(x: jax.Array, d2_bf16: jax.Array,
                         centroids: jax.Array, *,
                         tie_rtol: float = BF16_GUARD_RTOL,
                         real_mask=None, valid=None):
    """Guarded bf16-rate argmin over one chunk: ``(labels, n_corrected)``.

    ``d2_bf16`` is the chunk's 'matmul_bf16' distance tile.  Rows whose
    argmin margin is within ``tie_rtol`` of their distance scale are
    re-resolved by ONE full-precision ('matmul') distance pass over the
    chunk, executed under ``lax.cond`` — chunks without near-ties (the
    overwhelming majority on real data) never pay it.  The corrected
    count is the number of FLAGGED rows (the audit quantity the serving
    path also reports), not the (smaller) number of labels that actually
    flipped.

    ``real_mask`` (k,) excludes sentinel centroid rows from the distance
    scale: a 1e12 padding row (multi-fit k-sweep members) would blow
    ``max_k |c_k|^2`` up ~24 orders and flag EVERY row.  Sentinels never
    win best or second-best, so the margin itself needs no masking.
    ``valid`` (n,) excludes rows from the flag (zero-weight data
    padding): a pad row at the origin has ``d2_k ~= |c_k|^2`` and is a
    spurious near-tie whenever two centroid norms are close — it
    contributes nothing to any statistic, so it must not trigger the
    correction pass or inflate the audit."""
    acc = jnp.promote_types(x.dtype, jnp.float32)
    c2 = jnp.sum(centroids.astype(acc) ** 2, axis=1)
    if real_mask is not None:
        c2 = jnp.where(real_mask, c2, 0.0)
    c2max = jnp.max(c2)
    best, margin, scale = margin_chunk(x, d2_bf16, c2max)
    near = margin <= tie_rtol * scale
    if valid is not None:
        near = near & valid

    def fix():
        d2f = pairwise_sq_dists(x, centroids, mode="matmul")
        exact = jnp.argmin(d2f, axis=1).astype(jnp.int32)
        return jnp.where(near, exact, best)

    labels = lax.cond(jnp.any(near), fix, lambda: best)
    # dtype pinned: jnp.sum would promote to int64 under x64, breaking
    # the fixed-width audit carry in the device loops.
    return labels, jnp.sum(near, dtype=jnp.int32)


def _winner_sq_dists(x: jax.Array, centroids: jax.Array,
                     best: jax.Array, acc) -> jax.Array:
    """Full-precision squared distance of each row to its (already
    decided) winner: the same ``|x|^2 + |c|^2 - 2<x,c>`` clamped form as
    the 'matmul' tile, at 1/k of its FLOPs (one row-dot per point
    instead of k).  The VALUE equals the f32-class ``min(d2)`` up to the
    dot's reduction order (~1 ulp relative, measured) — which is why the
    guarded rung's SSE/per-cluster-SSE land in the repo's existing
    rtol-compared class (r10: "SSE history is a deliberate reduced
    quantity, rtol-compared") while labels/sums/counts stay bitwise."""
    xa = x.astype(acc)
    cb = centroids.astype(acc)[best]                     # (n, D) gather
    x2 = jnp.sum(xa * xa, axis=-1)
    c2 = jnp.sum(cb * cb, axis=-1)
    xcb = jnp.einsum("nd,nd->n", xa, cb,
                     preferred_element_type=acc)
    return jnp.maximum(x2 + c2 - 2.0 * xcb, 0.0)


def _scan_chunks(points: jax.Array, weights: jax.Array, chunk_size: int):
    """Reshape (n, D) -> (n_chunks, chunk, D); n must be pre-padded."""
    n, d = points.shape
    if n % chunk_size != 0:
        raise ValueError(
            f"points length {n} not a multiple of chunk_size {chunk_size}; "
            "pad first (kmeans_tpu.parallel.sharding.pad_points)")
    n_chunks = n // chunk_size
    return (points.reshape(n_chunks, chunk_size, d),
            weights.reshape(n_chunks, chunk_size))


def init_stats(k: int, d: int, acc) -> StepStats:
    """Zeroed accumulator (farthest seeded at -1.0, kmeans_spark.py:106)."""
    return StepStats(
        sums=jnp.zeros((k, d), acc),
        counts=jnp.zeros((k,), acc),
        sse=jnp.zeros((), acc),
        farthest_dist=jnp.full((), -1.0, acc),
        farthest_point=jnp.zeros((d,), acc),
        sse_per_cluster=jnp.zeros((k,), acc),
    )


def distance_stage(xc: jax.Array, centroids: jax.Array, *,
                   mode: str = "matmul") -> jax.Array:
    """Stage A of the two-stage Lloyd chunk schedule: the (chunk, k)
    distance tile — the MXU matmul that dominates the pass.  The guarded
    rung's tile computes at 'matmul_bf16' rate (its guard acts later, in
    stage B).  Splitting the tile from its consumption is what lets the
    software-pipelined schedule (ISSUE 8, the r8 ``_chunked_epass``
    discipline) overlap chunk i's matmul with chunk i-1's argmin +
    one-hot scatter epilogue."""
    dmode = "matmul_bf16" if mode == GUARDED_MODE else mode
    return pairwise_sq_dists(xc, centroids, mode=dmode)


def consume_chunk(carry: StepStats, d2: jax.Array, xc: jax.Array,
                  wc: jax.Array, centroids: jax.Array, *,
                  mode: str = "matmul", select_fn=None, real_mask=None,
                  need_sse: bool = True, need_farthest: bool = True,
                  need_sse_pc: bool = True):
    """Stage B of the two-stage chunk schedule: fold one (chunk, D) tile
    of points — whose distance tile ``d2`` stage A already computed —
    into the running StepStats.  Returns ``(StepStats, n_corrected)``
    where ``n_corrected`` is the chunk's bf16-guard-flagged row count
    (constant 0 for every unguarded mode).

    This is the single shared accumulation body for BOTH the
    single-device kernel (``assign_reduce``) and the SPMD step
    (parallel.distributed): argmin over the tile, one-hot matmul
    sums/counts (the dense replacement for the reference's keyed
    shuffle, kmeans_spark.py:169-171), fused SSE (the reference's second
    pass, :237) and fused farthest-point tracking (the dead
    ``_reinitialize_empty_cluster`` policy, :84-129, live and free).

    ``select_fn(best_local, mind2_local) -> (mine_mask, mind2_global)`` is
    the hook the centroid-sharded (model-axis) path uses to reconstruct the
    global argmin across shards; None means this device owns every centroid.

    The ``need_*`` flags skip the optional statistics' VPU work entirely
    (the corresponding StepStats fields stay at their init values) — the
    TPU analogue of the reference's ``compute_sse=False`` fast path
    (kmeans_spark.py:34).

    Guarded rung semantics (``mode='matmul_bf16_guarded'``): labels come
    from ``guarded_assign_chunk`` (bit-equal to the f32 argmin by
    construction), the one-hot scatter runs at FULL accumulation
    precision — so sums/counts/centroids are bit-equal to the 'matmul'
    class — and the optional min-distance statistics read the winner's
    full-precision distance (``_winner_sq_dists``, the rtol class).  The
    farthest-point policy is value-dependent on the min distance and is
    rejected upstream (parallel.distributed builders).  ``real_mask``
    (k,) marks real (non-sentinel) centroid rows for the guard's
    distance scale (``guarded_assign_chunk``); zero-weight rows are
    excluded from the guard automatically.
    """
    acc = carry.sums.dtype
    k = centroids.shape[0]
    need_min = (need_sse or need_farthest or need_sse_pc
                or select_fn is not None)
    corrected = jnp.zeros((), jnp.int32)
    if mode == GUARDED_MODE:
        # Zero-weight rows (data padding) contribute to no statistic —
        # keep them out of the flag and the audit; sentinel centroid
        # rows (real_mask) out of the distance scale.
        best, corrected = guarded_assign_chunk(
            xc, d2, centroids, real_mask=real_mask, valid=wc > 0)
        mind2 = _winner_sq_dists(xc, centroids, best, acc) \
            if need_min else None
    else:
        best = jnp.argmin(d2, axis=1).astype(jnp.int32)  # lowest-index ties
        mind2 = jnp.min(d2, axis=1) if need_min else None
    if select_fn is None:
        mine = jnp.ones_like(wc)
        mind2_g = mind2
    else:
        mine, mind2_g = select_fn(best, mind2)
        mine = mine.astype(acc)
    onehot = (best[:, None] == jnp.arange(k, dtype=jnp.int32)[None, :])
    onehot = onehot.astype(acc) * (wc * mine)[:, None]     # (c, k), padded=0
    # bf16 mode also runs the scatter-sum matmul at bf16 input rate (one-hot
    # weights are exact in bf16; only the point coordinates get rounded).
    # The GUARDED rung keeps the scatter at acc precision: its contract is
    # sums bit-equal to the f32 class, and the distance matmul (k times
    # this one's row count of useful work) is where the rate lives.
    mm = jnp.bfloat16 if mode == "matmul_bf16" else acc
    sums = carry.sums + jax.lax.dot_general(
        onehot.astype(mm), xc.astype(mm), (((0,), (0,)), ((), ())),
        preferred_element_type=acc)                        # (k, D) on the MXU
    counts = carry.counts + jnp.sum(onehot, axis=0)
    sse = carry.sse + jnp.sum(mind2_g * wc) if need_sse else carry.sse
    # Per-cluster SSE: the same one-hot (already weight- and ownership-
    # scaled) contracted against the min distances — a (k, c) matvec, ~free
    # next to the two matmuls above.  Feeds BisectingKMeans' split criterion.
    sse_pc = carry.sse_per_cluster + jnp.einsum(
        "ck,c->k", onehot, mind2_g.astype(acc)) if need_sse_pc \
        else carry.sse_per_cluster
    if need_farthest:
        masked = jnp.where(wc > 0, mind2_g, -jnp.inf)
        i = jnp.argmax(masked)
        far_d, far_p = masked[i], xc[i].astype(acc)
        better = far_d > carry.farthest_dist
        far_d = jnp.where(better, far_d, carry.farthest_dist)
        far_p = jnp.where(better, far_p, carry.farthest_point)
    else:
        far_d, far_p = carry.farthest_dist, carry.farthest_point
    return StepStats(sums, counts, sse, far_d, far_p, sse_pc), corrected


def accumulate_chunk(carry: StepStats, xc: jax.Array, wc: jax.Array,
                     centroids: jax.Array, *, mode: str = "matmul",
                     select_fn=None, need_sse: bool = True,
                     need_farthest: bool = True,
                     need_sse_pc: bool = True) -> StepStats:
    """Serial stage A + stage B fold of one chunk (the pre-ISSUE-8 body,
    arithmetic unchanged: ``consume_chunk(distance_stage(...))`` with the
    guard-audit count dropped).  Callers that schedule the stages
    themselves (the pipelined scan bodies) or consume the guard audit
    use the stage pair directly."""
    d2 = distance_stage(xc, centroids, mode=mode)
    return consume_chunk(carry, d2, xc, wc, centroids, mode=mode,
                         select_fn=select_fn, need_sse=need_sse,
                         need_farthest=need_farthest,
                         need_sse_pc=need_sse_pc)[0]


@functools.partial(jax.jit, static_argnames=("chunk_size", "mode"))
def assign_reduce(points: jax.Array, weights: jax.Array,
                  centroids: jax.Array, *, chunk_size: int,
                  mode: str = "matmul") -> StepStats:
    """One fused pass: assign every point, reduce all per-iteration stats.

    ``weights`` is 1.0 for real points and 0.0 for padding rows (padding keeps
    shapes static across shards/chunks); padded rows contribute nothing to any
    statistic.  See ``accumulate_chunk`` for the accumulation semantics.
    """
    k, d = centroids.shape
    acc = _accum_dtype(points.dtype)
    xs = _scan_chunks(points, weights.astype(acc), chunk_size)

    def body(carry, chunk):
        xc, wc = chunk
        return accumulate_chunk(carry, xc, wc, centroids, mode=mode), None

    stats, _ = lax.scan(body, init_stats(k, d, acc), xs)
    return stats


@functools.partial(jax.jit, static_argnames=("chunk_size", "mode"))
def assign_labels(points: jax.Array, centroids: jax.Array, *,
                  chunk_size: int, mode: str = "matmul") -> jax.Array:
    """Labels only — the kernel behind ``predict`` (kmeans_spark.py:343-348)."""
    n, d = points.shape
    pad = (-n) % chunk_size
    pts = jnp.pad(points, ((0, pad), (0, 0)))
    xs = pts.reshape(-1, chunk_size, d)
    labels = lax.map(lambda xc: assign_chunk(xc, centroids, mode=mode)[0], xs)
    return labels.reshape(-1)[:n]
