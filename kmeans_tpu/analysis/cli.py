"""``python -m kmeans_tpu lint [--json] [paths...]`` — run the
invariant linter (ISSUE 10).

Exit codes: 0 clean, 2 on findings or a malformed path.  ``--json``
prints the machine-readable report (findings + rule counts + the full
suppression inventory, so suppression-count regressions are reviewable
in CI diffs).  Default target: the installed ``kmeans_tpu`` package
directory.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def _default_target() -> str:
    import kmeans_tpu
    return str(Path(kmeans_tpu.__file__).parent)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu lint",
        description="AST invariant linter: trace/cache/dispatch/thread "
                    "discipline over the package (one rule per "
                    "historical incident class; see docs/ANALYSIS.md)")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint (default: "
                             "the kmeans_tpu package)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="RULE-ID",
                        help="run only this rule (repeatable)")
    args = parser.parse_args(argv)

    from kmeans_tpu.analysis import RULES, lint_paths
    if args.rule:
        unknown = [r for r in args.rule if r not in RULES]
        if unknown:
            print(f"error: unknown rule id(s) {unknown}; known: "
                  f"{sorted(RULES)}", file=sys.stderr)
            return 2
    paths = args.paths or [_default_target()]
    try:
        report = lint_paths(paths, rules=args.rule)
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except SyntaxError as e:
        print(f"error: cannot parse {e.filename}:{e.lineno}: {e.msg}",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(report.to_json(), indent=2, default=str))
    else:
        for f in report.findings:
            print(f.format())
        active = sum(1 for s in report.suppressions if s.used)
        print(f"lint: {len(report.findings)} finding"
              f"{'' if len(report.findings) == 1 else 's'} over "
              f"{report.files} files ({report.suppressed} suppressed "
              f"by {active} of {len(report.suppressions)} "
              f"suppressions)",
              file=sys.stderr if report.findings else sys.stdout)
    return 2 if report.findings else 0


if __name__ == "__main__":       # pragma: no cover
    sys.exit(main())
