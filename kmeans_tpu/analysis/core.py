"""Linter infrastructure: source loading, suppressions, the runner.

Pure stdlib — ``ast`` + ``tokenize`` only.  The linter inspects every
module in the package (including the accelerator paths) WITHOUT
importing any of them — checked code is never executed — so nothing in
this module may depend on jax/numpy.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional

# Suppression comment grammar — see the package docstring.  The reason
# separator accepts an em-dash, en-dash, or plain hyphen.
_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*ok\(\s*(?P<rules>[^)]*)\s*\)\s*"
    r"(?:[—–-]+\s*(?P<reason>.*\S))?\s*$")
# Anything that *tries* to be a suppression — used to catch malformed
# forms (a missing rule list or reason) as findings instead of silently
# ignoring them.
_SUPPRESS_ATTEMPT_RE = re.compile(r"#\s*lint:\s*ok\b")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str           # repo-relative (or as-given) posix path
    line: int
    message: str
    incident: str = ""  # one-line historical-incident citation

    def format(self) -> str:
        cite = f"  [{self.incident}]" if self.incident else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{cite}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "incident": self.incident}


@dataclass
class Suppression:
    """One parsed ``# lint: ok(...)`` comment."""

    path: str
    line: int
    rules: tuple
    reason: str
    used: int = 0       # findings this suppression absorbed

    def to_json(self) -> dict:
        return {"path": self.path, "line": self.line,
                "rules": list(self.rules), "reason": self.reason,
                "used": self.used}


class Module:
    """One parsed source file: AST + raw lines + suppression table."""

    def __init__(self, path: Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=str(path))
        # line -> Suppression; plus the malformed attempts for the
        # ``suppression`` rule.
        self.suppressions: Dict[int, Suppression] = {}
        self.malformed_suppressions: List[tuple] = []   # (line, comment)
        self._scan_comments()
        self._parents: Optional[dict] = None

    # -------------------------------------------------------- comments
    def _scan_comments(self) -> None:
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.text).readline))
        except (tokenize.TokenError, IndentationError):     # pragma: no cover
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            comment = tok.string
            if not _SUPPRESS_ATTEMPT_RE.search(comment):
                continue
            m = _SUPPRESS_RE.search(comment)
            line = tok.start[0]
            if m is None:
                self.malformed_suppressions.append((line, comment.strip()))
                continue
            rules = tuple(r.strip() for r in m.group("rules").split(",")
                          if r.strip())
            reason = (m.group("reason") or "").strip()
            if not rules or not reason:
                self.malformed_suppressions.append((line, comment.strip()))
                continue
            self.suppressions[line] = Suppression(
                path=self.rel, line=line, rules=rules, reason=reason)

    def suppression_for(self, rule: str, line: int) -> Optional[Suppression]:
        """The suppression covering ``rule`` at ``line``: on the line
        itself, or on a directly preceding standalone-comment line."""
        sup = self.suppressions.get(line)
        if sup is not None and rule in sup.rules:
            return sup
        # Walk up over a contiguous run of comment-only lines.
        probe = line - 1
        while probe >= 1 and self._is_comment_only(probe):
            sup = self.suppressions.get(probe)
            if sup is not None and rule in sup.rules:
                return sup
            probe -= 1
        return None

    def _is_comment_only(self, line: int) -> bool:
        if line > len(self.lines):
            return False
        stripped = self.lines[line - 1].strip()
        return stripped.startswith("#")

    # ------------------------------------------------------------- ast
    def parents(self) -> dict:
        """child node -> parent node map (built lazily, cached)."""
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def enclosing(self, node: ast.AST, kinds) -> Optional[ast.AST]:
        """Nearest ancestor of ``node`` whose type is in ``kinds``."""
        parents = self.parents()
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                return cur
            cur = parents.get(cur)
        return None

    def module_scope_names(self) -> set:
        """Names bound at module top level (imports, defs, classes,
        constants) — the 'static environment' a closure may freely use
        without it being a cache knob.  Import-bound names ANYWHERE in
        the module count too: a function-local ``import ... as dist``
        is still a static module reference, never a knob."""
        names = set()
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                names.add(node.name)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    names.add((alias.asname or alias.name).split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    names.add(alias.asname or alias.name)
        return names


class Package:
    """All modules under the linted paths, plus cross-module indexes."""

    def __init__(self, modules: List[Module]):
        self.modules = modules

    def __iter__(self) -> Iterable[Module]:
        return iter(self.modules)


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    suppressions: List[Suppression] = field(default_factory=list)
    files: int = 0
    suppressed: int = 0

    @property
    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "files": self.files,
            "findings": [f.to_json() for f in self.findings],
            "counts": self.counts,
            "suppressed": self.suppressed,
            "suppressions": [s.to_json() for s in self.suppressions],
        }


def _collect_files(paths: Iterable) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        p = Path(p)
        if not p.exists():
            raise FileNotFoundError(f"no such file or directory: {p}")
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
        else:
            raise ValueError(f"not a .py file or directory: {p}")
    # De-duplicate while preserving order (overlapping path args).
    seen = set()
    out = []
    for f in files:
        key = f.resolve()
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def load_package(paths: Iterable, root: Optional[Path] = None) -> Package:
    """Parse every ``.py`` under ``paths`` into a :class:`Package`.

    Raises ``FileNotFoundError``/``ValueError`` for malformed paths and
    ``SyntaxError`` for unparseable sources — path problems are CLI
    errors (exit 2 with a message), not findings.
    """
    files = _collect_files(paths)
    modules = []
    for f in files:
        try:
            rel = str(f.resolve().relative_to(
                Path(root).resolve() if root else Path.cwd()))
        except ValueError:
            rel = str(f)
        modules.append(Module(f, rel, f.read_text()))
    return Package(modules)


def lint_paths(paths: Iterable, rules: Optional[Iterable[str]] = None,
               root: Optional[Path] = None) -> Report:
    """Run the rule registry over ``paths``; returns the full report
    with suppressions applied (and counted)."""
    from kmeans_tpu.analysis.rules import RULES

    pkg = load_package(paths, root=root)
    active = [RULES[r] for r in rules] if rules is not None \
        else list(RULES.values())
    report = Report(files=len(pkg.modules))
    for rule in active:
        for finding in rule.run(pkg):
            mod = next((m for m in pkg if m.rel == finding.path), None)
            sup = mod.suppression_for(finding.rule, finding.line) \
                if mod is not None else None
            if sup is not None:
                sup.used += 1
                report.suppressed += 1
            else:
                report.findings.append(finding)
    for mod in pkg:
        report.suppressions.extend(mod.suppressions.values())
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return report
