"""The rule registry: one rule per hand-enforced incident class.

Each rule is an ``ast``-level check with an ``id``, a one-line
``incident`` citation (the historical review finding it mechanizes),
and a ``run(package) -> [Finding]``.  Rules never import the modules
they inspect.
"""

from __future__ import annotations

import ast
import builtins
from typing import Dict, Iterator, List, Optional, Set

from kmeans_tpu.analysis.core import Finding, Module, Package

_BUILTINS = set(dir(builtins))


# ------------------------------------------------------------ helpers

def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain rooted at a Name, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _value_paths(node: ast.AST) -> Set[str]:
    """Every maximal Name/Attribute dotted path loaded anywhere inside
    ``node`` (including within calls/subscripts)."""
    paths: Set[str] = set()

    class V(ast.NodeVisitor):
        def visit_Attribute(self, n):
            p = dotted(n)
            if p is not None:
                paths.add(p)
            else:
                self.generic_visit(n)

        def visit_Name(self, n):
            paths.add(n.id)

    V().visit(node)
    return paths


def _bound_in(node: ast.AST) -> Set[str]:
    """Names bound inside ``node``: lambda/def params, comprehension
    targets, assignments, with/except/for targets."""
    bound: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, (ast.Lambda, ast.FunctionDef,
                          ast.AsyncFunctionDef)):
            a = n.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs
                        + ([a.vararg] if a.vararg else [])
                        + ([a.kwarg] if a.kwarg else [])):
                bound.add(arg.arg)
        elif isinstance(n, ast.comprehension):
            for t in ast.walk(n.target):
                if isinstance(t, ast.Name):
                    bound.add(t.id)
        elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            bound.add(n.id)
        elif isinstance(n, ast.ExceptHandler) and n.name:
            bound.add(n.name)
    return bound


def _func_params(fn) -> Set[str]:
    a = fn.args
    return {arg.arg for arg in (a.posonlyargs + a.args + a.kwonlyargs
                                + ([a.vararg] if a.vararg else [])
                                + ([a.kwarg] if a.kwarg else []))}


class Rule:
    id: str = ""
    incident: str = ""

    def run(self, pkg: Package) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, mod: Module, line: int, message: str) -> Finding:
        return Finding(rule=self.id, path=mod.rel, line=line,
                       message=message, incident=self.incident)


# ------------------------------------------------------- trace-hazard

#: lax control-flow entry points -> positions of the traced callables.
_TRACED_ARGS = {
    "scan": (0,), "while_loop": (0, 1), "fori_loop": (2,),
    "cond": (1, 2), "associative_scan": (0,), "switch": None,  # 1.. all
}
#: Host-cast calls that force a traced value to Python (a trace-time
#: error at best, a silent constant-fold at worst).
_HOST_CASTS = {"float", "int", "bool"}
_HOST_FUNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
               "onp.asarray", "onp.array", "jax.device_get"}


class TraceHazardRule(Rule):
    """Host-Python operations inside functions handed to ``lax.scan`` /
    ``while_loop`` / ``fori_loop`` / ``cond`` in the compiled layers
    (``parallel/``, ``ops/``): ``float()/int()/bool()`` casts,
    ``.item()``, ``np.asarray``, Python ``while``, and ``if`` branches
    whose test reads the traced function's own parameters (the carry /
    chunk — always tracers inside the compiled body)."""

    id = "trace-hazard"
    incident = ("would recompile or fail under trace — the class the "
                "host_loop=False device loops exist to forbid")

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            p = mod.rel.replace("\\", "/")
            if "/parallel/" not in p and "/ops/" not in p:
                continue
            yield from self._check_module(mod)

    def _traced_functions(self, mod: Module):
        """(FunctionDef|Lambda) nodes passed to lax control flow."""
        names: Set[str] = set()
        lambdas: List[ast.Lambda] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            path = dotted(node.func)
            if path is None:
                continue
            leaf = path.split(".")[-1]
            if leaf not in _TRACED_ARGS:
                continue
            root = path.split(".")[0]
            if root not in ("lax", "jax") and "lax" not in path:
                continue
            positions = _TRACED_ARGS[leaf]
            args = node.args if positions is None \
                else [node.args[i] for i in positions if i < len(node.args)]
            for a in args:
                if isinstance(a, ast.Name):
                    names.add(a.id)
                elif isinstance(a, ast.Lambda):
                    lambdas.append(a)
        fns = [n for n in ast.walk(mod.tree)
               if isinstance(n, ast.FunctionDef) and n.name in names]
        return fns, lambdas

    def _check_module(self, mod: Module) -> Iterator[Finding]:
        fns, lambdas = self._traced_functions(mod)
        for fn in fns:
            yield from self._check_body(mod, fn, fn.body, _func_params(fn))
        for lam in lambdas:
            yield from self._check_body(mod, lam, [lam.body],
                                        _func_params(lam))

    def _check_body(self, mod: Module, fn, body, params: Set[str]
                    ) -> Iterator[Finding]:
        """Scoped walk: a nested def/lambda inside a traced body is
        traced too, so its params join the set — but only FOR ITS OWN
        SUBTREE (a sibling's ``c`` must not taint the outer scope)."""
        for stmt in body:
            yield from self._check_node(mod, fn, stmt, params)

    def _check_node(self, mod: Module, fn, node: ast.AST,
                    params: Set[str]) -> Iterator[Finding]:
        if isinstance(node, ast.FunctionDef):
            inner = params | _func_params(node)
            for child in node.body:
                yield from self._check_node(mod, fn, child, inner)
            return
        if isinstance(node, ast.Lambda):
            yield from self._check_node(
                mod, fn, node.body, params | _func_params(node))
            return
        yield from self._flag_node(mod, fn, node, params)
        for child in ast.iter_child_nodes(node):
            yield from self._check_node(mod, fn, child, params)

    def _flag_node(self, mod: Module, fn, node: ast.AST,
                   params: Set[str]) -> Iterator[Finding]:
        if isinstance(node, ast.Call):
            path = dotted(node.func)
            if path in _HOST_CASTS and node.args and not (
                    isinstance(node.args[0], ast.Constant)
                    or self._is_static(node.args[0])):
                yield self.finding(
                    mod, node.lineno,
                    f"host cast {path}() on a value inside a "
                    f"traced {type(fn).__name__} body")
            elif path in _HOST_FUNCS:
                yield self.finding(
                    mod, node.lineno,
                    f"{path}() materializes a traced value to "
                    f"host inside a compiled loop body")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" \
                    and not node.args:
                yield self.finding(
                    mod, node.lineno,
                    ".item() forces a host sync inside a "
                    "traced loop body")
        elif isinstance(node, ast.While):
            yield self.finding(
                mod, node.lineno,
                "Python while-loop inside a traced body (the "
                "trip count must be lax control flow)")
        elif isinstance(node, (ast.If, ast.IfExp)):
            tainted = sorted(_value_paths(node.test) & params)
            if tainted:
                yield self.finding(
                    mod, node.lineno,
                    f"Python branch on traced parameter"
                    f" {', '.join(tainted)!s} inside a traced "
                    f"body (use lax.cond/jnp.where)")

    @staticmethod
    def _is_static(node: ast.AST) -> bool:
        """Casts of shapes and lengths are static at trace time."""
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) and n.attr in ("shape",
                                                           "ndim", "size"):
                return True
            if isinstance(n, ast.Call) and dotted(n.func) == "len":
                return True
        return False


# ---------------------------------------------------------- cache-key

class CacheKeyRule(Rule):
    """Every ``*_CACHE.get_or_create(key, factory)`` call: each free
    variable the factory closes over (a local knob of the enclosing
    function — not a module global) must appear in the key tuple, else
    two distinct knob values collide on one cache entry (wrong program
    served) or salt-free twins duplicate-compile."""

    id = "cache-key"
    incident = ("r13 duplicate-compile class: predict_fn cached "
                "pipeline-free; serving score_rows key missing "
                "value_mode")

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            module_names = mod.module_scope_names()
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "get_or_create"):
                    continue
                base = dotted(node.func.value) or ""
                if not base.split(".")[-1].endswith("_CACHE"):
                    continue
                if len(node.args) < 2:
                    continue
                yield from self._check_site(mod, node, module_names)

    def _check_site(self, mod: Module, call: ast.Call,
                    module_names: Set[str]) -> Iterator[Finding]:
        key_expr = self._resolve_key(mod, call, call.args[0])
        if key_expr is None:
            yield self.finding(
                mod, call.lineno,
                "cache key is not a tuple literal resolvable in this "
                "function — the key/knob audit cannot run")
            return
        key_paths = _value_paths(key_expr)
        factory = call.args[1]
        if isinstance(factory, ast.Lambda):
            body, bound = factory.body, _bound_in(factory)
        else:
            body, bound = factory, _bound_in(factory)
        knobs = self._free_knobs(body, bound, module_names)
        missing = sorted(k for k in knobs
                         if not self._covered(k, key_paths))
        if missing:
            yield self.finding(
                mod, call.lineno,
                f"factory closes over {', '.join(missing)} but the "
                f"cache key does not include "
                f"{'it' if len(missing) == 1 else 'them'} — distinct "
                f"values would collide on one compiled entry")

    @staticmethod
    def _resolve_key(mod: Module, call: ast.Call,
                     key: ast.AST) -> Optional[ast.AST]:
        """A tuple/constant key is used directly; a ``key`` variable is
        chased to its nearest preceding tuple assignment in the same
        function."""
        if isinstance(key, (ast.Tuple, ast.Constant)):
            return key
        if not isinstance(key, ast.Name):
            return None
        fn = mod.enclosing(call, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda))
        if fn is None or isinstance(fn, ast.Lambda):
            return None
        best = None
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == key.id
                    and node.lineno <= call.lineno):
                if best is None or node.lineno > best.lineno:
                    best = node
        if best is not None and isinstance(best.value, (ast.Tuple,
                                                        ast.Constant)):
            return best.value
        return None

    @staticmethod
    def _free_knobs(body: ast.AST, bound: Set[str],
                    module_names: Set[str]) -> Set[str]:
        """Dotted paths in the factory whose root is neither bound in
        the factory, a module-scope name, nor a builtin — i.e. the
        closure's captured locals: the knobs."""
        knobs: Set[str] = set()
        for path in _value_paths(body):
            root = path.split(".")[0]
            if root in bound or root in module_names \
                    or root in _BUILTINS:
                continue
            knobs.add(path)
        return knobs

    @staticmethod
    def _covered(knob: str, key_paths: Set[str]) -> bool:
        """A knob is covered when the key carries it or any prefix of
        it (keying on ``self.mesh`` covers ``self.mesh.devices``)."""
        parts = knob.split(".")
        return any(".".join(parts[:i]) in key_paths
                   for i in range(1, len(parts) + 1))


# ----------------------------------------------------------- dispatch

class DispatchAccountingRule(Rule):
    """In ``serving/`` and ``parallel/``: a function that *calls* a
    compiled function (obtained from a ``*_CACHE.get_or_create`` /
    ``_get_step_fns`` / ``_get_fns`` / ``_predict_fn``) must account
    the dispatch — ``note_dispatch(...)``, ``._record(...)``, or a
    ``dispatches`` counter update — so dispatch-count pins and serving
    stats stay honest as call sites are added."""

    id = "dispatch"
    incident = ("the O(1)-dispatch pins (ISSUE 2/7) and serving stats "
                "only hold if every compiled call site is tagged")

    _SOURCES = {"get_or_create", "_get_step_fns", "_get_fns",
                "_predict_fn"}

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            p = mod.rel.replace("\\", "/")
            if "/serving/" not in p and "/parallel/" not in p:
                continue
            for fn in ast.walk(mod.tree):
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._check_function(mod, fn)

    def _is_source_call(self, node: ast.AST) -> bool:
        """Does this expression produce a compiled function?"""
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                path = dotted(n.func) or ""
                if path.split(".")[-1] in self._SOURCES:
                    return True
        return False

    def _compiled_call_sites(self, fn) -> List[ast.Call]:
        """Invocations of compiled functions inside ``fn``: direct
        invokes of a ``_SOURCES`` result (``self._predict_fn(...)(...)``,
        ``CACHE.get_or_create(...)(...)``) and calls through a name a
        ``_SOURCES`` call was assigned to.  Shared with
        :class:`ObsSpanRule` — ONE detection heuristic, two rules
        (dispatch accounting + span coverage), so the definition of "a
        compiled call site" can never drift between them."""
        compiled_names: Set[str] = set()
        sites: List[ast.Call] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and self._is_source_call(node.value):
                for t in node.targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            compiled_names.add(leaf.id)
            if isinstance(node, ast.Call):
                if isinstance(node.func, (ast.Call, ast.Subscript)) \
                        and self._is_source_call(node.func):
                    sites.append(node)
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in compiled_names:
                    sites.append(node)
        return sites

    @staticmethod
    def _has_accounting(fn) -> bool:
        """Does ``fn`` tag a dispatch anywhere — ``note_dispatch(...)``,
        ``._record(...)``, or a ``dispatch``-named counter update?  The
        ONE accounting predicate shared by the dispatch, obs-span and
        collective-span rules."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                path = dotted(node.func) or ""
                if path.split(".")[-1] in ("note_dispatch", "_record"):
                    return True
            if isinstance(node, (ast.AugAssign, ast.Assign)):
                target = node.target if isinstance(node, ast.AugAssign) \
                    else (node.targets[0] if node.targets else None)
                if target is not None and "dispatch" in (
                        dotted(target) or "").lower():
                    return True
        return False

    def _check_function(self, mod: Module, fn) -> Iterator[Finding]:
        call_sites = self._compiled_call_sites(fn)
        # Functions that only BUILD and return the compiled fn (no
        # invocation) are accounted at their call sites instead.
        if call_sites and not self._has_accounting(fn):
            yield self.finding(
                mod, call_sites[0].lineno,
                f"{fn.name}() invokes a compiled function but never "
                f"tags the dispatch (note_dispatch/._record/dispatch "
                f"counter)")


# ----------------------------------------------------------- obs-span

class ObsSpanRule(DispatchAccountingRule):
    """ISSUE 11 twin of the dispatch-accounting rule: in ``serving/``
    and ``parallel/``, a driver-level function that invokes a compiled
    function must also run it under a telemetry span — a ``with``
    statement whose context manager is a ``span(...)``/``obs.span``/
    ``trace.span`` call somewhere in the function (``span()`` is the
    no-op fast path when tracing is off, so coverage costs nothing
    disabled).  Without this, new dispatch call sites silently fall off
    the trace timeline the way they used to fall off the dispatch
    counters (the incident class the r14 ``dispatch`` rule closed)."""

    id = "obs-span"
    incident = ("ISSUE 11: a compiled dispatch invisible to the span "
                "timeline — the trace twin of the dispatch-counter "
                "class")

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            p = mod.rel.replace("\\", "/")
            if "/serving/" not in p and "/parallel/" not in p:
                continue
            parents = mod.parents()
            for fn in ast.walk(mod.tree):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                # Driver-level functions only: a nested closure's call
                # sites are covered by (and checked through) the
                # enclosing driver's subtree walk.
                if not isinstance(parents.get(fn),
                                  (ast.Module, ast.ClassDef)):
                    continue
                yield from self._check_spans(mod, fn)

    def _check_spans(self, mod: Module, fn) -> Iterator[Finding]:
        call_sites = self._compiled_call_sites(fn)
        if not call_sites:
            return
        if self._has_span(fn):
            return
        yield self.finding(
            mod, call_sites[0].lineno,
            f"{fn.name}() invokes a compiled function with no enclosing "
            f"telemetry span — wrap the dispatch in `with "
            f"obs_trace.span(...)` (a no-op when tracing is off) so it "
            f"appears on the trace timeline")

    @staticmethod
    def _has_span(fn) -> bool:
        for node in ast.walk(fn):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    leaf = (dotted(expr.func) or "").split(".")[-1]
                    if leaf in ("span", "tracing"):
                        return True
        return False


# ----------------------------------------------------- collective-span

class CollectiveSpanRule(ObsSpanRule):
    """ISSUE 13 extension of the r15 ``obs-span`` detection: in
    ``parallel/``, a driver-level function that performs a HOST-SIDE
    cross-process collective (``process_allgather`` /
    ``sync_global_devices`` / ``broadcast_one_to_all`` — the calls that
    block every process in the fleet, invisible to the compiled-fn
    rules) must run it under a telemetry span or carry a dispatch tag.
    Without this, fleet-blocking waits silently fall off the merged
    timeline — the one place an operator could have attributed a
    stalled fleet to the host that never arrived."""

    id = "collective-span"
    incident = ("ISSUE 13: a host-side collective invisible to the "
                "fleet timeline — the cross-process twin of the "
                "obs-span class")

    _COLLECTIVES = {"process_allgather", "sync_global_devices",
                    "broadcast_one_to_all"}

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            p = mod.rel.replace("\\", "/")
            if "/parallel/" not in p:
                continue
            parents = mod.parents()
            for fn in ast.walk(mod.tree):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                # Driver-level only — nested closures are checked
                # through the enclosing driver's subtree walk (the
                # obs-span convention).
                if not isinstance(parents.get(fn),
                                  (ast.Module, ast.ClassDef)):
                    continue
                sites = [node for node in ast.walk(fn)
                         if isinstance(node, ast.Call)
                         and (dotted(node.func) or "").split(".")[-1]
                         in self._COLLECTIVES]
                if not sites:
                    continue
                if self._has_span(fn) or self._has_accounting(fn):
                    continue
                yield self.finding(
                    mod, sites[0].lineno,
                    f"{fn.name}() runs a host-side cross-process "
                    f"collective with no enclosing telemetry span or "
                    f"dispatch tag — wrap it in `with "
                    f"obs_trace.span('collective', ...)` (a no-op when "
                    f"tracing is off) so the fleet-blocking wait lands "
                    f"on the merged timeline")


# -------------------------------------------------------- ingest-span

class IngestSpanRule(ObsSpanRule):
    """ISSUE 18 member of the obs-span lint family: in ``data/`` and
    ``parallel/sharding.py``, a driver-level function that PLACES host
    bytes onto devices (``jax.device_put`` /
    ``make_array_from_single_device_arrays`` /
    ``make_array_from_callback`` /
    ``make_array_from_process_local_data``) must run the placement
    under a ``stage``/``place`` telemetry span.  The TTFI table's
    ``stage`` row and the per-slab ingest breakdown are built from
    those spans alone; a placement path without one silently
    undercounts ingest in every TTFI artifact — the placement twin of
    the obs-span incident class."""

    id = "ingest-span"
    incident = ("ISSUE 18: a host->device placement invisible to the "
                "ingest timeline — the TTFI stage row silently "
                "undercounts; the placement twin of the obs-span class")

    _PLACERS = {"device_put", "make_array_from_single_device_arrays",
                "make_array_from_callback",
                "make_array_from_process_local_data"}

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            p = mod.rel.replace("\\", "/")
            if "/data/" not in p and not p.endswith(
                    "parallel/sharding.py"):
                continue
            parents = mod.parents()
            for fn in ast.walk(mod.tree):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                # Driver-level only (the obs-span convention): nested
                # closures — including a prefetch producer's stage
                # callback — are checked through the enclosing driver's
                # subtree walk.
                if not isinstance(parents.get(fn),
                                  (ast.Module, ast.ClassDef)):
                    continue
                sites = [node.lineno for node in ast.walk(fn)
                         if isinstance(node, ast.Call)
                         and (dotted(node.func) or "").split(".")[-1]
                         in self._PLACERS]
                if not sites:
                    continue
                if self._has_stage_span(fn):
                    continue
                yield self.finding(
                    mod, sites[0],
                    f"{fn.name}() places host bytes on device with no "
                    f"enclosing 'stage'/'place' span — wrap the "
                    f"placement in `with obs_trace.span('stage', "
                    f"rows=..., bytes=...)` (a no-op when tracing is "
                    f"off) so it lands on the ingest timeline and the "
                    f"TTFI stage row")

    @staticmethod
    def _has_stage_span(fn) -> bool:
        """Stricter than the parent's ``_has_span``: the span must be
        NAMED ``'stage'`` or ``'place'`` (a literal first argument) —
        an ingest placement filed under some other phase name would
        corrupt the TTFI decomposition rather than merely missing it."""
        for node in ast.walk(fn):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                expr = item.context_expr
                if not isinstance(expr, ast.Call):
                    continue
                if (dotted(expr.func) or "").split(".")[-1] != "span":
                    continue
                if expr.args and isinstance(expr.args[0], ast.Constant) \
                        and expr.args[0].value in ("stage", "place"):
                    return True
        return False


# ------------------------------------------------------ quality-counter

class QualityCounterRule(ObsSpanRule):
    """ISSUE 14 member of the obs-span lint family: in ``serving/``, a
    driver-level function that RECORDS serving traffic (calls the
    engine's ``._record`` stats recorder, or bumps the
    ``packed_dispatches`` counter — the packed path's accounting) must
    also feed the quality monitor (``._observe_quality``/
    ``.observe``).  A dispatch path that counts its traffic but skips
    the monitor silently starves the drift detectors of exactly that
    path's labels — the monitoring twin of the r14 dispatch-counter
    incident class, and how a future fifth dispatch path would
    otherwise go blind."""

    id = "quality-counter"
    incident = ("ISSUE 14: a serving dispatch path recorded in the "
                "stats but invisible to the drift monitor — the "
                "quality twin of the dispatch-counter class")

    _FEEDS = {"_observe_quality", "observe"}

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            p = mod.rel.replace("\\", "/")
            if "/serving/" not in p:
                continue
            parents = mod.parents()
            for fn in ast.walk(mod.tree):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                # Driver-level only (the obs-span convention): nested
                # closures are checked through the enclosing driver.
                if not isinstance(parents.get(fn),
                                  (ast.Module, ast.ClassDef)):
                    continue
                sites = self._traffic_sites(fn)
                if not sites:
                    continue
                if self._feeds_monitor(fn):
                    continue
                yield self.finding(
                    mod, sites[0],
                    f"{fn.name}() records serving traffic but never "
                    f"feeds the quality monitor — call "
                    f"_observe_quality(...) with the labels/scores "
                    f"this dispatch already computed (a no-op when "
                    f"monitoring is off)")

    @staticmethod
    def _traffic_sites(fn) -> List[int]:
        """Lines where ``fn`` records serving traffic: ``._record(...)``
        calls and ``packed_dispatches`` counter INCREMENTS (AugAssign
        only — the ``= 0`` declarations in __init__ are bookkeeping
        setup, not traffic)."""
        lines: List[int] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if (dotted(node.func) or "").split(".")[-1] == "_record":
                    lines.append(node.lineno)
            elif isinstance(node, ast.AugAssign):
                if "packed_dispatches" in (dotted(node.target) or ""):
                    lines.append(node.lineno)
        return lines

    @classmethod
    def _feeds_monitor(cls, fn) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if (dotted(node.func) or "").split(".")[-1] \
                        in cls._FEEDS:
                    return True
        return False


# -------------------------------------------------------- fleet-record

class FleetRecordRule(QualityCounterRule):
    """ISSUE 17 member of the quality-counter lint family: in
    ``serving/``, a driver-level function that FORWARDS a request to a
    replica engine (``<...>.engine.call/submit/score/predict/
    predict_multi(...)``) or SHEDS one (``raise FleetOverloadError``)
    must record the decision in the metrics registry — call the
    fleet's ``_record_route``/``_record_shed`` write-throughs.  The
    router's routing and admission decisions ARE the SLO signal
    (``fleet.route``/``fleet.shed`` counters, the scaling-curve
    denominators); a future routing path that forwards or sheds
    without recording silently starves that signal exactly the way
    unrecorded dispatch paths used to starve the r14 counters."""

    id = "fleet-record"
    incident = ("ISSUE 17: a fleet routing path that forwards or sheds "
                "without recording — the router twin of the "
                "dispatch-counter class")

    _FEEDS = {"_record_route", "_record_shed"}
    _FORWARD_LEAVES = {"call", "submit", "score", "predict",
                       "predict_multi"}

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            p = mod.rel.replace("\\", "/")
            if "/serving/" not in p:
                continue
            parents = mod.parents()
            for fn in ast.walk(mod.tree):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                # Driver-level only (the obs-span convention): nested
                # closures are checked through the enclosing driver.
                if not isinstance(parents.get(fn),
                                  (ast.Module, ast.ClassDef)):
                    continue
                sites = self._routing_sites(fn)
                if not sites:
                    continue
                if self._feeds_monitor(fn):
                    continue
                yield self.finding(
                    mod, sites[0],
                    f"{fn.name}() forwards or sheds fleet traffic but "
                    f"never records it — call _record_route(...) / "
                    f"_record_shed(...) so the routing decision lands "
                    f"in the fleet.route/fleet.shed counters (the SLO "
                    f"signal)")

    @classmethod
    def _routing_sites(cls, fn) -> List[int]:
        """Lines where ``fn`` makes a routing decision: forwards a
        request into a replica's engine (a call through an ``engine``
        attribute with a dispatch-surface leaf) or sheds one (raises
        ``FleetOverloadError``)."""
        lines: List[int] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                path = (dotted(node.func) or "").split(".")
                if len(path) >= 2 and path[-2] == "engine" \
                        and path[-1] in cls._FORWARD_LEAVES:
                    lines.append(node.lineno)
            elif isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                name = exc.func if isinstance(exc, ast.Call) else exc
                if (dotted(name) or "").split(".")[-1] \
                        == "FleetOverloadError":
                    lines.append(node.lineno)
        return lines


# ------------------------------------------------------------ threads

class ThreadHygieneRule(Rule):
    """Every ``threading.Thread`` the package creates must have a join
    on an owner close path: stored on ``self.x`` — some method of the
    class joins ``self.x``; a local — joined in the same function."""

    id = "thread"
    incident = ("prefetch producer / serving queue discipline: an "
                "unjoined worker outlives close() and races teardown")

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and (
                        dotted(node.func) in ("threading.Thread",
                                              "Thread")):
                    yield from self._check_site(mod, node)

    def _check_site(self, mod: Module, call: ast.Call) -> Iterator[Finding]:
        parent = mod.parents().get(call)
        target: Optional[str] = None
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            target = dotted(parent.targets[0])
        if target is None:
            yield self.finding(
                mod, call.lineno,
                "Thread created without binding it — nothing can ever "
                "join it")
            return
        if target.startswith("self."):
            cls = mod.enclosing(call, (ast.ClassDef,))
            if cls is None or not self._class_joins(cls, target):
                yield self.finding(
                    mod, call.lineno,
                    f"Thread stored on {target} but no method of the "
                    f"owning class joins it (close()/stop()/__exit__)")
        else:
            fn = mod.enclosing(call, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
            if fn is None or not self._scope_joins(fn, target):
                yield self.finding(
                    mod, call.lineno,
                    f"Thread bound to local {target!r} but this "
                    f"function never joins it")

    @staticmethod
    def _class_joins(cls: ast.ClassDef, target: str) -> bool:
        for node in ast.walk(cls):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join" \
                    and (dotted(node.func.value) or "") == target:
                return True
        return False

    @staticmethod
    def _scope_joins(fn, target: str) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join" \
                    and (dotted(node.func.value) or "") == target:
                return True
        return False


# ------------------------------------------------------ counter-reset

class CounterResetRule(Rule):
    """Classes with a ``fit`` method: every trailing-underscore
    (fitted/audit) attribute any method assigns must be declared in the
    init/reset region — ``__init__`` or a ``*reset*`` method of the
    class or an in-package ancestor — so a read before (or after a
    differently-pathed) fit sees a defined, deliberately-chosen value
    instead of a stale one."""

    id = "counter-reset"
    incident = ("r9 stale-audit class: checkpoint_segments_ survived "
                "into fits that never set it")

    def run(self, pkg: Package) -> Iterator[Finding]:
        # EVERY class body is visited, including same-named classes in
        # different modules (a coverage gate must not drop a class to a
        # name collision); the by-name map is only for base resolution,
        # where the first definition wins (ambiguous bases are rare and
        # resolve conservatively — extra declared attrs, never fewer
        # checks on the class itself).
        all_classes: List[tuple] = []               # (Module, ClassDef)
        classes: Dict[str, ast.ClassDef] = {}
        for mod in pkg:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    all_classes.append((mod, node))
                    classes.setdefault(node.name, node)
        for mod, cls in all_classes:
            if not any(isinstance(n, ast.FunctionDef) and n.name == "fit"
                       for n in cls.body):
                continue
            declared = self._declared_attrs(cls, classes)
            for method in cls.body:
                if not isinstance(method, ast.FunctionDef):
                    continue
                if self._is_reset_region(method.name):
                    continue
                for line, attr in self._stored_attrs(method):
                    if attr not in declared:
                        yield self.finding(
                            mod, line,
                            f"{cls.name}.{method.name} assigns audit attr "
                            f"self.{attr} never declared in __init__ "
                            f"or a *reset* method — stale across fits "
                            f"and undefined before the first")

    @staticmethod
    def _is_reset_region(method_name: str) -> bool:
        return method_name == "__init__" or "reset" in method_name

    def _declared_attrs(self, cls: ast.ClassDef,
                        classes: Dict[str, ast.ClassDef],
                        seen: Optional[Set[str]] = None) -> Set[str]:
        seen = seen if seen is not None else set()
        if cls.name in seen:
            return set()
        seen.add(cls.name)
        declared: Set[str] = set()
        for method in cls.body:
            if isinstance(method, ast.FunctionDef) \
                    and self._is_reset_region(method.name):
                declared.update(a for _, a in self._stored_attrs(method))
        for base in cls.bases:
            base_name = (dotted(base) or "").split(".")[-1]
            if base_name in classes:
                declared.update(self._declared_attrs(
                    classes[base_name], classes, seen))
        return declared

    @staticmethod
    def _stored_attrs(method: ast.FunctionDef):
        """(line, attr) for every ``self.x_ = ...`` in the method."""
        for node in ast.walk(method):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" \
                        and t.attr.endswith("_") \
                        and not t.attr.endswith("__") \
                        and not t.attr.startswith("_"):
                    yield node.lineno, t.attr


# ------------------------------------------------------- dead-private

class DeadPrivateRule(Rule):
    """Module-level private functions and class-level private methods
    with zero references anywhere in the linted tree: dead code that
    every call site silently bypassed."""

    id = "dead-private"
    incident = ("r11 `_serve_chunk` class: a private helper all call "
                "sites bypassed")

    def run(self, pkg: Package) -> Iterator[Finding]:
        defs = []      # (mod, node, qualifier)
        refs: Dict[str, int] = {}
        for mod in pkg:
            parents = mod.parents()
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    name = node.name
                    parent = parents.get(node)
                    # Only module-level defs and class methods: a
                    # nested closure is used where it is defined.
                    if isinstance(parent, (ast.Module, ast.ClassDef)) \
                            and name.startswith("_") \
                            and not name.startswith("__"):
                        defs.append((mod, node))
                if isinstance(node, ast.Name):
                    refs[node.id] = refs.get(node.id, 0) + 1
                elif isinstance(node, ast.Attribute):
                    refs[node.attr] = refs.get(node.attr, 0) + 1
                elif isinstance(node, ast.Call):
                    # getattr(self, "_x") / monkeypatch.setattr-style
                    # string references — call arguments only, so a
                    # docstring merely MENTIONING a helper never keeps
                    # it alive.
                    for arg in list(node.args) + [k.value
                                                  for k in node.keywords]:
                        for c in ast.walk(arg):
                            if isinstance(c, ast.Constant) \
                                    and isinstance(c.value, str):
                                refs[c.value] = refs.get(c.value, 0) + 1
        for mod, node in defs:
            if refs.get(node.name, 0) == 0:
                yield self.finding(
                    mod, node.lineno,
                    f"private helper {node.name}() has zero references "
                    f"in the linted tree — every call site bypasses it")


# --------------------------------------------------------- cache-name

class CacheNameRule(Rule):
    """Every module-level :class:`~kmeans_tpu.utils.cache.LRUCache`
    construction must pass ``name=``: an unnamed cache is invisible to
    the compile spans (its misses trace as the anonymous ``'cache'``)
    AND to the ISSUE 12 cost capture, whose CostRecords key on the
    cache name — so a new cache without one silently falls off both
    the timeline and the device-cost report.  Function-local caches
    (test fixtures, ad-hoc scopes) are exempt: only module-scope caches
    live long enough to be an observability surface."""

    id = "cache-name"
    incident = ("ISSUE 12: unnamed caches are invisible to compile "
                "spans and to device-cost capture")

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            parents = mod.parents()
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and (dotted(node.func) or "").split(".")[-1]
                        == "LRUCache"):
                    continue
                if any(kw.arg == "name" for kw in node.keywords):
                    continue
                if self._enclosing_scope_is_module(parents, node):
                    yield self.finding(
                        mod, node.lineno,
                        "module-level LRUCache(...) without name= — "
                        "unnamed caches are invisible to compile spans "
                        "and cost capture; pass name='<module>.<ATTR>'")

    @staticmethod
    def _enclosing_scope_is_module(parents: dict, node: ast.AST) -> bool:
        p = parents.get(node)
        while p is not None:
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return False
            if isinstance(p, ast.Module):
                return True
            p = parents.get(p)
        return False


# ------------------------------------------------------------ aot-key

class AotKeyRule(Rule):
    """ISSUE 15 member of the r14 cache-key rule family: every AOT
    artifact write (a ``.put(...)`` on a ``*store*``-named object — the
    :class:`~kmeans_tpu.utils.aot.AOTStore` surface) must derive its
    key through ``artifact_key(...)``, the one constructor that starts
    from the SAME in-memory ``_STEP_CACHE`` key the compiled entry
    lives under and appends the jax/jaxlib-version + backend-
    fingerprint fields.  A hand-rolled key dict misses components the
    way 4 r14 findings missed knobs — except across processes and
    builds, where the served artifact is a stale or foreign executable
    rather than a same-process wrong program."""

    id = "aot-key"
    incident = ("r14 cache-key class, cross-process: an AOT artifact "
                "keyed without a version/backend/in-memory-key field "
                "serves a stale executable to a later build")

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "put"):
                    continue
                base = dotted(node.func.value) or ""
                leaf = base.split(".")[-1].lower()
                if "store" not in leaf:
                    continue
                key_arg = node.args[0] if node.args else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "fields"), None)
                if key_arg is None or not self._is_blessed(mod, node,
                                                           key_arg):
                    yield self.finding(
                        mod, node.lineno,
                        "AOT store write with a hand-rolled key — "
                        "derive it with artifact_key(...) (the audited "
                        "constructor spanning the in-memory cache key "
                        "plus jax/jaxlib version and backend "
                        "fingerprint fields)")

    @staticmethod
    def _is_key_call(node: ast.AST) -> bool:
        return isinstance(node, ast.Call) and \
            (dotted(node.func) or "").split(".")[-1] == "artifact_key"

    def _is_blessed(self, mod: Module, call: ast.Call,
                    key_arg: ast.AST) -> bool:
        """Direct ``artifact_key(...)`` argument, or a Name chased to
        its nearest preceding same-function assignment from one (the
        CacheKeyRule._resolve_key discipline)."""
        if self._is_key_call(key_arg):
            return True
        if not isinstance(key_arg, ast.Name):
            return False
        fn = mod.enclosing(call, (ast.FunctionDef, ast.AsyncFunctionDef))
        if fn is None:
            return False
        best = None
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == key_arg.id
                    and node.lineno <= call.lineno):
                if best is None or node.lineno > best.lineno:
                    best = node
        return best is not None and self._is_key_call(best.value)


# ------------------------------------------------------------- large-k

#: Program builders whose E-pass materializes a dense (chunk, k)
#: distance tile per device (parallel.distributed's dispatch surface).
_DENSE_TILE_BUILDERS = {
    "make_step_fn", "make_fit_fn", "make_multi_fit_fn",
    "make_predict_fn", "make_transform_fn", "make_score_rows_fn",
    "make_assign_margin_fn",
}
#: Atoms whose presence marks the class as large-k-aware: a planner
#: fit-check (``plan_fit`` / the KMeans resolution helpers) or a
#: ``k_shard``/``assign`` dispatch branch (names, attributes, spec-dict
#: string keys and the 'two_level' route constant all count).
_LARGE_K_GUARDS = {
    "plan_fit", "_resolve_large_k", "_route_large_k",
    "k_shard", "assign", "two_level",
}


class LargeKRule(Rule):
    """ISSUE 16: any CLASS that builds dense-tile programs (a
    ``make_*_fn`` from the dispatch surface — each one materializes a
    (chunk, k) distance tile per device) must be large-k-aware: it must
    consult the r16 planner (``plan_fit``, or the KMeans
    ``_resolve_large_k``/``_route_large_k`` helpers that wrap it) or
    carry a ``k_shard``/``assign`` dispatch branch routing past the
    memory wall.  A class that unconditionally instantiates the dense
    tile re-opens the exact failure the massive-k tier closed: at
    k=64k x chunk=8192 the tile alone is 2 GiB/device, an OOM no knob
    can route around after the fact.  Class granularity is the honest
    scope — module-level builder calls (benchmarks, the builder layer
    itself) size their shapes deliberately."""

    id = "large-k"
    incident = ("ISSUE 16: an unguarded dense (chunk, k) tile "
                "materialization OOMs at massive k with no dispatch "
                "route around it")

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                calls = [
                    c for c in ast.walk(node)
                    if isinstance(c, ast.Call)
                    and (dotted(c.func) or "").split(".")[-1]
                    in _DENSE_TILE_BUILDERS]
                if not calls or self._atoms(node) & _LARGE_K_GUARDS:
                    continue
                yield self.finding(
                    mod, calls[0].lineno,
                    f"class {node.name} builds dense-tile programs "
                    f"({(dotted(calls[0].func) or '').split('.')[-1]}) "
                    f"with no plan_fit fit-check and no k_shard/assign "
                    f"dispatch branch — unguarded (chunk, k) tiles OOM "
                    f"at massive k (ISSUE 16)")

    @staticmethod
    def _atoms(node: ast.AST) -> Set[str]:
        """Every symbol-ish atom in the class body: Name ids, Attribute
        components, keyword-argument names, and string constants (spec
        keys like 'assign' and route constants like 'two_level' live as
        strings)."""
        atoms: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Name):
                atoms.add(n.id)
            elif isinstance(n, ast.Attribute):
                atoms.add(n.attr)
            elif isinstance(n, ast.keyword) and n.arg:
                atoms.add(n.arg)
            elif isinstance(n, ast.Constant) and isinstance(n.value, str):
                atoms.add(n.value)
        return atoms


# --------------------------------------------------------- fault-path

class FaultPathRule(Rule):
    """ISSUE 19: in ``orchestrator/`` and ``parallel/``, an ``except``
    clause catching a FAULT type — preemption/OOM/launch-flake
    injections, transient IO, torn checkpoints, timeouts, runtime
    device loss — must ROUTE the fault, not swallow it: the handler
    body must re-raise (typed or bare), return a typed
    ``policy.EXIT_*`` code for the supervisor to classify, or call
    into the committed retry/decision machinery (``*retry*``,
    ``*backoff*``, ``*give_up*``, ``*record*``, ``*decision*``,
    ``*exit*``, or the ``kill``/``terminate`` escalation).  The
    autopilot's whole robustness story is that every fault lands in
    the typed decision log under a committed budget; one bare
    ``except SimulatedPreemption: pass`` in a worker or launcher turns
    a supervised preemption into a silent wrong answer."""

    id = "fault-path"
    incident = ("ISSUE 19: a swallowed fault in the supervised tree — "
                "an except clause that catches a preemption/IO/timeout "
                "fault type and neither re-raises, returns a typed "
                "exit, nor routes through the committed retry policy")

    #: Exception LEAF names that mean "a fault the autopilot owns".
    _FAULT_TYPES = {
        "SimulatedPreemption", "SimulatedOOM", "SimulatedLaunchFailure",
        "TransientIOError", "CheckpointCorruptError", "LaunchError",
        "TraceReadError", "OSError", "IOError", "TimeoutError",
        "TimeoutExpired", "XlaRuntimeError",
    }
    #: Substrings of a called dotted name that count as routing the
    #: fault into the committed machinery.
    _ROUTING_MARKERS = ("retry", "backoff", "give_up", "record",
                        "decision", "exit", "kill", "terminate")

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            p = mod.rel.replace("\\", "/")
            if "/orchestrator/" not in p and "/parallel/" not in p:
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                caught = self._caught_faults(node)
                if not caught:
                    continue
                if self._routes(node):
                    continue
                yield self.finding(
                    mod, node.lineno,
                    f"except clause catches fault type(s) "
                    f"{', '.join(sorted(caught))} but neither "
                    f"re-raises, returns a typed EXIT_* code, nor "
                    f"routes through the committed retry policy "
                    f"(call one of *{'*/*'.join(self._ROUTING_MARKERS)}"
                    f"*) — a swallowed fault never reaches the "
                    f"autopilot decision log")

    @classmethod
    def _caught_faults(cls, handler: ast.ExceptHandler) -> Set[str]:
        """Leaf names of fault types this handler catches."""
        t = handler.type
        if t is None:
            return set()        # bare except: other rules' territory
        exprs = t.elts if isinstance(t, ast.Tuple) else [t]
        caught = set()
        for e in exprs:
            leaf = (dotted(e) or "").split(".")[-1]
            if leaf in cls._FAULT_TYPES:
                caught.add(leaf)
        return caught

    @classmethod
    def _routes(cls, handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(handler):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Return) and n.value is not None:
                leaf = (dotted(n.value) or "").split(".")[-1]
                if leaf.startswith("EXIT_"):
                    return True
            if isinstance(n, ast.Call):
                name = (dotted(n.func) or "").lower()
                if any(m in name for m in cls._ROUTING_MARKERS):
                    return True
        return False


# -------------------------------------------------------- atomic-swap

class AtomicSwapRule(Rule):
    """ISSUE 20: serving code that rebinds a resident model's table
    attributes (``centroids``/``means_``/... and their f64 carries) or
    touches the identity-keyed device-cache attributes
    (``_cents_cache``/``_params_cache`` — the ``_cents_dev``/
    ``_params_dev`` placement state) must route through the one swap
    helper (``serving.learn.publish_tables``).  The helper owns the
    publication ORDER — auxiliary state first, device placement
    pre-seeded, the ``centroids`` rebind LAST — which is what makes a
    concurrent reader see the old table or the new one, never a torn
    mix.  A future update path writing these attributes inline would
    compile-correctly, pass single-threaded tests, and publish torn
    tables under load; this rule makes that a static finding."""

    id = "atomic-swap"
    incident = ("ISSUE 20: an in-place table publication outside the "
                "atomic swap helper — readers could observe a torn "
                "centroid table mid-update")

    #: Attribute leaves whose rebinding IS a table publication: the
    #: model tables the serving dispatch reads (K-Means + GMM
    #: families), their float64 carries/lifetime counts, and the
    #: identity-keyed device caches behind ``_cents_dev``/
    #: ``_params_dev``.
    _SWAP_ATTRS = {
        "centroids", "_centroids_f64", "_seen", "cluster_sizes_",
        "_cents_cache",
        "means_", "covariances_", "weights_", "precisions_cholesky_",
        "_params_cache",
    }
    #: The designated swap helpers — the only serving/ functions
    #: allowed to write the attributes above.
    _SWAP_HELPERS = {"publish_tables"}

    def run(self, pkg: Package) -> Iterator[Finding]:
        for mod in pkg:
            p = mod.rel.replace("\\", "/")
            if "/serving/" not in p:
                continue
            exempt: Set[int] = set()
            for fn in ast.walk(mod.tree):
                if isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                        and fn.name in self._SWAP_HELPERS:
                    for n in ast.walk(fn):
                        exempt.add(id(n))
            for node in ast.walk(mod.tree):
                for line, attr in self._table_stores(node, exempt):
                    yield self.finding(
                        mod, line,
                        f"rebinds model table state .{attr} outside "
                        f"the atomic swap helper — route the "
                        f"publication through "
                        f"serving.learn.publish_tables() so "
                        f"concurrent readers never see a torn table")

    @classmethod
    def _table_stores(cls, node: ast.AST, exempt: Set[int]):
        """(line, attr) for every write/delete of a table attribute in
        ``node`` (Assign/AugAssign/AnnAssign targets and ``del``), one
        entry per statement, skipping the designated helpers."""
        if id(node) in exempt:
            return
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
        for t in targets:
            # Unpack tuple/list targets: `a.x, b.y = ...`.
            parts = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                else [t]
            for part in parts:
                if isinstance(part, ast.Attribute) \
                        and part.attr in cls._SWAP_ATTRS:
                    yield node.lineno, part.attr
                    return


# -------------------------------------------------------- suppression

class SuppressionFormatRule(Rule):
    """Malformed suppression comments (missing rule list or reason)
    and suppressions naming unknown rule ids are findings — a
    suppression must be auditable, never a silent typo."""

    id = "suppression"
    incident = ("suppressions are explicit and counted, never silent "
                "(ISSUE 10 contract)")

    def run(self, pkg: Package) -> Iterator[Finding]:
        known = set(RULES)
        for mod in pkg:
            for line, comment in mod.malformed_suppressions:
                yield self.finding(
                    mod, line,
                    f"malformed lint suppression {comment!r} — use "
                    f"'# lint: ok(rule-id) — reason'")
            for sup in mod.suppressions.values():
                bad = [r for r in sup.rules if r not in known]
                if bad:
                    yield self.finding(
                        mod, sup.line,
                        f"suppression names unknown rule id"
                        f" {', '.join(bad)} (known: {sorted(known)})")


RULES: Dict[str, Rule] = {rule.id: rule for rule in (
    TraceHazardRule(), CacheKeyRule(), DispatchAccountingRule(),
    ObsSpanRule(), CollectiveSpanRule(), IngestSpanRule(),
    QualityCounterRule(),
    FleetRecordRule(), ThreadHygieneRule(), CounterResetRule(),
    DeadPrivateRule(),
    CacheNameRule(), AotKeyRule(), LargeKRule(),
    FaultPathRule(), AtomicSwapRule(), SuppressionFormatRule(),
)}
