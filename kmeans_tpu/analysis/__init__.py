"""Static invariant linter for the kmeans_tpu package (ISSUE 10).

Every rule here is a machine-checked version of an invariant that a
human review pass has already had to enforce at least once in this
repo's history: compile-cache keys missing a knob that changes the
compiled program (two duplicate-compile findings in r13 alone), dead
private helpers silently bypassed by every call site (`_serve_chunk`,
r11), audit counters stale across fits (`checkpoint_segments_`, r9),
and the thread/close discipline of the prefetch producer and the
serving queue.  The analysis itself is pure stdlib ``ast`` +
``tokenize``: it never imports or executes the modules it CHECKS, so
linting triggers no device initialization and no side effects from the
checked code, and accelerator-only files lint fine on any host.
(Reaching it via ``python -m kmeans_tpu lint`` still imports the
package like any other subcommand — jax must be installed, as for the
rest of the CLI.)

Public surface:

* :func:`lint_paths` — run every rule over a set of files/directories,
  returning a :class:`Report` (findings + suppression inventory).
* :data:`RULES` — the rule registry (id -> rule instance).
* ``python -m kmeans_tpu lint [--json] [paths]`` — the CLI
  (:mod:`kmeans_tpu.analysis.cli`, re-exported as
  ``kmeans_tpu.cli.lint_main``); exit 2 on findings.

Suppression grammar (explicit and counted, never silent)::

    some_flagged_line()   # lint: ok(rule-id) — short reason

The comment must name the rule id and carry a non-empty reason after
an em-dash or hyphen; it applies to its own line or, when written on
its own line, to the next code line.  Malformed suppressions are
themselves findings (rule ``suppression``), and the full suppression
inventory is part of the ``--json`` report so count regressions are
reviewable.
"""

from kmeans_tpu.analysis.core import (Finding, Package, Report,
                                      Suppression, lint_paths)
from kmeans_tpu.analysis.rules import RULES

__all__ = ["Finding", "Package", "Report", "Suppression", "lint_paths",
           "RULES"]
