"""Fit-lifecycle heartbeats: periodic progress records for orchestration.

ROADMAP item 1's elastic multi-host orchestration loop needs a
health/progress channel: "is the fit alive, how far along, what is it
doing" — without adding dispatches to the training loop.  This module
is that channel, opt-in and zero-cost when off:

* Models report progress at the host-sync points they ALREADY pay —
  host-loop iteration finishes, device-loop segment boundaries, and
  checkpoint writes (``AutoCheckpointMixin._write_autockpt``) — via
  :func:`note_progress`, a no-op unless a :class:`Heartbeat` is
  installed.  Zero extra dispatches by construction: every record is
  assembled from host-side attrs the boundary already materialized.
* A :class:`Heartbeat` turns those reports into records on a JSONL
  file and/or a callback.  With ``interval_s`` set, a background
  thread additionally re-emits the latest record on that cadence
  (stamped ``"tick": true``) — the liveness signal an orchestrator
  watches during a long device segment, when no boundary fires.  The
  thread is joined on ``close()`` (the prefetch shutdown discipline;
  the ``thread`` lint rule covers it).

Record schema (one JSON object per emission)::

    {"ts": <wall seconds>, "mono": <monotonic seconds>,
     "family": "kmeans", "model_class": "KMeans", "k": 64,
     "phase": "iteration" | "segment" | "checkpoint" | "split" | ...,
     "iteration": 12, "segment": 3, "shift": 1.3e-3,
     "inertia": 8.1e4, "effective_chunk": 65536, "oom_backoffs": 0,
     "dispatch_counts": {...},        # registry dispatch.* counters
     "phase_elapsed": {...},          # tracer per-phase self seconds
     "mem_peak_bytes": 420304,        # max captured program peak (ISSUE
     "program_flops": 1.97e7,         #   12; only when cost capture on)
     "tick": true                     # only on timer re-emissions
    }

Fields are best-effort: a family without an attr simply omits it.
Pure stdlib; never imports models or jax.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Callable, Optional

from kmeans_tpu.obs import cost as _cost
from kmeans_tpu.obs import identity as _identity
from kmeans_tpu.obs import trace as _trace
from kmeans_tpu.obs.metrics_registry import registry as _registry

__all__ = ["Heartbeat", "heartbeat", "note_progress", "get_heartbeat"]

#: Process-wide active heartbeat (None = off, the default).
_ACTIVE: Optional["Heartbeat"] = None


#: model attr -> record field, the host-side state a boundary already
#: materialized (never a device read).
_MODEL_FIELDS = (
    ("iterations_run", "iteration"),
    ("n_iter_", "iteration"),
    ("effective_chunk_", "effective_chunk"),
    ("oom_backoffs_", "oom_backoffs"),
    ("io_retries_used_", "io_retries"),
    ("checkpoint_segments_", "checkpoint_segments"),
    ("shift_", "shift"),
    ("lower_bound_", "lower_bound"),
)


def _model_record(model) -> dict:
    """Best-effort progress fields from a model's host-side attrs."""
    rec = {"model_class": type(model).__name__}
    spec_family = {"GaussianMixture": "gmm"}
    rec["family"] = spec_family.get(rec["model_class"], "kmeans")
    k = getattr(model, "k", None) or getattr(model, "n_components", None)
    if k is not None:
        rec["k"] = int(k)
    for attr, field in _MODEL_FIELDS:
        v = getattr(model, attr, None)
        if v is not None and field not in rec:
            try:
                rec[field] = float(v) if field in ("shift", "lower_bound") \
                    else int(v)
            except (TypeError, ValueError):
                pass
    hist = getattr(model, "sse_history", None)
    if hist:
        rec["inertia"] = float(hist[-1])
        if len(hist) >= 2 and "shift" not in rec:
            rec["sse_delta"] = float(hist[-1] - hist[-2])
    # Rows this host processes per iteration (ISSUE 13): set by the fit
    # preludes (``_progress_rows`` — process-local rows for multi-host
    # process-local datasets, the batch for minibatch engines).  The
    # heartbeat derives ``rows_per_sec`` from consecutive beats, so the
    # weak-scaling curve of ROADMAP item 1 is a ``fleet-status``
    # read-off, not a bespoke script.
    rows = getattr(model, "_progress_rows", None)
    if rows:
        rec["rows"] = int(rows)
    return rec


def note_progress(model=None, **fields) -> None:
    """Report one progress point to the active heartbeat; a true no-op
    (one None check) when none is installed — the hook every model
    boundary calls unconditionally."""
    hb = _ACTIVE
    if hb is None:
        return
    rec = _model_record(model) if model is not None else {}
    rec.update(fields)
    hb.beat(rec)


def get_heartbeat() -> Optional["Heartbeat"]:
    return _ACTIVE


class Heartbeat:
    """Progress-record sink: JSONL file and/or callback, optional timer.

    Parameters
    ----------
    path : file path for JSONL output (opened lazily, line-buffered,
        closed by ``close()``); None = no file.
    callback : ``callback(record: dict)`` invoked per emission (the
        orchestration-loop hook); exceptions are swallowed after
        counting (``hb.callback_errors``) — a broken observer must
        never kill a healthy fit.
    interval_s : with a value, a background thread re-emits the latest
        record every ``interval_s`` seconds (stamped ``tick: true``)
        between boundary reports — the liveness channel.  None (default)
        = boundary-driven only, no thread.
    min_period_s : boundary reports are throttled to at most one per
        this many seconds (0 = every boundary); the latest record
        always wins, and ``close()`` flushes it so the final state is
        never lost to the throttle.
    per_process : multi-host sink policy (ISSUE 13), resolved at the
        FIRST emission (identity is cached then).  ``'auto'`` (default):
        under ``process_count > 1`` the file path gains the per-process
        suffix (``hb.jsonl`` -> ``hb.p3.jsonl``) so N hosts never tear
        one file; single-process keeps the verbatim path.  ``False``:
        primary-only — non-zero processes drop the FILE sink (callbacks
        still fire on every host).  ``True``: always suffix.

    Every record additionally stamps the producing process's
    ``process_index``/``process_count``/``host`` (the fleet identity
    the straggler report and ``fleet-status`` key on), and — when the
    fit prelude recorded a per-iteration row count — ``rows_per_sec``,
    derived from consecutive boundary beats' iteration/monotonic
    deltas (ticks re-emit the last derived value; no recomputation).
    """

    def __init__(self, path=None, callback: Optional[Callable] = None,
                 *, interval_s: Optional[float] = None,
                 min_period_s: float = 0.0, per_process: object = "auto"):
        if interval_s is not None and interval_s <= 0:
            raise ValueError(f"interval_s must be positive or None, got "
                             f"{interval_s!r}")
        if per_process not in ("auto", True, False):
            raise ValueError(f"per_process must be 'auto', True or "
                             f"False, got {per_process!r}")
        self.path = path
        self.per_process = per_process
        self.resolved_path = None       # set at first file open
        self.callback = callback
        self.interval_s = interval_s
        self.min_period_s = float(min_period_s)
        self.emitted = 0
        self.callback_errors = 0
        self.sink_errors = 0
        self._file = None
        self._file_failed = False
        # _lock guards the cheap bookkeeping state only; emission (file
        # IO + user callback) runs under the REENTRANT _emit_lock so a
        # slow or re-entrant observer can never stall a boundary beat's
        # state update or deadlock against itself (review finding).
        self._lock = threading.Lock()
        self._emit_lock = threading.RLock()
        self._ident: Optional[dict] = None
        # (iteration, mono) of the last rate-bearing beat per model
        # class — the rows_per_sec derivation state.
        self._rate: dict = {}
        self._latest: Optional[dict] = None
        self._latest_unflushed = False
        self._last_emit = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        if interval_s is not None:
            self._thread = threading.Thread(
                target=self._tick_loop, name="kmeans_tpu-heartbeat",
                daemon=True)
            self._thread.start()

    # -------------------------------------------------------- emission
    def beat(self, record: dict) -> None:
        """One boundary report: stamp timestamps, remember as latest,
        emit (throttled by ``min_period_s``)."""
        now = time.monotonic()
        rec = dict(record)
        rec.setdefault("ts", time.time())
        rec.setdefault("mono", now)
        if self._ident is None:
            self._ident = _identity.identity()
        for k, v in self._ident.items():
            rec.setdefault(k, v)
        # rows_per_sec (ISSUE 13): Δiteration × rows / Δmono between
        # consecutive boundary beats of the same model class — the
        # per-host throughput the weak-scaling curve reads off.
        mc = rec.get("model_class")
        if "iteration" in rec and "rows" in rec:
            prev = self._rate.get(mc)
            if prev is not None and rec["iteration"] > prev[0] \
                    and now > prev[1]:
                rec.setdefault("rows_per_sec",
                               (rec["iteration"] - prev[0]) * rec["rows"]
                               / (now - prev[1]))
            self._rate[mc] = (rec["iteration"], now)
        tr = _trace.get_tracer()
        if tr is not None:
            rec.setdefault("phase_elapsed", tr.phase_totals())
        col = _cost.get_collector()
        if col is not None:
            # Device-cost fields (ISSUE 12): the max available per-
            # program peak/flops across captured programs — the step
            # program dominates both.  Host-side dict reads only.
            mx = col.max_metrics()
            if mx["mem_peak_bytes"] is not None:
                rec.setdefault("mem_peak_bytes", mx["mem_peak_bytes"])
            if mx["program_flops"] is not None:
                rec.setdefault("program_flops", mx["program_flops"])
        counts = {name: m["value"]
                  for name, m in _registry().snapshot().items()
                  if name.startswith("dispatch.")}
        if counts:
            rec.setdefault("dispatch_counts", counts)
        with self._lock:
            if self._closed:
                return
            self._latest = rec
            if self.min_period_s and \
                    now - self._last_emit < self.min_period_s:
                self._latest_unflushed = True
                return
            self._last_emit = now
            self._latest_unflushed = False
        self._emit(rec)             # IO/callback OUTSIDE the state lock

    def _emit(self, rec: dict) -> None:
        """Deliver one record to the sinks.  Serialized by the
        reentrant ``_emit_lock`` (file lines never interleave across
        the beat and tick threads; a callback that re-enters
        ``note_progress`` recurses instead of deadlocking).  BOTH sinks
        are exception-isolated — a full disk or an unserializable user
        field must never kill the fit being observed; failures are
        counted (``sink_errors``/``callback_errors``) and, for the
        file, the sink is disabled after the first failure so a dead
        disk is not retried per record."""
        with self._emit_lock:
            self.emitted += 1
            # A beat that raced close() must not reopen the closed file
            # (close() flushes the throttled tail BEFORE flipping
            # _closed, so the tail still lands).
            if self.path is not None and not self._file_failed \
                    and not self._closed:
                if self._file is None and self.resolved_path is None:
                    self.resolved_path = self._resolve_path()
                    if self.resolved_path is None:
                        # primary-only policy on a non-zero process:
                        # the file sink is deliberately off (not an
                        # error — sink_errors stays 0).
                        self._file_failed = True
                try:
                    if not self._file_failed:
                        if self._file is None:
                            self._file = open(self.resolved_path, "a")
                        # default=str: user fields (numpy scalars,
                        # paths) serialize best-effort, never raising.
                        self._file.write(
                            json.dumps(rec, default=str) + "\n")
                        self._file.flush()
                except Exception:   # noqa: BLE001 — observer isolation
                    self.sink_errors += 1
                    self._file_failed = True
            if self.callback is not None:
                try:
                    self.callback(rec)
                except Exception:   # noqa: BLE001 — observer isolation
                    self.callback_errors += 1

    def _resolve_path(self) -> Optional[str]:
        """The actual file path per the ``per_process`` policy (see the
        class docstring); None = this process's file sink is off."""
        ident = self._ident if self._ident is not None \
            else _identity.identity()
        self._ident = ident
        if self.per_process is True or (
                self.per_process == "auto"
                and ident["process_count"] > 1):
            return _identity.per_process_path(self.path,
                                              ident["process_index"])
        if self.per_process is False and ident["process_count"] > 1 \
                and ident["process_index"] != 0:
            return None
        return str(self.path)

    def _tick_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            with self._lock:
                if self._closed or self._latest is None:
                    continue
                rec = dict(self._latest)
                rec["tick"] = True
                rec["ts"] = time.time()
                rec["mono"] = time.monotonic()
                self._last_emit = time.monotonic()
                self._latest_unflushed = False
            self._emit(rec)

    # ------------------------------------------------------- lifecycle
    def close(self) -> None:
        """Flush the last throttled record, stop + JOIN the timer
        thread, close the file.  Idempotent."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        with self._lock:
            if self._closed:
                return
            tail = self._latest if self._latest_unflushed else None
            self._latest_unflushed = False
        if tail is not None:
            self._emit(tail)
        with self._lock:
            self._closed = True
        with self._emit_lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


@contextlib.contextmanager
def heartbeat(hb_or_path=None, **kwargs):
    """Install a heartbeat for the ``with`` body (nested scopes shadow);
    the heartbeat is CLOSED on exit when this scope constructed it.

    Usage::

        with obs.heartbeat("progress.jsonl", interval_s=5.0) as hb:
            model.fit(X, checkpoint_every=8, checkpoint_path=p)
        # progress.jsonl: one record per boundary + 5 s liveness ticks
    """
    global _ACTIVE
    own = not isinstance(hb_or_path, Heartbeat)
    if not own and kwargs:
        # A pre-built Heartbeat carries its own configuration; silently
        # ignoring kwargs here would e.g. drop an interval_s the caller
        # expects liveness ticks from (review finding).
        raise ValueError(
            f"heartbeat() got keyword arguments {sorted(kwargs)} "
            f"alongside an existing Heartbeat instance — configure the "
            f"instance at construction, or pass a path/None here")
    hb = Heartbeat(hb_or_path, **kwargs) if own else hb_or_path
    prev, _ACTIVE = _ACTIVE, hb
    try:
        yield hb
    finally:
        _ACTIVE = prev
        if own:
            hb.close()
