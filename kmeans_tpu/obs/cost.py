"""Device-cost capture: XLA cost/memory analysis per compiled program.

ISSUE 12 tentpole.  r15's telemetry sees host-side wall time at sync
points; nothing in the system could say what a compiled program *costs
on the device* — FLOPs, bytes moved, peak HBM — so MFU rows rested on
hand-derived FLOP formulas and OOM was discovered by catching
``RESOURCE_EXHAUSTED``.  This module captures XLA's own per-program
analyses (``Compiled.cost_analysis()`` / ``memory_analysis()``) into
typed :class:`CostRecord`\\ s at the step-cache miss the r15 compile
span already instruments (``utils.cache.LRUCache.get_or_create``
calls :func:`instrument` on every MISS), and layers the analytic
roofline on top (:func:`analytic_step_flops`, :func:`crosscheck`,
:func:`roofline_fields`).

Capture contract (mirrors the tracer's):

* OFF by default; :func:`instrument` with no collector installed is one
  ``None`` check returning the value untouched — the ``obs=0`` parity
  oracle holds trivially and the warm path never changes.
* When a :func:`collecting` scope is active, a cache MISS wraps the
  built program(s) in a one-shot capturing proxy.  On the program's
  FIRST call the proxy AOT-lowers it against the real call's arguments
  (``fn.lower(*args).compile()`` — shape/dtype/sharding only, the
  buffers are never read, so donated inputs are safe) and records the
  analyses; the real call then proceeds through the jit path unchanged.
  Capture adds ZERO dispatches (the AOT executable is analyzed, never
  executed) and changes no numerics; it costs one extra XLA compile per
  captured program, deduplicated by the persistent compilation cache
  when one is enabled.
* A backend that cannot report (or reports partially) yields a record
  with ``available=False`` and never fails the fit, the compile, or the
  recompilation sentinel — degraded observability is still
  observability.

Semantics worth knowing (documented, load-bearing):

* **Analyses are per-device.**  XLA runs them on the post-SPMD-
  partitioning module — the program ONE device executes — so reported
  flops/bytes are already "after mesh division".  ``n_devices`` (from
  the argument sharding) is recorded so totals are derivable.
* **Loop bodies are counted once.**  HLO cost analysis does not
  multiply by trip counts: a ``while_loop`` fit program reports ONE
  iteration's cost, and a ``scan``-chunked pass reports ONE CHUNK's.
  :func:`analytic_step_flops` applies the same convention to the hand
  formulas so the cross-check compares like with like.
* **Peak is per-program, not allocator-global.**  ``peak_bytes`` is
  the executable's arg+output+temp footprint (minus aliased buffers);
  other resident buffers (datasets, other models' tables) share the
  allocator, so the footprint planner (:mod:`kmeans_tpu.obs.memory`)
  treats it as a component, not the device total.

Pure stdlib at import (jax loads lazily at capture time) — importable
from every layer including ``utils.cache``.
"""

from __future__ import annotations

import contextlib
import json
import re
import threading
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional

from kmeans_tpu.obs import trace as _trace
from kmeans_tpu.obs.metrics_registry import REGISTRY

__all__ = ["CostRecord", "CostCollector", "collecting", "get_collector",
           "instrument", "analyze_jitted", "normalize_compiled",
           "analytic_step_flops", "crosscheck", "roofline_fields",
           "hlo_collective_bytes", "FLOPS_AGREEMENT_RTOL"]

#: The committed analytic-vs-XLA FLOPs agreement band (pre-registered,
#: the repo's decision-rule discipline): |reported/analytic - 1| <= 10%
#: on the kmeans and gmm-diag step programs.  A larger mismatch is a
#: REPORTED finding (``crosscheck()['agree'] = False`` in the bench/CLI
#: artifacts), never silently trusted in an MFU row.
FLOPS_AGREEMENT_RTOL = 0.10


@dataclass
class CostRecord:
    """One compiled program's device-cost analysis, normalized.

    All byte/flop figures are PER-DEVICE (see the module docstring);
    ``None`` means the backend did not report that figure.  ``key`` is
    the (truncated) repr of the compile-cache key, so a record joins
    back to the compile span that built the program.
    """

    cache: str
    key: str
    role: Optional[int] = None        # index inside a tuple cache entry
    backend: str = "?"
    n_devices: int = 1
    available: bool = False
    error: Optional[str] = None
    flops: Optional[float] = None
    transcendentals: Optional[float] = None
    bytes_accessed: Optional[float] = None
    arg_bytes: Optional[int] = None
    out_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    alias_bytes: Optional[int] = None
    code_bytes: Optional[int] = None
    peak_bytes: Optional[int] = None  # arg + out + temp - alias
    # Collective-comms accounting (ISSUE 13): result-shape bytes and
    # instruction count of the all-reduce/all-gather/reduce-scatter/
    # all-to-all/collective-permute ops in the compiled (post-SPMD)
    # module, one loop-body pass — the MEASURED side the fleet layer's
    # analytic byte model (obs.fleet.comm_bytes_model) cross-checks
    # against.  None when the backend exposes no HLO text.
    collective_bytes: Optional[float] = None
    collectives: Optional[int] = None

    def arithmetic_intensity(self) -> Optional[float]:
        """flops / bytes-accessed — the roofline x-axis; None when
        either figure is unreported or bytes are zero."""
        if self.flops is None or not self.bytes_accessed:
            return None
        return self.flops / self.bytes_accessed

    def to_dict(self) -> dict:
        d = asdict(self)
        d["ai"] = self.arithmetic_intensity()
        return d


# ------------------------------------------------------------ collector

#: Process-wide active collector (None = capture off, the default).
_COLLECTOR: Optional["CostCollector"] = None


class CostCollector:
    """Sink for captured :class:`CostRecord`\\ s.

    Thread-safe (serving captures from queue workers); one record per
    (cache, key, role) — a program is analyzed once, on its first call.
    Each accepted record also writes through the shared surfaces:
    ``cost.captured`` / ``cost.unavailable`` registry counters, the
    ``cost.peak_bytes`` gauge (max seen), and — when a tracer is active
    — an instant ``cost.record`` event on the span timeline, so trace
    JSONL carries the records for ``trace summarize --cost``.
    """

    def __init__(self):
        self.closed = False
        self._lock = threading.Lock()
        self._records: List[CostRecord] = []
        self._seen: set = set()

    def add(self, rec: CostRecord) -> bool:
        ident = (rec.cache, rec.key, rec.role)
        with self._lock:
            if self.closed or ident in self._seen:
                return False
            self._seen.add(ident)
            self._records.append(rec)
        REGISTRY.counter("cost.captured" if rec.available
                         else "cost.unavailable").inc()
        if rec.available and rec.peak_bytes is not None:
            g = REGISTRY.gauge("cost.peak_bytes")
            if g.value is None or rec.peak_bytes > g.value:
                g.set(rec.peak_bytes)
        _trace.event("cost.record", **{
            k: v for k, v in rec.to_dict().items() if v is not None})
        return True

    def records(self) -> List[CostRecord]:
        with self._lock:
            return list(self._records)

    def by_cache(self) -> Dict[str, List[CostRecord]]:
        out: Dict[str, List[CostRecord]] = {}
        for rec in self.records():
            out.setdefault(rec.cache, []).append(rec)
        return out

    def max_metrics(self) -> dict:
        """Max available per-device peak bytes / flops across captured
        programs — the step program dominates both, so these are the
        heartbeat's ``mem_peak_bytes``/``program_flops`` fields."""
        peaks = [r.peak_bytes for r in self.records()
                 if r.available and r.peak_bytes is not None]
        flops = [r.flops for r in self.records()
                 if r.available and r.flops is not None]
        return {"mem_peak_bytes": max(peaks) if peaks else None,
                "program_flops": max(flops) if flops else None}

    def write_jsonl(self, path) -> None:
        with open(path, "w") as f:
            for rec in self.records():
                f.write(json.dumps(rec.to_dict(), default=str) + "\n")


def get_collector() -> Optional[CostCollector]:
    """The active collector, or None (capture off — the default)."""
    return _COLLECTOR


@contextlib.contextmanager
def collecting(path=None, collector: Optional[CostCollector] = None):
    """Install a cost collector for the ``with`` body (nested scopes
    shadow, the ``tracing``/``heartbeat`` discipline); on exit restore
    the previous one, mark the scope's collector closed (a cached proxy
    whose first call lands later must not capture into a dead scope),
    and write the records as JSONL when ``path`` is given.

    Usage::

        with obs.cost.collecting() as col:
            model.fit(X)          # step-cache MISSES are captured
        for rec in col.records():
            print(rec.cache, rec.flops, rec.peak_bytes)
    """
    global _COLLECTOR
    col = collector if collector is not None else CostCollector()
    prev, _COLLECTOR = _COLLECTOR, col
    try:
        yield col
    finally:
        _COLLECTOR = prev
        col.closed = True
        if path is not None:
            col.write_jsonl(path)


# -------------------------------------------------------- normalization

#: HLO dtype -> element bytes, for the collective-shape parser.
_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16,
}

_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "all-to-all", "collective-permute")

#: One collective instruction: ``%name = <result shapes> <op>(...)``.
#: The result segment may be a tuple — every dtype[shape] token in it
#: is summed.  ``-start`` variants (async collectives) are counted at
#: the start instruction only (the ``-done`` re-states the same shape).
_COLLECTIVE_RE = re.compile(
    r"= (?P<result>[^=]*?) (?P<op>" + "|".join(_COLLECTIVE_OPS)
    + r")(?:-start)?\(")
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def hlo_collective_bytes(hlo_text: str) -> dict:
    """Sum the RESULT-shape bytes of every collective instruction in an
    HLO module dump: ``{"bytes", "count", "by_op": {op: bytes}}``.

    Conventions (matching XLA's own cost analysis, so these compose
    with :class:`CostRecord`): per-device (the post-SPMD module is one
    device's program), one loop-body pass (a collective inside a
    ``scan``/``while`` body appears — and is counted — once), and
    RESULT bytes (an all-reduce's result equals its payload; an
    all-gather's result is ``shards x local``, the bytes the device
    actually materializes).  Wire traffic per device on a ring is
    ``2 (S-1)/S`` of the all-reduce payload — a topology statement the
    fleet layer derives separately; this function reports what the
    compiled program SAYS it moves."""
    total = 0.0
    count = 0
    by_op: Dict[str, float] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        tokens = _SHAPE_RE.findall(m.group("result"))
        if m.group(0).endswith("-start("):
            # Async form: the -start result tuple re-states the operand
            # alongside the true result — keep the result half only.
            tokens = tokens[(len(tokens) + 1) // 2:]
        nbytes = 0.0
        for dtype, dims in tokens:
            if dtype not in _HLO_DTYPE_BYTES:
                continue
            elems = 1
            for d in dims.split(","):
                if d:
                    elems *= int(d)
            nbytes += elems * _HLO_DTYPE_BYTES[dtype]
        if nbytes == 0.0:
            continue                      # token-shaped / degenerate
        total += nbytes
        count += 1
        op = m.group("op")
        by_op[op] = by_op.get(op, 0.0) + nbytes
    return {"bytes": total, "count": count, "by_op": by_op}


def _cost_dict(compiled) -> Optional[dict]:
    """``cost_analysis()`` result as one flat dict (jax returns a
    one-element list on some versions, a dict on others), or None."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return ca if isinstance(ca, dict) else None


def normalize_compiled(compiled, *, cache: str = "adhoc", key: str = "",
                       role: Optional[int] = None, backend: str = "?",
                       n_devices: int = 1) -> CostRecord:
    """One :class:`CostRecord` from a jax ``Compiled`` (or anything
    shaped like one).  Never raises: an analysis that throws or reports
    partially yields ``available=False`` with the failure named in
    ``error`` and every figure that WAS reported kept — the degraded-
    backend contract tests/test_cost.py pins."""
    rec = CostRecord(cache=cache, key=key, role=role, backend=backend,
                     n_devices=int(n_devices))
    errors = []
    try:
        ca = _cost_dict(compiled)
        if ca is None:
            errors.append("cost_analysis: unreported")
        else:
            flops = ca.get("flops")
            rec.flops = float(flops) if flops is not None else None
            ba = ca.get("bytes accessed")
            rec.bytes_accessed = float(ba) if ba is not None else None
            tr = ca.get("transcendentals")
            rec.transcendentals = float(tr) if tr is not None else None
            if rec.flops is None:
                errors.append("cost_analysis: no flops key")
    except Exception as e:  # noqa: BLE001 — backend-specific failures
        errors.append(f"cost_analysis: {type(e).__name__}: {e}")
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            errors.append("memory_analysis: unreported")
        else:
            rec.arg_bytes = _int_attr(ma, "argument_size_in_bytes")
            rec.out_bytes = _int_attr(ma, "output_size_in_bytes")
            rec.temp_bytes = _int_attr(ma, "temp_size_in_bytes")
            rec.alias_bytes = _int_attr(ma, "alias_size_in_bytes")
            rec.code_bytes = _int_attr(ma, "generated_code_size_in_bytes")
            parts = (rec.arg_bytes, rec.out_bytes, rec.temp_bytes)
            if any(p is None for p in parts):
                errors.append("memory_analysis: partial sizes")
            else:
                rec.peak_bytes = (rec.arg_bytes + rec.out_bytes
                                  + rec.temp_bytes
                                  - (rec.alias_bytes or 0))
    except Exception as e:  # noqa: BLE001 — backend-specific failures
        errors.append(f"memory_analysis: {type(e).__name__}: {e}")
    try:
        # Collective accounting (ISSUE 13): best-effort AND silent — a
        # backend without an HLO text dump leaves the fields None
        # without polluting `error` or `available` (flops/peak are the
        # record's contract; comm_crosscheck reports agree=None for
        # the missing-measurement case).
        txt = compiled.as_text()
        if isinstance(txt, str) and txt:
            coll = hlo_collective_bytes(txt)
            rec.collective_bytes = coll["bytes"]
            rec.collectives = coll["count"]
    except Exception:  # noqa: BLE001 — auxiliary capture, degrade silently
        pass
    rec.available = rec.flops is not None and rec.peak_bytes is not None
    rec.error = "; ".join(errors) if errors else None
    return rec


def _int_attr(obj, name: str) -> Optional[int]:
    v = getattr(obj, name, None)
    try:
        return int(v) if v is not None else None
    except (TypeError, ValueError):
        return None


def _args_n_devices(args, kwargs) -> int:
    """Devices participating in the call, read off the first sharded
    argument (the analyses are per-device; this makes totals
    derivable).  1 when nothing is sharded or jax is unavailable."""
    try:
        import jax
        for leaf in jax.tree_util.tree_leaves((args, kwargs)):
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None:
                return max(1, len(sharding.device_set))
    except Exception:  # noqa: BLE001 — observability only
        pass
    return 1


def analyze_jitted(fn, *args, cache: str = "adhoc", key: str = "",
                   role: Optional[int] = None, **kwargs) -> CostRecord:
    """AOT-analyze a jitted function against concrete call arguments:
    ``fn.lower(*args, **kwargs).compile()`` (avals only — buffers are
    never read, donation-safe) normalized into a :class:`CostRecord`.
    Never raises and never dispatches; a function without ``lower`` (or
    a backend that cannot compile AOT) yields ``available=False``."""
    backend = "?"
    try:
        import jax
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — observability only
        pass
    n_dev = _args_n_devices(args, kwargs)
    try:
        lower = getattr(fn, "lower", None)
        if lower is None:
            raise TypeError(f"{type(fn).__name__} has no .lower — not "
                            f"an AOT-analyzable program")
        compiled = lower(*args, **kwargs).compile()
    except Exception as e:  # noqa: BLE001 — capture must never fail a fit
        return CostRecord(cache=cache, key=key, role=role, backend=backend,
                          n_devices=n_dev, available=False,
                          error=f"lower/compile: {type(e).__name__}: {e}")
    return normalize_compiled(compiled, cache=cache, key=key, role=role,
                              backend=backend, n_devices=n_dev)


# ------------------------------------------------------- capture proxy

class _CapturedProgram:
    """One-shot capturing proxy around a cached compiled-function: the
    first call AOT-analyzes the program against the call's own
    arguments, every call delegates to the wrapped function unchanged
    (same jit path, same numerics, zero extra dispatches).  Attribute
    access falls through, so ``.lower``/jit introspection keep working.
    """

    __slots__ = ("_fn", "_cache", "_key", "_role", "_collector", "_done")

    def __init__(self, fn, cache: str, key: str, role: Optional[int],
                 collector: CostCollector):
        self._fn = fn
        self._cache = cache
        self._key = key
        self._role = role
        self._collector = collector
        self._done = False

    def __call__(self, *args, **kwargs):
        if not self._done:
            # Benign race: two threads may both analyze; the collector
            # dedupes by (cache, key, role), so at worst one redundant
            # AOT compile — never a wrong record.
            self._done = True
            if not self._collector.closed:
                try:
                    rec = analyze_jitted(
                        self._fn, *args, cache=self._cache,
                        key=self._key, role=self._role, **kwargs)
                except Exception as e:  # noqa: BLE001 — never fail a fit
                    # analyze_jitted is non-raising by design; this
                    # guard covers a patched/broken analyzer too —
                    # degraded capture must never take the fit down.
                    rec = CostRecord(
                        cache=self._cache, key=self._key,
                        role=self._role, available=False,
                        error=f"capture: {type(e).__name__}: {e}")
                try:
                    self._collector.add(rec)
                except Exception:  # noqa: BLE001 — broken collector
                    pass
        return self._fn(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)


def instrument(cache_name: str, key, value):
    """The ``LRUCache.get_or_create`` MISS hook: wrap the freshly built
    program(s) for capture when a collector is active; return ``value``
    untouched otherwise (one ``None`` check — the disabled-path
    contract).  Tuple-valued entries (kmeans' ``(step_fn, predict_fn)``
    pair) keep their structure, each callable member wrapped with its
    index as ``role``; non-callable values pass through."""
    col = _COLLECTOR
    if col is None:
        return value
    key_repr = repr(key)[:160]
    if isinstance(value, tuple):
        return tuple(
            _CapturedProgram(v, cache_name, key_repr, i, col)
            if callable(v) else v
            for i, v in enumerate(value))
    if callable(value):
        return _CapturedProgram(value, cache_name, key_repr, None, col)
    return value


# ------------------------------------------------------------- roofline

def analytic_step_flops(family: str, n: int, d: int, k: int, *,
                        chunk: Optional[int] = None, n_devices: int = 1,
                        cov_type: str = "diag") -> float:
    """The hand-derived FLOPs of ONE compiled step-program pass, under
    the same conventions XLA's cost analysis uses (per-device rows;
    loop bodies counted once, so a ``scan``-chunked program counts one
    chunk) — the roofline cross-check's analytic side.  Families:
    ``kmeans``/``spherical``/``bisecting``/``minibatch`` (the Lloyd
    4·rows·D·k pass; minibatch rows = its batch) and ``gmm`` (per
    ``cov_type``, ``benchmarks.gmm_flops_per_iter``)."""
    from kmeans_tpu.benchmarks import (gmm_flops_per_iter,
                                       kmeans_flops_per_iter)
    rows = -(-int(n) // max(1, int(n_devices)))
    if chunk:
        rows = min(rows, int(chunk))
    if family == "gmm":
        return gmm_flops_per_iter(rows, d, k, cov_type)
    if family in ("kmeans", "spherical", "bisecting", "minibatch"):
        return kmeans_flops_per_iter(rows, d, k)
    raise ValueError(f"unknown family {family!r}")


def crosscheck(analytic_flops: float, record: CostRecord,
               rtol: float = FLOPS_AGREEMENT_RTOL) -> dict:
    """Analytic-vs-XLA FLOPs agreement for one program: ``ratio`` =
    reported/analytic, ``agree`` = within ``rtol`` (the committed 10%
    band).  XLA counts every elementwise/reduction op while the hand
    formulas count only the real matmul work (padding and bookkeeping
    get no credit — the repo's MFU definition), so the ratio runs
    slightly ABOVE 1 and shrinks as D·k grows; a mismatch beyond the
    band is a reported finding, not a silently trusted number."""
    ratio = (record.flops / analytic_flops
             if record.flops is not None and analytic_flops > 0 else None)
    return {"analytic_flops": analytic_flops,
            "reported_flops": record.flops,
            "ratio": ratio,
            "agree": bool(ratio is not None
                          and abs(ratio - 1.0) <= rtol),
            "rtol": rtol}


def roofline_fields(analytic_flops: float, seconds: Optional[float],
                    record: Optional[CostRecord] = None,
                    peak_tflops: Optional[float] = None) -> dict:
    """The three roofline columns a BASELINE row carries:
    ``analytic_flops`` (the hand formula), ``ai`` (XLA flops/bytes when
    a record is available, else None), and ``mfu_analytic`` (analytic
    flops over measured seconds against the pinned peak; None without a
    peak — the CPU container publishes the flops so the MFU is
    derivable the moment a peak is pinned)."""
    ai = record.arithmetic_intensity() if record is not None else None
    mfu = None
    if peak_tflops and seconds and seconds > 0:
        mfu = analytic_flops / seconds / (peak_tflops * 1e12)
    return {"analytic_flops": analytic_flops, "ai": ai,
            "mfu_analytic": mfu}
