"""Unified telemetry layer (ISSUE 11): spans, metrics, heartbeats.

Three parts, one discipline:

* :mod:`kmeans_tpu.obs.trace` — process-wide span tracing of the
  lifecycle phases an operator waits on (place/stage/compile/seed/
  dispatch/segment/checkpoint/io/serve), exported as JSONL and Chrome
  ``trace_event`` timelines.
* :mod:`kmeans_tpu.obs.metrics_registry` — typed counters/gauges/
  histograms the existing ad-hoc signals write through (model audit
  attrs and serving counters keep their public APIs).
* :mod:`kmeans_tpu.obs.heartbeat` — opt-in fit-progress records to a
  callback or JSONL file, driven from boundaries the fit already pays
  (zero extra dispatches) — the health channel ROADMAP item 1's
  orchestration loop consumes.

Telemetry is OFF by default and the disabled path is a true no-op
(one None check); ``obs=0`` is the bit-exact parity oracle, pinned for
all five model families by tests/test_obs.py.  Quick start::

    from kmeans_tpu import obs

    with obs.tracing("fit.jsonl") as tr:
        model.fit(X)
    print(obs.format_phase_table(obs.time_to_first_iteration(
        tr.records())))

The trace/metrics/heartbeat modules are pure stdlib (no jax/numpy), so
every layer — including ``utils.cache``, which emits the compile spans
— can import them without cost or cycles; the report helpers (which
pull ``utils.profiling``) load lazily.
"""

from kmeans_tpu.obs.trace import (SPAN_NAMES, TraceReadError, Tracer,
                                  chrome_events, event, get_tracer,
                                  read_jsonl, span, summarize, tracing)
from kmeans_tpu.obs.metrics_registry import (REGISTRY, Counter, Gauge,
                                             Histogram, MetricsRegistry,
                                             registry)
# NOTE: re-exporting the `heartbeat` SCOPE function shadows the
# `kmeans_tpu.obs.heartbeat` submodule as a package attribute —
# `from kmeans_tpu.obs import heartbeat` yields the function.  In-
# package consumers therefore import names straight from the
# submodule (`from kmeans_tpu.obs.heartbeat import note_progress`),
# which resolves via sys.modules and is immune to the shadowing.
from kmeans_tpu.obs.heartbeat import (Heartbeat, get_heartbeat, heartbeat,
                                      note_progress)

__all__ = [
    "SPAN_NAMES", "TraceReadError", "Tracer", "chrome_events", "event",
    "get_tracer", "read_jsonl", "span", "summarize", "tracing",
    "REGISTRY", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "registry", "Heartbeat", "get_heartbeat", "heartbeat",
    "note_progress",
    # lazy (pull utils.profiling, which imports jax):
    "ttfi_ladder", "time_to_first_iteration", "format_phase_table",
]

_LAZY_REPORT = ("ttfi_ladder", "time_to_first_iteration",
                "format_phase_table", "TTFI_PHASES")


def __getattr__(name):
    if name in _LAZY_REPORT:
        from kmeans_tpu.obs import report
        return getattr(report, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
