"""Unified telemetry layer (ISSUE 11 + 12): spans, metrics, heartbeats,
device cost.

Four parts, one discipline:

* :mod:`kmeans_tpu.obs.trace` — process-wide span tracing of the
  lifecycle phases an operator waits on (place/stage/compile/seed/
  dispatch/segment/checkpoint/io/serve), exported as JSONL and Chrome
  ``trace_event`` timelines.
* :mod:`kmeans_tpu.obs.metrics_registry` — typed counters/gauges/
  histograms the existing ad-hoc signals write through (model audit
  attrs and serving counters keep their public APIs).
* :mod:`kmeans_tpu.obs.heartbeat` — opt-in fit-progress records to a
  callback or JSONL file, driven from boundaries the fit already pays
  (zero extra dispatches) — the health channel ROADMAP item 1's
  orchestration loop consumes.
* :mod:`kmeans_tpu.obs.cost` / :mod:`kmeans_tpu.obs.memory` — device-
  cost capture (XLA cost/memory analysis per compiled step-cache
  program, ISSUE 12) and the HBM footprint planner built on it.
* :mod:`kmeans_tpu.obs.fleet` / :mod:`kmeans_tpu.obs.identity` — the
  fleet layer (ISSUE 13): per-process telemetry identity and sink
  paths, clock-aligned merged timelines over N hosts' streams,
  analytic collective-comms accounting cross-checked against the
  compiled HLO, and the straggler report behind
  ``python -m kmeans_tpu fleet-status``.
* :mod:`kmeans_tpu.obs.drift` — serving-quality & drift observability
  (ISSUE 14): PSI/JS assignment-distribution detectors, rolling
  score-per-row ratio vs the fit-time reference profile, bf16-guard
  margin shift — committed thresholds + debounce, per-model JSONL
  sinks, and the report behind ``python -m kmeans_tpu serve-status``.
  The one obs module that imports numpy (array detectors), so it
  loads LAZILY — ``obs.drift`` / ``from kmeans_tpu.obs import drift``
  both work, and the package itself stays stdlib at import.

Telemetry is OFF by default and the disabled path is a true no-op
(one None check); ``obs=0`` is the bit-exact parity oracle, pinned for
all five model families by tests/test_obs.py.  Quick start::

    from kmeans_tpu import obs

    with obs.tracing("fit.jsonl") as tr, obs.cost.collecting() as col:
        model.fit(X)
    print(obs.format_phase_table(obs.time_to_first_iteration(
        tr.records())))
    for rec in col.records():
        print(rec.cache, rec.flops, rec.peak_bytes)

The trace/metrics/heartbeat/cost/memory modules are pure stdlib at
import (no jax/numpy), so every layer — including ``utils.cache``,
which emits the compile spans and the cost-capture hook — can import
them without cost or cycles; the report helpers (which pull
``utils.profiling``) load lazily.

NAMESPACE GOTCHA, resolved deliberately (r15 wart, closed r18):
re-exporting the ``heartbeat`` SCOPE FUNCTION shadows the
``kmeans_tpu.obs.heartbeat`` submodule as a package attribute —
``obs.heartbeat`` IS the callable (the documented scope-manager
surface), while the module stays importable as ``from
kmeans_tpu.obs.heartbeat import note_progress`` (resolved via
sys.modules, immune to the shadowing).  The submodule's public names
— ``note_progress``, ``Heartbeat``, ``get_heartbeat`` — are therefore
ALSO re-exported at package level below, and since r18 that is the
SUPPORTED consumer spelling: every in-repo consumer imports them from
``kmeans_tpu.obs`` (the models' fit boundaries included), so nothing
reaches through the shadowed attribute anymore.  Back-compat for both
routes — the package-level names, the submodule path, and the
callable-shadows-module behavior — is pinned by
tests/test_quality.py::test_obs_heartbeat_namespace_backcompat.
"""

from kmeans_tpu.obs import cost, fleet, identity, memory
from kmeans_tpu.obs.trace import (SPAN_NAMES, TraceReadError, Tracer,
                                  chrome_events, event, get_tracer,
                                  read_jsonl, span, summarize, tracing)
from kmeans_tpu.obs.metrics_registry import (REGISTRY, Counter, Gauge,
                                             Histogram, MetricsRegistry,
                                             registry)
# This import block MUST stay last: binding the `heartbeat` callable is
# what shadows the submodule attribute (see the docstring), and the
# package-level re-exports of Heartbeat/get_heartbeat/note_progress are
# the supported spelling for everything else the submodule exports.
from kmeans_tpu.obs.heartbeat import (Heartbeat, get_heartbeat, heartbeat,
                                      note_progress)

__all__ = [
    "SPAN_NAMES", "TraceReadError", "Tracer", "chrome_events", "event",
    "get_tracer", "read_jsonl", "span", "summarize", "tracing",
    "REGISTRY", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "registry", "Heartbeat", "get_heartbeat", "heartbeat",
    "note_progress", "cost", "memory", "fleet", "identity", "drift",
    # lazy (pull utils.profiling, which imports jax):
    "ttfi_ladder", "time_to_first_iteration", "format_phase_table",
    "merge_cost", "format_cost_table",
]

_LAZY_REPORT = ("ttfi_ladder", "time_to_first_iteration",
                "format_phase_table", "TTFI_PHASES", "merge_cost",
                "format_cost_table", "device_cost_report")


def __getattr__(name):
    if name in _LAZY_REPORT:
        from kmeans_tpu.obs import report
        return getattr(report, name)
    if name == "drift":
        # Lazy: drift is the one obs module that imports numpy (see
        # the docstring); loading it here instead of eagerly keeps the
        # package stdlib at import for utils.cache and the linter.
        # importlib (not `from ... import`): the from-form re-enters
        # this __getattr__ before the submodule import runs.
        import importlib
        return importlib.import_module("kmeans_tpu.obs.drift")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
