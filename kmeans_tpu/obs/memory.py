"""HBM footprint planner: predict per-device peak bytes before dispatch.

ISSUE 12 (b).  The OOM story before this module was reactive: dispatch,
catch ``RESOURCE_EXHAUSTED``, halve the chunk, replay
(``models.fault_tolerance._dispatch_oom_safe``).  This module is the
predictive half: :func:`plan_fit` models a family's per-device working
set from the shapes alone (the same padding/sharding arithmetic the fit
actually performs), optionally joined with captured
:class:`~kmeans_tpu.obs.cost.CostRecord`\\ s for the XLA-observed
per-program peak, and :func:`advise_dispatch` runs the comparison
against the device's free memory as an ADVISORY pre-dispatch check —
logged and recorded, never steering: ``chunk`` semantics and every
parity oracle stay bit-exact, and the reactive backoff remains the
enforcement path.

Planner caveats (documented, load-bearing):

* **XLA-reported peak is per-program, not allocator-global.**  A step
  program's arg+output+temp footprint shares the allocator with the
  resident dataset, other models' tables, and the staging buffers —
  the plan therefore models the RESIDENT set (points/weights/tables)
  and the per-dispatch temporaries separately and sums them; the
  observed per-program peak cross-checks the temporaries term only.
* **The model is an upper-bound sketch, not an allocator simulation.**
  XLA fuses, rematerializes, and reuses buffers; the plan's job is the
  operator question "will this chunk fit, roughly, before I pay the
  dispatch" — the committed predicted-vs-observed comparison
  (``BENCH_COST=1``) keeps it honest.

Pure stdlib at import; jax loads lazily inside
:func:`device_memory_info`.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kmeans_tpu.obs import trace as _trace
from kmeans_tpu.obs.metrics_registry import REGISTRY

__all__ = ["plan_fit", "plan_ingest", "device_memory_info",
           "advise_dispatch", "format_plan_table", "FAMILIES",
           "INGEST_SLAB_TARGET_BYTES"]

#: Families the planner models (the five shipped fit engines; the three
#: non-diag mixture covariance shapes ride on the ``cov_type`` knob).
FAMILIES = ("kmeans", "spherical", "bisecting", "minibatch", "gmm")

_DTYPE_BYTES = {"float32": 4, "float64": 8, "bfloat16": 2, "float16": 2}


def _itemsize(dtype) -> int:
    name = getattr(dtype, "name", None) or str(dtype)
    return _DTYPE_BYTES.get(name.replace("np.", "").replace("jnp.", ""), 4)


def plan_fit(family: str, n: int, d: int, k: int, *,
             data_shards: int = 1, model_shards: int = 1,
             dtype="float32", chunk: Optional[int] = None,
             cov_type: str = "diag", batch: Optional[int] = None,
             pipeline: int = 0, k_shard: int = 0, records=None) -> dict:
    """Predict one device's working set for a family's fit at a shape.

    Mirrors the real placement arithmetic: rows pad up to
    ``data_shards * chunk`` multiples (``parallel.sharding``), the
    centroid/parameter tables row-shard over ``model_shards``, and the
    per-dispatch temporary is the (chunk, k) distance/responsibility
    tile (doubled under the pipelined schedule, which carries two
    tiles in flight) plus the (k, d) stats accumulators.

    Returns a dict of per-device byte components plus
    ``predicted_resident_bytes`` (dataset + tables: survives the
    dispatch), ``predicted_temp_bytes`` (per-dispatch transient), and
    ``predicted_peak_bytes`` (their sum).  When ``records`` (an
    iterable of :class:`~kmeans_tpu.obs.cost.CostRecord`) holds an
    available record for the family's step cache, the XLA-observed
    per-program ``observed_peak_bytes`` joins the plan for the
    predicted-vs-observed comparison.

    ``k_shard`` (ISSUE 16) distinguishes the two TP placements of the
    k-means stats accumulators: the dense TP path (``k_shard=0``)
    psums FULL ``(k_pad, d)`` sums / ``(k_pad,)`` counts replicated on
    every device, while the k-sharded step keeps only the local
    ``(k_local, d)`` shard resident — the term sharding removes.  The
    distance tile is ``(chunk, k_local)`` under either placement, and
    at ``model_shards=1`` the knob is a no-op (``k_pad == k_local``).
    """
    if family not in FAMILIES:
        raise ValueError(f"unknown family {family!r}; families: "
                         f"{FAMILIES}")
    item = _itemsize(dtype)
    data_shards = max(1, int(data_shards))
    model_shards = max(1, int(model_shards))
    rows_local = -(-int(n) // data_shards)
    if chunk:
        chunk_eff = int(chunk)
        rows_local = -(-rows_local // chunk_eff) * chunk_eff
    else:
        chunk_eff = rows_local
    k_pad = -(-int(k) // model_shards) * model_shards
    k_local = k_pad // model_shards

    rows_for_data = int(batch) if (family == "minibatch" and batch) \
        else rows_local
    comp: Dict[str, int] = {
        "points_bytes": rows_local * d * item,
        "weights_bytes": rows_local * item,
    }
    # Tables are f32/f64 model state at the fit dtype; the distance/
    # responsibility tile accumulates in f32 regardless of a bf16 rung.
    tile_rows = min(chunk_eff, rows_for_data)
    if family == "gmm":
        cov_elems = {"diag": k_local * d, "spherical": k_local,
                     "tied": d * d, "full": k_local * d * d}
        if cov_type not in cov_elems:
            raise ValueError(f"unknown covariance type {cov_type!r}")
        comp["table_bytes"] = (2 * k_local * d + k_local
                               + cov_elems[cov_type]) * item
        # E-step holds the (chunk, k) log-density AND responsibility
        # tiles plus two (chunk, d) moment buffers (weighted points /
        # squares feeding the scatter) — matches the XLA-observed
        # per-program temp within ~10% on the CPU capture.
        comp["tile_bytes"] = (2 * tile_rows * k_local
                              + 2 * tile_rows * d) * 4
        comp["stats_bytes"] = (2 * k_local * d + k_local
                               + cov_elems[cov_type]) * 4
    else:
        # Distance tile + the one-hot/select tile the scatter matmul
        # consumes — two (chunk, k) f32 buffers live at the peak.
        comp["table_bytes"] = k_local * d * item
        comp["tile_bytes"] = 2 * tile_rows * k_local * 4
        # Dense TP replicates the full-k psum'd accumulators on every
        # device; the k-sharded step keeps only its local shard.
        k_stats = k_local if (k_shard and model_shards > 1) else k_pad
        comp["stats_bytes"] = (k_stats * d + k_stats) * 4
    if pipeline:
        comp["tile_bytes"] *= 2            # two chunk tiles in flight
    if family == "minibatch" and batch:
        comp["batch_bytes"] = int(batch) * d * item

    resident = comp["points_bytes"] + comp["weights_bytes"] \
        + comp["table_bytes"]
    temp = comp["tile_bytes"] + comp["stats_bytes"] \
        + comp.get("batch_bytes", 0)
    plan = {
        "family": family, "n": int(n), "d": int(d), "k": int(k),
        "cov_type": cov_type if family == "gmm" else None,
        "data_shards": data_shards, "model_shards": model_shards,
        "dtype": str(getattr(dtype, "name", dtype)),
        "chunk": chunk_eff, "pipeline": int(bool(pipeline)),
        "k_shard": int(k_shard), "components": comp,
        "predicted_resident_bytes": resident,
        "predicted_temp_bytes": temp,
        "predicted_peak_bytes": resident + temp,
        "observed_peak_bytes": None,
    }
    observed = _observed_peak(family, records)
    if observed is not None:
        plan["observed_peak_bytes"] = observed
    return plan


#: Staged-ingest slab granularity target (ISSUE 18): how many bytes of
#: host->device transfer the slabbed placement keeps in flight per slab.
#: 64 MB is large enough to amortize per-transfer dispatch overhead on
#: every PJRT backend measured and small enough that the double-buffered
#: pair (2 slabs in flight) stays far below any chip's HBM headroom; on
#: backends reporting allocator stats the effective target additionally
#: caps at 1/8 of the device's free bytes, so staging can never become
#: the allocation that OOMs the fit it feeds.
INGEST_SLAB_TARGET_BYTES = 64 << 20


def plan_ingest(n: int, d: int, *, data_shards: int = 1,
                chunk: int = 1, dtype="float32") -> dict:
    """Slab geometry for the staged ingest path (ISSUE 18): how the
    ``ingest='slab'`` placement groups device shards into staging slabs.

    Mirrors the placement arithmetic of ``parallel.sharding``: rows pad
    to ``data_shards * chunk`` multiples and each device shard holds
    ``n_pad / data_shards`` rows.  A slab is a group of WHOLE shards
    (``make_array_from_single_device_arrays`` assembles per-device
    buffers, so a shard is the smallest stageable unit); the group size
    targets :data:`INGEST_SLAB_TARGET_BYTES`, capped at 1/8 of the
    device's reported free bytes when the backend exposes allocator
    stats.  Double-buffering keeps at most two slabs in flight, so the
    transfer high-water is ``2 * slab_bytes``.
    """
    item = _itemsize(dtype)
    data_shards = max(1, int(data_shards))
    chunk = max(1, int(chunk))
    mult = data_shards * chunk
    n_pad = -(-int(n) // mult) * mult
    shard_rows = n_pad // data_shards
    shard_bytes = shard_rows * int(d) * item
    target = INGEST_SLAB_TARGET_BYTES
    free = device_memory_info()
    if free.get("available") and free.get("bytes_free"):
        target = min(target, max(free["bytes_free"] // 8, 1))
    slab_shards = max(1, min(data_shards,
                             target // max(shard_bytes, 1)))
    slabs = -(-data_shards // slab_shards)
    return {
        "n": int(n), "d": int(d), "n_pad": n_pad,
        "data_shards": data_shards, "chunk": chunk,
        "dtype": str(getattr(dtype, "name", dtype)),
        "shard_rows": shard_rows, "shard_bytes": shard_bytes,
        "slab_shards": slab_shards, "slabs": slabs,
        "slab_rows": slab_shards * shard_rows,
        "slab_bytes": slab_shards * shard_bytes,
        "target_bytes": target,
        "total_bytes": n_pad * int(d) * item,
    }


#: family -> the compile-cache whose step program carries that family's
#: footprint (the join key between a plan and captured CostRecords).
_FAMILY_CACHES = {
    "kmeans": "kmeans._STEP_CACHE",
    "spherical": "kmeans._STEP_CACHE",
    "bisecting": "kmeans._STEP_CACHE",
    "minibatch": "kmeans._STEP_CACHE",
    "gmm": "gmm._STEP_CACHE",
}


def _observed_peak(family: str, records) -> Optional[int]:
    """Largest available per-program peak among records from the
    family's step cache (the step program dominates)."""
    if not records:
        return None
    cache = _FAMILY_CACHES.get(family)
    peaks = [r.peak_bytes for r in records
             if r.available and r.peak_bytes is not None
             and (cache is None or r.cache == cache)]
    return max(peaks) if peaks else None


def device_memory_info() -> dict:
    """Best-effort allocator stats of the first local device:
    ``{"available": bool, "bytes_limit", "bytes_in_use",
    "bytes_free"}``.  CPU (and any backend without ``memory_stats``)
    reports ``available=False`` — the planner then prints the plan
    without a headroom verdict instead of failing."""
    try:
        import jax
        dev = jax.local_devices()[0]
        stats = dev.memory_stats()
        if not stats or "bytes_limit" not in stats:
            return {"available": False, "bytes_limit": None,
                    "bytes_in_use": None, "bytes_free": None}
        limit = int(stats["bytes_limit"])
        in_use = int(stats.get("bytes_in_use", 0))
        return {"available": True, "bytes_limit": limit,
                "bytes_in_use": in_use, "bytes_free": limit - in_use}
    except Exception as e:  # noqa: BLE001 — observability only
        return {"available": False, "bytes_limit": None,
                "bytes_in_use": None, "bytes_free": None,
                "error": f"{type(e).__name__}: {e}"}


def advise_dispatch(model, chunk: int, segment: int = 0) -> Optional[dict]:
    """Advisory pre-dispatch memory check for ``_dispatch_oom_safe``:
    with a tracer active, predict the (chunk, k) tile footprint from
    the model's host-side attrs, compare against the device's free
    bytes, emit a ``mem.plan`` event and set the
    ``fit.mem_planned_chunk`` gauge.  Returns the advisory dict, or
    None when tracing is off (the default true-no-op path — one check).
    Advisory ONLY: never raises, never changes the chunk, and a model
    the attrs cannot describe simply yields fewer fields."""
    if not _trace.active():
        return None
    try:
        k = getattr(model, "k", None) or getattr(model, "n_components",
                                                 None)
        cents = getattr(model, "centroids", None)
        if cents is None:
            cents = getattr(model, "means_", None)
        d = int(cents.shape[1]) if cents is not None \
            and getattr(cents, "ndim", 0) == 2 else None
        tile = int(chunk) * int(k) * 4 if k else None
        table = int(k) * d * 4 if (k and d) else None
        free = device_memory_info()
        advisory = {
            "segment": int(segment), "chunk": int(chunk),
            "k": int(k) if k else None, "d": d,
            "predicted_tile_bytes": tile,
            "predicted_table_bytes": table,
            "device_bytes_free": free.get("bytes_free"),
            "fits": (bool(tile <= free["bytes_free"])
                     if tile is not None and free.get("bytes_free")
                     is not None else None),
        }
        REGISTRY.gauge("fit.mem_planned_chunk").set(int(chunk))
        _trace.event("mem.plan", **{k_: v for k_, v in advisory.items()
                                    if v is not None})
        return advisory
    except Exception:  # noqa: BLE001 — advisory must never fail a fit
        return None


def _fmt_bytes(b: Optional[float]) -> str:
    if b is None:
        return "-"
    b = float(b)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(b) < 1024.0 or unit == "TB":
            return f"{b:.0f}{unit}" if unit == "B" else f"{b:.2f}{unit}"
        b /= 1024.0
    return f"{b:.2f}TB"


def format_plan_table(plans: List[dict],
                      title: str = "hbm footprint plan") -> str:
    """Fixed-width rendering of :func:`plan_fit` rows (the
    ``cost-report`` / ``dryrun_multichip`` artifact)."""
    lines = [f"{title} (per device):",
             f"  {'family':<10} {'shape':<22} {'chunk':>8} "
             f"{'resident':>10} {'temp':>10} {'predicted':>10} "
             f"{'observed':>10}"]
    for p in plans:
        shape = f"{p['n']}x{p['d']} k={p['k']}"
        if p.get("cov_type"):
            shape += f" {p['cov_type']}"
        lines.append(
            f"  {p['family']:<10} {shape:<22} {p['chunk']:>8} "
            f"{_fmt_bytes(p['predicted_resident_bytes']):>10} "
            f"{_fmt_bytes(p['predicted_temp_bytes']):>10} "
            f"{_fmt_bytes(p['predicted_peak_bytes']):>10} "
            f"{_fmt_bytes(p.get('observed_peak_bytes')):>10}")
    free = device_memory_info()
    if free.get("available"):
        lines.append(f"  device free: {_fmt_bytes(free['bytes_free'])} "
                     f"of {_fmt_bytes(free['bytes_limit'])}")
    else:
        lines.append("  device free: unreported on this backend")
    return "\n".join(lines)
