"""Telemetry identity: which process of which fleet produced a record.

ISSUE 13 (fleet observability) makes every telemetry surface — trace
spans, heartbeat records, metrics exports — carry the producing
process's coordinates, so N hosts' streams can be merged into one
timeline and attributed without guessing from file names:

* ``process_index`` / ``process_count`` — the jax.distributed
  coordinates when the process is part of an initialized multi-process
  job; ``0`` / ``1`` otherwise (a single-process fit IS a one-host
  fleet).
* ``host`` — the machine name (``socket.gethostname()``), the
  operator-facing label on merged-timeline tracks and straggler tables.

Resolution order (first hit wins):

1. ``KMEANS_TPU_PROCESS_INDEX`` / ``KMEANS_TPU_PROCESS_COUNT`` /
   ``KMEANS_TPU_HOST`` environment overrides — for harnesses that run a
   simulated fleet of plain processes (no jax.distributed), and for
   launchers that know the topology before jax does.
2. jax's ``process_index()``/``process_count()`` — read ONLY when jax
   is already imported AND ``jax.distributed`` reports initialized:
   probing jax from a telemetry call must never itself initialize the
   backends (that would pin single-process mode under a caller that
   planned to call ``jax.distributed.initialize`` later — the exact
   hazard ``parallel.multihost.initialize`` documents).
3. ``{process_index: 0, process_count: 1}`` — the single-process
   default.

The lookup is cheap but not free (env reads + a getattr chain), so the
tracer and heartbeat cache it per instance; a process's identity is
fixed for the lifetime of a telemetry scope by construction (scopes are
installed after ``jax.distributed.initialize`` in any multi-host
program — the mesh needs it first).

Pure stdlib — importable from every layer, like the rest of ``obs``.
"""

from __future__ import annotations

import os
import socket
import sys
from typing import Optional

__all__ = ["identity", "per_process_path"]


def _jax_coords() -> Optional[tuple]:
    """(index, count) from an ALREADY-initialized jax.distributed, else
    None.  Never imports jax and never initializes backends."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        probe = getattr(jax.distributed, "is_initialized", None)
        if probe is not None:
            initialized = bool(probe())
        else:                           # pre-0.6 jax: global_state probe
            from jax._src import distributed as _dist
            initialized = getattr(_dist.global_state, "client",
                                  None) is not None
        if not initialized:
            return None
        return int(jax.process_index()), int(jax.process_count())
    except Exception:  # noqa: BLE001 — telemetry must never raise here
        return None


def identity() -> dict:
    """``{"process_index", "process_count", "host"}`` for this process
    (see the module docstring for the resolution order)."""
    host = os.environ.get("KMEANS_TPU_HOST")
    if host is None:
        try:
            host = socket.gethostname()
        except Exception:  # noqa: BLE001 — containers without a hostname
            host = "?"
    env_idx = os.environ.get("KMEANS_TPU_PROCESS_INDEX")
    env_cnt = os.environ.get("KMEANS_TPU_PROCESS_COUNT")
    if env_idx is not None or env_cnt is not None:
        try:
            return {"process_index": int(env_idx or 0),
                    "process_count": int(env_cnt or 1), "host": host}
        except ValueError:
            pass                        # malformed override: fall through
    coords = _jax_coords()
    if coords is not None:
        return {"process_index": coords[0], "process_count": coords[1],
                "host": host}
    return {"process_index": 0, "process_count": 1, "host": host}


def per_process_path(path, process_index: int) -> str:
    """The per-process sink path: ``trace.jsonl`` -> ``trace.p3.jsonl``
    (suffix inserted before the final extension; appended when the path
    has none).  This is THE naming convention the fleet tools glob for
    (``obs.fleet.expand_fleet_paths``), fixing the r15 multi-host sink
    collision where every host opened the same file."""
    s = str(path)
    base, dot, ext = s.rpartition(".")
    if not dot or os.sep in ext or (os.altsep and os.altsep in ext):
        return f"{s}.p{process_index}"
    return f"{base}.p{process_index}.{ext}"
