"""Serving-quality & drift observability (ISSUE 14 tentpole).

r15-r17 instrumented the fit lifecycle, the device cost, and the
fleet; the model IN PRODUCTION was still blind — ``ServingEngine``
counted requests and dispatches, but nothing could say whether the
clusters still describe the traffic.  This module is that layer: pure
numpy detectors over ring-buffered traffic windows, fed ONLY by the
labels/distances serving dispatches already compute (the
zero-extra-dispatch rule), compared against a fit-time reference
profile the checkpoint carries — the concept-drift monitoring
discipline of Gama et al. (2014) applied to the one signal set that is
free at serve time.

Three detector families, one committed decision table:

* **Assignment-distribution shift** — PSI (population stability index)
  and Jensen-Shannon divergence between the serving window's
  assignment histogram and the training histogram from the reference
  :func:`build_profile`.  Both use the same empty-bin smoothing
  (:data:`HIST_SMOOTHING` added per bin before normalizing — a cluster
  that receives zero traffic must contribute a finite, bounded term,
  never an infinity).  Labels outside ``[0, k)`` are MASKED
  (:func:`assignment_counts`): the k-sweep / TP padding discipline
  pads centroid tables with inert sentinel rows, and a sentinel label
  leaking into a histogram would fabricate a phantom cluster.
* **Score shift** — rolling serving score-per-row over the reference's
  training score-per-row (``score_kind='sse'``: nearest-centroid
  squared distance, the K-Means family's inertia/row;
  ``'neg_log_lik'``: per-row negative log-likelihood, the mixture
  family's analogue).  The ratio rule is only sound for positive
  references; a non-positive ``score_per_row`` deactivates this
  detector (reported, never silently passed).
* **bf16-guard margin shift** — the fraction of guarded-path rows the
  near-tie guard re-labeled at f32.  Rising near-tie traffic means
  requests are migrating toward Voronoi boundaries — cluster blur, the
  earliest geometric drift signal the engine computes anyway.

Decision rules are COMMITTED constants (the fleet-status discipline:
pre-registered numbers, not prose) with a debounce: a detector firing
needs :data:`DRIFT_DEBOUNCE_WINDOWS` CONSECUTIVE breaching windows, so
one unlucky window of boundary traffic never pages anyone.  Events are
emitted three ways at once: a ``serve.drift`` tracer event, the
``serve.drift.*`` registry counters, and a per-model JSONL sink — the
stream ``python -m kmeans_tpu serve-status`` reads (exit 1 = drifting,
the trigger signal ROADMAP item 4's serve-and-learn loop consumes,
exactly as ``fleet-status`` exit 1 is the elastic orchestrator's).

This is the one ``obs`` module that imports numpy (the detectors are
array arithmetic over label batches); the package ``__init__`` loads
it lazily so ``kmeans_tpu.obs`` itself stays pure-stdlib at import.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from kmeans_tpu.obs import trace as _trace
from kmeans_tpu.obs.metrics_registry import registry as _registry

__all__ = [
    "PSI_ALERT", "JS_ALERT", "SCORE_RATIO_ALERT",
    "NEAR_TIE_FRAC_ALERT", "HIST_SMOOTHING", "DRIFT_WINDOW_ROWS",
    "DRIFT_DEBOUNCE_WINDOWS", "DRIFT_HISTORY_WINDOWS",
    "COMMITTED_THRESHOLDS", "PROFILE_VERSION",
    "assignment_counts", "psi", "js_divergence", "build_profile",
    "QualityMonitor", "read_quality_log", "quality_report",
    "format_quality_status",
]

# --------------------------------------------------------- committed rules

#: PSI alert threshold.  The industry-standard PSI bands are < 0.1
#: stable, 0.1-0.25 moderate shift, > 0.25 major shift; the committed
#: rule fires at the major-shift boundary — serving traffic whose
#: assignment mix moved this far no longer matches the clusters.
PSI_ALERT = 0.25

#: Jensen-Shannon divergence alert (base-2 logs, so the value is in
#: bits and bounded by 1.0).  0.1 bit corresponds to a clearly visible
#: redistribution of assignment mass; JS is the bounded second opinion
#: next to PSI's unbounded tails (PSI explodes on near-empty reference
#: bins even smoothed; JS cannot).
JS_ALERT = 0.10

#: Serving score-per-row over training score-per-row.  2.0 = requests
#: land on average twice as far from their nearest centroid (or at
#: twice the negative log-likelihood) as the training data did — the
#: rolling-SSE rule ROADMAP item 4 names.
SCORE_RATIO_ALERT = 2.0

#: Fraction of bf16-guarded rows the near-tie guard re-labeled at f32.
#: Separated traffic measures ~per-mille (the r11 serving tests);
#: uniform-random — the adversarial no-structure case — measured 45%
#: (r13 bench).  5% is an order of magnitude above the separated
#: baseline while far below the structureless ceiling: traffic
#: migrating to Voronoi boundaries.
NEAR_TIE_FRAC_ALERT = 0.05

#: Per-bin additive smoothing applied to BOTH histograms before
#: normalizing (empty serving bins and empty training bins alike), so
#: PSI/JS stay finite when a cluster receives zero traffic.
HIST_SMOOTHING = 1e-6

#: Rows per evaluation window.  Windows are row-counted, not
#: wall-clocked: detector variance is a function of sample size, and a
#: fixed row count makes the committed thresholds mean the same thing
#: at 10 QPS and 10k QPS.
DRIFT_WINDOW_ROWS = 512

#: Consecutive breaching windows before a drift event fires (and
#: consecutive clean windows before it clears).  One window of
#: boundary-heavy traffic is weather; two in a row is climate.
DRIFT_DEBOUNCE_WINDOWS = 2

#: Closed-window summaries retained in the ring buffer (the ``stats()``
#: / ``serve-status`` history depth; the JSONL sink keeps everything).
DRIFT_HISTORY_WINDOWS = 64

#: The committed decision table, detector name -> threshold — exported
#: as one dict so tests, ``serve-status``, and the docs pin the SAME
#: numbers (a drifted copy of a threshold is itself a drift bug).
COMMITTED_THRESHOLDS: Dict[str, float] = {
    "psi": PSI_ALERT,
    "js": JS_ALERT,
    "score_ratio": SCORE_RATIO_ALERT,
    "near_tie_frac": NEAR_TIE_FRAC_ALERT,
}

#: Reference-profile schema version (persisted in checkpoint metadata).
PROFILE_VERSION = 1

#: Record kinds a quality JSONL sink may contain (the ``serve-status``
#: classification rule; anything else in a stream is malformed).
#: ``update``/``rollback`` are the serve-and-learn actuator's decision
#: records (ISSUE 20): one line per in-place online update attempt and
#: one per rollback-to-last-good, written through the SAME per-model
#: sink the drift trigger writes — the multi-file reader aggregates
#: trigger and actuator into one per-model row.
QUALITY_KINDS = ("profile", "window", "drift", "recovered",
                 "update", "rollback")


# ------------------------------------------------------------- detectors

def assignment_counts(labels, k: int) -> np.ndarray:
    """(k,) float64 label counts with out-of-range labels MASKED.

    Sentinel/padded centroid rows (the k-sweep and TP padding
    discipline) can never legitimately win an assignment, but a
    histogram must be robust to one leaking through: labels outside
    ``[0, k)`` are dropped, not clipped — clipping would silently
    credit the first/last real cluster with phantom mass."""
    labels = np.asarray(labels).ravel()
    try:
        # Fast path (the per-dispatch serving feed): labels from an
        # argmin are non-negative, so bincount runs without the mask
        # allocations; sentinel labels >= k land in the tail and are
        # trimmed.
        counts = np.bincount(labels, minlength=int(k))
    except (ValueError, TypeError):
        # Negative or non-integer labels (hand-built test fixtures):
        # the masked slow path.
        valid = labels[(labels >= 0) & (labels < k)]
        counts = np.bincount(valid.astype(np.int64), minlength=int(k))
    return counts[: int(k)].astype(np.float64)


def _smoothed(hist, smoothing: float) -> np.ndarray:
    h = np.asarray(hist, np.float64) + float(smoothing)
    return h / h.sum()


def psi(ref: Sequence[float], cur: Sequence[float],
        smoothing: float = HIST_SMOOTHING) -> float:
    """Population stability index between two count/probability
    vectors: ``sum((c_i - r_i) * ln(c_i / r_i))`` over smoothed,
    normalized bins.  Symmetric in sign contributions, >= 0, unbounded
    above; the committed band is :data:`PSI_ALERT`."""
    r = _smoothed(ref, smoothing)
    c = _smoothed(cur, smoothing)
    if r.shape != c.shape:
        raise ValueError(f"histogram shapes differ: {r.shape} vs "
                         f"{c.shape}")
    return float(np.sum((c - r) * np.log(c / r)))


def js_divergence(ref: Sequence[float], cur: Sequence[float],
                  smoothing: float = HIST_SMOOTHING) -> float:
    """Jensen-Shannon divergence (base-2 logs -> bits, bounded [0, 1])
    between two count/probability vectors, smoothed like :func:`psi`."""
    r = _smoothed(ref, smoothing)
    c = _smoothed(cur, smoothing)
    if r.shape != c.shape:
        raise ValueError(f"histogram shapes differ: {r.shape} vs "
                         f"{c.shape}")
    m = 0.5 * (r + c)

    def _kl(a, b):
        return float(np.sum(a * np.log2(a / b)))

    return 0.5 * _kl(r, m) + 0.5 * _kl(c, m)


# ------------------------------------------------------- reference profile

def build_profile(*, family: str, model_class: str, k: int,
                  counts=None, score_kind: Optional[str] = None,
                  score_per_row: Optional[float] = None,
                  per_cluster_sse=None,
                  n_rows: Optional[float] = None) -> dict:
    """Assemble one JSON-ready reference profile (the checkpoint
    metadata block's ``quality_profile`` payload and the
    :class:`QualityMonitor` reference).

    ``counts`` is the raw training assignment mass per cluster
    (weighted sizes for the K-Means family, mixing weights for the
    mixture family); it is normalized here.  Every value is coerced to
    plain Python types — numpy scalars would break the checkpoint
    meta JSON."""
    if score_kind not in (None, "sse", "neg_log_lik"):
        raise ValueError(f"score_kind must be None, 'sse' or "
                         f"'neg_log_lik', got {score_kind!r}")
    hist = None
    if counts is not None:
        c = np.asarray(counts, np.float64).ravel()
        if c.shape[0] != int(k):
            raise ValueError(f"counts has {c.shape[0]} bins, model has "
                             f"k={k}")
        total = float(c.sum())
        if total > 0:
            hist = [float(v) for v in c / total]
    return {
        "profile_version": PROFILE_VERSION,
        "family": str(family),
        "model_class": str(model_class),
        "k": int(k),
        "n_rows": float(n_rows) if n_rows is not None else None,
        "assignment_hist": hist,
        "score_kind": score_kind,
        "score_per_row": (float(score_per_row)
                          if score_per_row is not None else None),
        "per_cluster_sse": ([float(v) for v in
                             np.asarray(per_cluster_sse,
                                        np.float64).ravel()]
                            if per_cluster_sse is not None else None),
    }


# ----------------------------------------------------------- the monitor

class QualityMonitor:
    """Per-resident-model drift monitor over ring-buffered traffic
    windows.

    Fed exclusively through :meth:`observe` with the host-side arrays
    serving dispatches already materialized — labels, per-row scores,
    bf16-guard correction counts.  Zero extra dispatches and zero
    writes into the dispatch outputs by construction (the monitor only
    READS); the obs=0 parity contract (monitoring on/off labels
    bit-equal) is therefore trivial and pinned by
    tests/test_quality.py.

    Thread-safe: serving dispatches arrive from the queue worker and
    from direct callers concurrently.  The JSONL sink follows the
    Heartbeat isolation discipline — a full disk or unserializable
    field is counted (``sink_errors``) and the sink disabled, never a
    serving failure.
    """

    def __init__(self, model_id: str, k: int, *,
                 profile: Optional[dict] = None,
                 window_rows: int = DRIFT_WINDOW_ROWS,
                 debounce: int = DRIFT_DEBOUNCE_WINDOWS,
                 thresholds: Optional[Dict[str, float]] = None,
                 sink_path=None,
                 history: int = DRIFT_HISTORY_WINDOWS):
        if window_rows <= 0:
            raise ValueError(f"window_rows must be positive, got "
                             f"{window_rows!r}")
        if debounce <= 0:
            raise ValueError(f"debounce must be positive, got "
                             f"{debounce!r}")
        if profile is not None and int(profile.get("k", k)) != int(k):
            raise ValueError(
                f"reference profile is for k={profile.get('k')}, "
                f"monitor serves k={k} — a mismatched reference would "
                f"compare histograms bin-by-bin across different "
                f"clusters")
        self.model_id = str(model_id)
        self.k = int(k)
        self.profile = profile
        self.window_rows = int(window_rows)
        self.debounce = int(debounce)
        self.thresholds = dict(COMMITTED_THRESHOLDS)
        if thresholds:
            unknown = sorted(set(thresholds) - set(self.thresholds))
            if unknown:
                raise ValueError(f"unknown detector thresholds "
                                 f"{unknown}; known: "
                                 f"{sorted(self.thresholds)}")
            self.thresholds.update(thresholds)
        self.sink_path = str(sink_path) if sink_path is not None else None
        self.sink_errors = 0
        self._file = None
        self._file_failed = False
        self._lock = threading.Lock()
        # Sink IO runs OUTSIDE _lock (emission must never serialize
        # dispatches) but still needs ITS OWN serialization: two
        # threads closing consecutive windows would otherwise
        # interleave JSON lines mid-write or double-open the lazy file
        # (review finding) — the Heartbeat _emit_lock discipline.
        self._sink_lock = threading.Lock()
        self._ref_hist = (np.asarray(profile["assignment_hist"],
                                     np.float64)
                          if profile and profile.get("assignment_hist")
                          else None)
        # Smoothed reference + its logs, computed ONCE: the window
        # close is on the serving dispatch path (every ~window_rows
        # rows), and re-smoothing a constant there is pure overhead
        # against the <=1.01 bench rule.
        if self._ref_hist is not None:
            self._ref_sm = _smoothed(self._ref_hist, HIST_SMOOTHING)
            self._ref_log = np.log(self._ref_sm)
        else:
            self._ref_sm = self._ref_log = None
        ref_score = profile.get("score_per_row") if profile else None
        # The ratio rule needs a positive reference (docstring); a
        # non-positive one deactivates the detector, visibly.
        self._ref_score = (float(ref_score)
                           if ref_score is not None and ref_score > 0
                           else None)
        # Current (open) window accumulators.
        self._counts = np.zeros(self.k, np.float64)
        self._label_rows = 0
        self._score_sum = 0.0
        self._score_rows = 0
        self._near_ties = 0
        self._guarded_rows = 0
        self._rows_in_window = 0
        # Lifetime state.
        self.windows = 0
        self.rows = 0
        self.events = 0
        self.drifting = False
        self._consecutive = 0
        self._clean_streak = 0
        self._history = deque(maxlen=int(history))
        if profile is not None:
            self._sink({"kind": "profile", "model": self.model_id,
                        "ts": time.time(), "profile": profile,
                        "thresholds": self.thresholds,
                        "window_rows": self.window_rows,
                        "debounce": self.debounce})

    # ---------------------------------------------------------- feeding

    def observe(self, rows: int, *, labels=None, score=None,
                near_ties: int = 0, guarded_rows: int = 0) -> None:
        """Fold one dispatch's already-computed outputs into the open
        window.  ``labels``: int labels (sentinels masked); ``score``:
        per-row scores in the profile's ``score_kind`` convention
        (nearest squared distance / negative log-likelihood);
        ``near_ties``/``guarded_rows``: the bf16 guard's correction
        count and the rows that went through the guarded path."""
        closed = None
        with self._lock:
            self._rows_in_window += int(rows)
            self.rows += int(rows)
            if labels is not None:
                self._counts += assignment_counts(labels, self.k)
                self._label_rows += int(np.asarray(labels).size)
            if score is not None:
                s = np.asarray(score, np.float64).ravel()
                self._score_sum += float(s.sum())
                self._score_rows += int(s.size)
            if guarded_rows:
                self._near_ties += int(near_ties)
                self._guarded_rows += int(guarded_rows)
            if self._rows_in_window >= self.window_rows:
                closed = self._close_window_locked()
        if closed is not None:
            self._emit(closed)

    # ----------------------------------------------------- window close

    def _close_window_locked(self) -> dict:
        """Evaluate the committed detectors over the closed window and
        advance the debounce state.  Returns the window summary (the
        caller emits OUTSIDE the lock — sink IO and tracer events must
        never serialize dispatches)."""
        detectors: Dict[str, Optional[float]] = {
            "psi": None, "js": None, "score_ratio": None,
            "near_tie_frac": None}
        if self._ref_hist is not None and self._label_rows > 0:
            # One smoothing pass + the cached reference logs feed BOTH
            # histogram detectors (this runs on the serving dispatch
            # path — op/allocation count matters; identical arithmetic
            # to psi()/js_divergence(), pinned by the unit fixtures).
            r, logr = self._ref_sm, self._ref_log
            c = _smoothed(self._counts, HIST_SMOOTHING)
            logc = np.log(c)
            detectors["psi"] = float(np.sum((c - r) * (logc - logr)))
            m = 0.5 * (r + c)
            logm = np.log(m)
            detectors["js"] = float(
                (0.5 * np.sum(r * (logr - logm))
                 + 0.5 * np.sum(c * (logc - logm))) / math.log(2.0))
        if self._ref_score is not None and self._score_rows > 0:
            detectors["score_ratio"] = (
                self._score_sum / self._score_rows) / self._ref_score
        if self._guarded_rows > 0:
            detectors["near_tie_frac"] = (self._near_ties
                                          / self._guarded_rows)
        breaching = sorted(
            name for name, v in detectors.items()
            if v is not None and v > self.thresholds[name])
        self.windows += 1
        fired = recovered = False
        # A window where NO detector could evaluate (e.g. filled by
        # transform-only traffic — rows but no labels/scores) is not
        # evidence in either direction: it must neither reset a breach
        # streak nor count toward recovery (review finding — info-free
        # windows interleaved with breaching ones would otherwise keep
        # drift from ever reaching the debounce, and two of them could
        # "recover" a drifting model with zero readings).
        informative = any(v is not None for v in detectors.values())
        if not informative:
            pass
        elif breaching:
            self._consecutive += 1
            self._clean_streak = 0
            if self._consecutive >= self.debounce and not self.drifting:
                self.drifting = True
                self.events += 1
                fired = True
        else:
            self._consecutive = 0
            self._clean_streak += 1
            if self.drifting and self._clean_streak >= self.debounce:
                self.drifting = False
                recovered = True
        summary = {
            "kind": "window", "model": self.model_id,
            "ts": time.time(), "window": self.windows,
            "rows": self._rows_in_window,
            "label_rows": self._label_rows,
            "score_rows": self._score_rows,
            "guarded_rows": self._guarded_rows,
            "detectors": detectors, "breaching": breaching,
            "informative": informative,
            "consecutive": self._consecutive,
            "drifting": self.drifting,
        }
        self._history.append(summary)
        self._counts = np.zeros(self.k, np.float64)
        self._label_rows = 0
        self._score_sum = 0.0
        self._score_rows = 0
        self._near_ties = 0
        self._guarded_rows = 0
        self._rows_in_window = 0
        return {**summary, "fired": fired, "recovered": recovered}

    def _emit(self, closed: dict) -> None:
        """Deliver one closed window: the JSONL record always; on a
        debounced state CHANGE additionally the drift/recovered record,
        the tracer event, and the registry counters."""
        fired = closed.pop("fired")
        recovered = closed.pop("recovered")
        reg = _registry()
        reg.counter("serve.drift.windows").inc()
        self._sink(closed)
        if fired:
            reg.counter("serve.drift.events").inc()
            for name in closed["breaching"]:
                reg.counter(f"serve.drift.{name}").inc()
            attrs = {f"detector_{n}": v
                     for n, v in closed["detectors"].items()
                     if v is not None}
            _trace.event("serve.drift", model=self.model_id,
                         breaching=",".join(closed["breaching"]),
                         window=closed["window"], **attrs)
            self._sink({**closed, "kind": "drift"})
        elif recovered:
            reg.counter("serve.drift.recovered").inc()
            _trace.event("serve.drift.recovered", model=self.model_id,
                         window=closed["window"])
            self._sink({**closed, "kind": "recovered"})

    def _sink(self, rec: dict) -> None:
        if self.sink_path is None or self._file_failed:
            return
        with self._sink_lock:
            if self._file_failed:           # raced close()/failure
                return
            try:
                if self._file is None:
                    os.makedirs(os.path.dirname(self.sink_path) or ".",
                                exist_ok=True)
                    self._file = open(self.sink_path, "a")
                self._file.write(json.dumps(rec, default=str) + "\n")
                self._file.flush()
            except Exception:   # noqa: BLE001 — observer isolation
                self.sink_errors += 1
                self._file_failed = True

    # ----------------------------------------------------------- status

    def status(self) -> dict:
        """Operator-facing snapshot: the ``stats()['quality']`` block
        and the ``{"quality": true}`` serve-CLI payload."""
        with self._lock:
            last = self._history[-1] if self._history else None
            return {
                "model": self.model_id, "k": self.k,
                "reference": self.profile is not None,
                "score_kind": (self.profile or {}).get("score_kind"),
                "windows": self.windows, "rows": self.rows,
                "open_window_rows": self._rows_in_window,
                "drifting": self.drifting,
                "consecutive_breaches": self._consecutive,
                "events": self.events,
                "detectors": dict(last["detectors"]) if last else None,
                "breaching": list(last["breaching"]) if last else [],
                "thresholds": dict(self.thresholds),
                "window_rows": self.window_rows,
                "debounce": self.debounce,
                "sink_path": self.sink_path,
                "sink_errors": self.sink_errors,
            }

    def history(self) -> List[dict]:
        with self._lock:
            return [dict(w) for w in self._history]

    def record(self, kind: str, **fields) -> None:
        """Append one serve-and-learn decision record (ISSUE 20) to
        this model's quality sink: the actuator's ``update``/
        ``rollback`` lines share the stream with the trigger's window/
        drift records so ``serve-status`` reads one file per (model,
        replica).  Sink-only — the caller owns its tracer events and
        registry counters (the learner's triple-recording contract);
        isolation and write-after-close behavior are ``_sink``'s."""
        if kind not in ("update", "rollback"):
            raise ValueError(
                f"record() writes serve-and-learn decision records "
                f"('update'/'rollback'), got kind {kind!r}")
        self._sink({"kind": kind, "model": self.model_id,
                    "ts": time.time(), **fields})

    def close(self) -> None:
        with self._sink_lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            # Unconditional (review finding): a monitor whose sink was
            # never lazily opened must not create and write the file
            # from an in-flight dispatch AFTER close.
            self._file_failed = True


# -------------------------------------------------- serve-status reading

def read_quality_log(path) -> List[dict]:
    """Quality JSONL -> records.  Tolerant of a torn trailing line (a
    live monitor may be mid-write — the serve-status use case), strict
    about everything else: a stream with no parseable quality record
    is malformed (the exit-2 classification, via TraceReadError)."""
    from kmeans_tpu.obs.trace import TraceReadError
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as e:
        raise TraceReadError(f"cannot read quality file {path}: {e}") \
            from e
    records = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            if i == len(lines) - 1:
                continue                # torn tail of a live writer
            raise TraceReadError(
                f"{path}:{i + 1}: not a JSON record ({e.msg})") from e
        if not isinstance(rec, dict) or rec.get("kind") not in \
                QUALITY_KINDS or "model" not in rec:
            raise TraceReadError(
                f"{path}:{i + 1}: not a serving-quality record "
                f"(kind must be one of {QUALITY_KINDS} with a "
                f"'model' field)")
        records.append(rec)
    if not records:
        raise TraceReadError(f"{path}: no serving-quality records")
    return records


def _is_quality_stream(path) -> bool:
    """First-line sniff: does this file hold quality records?  Used to
    skip co-located trace/heartbeat sinks when a DIRECTORY is given
    (an explicitly named file stays strict — read_quality_log)."""
    try:
        with open(path) as f:
            rec = json.loads(f.readline())
    except (OSError, ValueError):
        return False
    return isinstance(rec, dict) and rec.get("kind") in QUALITY_KINDS \
        and "model" in rec


def quality_report(paths) -> dict:
    """Aggregate quality sinks into the ``serve-status`` payload.

    ``paths``: files, directories, or globs (``obs.fleet``'s expansion
    rule); directories/globs keep only quality streams (trace/
    heartbeat sinks naturally share the directory), explicit files are
    read strictly.  Per model the CURRENT state is the newest record's
    debounced ``drifting`` flag; ``healthy`` mirrors ``fleet-status``:
    False when any model is drifting (exit 1)."""
    from kmeans_tpu.obs import fleet as _fleet
    from kmeans_tpu.obs.trace import TraceReadError
    raw = [paths] if isinstance(paths, (str, os.PathLike)) else list(paths)
    # Explicitly named files stay strict (reading one as a quality log
    # is what the caller asked for); dir/glob expansions keep only the
    # quality streams — trace/heartbeat sinks naturally co-locate.
    explicit = {str(p) for p in raw if os.path.isfile(str(p))}
    files = _fleet.expand_fleet_paths(raw)
    keep = [p for p in files
            if str(p) in explicit or _is_quality_stream(p)]
    if not keep:
        raise TraceReadError(
            f"no serving-quality streams among {files} (trace/"
            f"heartbeat files are read by 'trace summarize' / "
            f"'fleet-status')")
    files = keep
    records: List[dict] = []
    for p in files:
        records.extend(read_quality_log(p))
    records.sort(key=lambda r: r.get("ts", 0.0))
    models: Dict[str, dict] = {}
    for rec in records:
        row = models.setdefault(rec["model"], {
            "model": rec["model"], "windows": 0, "rows": 0,
            "events": 0, "reference": False, "detectors": None,
            "breaching": [], "drifting": False, "last_ts": None,
            "updates": 0, "update_failures": 0, "rollbacks": 0,
            "last_update": None})
        row["last_ts"] = rec.get("ts")
        if rec["kind"] == "profile":
            row["reference"] = True
            row["thresholds"] = rec.get("thresholds")
        elif rec["kind"] == "window":
            row["windows"] += 1
            row["rows"] += int(rec.get("rows", 0))
            row["detectors"] = rec.get("detectors")
            row["breaching"] = rec.get("breaching", [])
            row["drifting"] = bool(rec.get("drifting"))
        elif rec["kind"] == "drift":
            row["events"] += 1
            row["drifting"] = True
        elif rec["kind"] == "recovered":
            row["drifting"] = False
        elif rec["kind"] == "update":
            # Serve-and-learn actuator records (ISSUE 20).  Every
            # learner decision rides the stream (the triple-recording
            # contract), tagged by ``action``: only APPLIED updates
            # count as updates and only failed attempts as failures —
            # skips/evaluations are context, not actuation.
            act = rec.get("action", "applied" if rec.get("ok", True)
                          else "failed")
            if act == "applied":
                row["updates"] += 1
                row["last_update"] = rec.get("ts")
            elif act == "failed":
                row["update_failures"] += 1
        elif rec["kind"] == "rollback":
            row["rollbacks"] += 1
    drifting = sorted(m for m, r in models.items() if r["drifting"])
    return {"files": [str(f) for f in files],
            "models": dict(sorted(models.items())),
            "drifting": drifting,
            "healthy": not drifting,
            "thresholds": dict(COMMITTED_THRESHOLDS)}


def format_quality_status(report: dict) -> str:
    """The ``serve-status`` table: one row per model — windows, rows,
    latest detector readings, debounced state."""
    n = len(report["models"])
    head = (f"serving quality: {n} model{'s' if n != 1 else ''}, "
            f"{'HEALTHY' if report['healthy'] else 'DRIFTING: ' + str(report['drifting'])}")
    lines = [head,
             f"  {'model':<16} {'windows':>7} {'rows':>9} {'psi':>8} "
             f"{'js':>8} {'score_r':>8} {'neartie':>8} {'events':>6}"
             f"  state"]

    def _fmt(v):
        return f"{v:.4f}" if isinstance(v, (int, float)) else "-"

    for mid, row in report["models"].items():
        det = row.get("detectors") or {}
        state = "DRIFTING" if row["drifting"] else (
            "ok" if row.get("reference") else "no-reference")
        # Serve-and-learn annotation (ISSUE 20): the actuator's applied
        # updates / rollbacks ride the state column, so a drifting row
        # also says whether the loop already acted on it.
        learn = []
        if row.get("updates"):
            learn.append(f"{row['updates']}upd")
        if row.get("rollbacks"):
            learn.append(f"{row['rollbacks']}rb")
        if learn:
            state += f" ({','.join(learn)})"
        lines.append(
            f"  {mid[:16]:<16} {row['windows']:>7} {row['rows']:>9} "
            f"{_fmt(det.get('psi')):>8} {_fmt(det.get('js')):>8} "
            f"{_fmt(det.get('score_ratio')):>8} "
            f"{_fmt(det.get('near_tie_frac')):>8} "
            f"{row['events']:>6}  {state}")
    return "\n".join(lines)
