"""Time-to-first-iteration report: the per-phase table, from spans alone.

ROADMAP item 5's attack on the 47-324 s "compile+warmup" window needs a
measured decomposition of what an operator waits for between calling
``fit`` and the first iteration actually running: dataset placement,
program build, seeding, and the first dispatch (which, under JAX's lazy
jit, carries the XLA executable build).  Before ISSUE 11 that
decomposition existed only as prose in docs/PERFORMANCE.md; this module
produces it from a trace — run any fit under ``obs.tracing()`` and the
span records alone yield the table, formatted through the SAME
``phase_ceiling_table`` rule engine the r13 per-iteration ceiling table
uses (share-of-total, implied ceiling if the phase were free, the
committed >= 15% "actionable" decision rule).

Phase attribution rules (deliberate, documented):

* A phase row sums the SELF time (nested children excluded —
  ``trace.self_times``) of its spans that START before the first
  ``dispatch`` span starts — the pre-first-iteration window.
* ``first_dispatch`` is the first ``dispatch`` span's full duration.
  Under lazy jit it contains trace+lower+XLA-compile+execute of
  iteration 1; keeping it a single honest row (instead of pretending
  spans can split it) is why it is named ``first_dispatch`` and not
  ``iteration``.
* A segment span is NEVER a phase row (it wraps dispatch attempts);
  an OOM-replayed segment therefore cannot double-count.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kmeans_tpu.obs import trace as _trace

__all__ = ["ttfi_ladder", "time_to_first_iteration",
           "format_phase_table", "TTFI_PHASES"]

#: Lifecycle order of the pre-first-iteration phase rows.
TTFI_PHASES = ("place", "stage", "trace", "compile", "seed")


def ttfi_ladder(records: List[dict]) -> List[dict]:
    """Span records -> a ``measure_phase_ladder``-shaped ladder
    (``{"phase", "seconds", "cumulative", "spread"}`` rows in lifecycle
    order, ending with ``first_dispatch``).  ``spread`` is 0.0: a trace
    is one observed run, not a repeated measurement — re-trace to
    estimate variance.  Raises ``ValueError`` when the trace holds no
    ``dispatch`` span (nothing ran; there is no first iteration to
    report)."""
    spans = [r for r in records if r.get("kind") == "span"]
    dispatches = sorted((s for s in spans if s["name"] == "dispatch"),
                        key=lambda s: s["t0"])
    if not dispatches:
        raise ValueError(
            "trace holds no 'dispatch' span — nothing was dispatched, "
            "so there is no first iteration to decompose")
    fd = dispatches[0]
    selfs = _trace.self_times(records)
    totals: Dict[str, float] = {name: 0.0 for name in TTFI_PHASES}
    for s in spans:
        if s["name"] in totals and s["t0"] <= fd["t0"]:
            totals[s["name"]] += selfs[s["id"]]
    ladder = []
    cum = 0.0
    for name in TTFI_PHASES:
        cum += totals[name]
        ladder.append({"phase": name, "seconds": totals[name],
                       "cumulative": cum, "spread": 0.0})
    cum += fd.get("dur") or 0.0
    ladder.append({"phase": "first_dispatch",
                   "seconds": fd.get("dur") or 0.0,
                   "cumulative": cum, "spread": 0.0})
    return ladder


def time_to_first_iteration(records: List[dict],
                            decision_share: Optional[float] = None
                            ) -> List[dict]:
    """The publishable per-phase time-to-first-iteration table: one row
    per phase with ``ms`` / ``share`` / ``implied_ceiling_speedup`` /
    ``actionable`` — ``utils.profiling.phase_ceiling_table`` applied to
    the span-derived ladder, so the TTFI artifact and the r13 per-
    iteration ceiling table share one schema and one committed decision
    rule (>= ``PHASE_DECISION_SHARE`` of the total marks the phase as
    the next attack surface for ROADMAP item 5)."""
    from kmeans_tpu.utils import profiling
    share = profiling.PHASE_DECISION_SHARE if decision_share is None \
        else decision_share
    return profiling.phase_ceiling_table(ttfi_ladder(records),
                                         decision_share=share)


def format_phase_table(rows: List[dict], title: str =
                       "time-to-first-iteration") -> str:
    """Fixed-width text rendering of a phase table (CLI + dry-run
    artifact)."""
    lines = [f"{title}:",
             f"  {'phase':<16} {'ms':>10} {'share':>7} "
             f"{'ceiling':>8}  actionable"]
    for r in rows:
        ceil = r.get("implied_ceiling_speedup")
        lines.append(
            f"  {r['phase']:<16} {r['ms']:>10.2f} {r['share']:>6.1%} "
            f"{(f'{ceil:.3f}x' if ceil is not None else '-'):>8}  "
            f"{'YES' if r.get('actionable') else 'no'}")
    total_ms = sum(r["ms"] for r in rows)
    lines.append(f"  {'TOTAL':<16} {total_ms:>10.2f}")
    return "\n".join(lines)
