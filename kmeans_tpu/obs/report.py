"""Time-to-first-iteration report: the per-phase table, from spans alone.

ROADMAP item 5's attack on the 47-324 s "compile+warmup" window needs a
measured decomposition of what an operator waits for between calling
``fit`` and the first iteration actually running: dataset placement,
program build, seeding, and the first dispatch (which, under JAX's lazy
jit, carries the XLA executable build).  Before ISSUE 11 that
decomposition existed only as prose in docs/PERFORMANCE.md; this module
produces it from a trace — run any fit under ``obs.tracing()`` and the
span records alone yield the table, formatted through the SAME
``phase_ceiling_table`` rule engine the r13 per-iteration ceiling table
uses (share-of-total, implied ceiling if the phase were free, the
committed >= 15% "actionable" decision rule).

Phase attribution rules (deliberate, documented):

* A phase row sums the SELF time (nested children excluded —
  ``trace.self_times``) of its spans that START before the first
  ``dispatch`` span starts — the pre-first-iteration window.
* ``first_dispatch`` is the first ``dispatch`` span's full duration.
  Under lazy jit it contains trace+lower+XLA-compile+execute of
  iteration 1; keeping it a single honest row (instead of pretending
  spans can split it) is why it is named ``first_dispatch`` and not
  ``iteration``.
* A segment span is NEVER a phase row (it wraps dispatch attempts);
  an OOM-replayed segment therefore cannot double-count.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kmeans_tpu.obs import trace as _trace

__all__ = ["ttfi_ladder", "time_to_first_iteration",
           "format_phase_table", "TTFI_PHASES", "merge_cost",
           "format_cost_table", "device_cost_report",
           "ingest_breakdown", "format_ingest_table"]

#: Lifecycle order of the pre-first-iteration phase rows.
TTFI_PHASES = ("place", "stage", "trace", "compile", "seed")


def ttfi_ladder(records: List[dict]) -> List[dict]:
    """Span records -> a ``measure_phase_ladder``-shaped ladder
    (``{"phase", "seconds", "cumulative", "spread"}`` rows in lifecycle
    order, ending with ``first_dispatch``).  ``spread`` is 0.0: a trace
    is one observed run, not a repeated measurement — re-trace to
    estimate variance.  Raises ``ValueError`` when the trace holds no
    ``dispatch`` span (nothing ran; there is no first iteration to
    report).

    Attribution rule (revised for ISSUE 15): phase rows sum SELF time
    of their spans up to the END of the first dispatch — not just its
    start — and ``first_dispatch`` is that span's SELF time.  Under
    lazy jit the XLA executable build hides inside the first dispatch
    with no span of its own (it lands in the ``first_dispatch`` row, as
    before); with an AOT store active the build/load is an explicit
    ``compile(via='aot-build'/'aot-load')`` span NESTED in that first
    dispatch — the revised rule attributes it to the ``compile`` row,
    which is what makes the cold-vs-AOT-warm compile comparison an
    honest measured before/after (self-time accounting keeps the total
    double-count-free either way)."""
    spans = [r for r in records if r.get("kind") == "span"]
    dispatches = sorted((s for s in spans if s["name"] == "dispatch"),
                        key=lambda s: s["t0"])
    if not dispatches:
        raise ValueError(
            "trace holds no 'dispatch' span — nothing was dispatched, "
            "so there is no first iteration to decompose")
    fd = dispatches[0]
    fd_end = fd["t1"] if fd.get("t1") is not None else fd["t0"]
    selfs = _trace.self_times(records)
    totals: Dict[str, float] = {name: 0.0 for name in TTFI_PHASES}
    for s in spans:
        if s["name"] in totals and s["t0"] <= fd_end:
            totals[s["name"]] += selfs[s["id"]]
    ladder = []
    cum = 0.0
    for name in TTFI_PHASES:
        cum += totals[name]
        ladder.append({"phase": name, "seconds": totals[name],
                       "cumulative": cum, "spread": 0.0})
    fd_self = selfs.get(fd["id"], fd.get("dur") or 0.0)
    cum += fd_self
    ladder.append({"phase": "first_dispatch",
                   "seconds": fd_self,
                   "cumulative": cum, "spread": 0.0})
    return ladder


def time_to_first_iteration(records: List[dict],
                            decision_share: Optional[float] = None,
                            comm_model: Optional[dict] = None
                            ) -> List[dict]:
    """The publishable per-phase time-to-first-iteration table: one row
    per phase with ``ms`` / ``share`` / ``implied_ceiling_speedup`` /
    ``actionable`` — ``utils.profiling.phase_ceiling_table`` applied to
    the span-derived ladder, so the TTFI artifact and the r13 per-
    iteration ceiling table share one schema and one committed decision
    rule (>= ``PHASE_DECISION_SHARE`` of the total marks the phase as
    the next attack surface for ROADMAP item 5).  ``comm_model`` (an
    ``obs.fleet.comm_bytes_model`` dict, ISSUE 13) attaches the
    analytic collective-bytes columns to the ``first_dispatch`` row —
    the dispatch is where the fit pays them."""
    from kmeans_tpu.utils import profiling
    share = profiling.PHASE_DECISION_SHARE if decision_share is None \
        else decision_share
    rows = profiling.phase_ceiling_table(ttfi_ladder(records),
                                         comm_model=comm_model,
                                         decision_share=share)
    # Device-cost join (ISSUE 12): when the trace carries cost.record
    # events (capture ran alongside tracing), each phase row gains the
    # captured flops/bytes/arithmetic-intensity of the programs whose
    # first call landed under that phase's spans; first_dispatch joins
    # the ``dispatch`` phase (that is where step programs fire).
    cost = merge_cost(records)
    if cost:
        for row in rows:
            phase = "dispatch" if row["phase"] == "first_dispatch" \
                else row["phase"]
            c = cost.get(phase)
            if c and c["programs"]:
                row["flops"] = c["flops"]
                row["bytes_accessed"] = c["bytes_accessed"]
                row["ai"] = c["ai"]
    return rows


def ingest_breakdown(records: List[dict]) -> List[dict]:
    """Per-slab ingest attribution (ISSUE 18): the ``stage`` spans
    carrying a ``slab`` attr — one per slab-staged upload group, emitted
    by the slab/streamed placement paths — rolled into rows of
    ``{"slab", "slabs", "rows", "bytes", "ms"}`` in upload order.  ``ms``
    is the span's SELF time (the host-side slice/copy + device_put issue
    + previous-slab completion wait), so the rows sum to the ``stage``
    phase row's slab-staged share in the TTFI table instead of hiding
    inside one opaque number.  Empty list when the trace holds no
    slab-attributed stage spans (mono ingest, or no ingest at all)."""
    spans = [r for r in records if r.get("kind") == "span"]
    selfs = _trace.self_times(records)
    rows = []
    for s in sorted(spans, key=lambda s: s["t0"]):
        attrs = s.get("attrs", {}) or {}
        if s["name"] == "stage" and "slab" in attrs:
            rows.append({"slab": int(attrs["slab"]),
                         "slabs": attrs.get("slabs"),
                         "rows": attrs.get("rows"),
                         "bytes": attrs.get("bytes"),
                         "ms": selfs[s["id"]] * 1e3})
    return rows


def format_ingest_table(rows: List[dict], title: str =
                        "ingest slabs (stage self-time per slab)") -> str:
    """Fixed-width text rendering of an :func:`ingest_breakdown` —
    printed under the TTFI table by ``trace summarize`` when the trace
    carries slab-staged ingest."""
    lines = [f"{title}:",
             f"  {'slab':>6} {'rows':>10} {'bytes':>12} {'ms':>10}"]
    t_rows = t_bytes = 0
    t_ms = 0.0
    for r in rows:
        lines.append(f"  {r['slab']:>6} "
                     f"{(r['rows'] if r['rows'] is not None else '-'):>10} "
                     f"{(r['bytes'] if r['bytes'] is not None else '-'):>12} "
                     f"{r['ms']:>10.2f}")
        t_rows += int(r["rows"] or 0)
        t_bytes += int(r["bytes"] or 0)
        t_ms += r["ms"]
    lines.append(f"  {'TOTAL':>6} {t_rows:>10} {t_bytes:>12} "
                 f"{t_ms:>10.2f}")
    return "\n".join(lines)


def merge_cost(records: List[dict]) -> Dict[str, dict]:
    """Roll ``cost.record`` events (ISSUE 12: one per captured program,
    emitted by the cost collector when tracing is active) up by the
    span phase their first call ran under: ``{phase: {programs, flops,
    bytes_accessed, peak_bytes, ai, unavailable}}``.  Empty dict when
    the trace holds no cost records — the ``--cost`` CLI columns then
    stay blank."""
    spans = {r["id"]: r for r in records if r.get("kind") == "span"}
    out: Dict[str, dict] = {}
    for r in records:
        if r.get("kind") != "event" or r.get("name") != "cost.record":
            continue
        attrs = r.get("attrs", {}) or {}
        parent = spans.get(r.get("parent"))
        phase = parent["name"] if parent else "-"
        agg = out.setdefault(phase, {
            "programs": 0, "flops": 0.0, "bytes_accessed": 0.0,
            "peak_bytes": 0, "unavailable": 0, "ai": None})
        if attrs.get("available"):
            agg["programs"] += 1
            agg["flops"] += float(attrs.get("flops") or 0.0)
            agg["bytes_accessed"] += float(attrs.get("bytes_accessed")
                                           or 0.0)
            agg["peak_bytes"] = max(agg["peak_bytes"],
                                    int(attrs.get("peak_bytes") or 0))
        else:
            agg["unavailable"] += 1
    for agg in out.values():
        if agg["bytes_accessed"]:
            agg["ai"] = agg["flops"] / agg["bytes_accessed"]
    return out


def format_phase_table(rows: List[dict], title: str =
                       "time-to-first-iteration") -> str:
    """Fixed-width text rendering of a phase table (CLI + dry-run
    artifact)."""
    lines = [f"{title}:",
             f"  {'phase':<16} {'ms':>10} {'share':>7} "
             f"{'ceiling':>8}  actionable"]
    for r in rows:
        ceil = r.get("implied_ceiling_speedup")
        lines.append(
            f"  {r['phase']:<16} {r['ms']:>10.2f} {r['share']:>6.1%} "
            f"{(f'{ceil:.3f}x' if ceil is not None else '-'):>8}  "
            f"{'YES' if r.get('actionable') else 'no'}")
    total_ms = sum(r["ms"] for r in rows)
    lines.append(f"  {'TOTAL':<16} {total_ms:>10.2f}")
    for r in rows:
        if "comm_bytes_per_iter" in r:
            lines.append(
                f"  comm ({r['phase']}): "
                f"{r['comm_bytes_per_iter']:.0f} B/iter analytic "
                f"collectives, "
                f"{r['comm_wire_bytes_per_device']:.0f} B/iter wire "
                f"per device (ring)")
    return "\n".join(lines)


# ------------------------------------------------------ device cost

def _fmt_num(v, unit: str = "") -> str:
    if v is None:
        return "-"
    v = float(v)
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"),
                          (1e3, "k")):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{suffix}{unit}"
    return f"{v:.2f}{unit}"


def format_cost_table(rows: List[dict],
                      title: str = "device cost") -> str:
    """Fixed-width rendering of :func:`device_cost_report` rows (the
    ``cost-report`` CLI / ``dryrun_multichip`` artifact)."""
    lines = [f"{title}:",
             f"  {'family':<10} {'program':<26} {'flops':>9} "
             f"{'analytic':>9} {'ratio':>6} {'agree':>5} {'ai':>7} "
             f"{'peak':>9} {'planned':>9}"]
    for r in rows:
        ratio = r.get("ratio")
        ratio_s = f"{ratio:.3f}" if ratio is not None else "-"
        agree_s = "-" if ratio is None else \
            ("yes" if r.get("agree") else "NO")
        ai = r.get("ai")
        ai_s = f"{ai:.2f}" if ai is not None else "-"
        lines.append(
            f"  {r['family']:<10} {r['program'][:26]:<26} "
            f"{_fmt_num(r.get('flops')):>9} "
            f"{_fmt_num(r.get('analytic_flops')):>9} "
            f"{ratio_s:>6} {agree_s:>5} {ai_s:>7} "
            f"{_fmt_num(r.get('peak_bytes'), 'B'):>9} "
            f"{_fmt_num(r.get('planned_peak_bytes'), 'B'):>9}")
    return "\n".join(lines)


#: The small shapes the report fits each family at on the CPU proxy —
#: single-chunk (whole shard), D large enough that the elementwise
#: share XLA counts (and the hand formulas exclude) sits inside the
#: committed 10% band for the kmeans/gmm-diag cross-check.
REPORT_SPECS = {
    "kmeans": dict(n=8192, d=128, k=64),
    "spherical": dict(n=8192, d=64, k=32),
    "bisecting": dict(n=4096, d=64, k=4),
    "minibatch": dict(n=8192, d=64, k=32, batch=2048),
    "gmm": dict(n=8192, d=64, k=32),
}


def device_cost_report(families=None, *, specs=None,
                       chunk: Optional[int] = None) -> dict:
    """Run each family's small fit under cost capture and report the
    captured step-program analyses against the analytic roofline and
    the HBM footprint plan — the ``python -m kmeans_tpu cost-report``
    payload.  Returns ``{"rows": [...], "plans": [...],
    "device_memory": {...}, "backend": ...}``.

    Each family fits at its ``REPORT_SPECS`` shape (override per family
    via ``specs``) with the library's own chunk rule made EXPLICIT
    (``choose_chunk_size``; override via ``chunk``): the step-cache key
    is fresh in a warm process, the small shapes run single-chunk so
    XLA's loop-body-once counting lines up with the per-iteration hand
    formulas, and large (hardware) shapes scan at the committed chunk —
    the analytic side then counts one chunk too
    (``analytic_step_flops``).  A backend that cannot report yields
    ``available=False`` rows — the report never fails with the fit
    working."""
    import numpy as np

    import jax

    from kmeans_tpu.obs import cost as cost_mod
    from kmeans_tpu.obs import memory as memory_mod
    from kmeans_tpu.parallel.mesh import make_mesh, mesh_shape
    from kmeans_tpu.parallel.sharding import choose_chunk_size

    families = list(families or REPORT_SPECS)
    merged = dict(REPORT_SPECS)
    if specs:
        for fam, s in specs.items():
            merged[fam] = dict(merged.get(fam, {}), **s)
    backend = jax.default_backend()
    data_shards, model_shards = mesh_shape(make_mesh())
    rows: List[dict] = []
    plans: List[dict] = []
    rng = np.random.default_rng(42)
    for family in families:
        spec = merged[family]
        n, d, k = spec["n"], spec["d"], spec["k"]
        X = (rng.standard_normal((n, d))
             + 3.0 * rng.integers(0, 3, size=(n, 1))).astype(np.float32)
        eff_chunk = int(chunk) if chunk \
            else choose_chunk_size(-(-n // data_shards), k, d)
        with cost_mod.collecting() as col:
            _report_fit(family, X, k, eff_chunk, spec)
        recs = col.records()
        step = max((r for r in recs if r.available and r.flops),
                   key=lambda r: r.flops, default=None)
        analytic = cost_mod.analytic_step_flops(
            family, n=spec.get("batch", n) if family == "minibatch"
            else n, d=d, k=k, chunk=eff_chunk, n_devices=data_shards)
        plan = memory_mod.plan_fit(
            family, n, d, k, chunk=eff_chunk, data_shards=data_shards,
            model_shards=model_shards, batch=spec.get("batch"),
            records=recs)
        plans.append(plan)
        row = {"family": family, "backend": backend,
               "n": n, "d": d, "k": k, "chunk": eff_chunk,
               "captured": len(recs),
               "available": bool(step is not None),
               "program": step.cache if step else "-",
               "planned_peak_bytes": plan["predicted_peak_bytes"]}
        if step is not None:
            row.update(step.to_dict())
            row.update(cost_mod.crosscheck(analytic, step))
        else:
            row.update({"analytic_flops": analytic, "ratio": None,
                        "agree": False,
                        "error": "; ".join(sorted(
                            {r.error for r in recs if r.error}))
                        or "no program captured"})
        rows.append(row)
    return {"rows": rows, "plans": plans,
            "device_memory": memory_mod.device_memory_info(),
            "backend": backend}


def _report_fit(family: str, X, k: int, chunk: int, spec: dict) -> None:
    """One small fit driving the family's real step-cache capture path
    (host_loop=False: the one-dispatch device program IS the step
    program the headline rows measure)."""
    from kmeans_tpu.models import (BisectingKMeans, GaussianMixture,
                                   KMeans, MiniBatchKMeans,
                                   SphericalKMeans)
    common = dict(max_iter=3, seed=0, verbose=False)
    if family == "gmm":
        GaussianMixture(n_components=k, covariance_type="diag", tol=0.0,
                        init_params="random", host_loop=False,
                        chunk_size=chunk, **common).fit(X)
    elif family == "minibatch":
        MiniBatchKMeans(k=k, batch_size=spec.get("batch", 2048),
                        tolerance=1e-30, host_loop=False,
                        compute_labels=False, chunk_size=chunk,
                        **common).fit(X)
    elif family == "bisecting":
        BisectingKMeans(k=k, tolerance=1e-30, host_loop=False,
                        compute_labels=False, chunk_size=chunk,
                        **common).fit(X)
    elif family == "spherical":
        SphericalKMeans(k=k, tolerance=1e-30, host_loop=False,
                        empty_cluster="keep", compute_labels=False,
                        chunk_size=chunk, **common).fit(X)
    else:
        KMeans(k=k, tolerance=1e-30, host_loop=False,
               empty_cluster="keep", compute_labels=False,
               chunk_size=chunk, **common).fit(X)
