"""Span tracing: timestamped lifecycle phases, JSONL + Chrome export.

The repo's observability story before ISSUE 11 was a dozen ad-hoc
signals (``note_dispatch`` labels, ``oom_backoffs_``, serving counters,
the recompilation sentinel) with no shared schema, no timestamps, and
no export path.  This module is the shared substrate: a process-wide
:class:`Tracer` records nested, timestamped SPANS for the lifecycle
phases an operator actually waits on, and exports them as JSONL (one
record per line — the ``python -m kmeans_tpu trace summarize`` input)
and Chrome ``trace_event`` JSON (load in ``chrome://tracing`` or
Perfetto for the timeline view).

Span taxonomy (the names instrumented call sites use; full catalog with
the lifecycle diagram in docs/OBSERVABILITY.md):

* ``place`` — dataset upload onto the mesh (``sharding.to_device``).
* ``stage`` — per-block host->device staging (``shard_points``; the
  streamed-fit producer thread emits these from its own ``tid``).
* ``compile`` — a compile-cache MISS: the ``*_STEP_CACHE``-class
  factory building a program (``utils.cache.LRUCache.get_or_create``
  emits one per miss, named with the cache and key; the XLA executable
  build itself is lazy — it lands inside the FIRST ``dispatch`` span
  after the miss, which is why the time-to-first-iteration report keeps
  ``first_dispatch`` as its own row).
* ``trace`` — builder-side program construction inside a compile span
  (``distributed``/``gmm_step`` builders).
* ``seed`` — initialization draws (``resolve_init``, GMM init).
* ``dispatch`` — one host->device dispatch the host then waits on
  (device-loop segments, host-loop steps); ``note_dispatch`` labels
  additionally land as instant events under their own names.
* ``segment`` — one checkpoint segment of a segmented device fit,
  wrapping its dispatch ATTEMPTS (an OOM-backoff replay adds attempt
  spans inside the same segment span — never a second segment).
* ``checkpoint.save`` / ``checkpoint.restore`` — rotating checkpoint
  writes and resume loads (``utils.checkpoint``).
* ``io.block`` — one streamed block read (``data.io``).
* ``serve.request`` / ``serve.flush`` — serving-engine dispatches and
  micro-batch queue flushes.

Disabled-path contract (the ``obs=0`` parity oracle): with no tracer
installed, :func:`span` returns a shared null context manager and
:func:`event` returns immediately — no allocation, no lock, no record.
Tracing never touches model arithmetic either way, so a traced fit is
bit-identical to an untraced one (pinned for all five families by
tests/test_obs.py).

Pure stdlib — importable from every layer (including the linter-adjacent
``utils.cache``) without pulling in jax/numpy.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from kmeans_tpu.obs import identity as _identity
from kmeans_tpu.obs.metrics_registry import nearest_rank

__all__ = ["Tracer", "span", "event", "tracing", "get_tracer",
           "read_jsonl", "summarize", "SPAN_NAMES", "TraceReadError"]

#: The span taxonomy (documentation + the CLI's table ordering; call
#: sites may add dotted sub-names like ``checkpoint.save``).  The
#: ``collective`` span (ISSUE 13) wraps host-side cross-process
#: collectives (``process_allgather``, the fleet barrier) — the
#: ``collective-span`` lint rule enforces coverage in ``parallel/``.
SPAN_NAMES = (
    "place", "stage", "compile", "trace", "seed", "dispatch", "segment",
    "checkpoint.save", "checkpoint.restore", "io.block",
    "serve.request", "serve.flush", "collective",
)


class TraceReadError(ValueError):
    """A trace JSONL file is unreadable or malformed (the CLI's exit-2
    classification)."""


class _NullSpan:
    """The disabled-path context manager: one shared instance, no state."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()

#: Process-wide active tracer (None = telemetry off, the default).
#: Installed/restored by :func:`tracing`; read by the module-level
#: fast paths.  A plain attribute (not thread-local): one fit's spans
#: may come from several threads (the prefetch producer stages blocks),
#: and they must all land in the same trace.
_TRACER: Optional["Tracer"] = None


class Tracer:
    """Process-wide span recorder.

    Records are plain dicts (JSON-ready).  Span nesting is tracked with
    a PER-THREAD stack, so spans opened on the prefetch producer thread
    nest among themselves and never corrupt the fit thread's stack.
    Timestamps are ``time.perf_counter()`` relative to the tracer's
    start (monotonic, sub-µs); ``wall0`` anchors them to wall time for
    cross-process correlation.
    """

    def __init__(self):
        self.wall0 = time.time()
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._records: List[dict] = []
        self._tls = threading.local()
        self._next_id = 0
        self._ident: Optional[dict] = None
        # Incremental per-name SELF-time accumulators: +dur on close,
        # -dur from the enclosing span's name — so phase_totals() is
        # O(names), not a re-walk of every record (the heartbeat reads
        # it per boundary; a full summarize() there would make
        # tracing+heartbeat quadratic in iterations — review finding).
        self._phase_self: Dict[str, float] = {}

    # ------------------------------------------------------------ time
    def _now(self) -> float:
        return time.perf_counter() - self._t0

    def identity(self) -> dict:
        """This tracer's fleet identity (process_index/count, host) —
        resolved lazily on first use (by which time a multi-host
        program has initialized jax.distributed: the mesh needs it
        before any fit runs) and cached for the tracer's lifetime, so
        per-record stamping costs three dict inserts, not a lookup."""
        if self._ident is None:
            self._ident = _identity.identity()
        return self._ident

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # ----------------------------------------------------------- spans
    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """One timed, nested span.  Exceptions propagate (the span still
        closes, stamped ``error`` with the exception type) — tracing a
        failing fit must record the failure, never mask it."""
        stack = self._stack()
        with self._lock:
            sid = self._next_id
            self._next_id += 1
        parent = stack[-1] if stack else None
        # Fleet identity (ISSUE 13): every record carries its producer's
        # coordinates so merged multi-host streams stay attributable
        # record-by-record (the file header alone would be lost on
        # re-slicing).  Cached — three dict inserts per span.
        rec = {"kind": "span", "name": name, "id": sid,
               "parent": parent["id"] if parent else None,
               "depth": len(stack),
               "tid": threading.get_ident(),
               **self.identity(),
               "t0": self._now(), "t1": None, "dur": None}
        if attrs:
            rec["attrs"] = _jsonable(attrs)
        stack.append(rec)
        try:
            yield rec
        except BaseException as e:
            rec["error"] = type(e).__name__
            raise
        finally:
            stack.pop()
            rec["t1"] = self._now()
            rec["dur"] = rec["t1"] - rec["t0"]
            with self._lock:
                self._records.append(rec)
                ps = self._phase_self
                ps[name] = ps.get(name, 0.0) + rec["dur"]
                if parent is not None:
                    # The enclosing span will add its FULL duration
                    # when it closes; subtracting the child here keeps
                    # the accumulator a self-time total.
                    pname = parent["name"]
                    ps[pname] = ps.get(pname, 0.0) - rec["dur"]

    def event(self, name: str, **attrs) -> None:
        """One instant (zero-duration) event at the current nesting."""
        stack = self._stack()
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            self._records.append({
                "kind": "event", "name": name, "id": sid,
                "parent": stack[-1]["id"] if stack else None,
                "depth": len(stack), "tid": threading.get_ident(),
                **self.identity(),
                "t0": self._now(), "t1": None, "dur": 0.0,
                **({"attrs": _jsonable(attrs)} if attrs else {})})

    def instant_span(self, name: str, **attrs) -> None:
        """A zero-length SPAN (not an event): what the recompilation
        sentinel emits for cache growth it detected after the fact, so
        a sentinel violation appears on the timeline as a ``compile``
        span naming the cache even though the miss itself was not
        traced."""
        with self.span(name, **attrs):
            pass

    # --------------------------------------------------------- reading
    def records(self) -> List[dict]:
        """Snapshot of all closed records (spans close at exit; an open
        span is not yet visible)."""
        with self._lock:
            return list(self._records)

    def phase_totals(self) -> Dict[str, float]:
        """name -> total SELF seconds (nested child time excluded) —
        the heartbeat's elapsed-per-phase payload.  O(names) from the
        incremental accumulators, never a record re-walk; a name whose
        enclosing span is still open may read transiently low (its
        children already subtracted) — clamped at 0, and exact again
        once the parent closes.  ``summarize(records())`` is the exact
        post-hoc computation."""
        with self._lock:
            return {name: max(v, 0.0)
                    for name, v in self._phase_self.items()}

    # --------------------------------------------------------- exports
    def write_jsonl(self, path) -> None:
        """One JSON record per line; first line is a header record
        carrying the wall-clock anchor and pid."""
        with open(path, "w") as f:
            self._dump_jsonl(f)

    def to_jsonl(self) -> str:
        buf = io.StringIO()
        self._dump_jsonl(buf)
        return buf.getvalue()

    def _dump_jsonl(self, f) -> None:
        f.write(json.dumps({"kind": "header", "wall0": self.wall0,
                            "pid": os.getpid(), **self.identity(),
                            "format": "kmeans_tpu.trace.v1"}) + "\n")
        for rec in self.records():
            f.write(json.dumps(rec) + "\n")

    def write_chrome(self, path) -> None:
        with open(path, "w") as f:
            json.dump({"traceEvents": chrome_events(self.records()),
                       "displayTimeUnit": "ms"}, f)


def _jsonable(attrs: dict) -> dict:
    """Attrs must serialize; anything exotic is repr'd (truncated) so a
    span can never make the export throw."""
    out = {}
    for k, v in attrs.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
        else:
            out[k] = repr(v)[:120]
    return out


def chrome_events(records: List[dict]) -> List[dict]:
    """Chrome ``trace_event`` array from trace records: complete events
    (``ph='X'``) for spans, instant events (``ph='i'``) for events —
    the schema chrome://tracing and Perfetto load directly.

    Fleet rendering (ISSUE 13): records from a multi-process fit carry
    ``process_index``/``host``; each host then becomes its OWN Chrome
    process (``pid`` = process_index, a ``process_name`` metadata event
    labels it with the host name), so a merged timeline shows one track
    group per host.  Single-process records keep ``pid`` = the OS pid —
    the r15 schema, unchanged."""
    os_pid = os.getpid()
    out = []
    hosts = {}                      # pid -> host label (fleet records)
    for rec in records:
        if rec.get("kind") == "header":
            continue
        if rec.get("process_count", 1) > 1:
            pid = int(rec.get("process_index", 0))
            hosts.setdefault(
                pid, f"{rec.get('host', '?')} (p{pid})")
        else:
            pid = rec.get("process_index") if "process_index" in rec \
                and _is_merged(rec) else os_pid
            pid = os_pid if pid is None else pid
        base = {"name": rec["name"], "pid": pid, "tid": rec["tid"],
                "ts": round(rec["t0"] * 1e6, 3),
                "args": rec.get("attrs", {})}
        if rec["kind"] == "span":
            out.append({**base, "ph": "X",
                        "dur": round((rec["dur"] or 0.0) * 1e6, 3)})
        else:
            out.append({**base, "ph": "i", "s": "t"})
    out.sort(key=lambda e: e["ts"])
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": label}}
            for pid, label in sorted(hosts.items())]
    return meta + out


def _is_merged(rec: dict) -> bool:
    """True for records a fleet merge re-stamped (they carry the
    merged-stream marker) — their process_index is a track id even when
    the source fit was single-process-per-host."""
    return bool(rec.get("fleet_merged"))


# --------------------------------------------------- module fast paths

def get_tracer() -> Optional[Tracer]:
    """The active tracer, or None (telemetry off — the default)."""
    return _TRACER


def active() -> bool:
    return _TRACER is not None


def span(name: str, **attrs):
    """Context manager recording a span under the active tracer; the
    shared no-op context when tracing is off (no allocation)."""
    t = _TRACER
    if t is None:
        return _NULL_SPAN
    return t.span(name, **attrs)


def event(name: str, **attrs) -> None:
    """Instant event under the active tracer; no-op when tracing is off."""
    t = _TRACER
    if t is not None:
        t.event(name, **attrs)


def traced_builder(fn):
    """Decorator for the ``parallel`` program builders: runs the
    builder under a ``trace`` span (program construction — nested
    inside the ``compile`` span its cache-miss caller opened) when a
    tracer is active; one extra Python call and nothing else when off.
    Named after what the phase IS: the builder assembles/traces the
    program; the XLA executable build stays lazy and lands in the first
    ``dispatch`` span."""
    import functools

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        t = _TRACER
        if t is None:
            return fn(*args, **kwargs)
        with t.span("trace", builder=fn.__name__):
            return fn(*args, **kwargs)
    return wrapper


@contextlib.contextmanager
def tracing(path=None, chrome=None, tracer: Optional[Tracer] = None,
            per_process: object = "auto"):
    """Install a tracer for the ``with`` body (nested scopes shadow,
    like ``log_dispatches``); on exit restore the previous one and
    write the JSONL/Chrome exports when paths were given.

    Multi-host sinks (ISSUE 13): under ``process_count > 1`` every host
    runs this scope, and N hosts appending to ONE path would tear the
    file — so by default (``per_process='auto'``) each process writes
    to the suffixed ``identity.per_process_path`` (``trace.jsonl`` ->
    ``trace.p3.jsonl``; ``obs.fleet``/``trace summarize`` glob these
    back together).  ``per_process=False`` is the primary-only
    alternative: ONLY process 0 writes, at the verbatim path — a
    one-host sample of the fleet, for operators who want a single file
    and accept losing the other hosts' spans.  ``per_process=True``
    forces the suffix even single-process (harness use).  Single
    process + 'auto' keeps the verbatim path — the r15 contract.

    Usage::

        with obs.tracing("fit.jsonl") as tr:
            model.fit(X)
        # fit.jsonl now holds the span records; also:
        table = obs.time_to_first_iteration(tr.records())
    """
    global _TRACER
    if per_process not in ("auto", True, False):
        # Validate up front (the Heartbeat rule): silently degrading a
        # typo'd policy to every-host-writes-the-verbatim-path would
        # reintroduce the torn-shared-file collision this knob fixes.
        raise ValueError(f"per_process must be 'auto', True or False, "
                         f"got {per_process!r}")
    t = tracer if tracer is not None else Tracer()
    prev, _TRACER = _TRACER, t
    try:
        yield t
    finally:
        _TRACER = prev
        ident = t.identity()
        suffix = per_process is True or (
            per_process == "auto" and ident["process_count"] > 1)
        primary_only = per_process is False \
            and ident["process_count"] > 1
        writer = not (primary_only and ident["process_index"] != 0)
        if path is not None and writer:
            t.write_jsonl(_identity.per_process_path(
                path, ident["process_index"]) if suffix else path)
        if chrome is not None and writer:
            t.write_chrome(_identity.per_process_path(
                chrome, ident["process_index"]) if suffix else chrome)


# ----------------------------------------------------------- analysis

def read_jsonl(path) -> List[dict]:
    """Load a trace JSONL file back into records.

    Raises :class:`TraceReadError` for unreadable files, non-JSON
    lines, or records missing the span schema — the CLI's exit-2
    classification (a partial file from a crashed writer is malformed,
    not silently half-summarized)."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as e:
        raise TraceReadError(f"cannot read trace file {path}: {e}") from e
    records = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            raise TraceReadError(
                f"{path}:{i + 1}: not a JSON record ({e.msg})") from e
        if not isinstance(rec, dict) or "kind" not in rec:
            raise TraceReadError(
                f"{path}:{i + 1}: not a trace record (missing 'kind')")
        if rec["kind"] in ("span", "event") and any(
                field not in rec for field in ("name", "t0", "id")):
            # 'id' is load-bearing downstream (self_times keys on it);
            # a truncated/hand-edited record without it must classify
            # as malformed here, not KeyError deep in summarize.
            raise TraceReadError(
                f"{path}:{i + 1}: malformed {rec['kind']} record "
                f"(missing name/t0/id)")
        records.append(rec)
    if not any(r.get("kind") in ("span", "event") for r in records):
        raise TraceReadError(f"{path}: no span or event records")
    return records


def self_times(records: List[dict]) -> Dict[int, float]:
    """span id -> EXCLUSIVE seconds (duration minus direct children):
    the double-count-free attribution nested spans need (a ``stage``
    span inside a prefetch ``stage`` span must not count twice)."""
    spans = [r for r in records if r.get("kind") == "span"]
    child_dur: Dict[int, float] = {}
    for s in spans:
        p = s.get("parent")
        if p is not None:
            child_dur[p] = child_dur.get(p, 0.0) + (s.get("dur") or 0.0)
    return {s["id"]: max((s.get("dur") or 0.0)
                         - child_dur.get(s["id"], 0.0), 0.0)
            for s in spans}


def summarize(records: List[dict]) -> Dict[str, dict]:
    """Per-phase rollup: ``{name: {count, total, p50, p99, events}}``
    with ``total``/percentiles over SELF time (nested child time
    excluded, :func:`self_times`) in seconds.  Instant events roll up
    as counts under their own names."""
    selfs = self_times(records)
    by_name: Dict[str, List[float]] = {}
    ev_counts: Dict[str, int] = {}
    for rec in records:
        if rec.get("kind") == "span":
            by_name.setdefault(rec["name"], []).append(selfs[rec["id"]])
        elif rec.get("kind") == "event":
            ev_counts[rec["name"]] = ev_counts.get(rec["name"], 0) + 1
    out: Dict[str, dict] = {}
    for name, vals in by_name.items():
        vals = sorted(vals)
        out[name] = {"count": len(vals), "total": sum(vals),
                     "p50": nearest_rank(vals, 0.50),
                     "p99": nearest_rank(vals, 0.99),
                     "events": 0}
    for name, n in ev_counts.items():
        row = out.setdefault(name, {"count": 0, "total": 0.0,
                                    "p50": 0.0, "p99": 0.0, "events": 0})
        row["events"] += n
    return out


def run_scoped(fn: Callable, *args, **kwargs):
    """(result, records): run ``fn`` under a fresh tracer and return its
    records — the programmatic one-shot the report helpers build on."""
    with tracing() as t:
        result = fn(*args, **kwargs)
    return result, t.records()
