"""Typed metrics registry: counters, gauges, histograms, one snapshot.

Before ISSUE 11 the repo's counters were scattered attributes with no
shared schema: model audit attrs (``oom_backoffs_``,
``io_retries_used_``, ``bf16_guard_corrected_rows_``), serving's
per-model counters, and the ``note_dispatch`` label list.  This module
gives them one home: a process-wide :class:`MetricsRegistry` of typed
metrics that the existing signals WRITE THROUGH at their increment
sites — every public API (model attrs, ``ServingEngine.stats()``,
``log_dispatches``) keeps its exact surface, and the registry adds the
cross-cutting view: ``snapshot()`` as a dict, ``to_json()`` for export.

Write-through contract: registry writes are host-side integer/float
bookkeeping only — no dispatches, no threads, no IO — so they can never
perturb a trajectory (the obs=0 parity oracle holds trivially) and cost
nanoseconds at sites that already take a lock or cross the dispatch
boundary.  ``reset()`` zeroes the process view (bench harnesses isolate
runs with it); per-fit semantics stay on the model attrs, which remain
the documented per-fit reading surface.

Metric naming: dotted lowercase paths, subsystem first —
``fit.oom_backoffs``, ``io.retries``, ``serve.dispatches``,
``dispatch.<label>`` (the migrated ``note_dispatch`` labels).

Pure stdlib — importable from every layer.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Union

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "REGISTRY", "registry", "nearest_rank"]


def nearest_rank(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list (no numpy — the
    obs modules stay stdlib).  The ONE implementation both the
    histogram metrics and the trace summaries use; 0.0 on empty."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class Counter:
    """Monotonic event count (increments only)."""

    kind = "counter"
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def snapshot(self) -> Union[int, float]:
        return self.value


class Gauge:
    """Last-written level (set/add; e.g. the effective scan chunk)."""

    kind = "gauge"
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[float] = None

    def set(self, v) -> None:
        self.value = v

    def add(self, v) -> None:
        self.value = (self.value or 0) + v

    def snapshot(self):
        return self.value


class Histogram:
    """Streaming distribution summary: count/sum/min/max plus a bounded
    reservoir for percentile estimates (uniform over the first
    ``reservoir`` observations, then systematic thinning — deterministic,
    no RNG, good enough for operator-facing p50/p99)."""

    kind = "histogram"
    __slots__ = ("name", "count", "sum", "min", "max",
                 "_reservoir", "_cap", "_stride")

    def __init__(self, name: str, reservoir: int = 512):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._reservoir: List[float] = []
        self._cap = int(reservoir)
        self._stride = 1

    def observe(self, v) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if (self.count - 1) % self._stride == 0:
            self._reservoir.append(v)
            if len(self._reservoir) > self._cap:
                # Thin deterministically: keep every other sample and
                # double the stride — the reservoir stays a uniform
                # systematic sample of the stream.
                self._reservoir = self._reservoir[::2]
                self._stride *= 2

    def percentile(self, q: float) -> Optional[float]:
        if not self._reservoir:
            return None
        return nearest_rank(sorted(self._reservoir), q)

    def snapshot(self) -> dict:
        return {"count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "mean": self.sum / self.count if self.count else None,
                "p50": self.percentile(0.50),
                "p99": self.percentile(0.99)}


class MetricsRegistry:
    """Name -> typed metric, get-or-create semantics.

    A name is permanently bound to its first-requested type; asking for
    the same name as a different type raises (two call sites silently
    sharing a name across types would corrupt both readings)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} is already registered as a "
                    f"{type(m).__name__}, not a {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, reservoir: int = 512) -> Histogram:
        return self._get(name, Histogram, reservoir)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, dict]:
        """``{name: {"kind", "value"}}`` over every registered metric —
        the operator-facing dict (and the heartbeat's counter block)."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: {"kind": m.kind, "value": m.snapshot()}
                for m in metrics}

    def identity(self) -> dict:
        """The producing process's fleet coordinates (ISSUE 13) —
        ``process_index``/``process_count``/``host`` — so exported
        snapshots from N hosts stay attributable.  Uncached: the
        registry is process-global and outlives telemetry scopes."""
        from kmeans_tpu.obs.identity import identity
        return identity()

    def to_json(self, indent: Optional[int] = None) -> str:
        """Snapshot as JSON, stamped with the producer's fleet identity
        under ``__identity__`` (a reserved name no metric can take:
        metric names are dotted lowercase paths by convention)."""
        out = dict(self.snapshot())
        out["__identity__"] = self.identity()
        return json.dumps(out, indent=indent, sort_keys=True)

    def reset(self) -> None:
        """Drop every metric (bench/test isolation).  Live references
        held by call sites keep counting into detached objects, so
        reset between workloads, not mid-flight."""
        with self._lock:
            self._metrics.clear()


#: The process-wide registry every instrumented site writes through.
REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide default registry (function form, so call sites
    can be monkeypatched in tests without touching the module global)."""
    return REGISTRY
