"""Fleet observability: merged timelines, comm accounting, stragglers.

ISSUE 13 tentpole.  r15/r16 telemetry is strictly per-process: one
tracer, one heartbeat sink, per-device XLA cost.  Before ROADMAP item
1's elastic multi-host orchestration loop (and the >= 1e9-row run it
drives) can land, the fleet itself must be observable: who is slow,
what the collectives cost, and one merged timeline an operator can
read.  This module is that layer, built ON the per-process streams —
it never adds a dispatch, a thread, or a byte to the fits it observes:

* **Merged timelines** — :func:`merge_traces` aligns N per-host trace
  JSONL streams (the ``trace.p{idx}.jsonl`` files ``obs.tracing``
  writes) onto one clock and returns a single record list; Chrome
  export puts each host on its own track (``obs.chrome_events``).
  Clock rule: hosts exiting a SYNCED barrier
  (``parallel.multihost.fleet_barrier`` — emitted at every fit start
  while telemetry is on) do so at the same true instant up to the
  barrier release skew, so the k-th common barrier event anchors host
  k's monotonic clock to the reference host's.  The residual is
  MEASURED, not assumed: with m >= 2 common barriers the per-host
  offset spread across barriers bounds the drift (``skew_bound_s``),
  and the committed :data:`FLEET_SKEW_BOUND_S` is the acceptance
  threshold the multi-process tests assert.  Streams without synced
  barriers (simulated fleets, single-host files) fall back to the
  wall-clock anchors in their headers (``align='wall'`` — exact on one
  machine, NTP-trusting across machines, ``skew_bound_s=None``).
  Unalignable inputs (no barriers AND no headers) raise
  :class:`~kmeans_tpu.obs.trace.TraceReadError` — the CLI's exit-2
  classification.

* **Collective-comms accounting** — :func:`comm_bytes_model` is the
  analytic per-dispatch byte bill of the collectives a fit actually
  pays (the per-iteration (k, D) stat psums, seeding's cross-shard
  top-k combine, ``from_process_local``'s ``process_allgather``, the
  TP per-chunk minima gathers), in the SAME convention as the measured
  side: per-device result bytes, loop bodies once.  The measured side
  is :attr:`CostRecord.collective_bytes` (the collective instructions
  XLA actually emitted into the compiled program, ISSUE 12's capture
  extended); :func:`comm_crosscheck` applies the committed
  :data:`COMM_AGREEMENT_RTOL` band.  ``wire_bytes_per_device`` adds
  the ring-algorithm estimate (``2 (S-1)/S`` of an all-reduce payload)
  for hardware interconnect budgeting.

* **Straggler/skew detection** — :func:`straggler_report` over merged
  heartbeats flags per-host lag and throughput skew with committed
  thresholds (:data:`STRAGGLER_RATE_FACTOR` /
  :data:`STRAGGLER_BEHIND_ITERS` / :data:`STRAGGLER_STALL_FACTOR`),
  and ``python -m kmeans_tpu fleet-status <dir>`` renders the table —
  the exact surface ROADMAP item 1's elastic loop will consume.

Pure stdlib at import (numpy/jax never load); the comm model is plain
arithmetic.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional, Sequence

from kmeans_tpu.obs import trace as _trace
from kmeans_tpu.obs.trace import TraceReadError

__all__ = [
    "expand_fleet_paths", "sniff_stream", "load_trace",
    "merge_traces",
    "read_heartbeats", "merge_heartbeats", "straggler_report",
    "format_fleet_status", "format_fleet_summary",
    "comm_bytes_model", "comm_crosscheck", "format_comm_table",
    "FLEET_SKEW_BOUND_S", "COMM_AGREEMENT_RTOL",
    "STRAGGLER_RATE_FACTOR", "STRAGGLER_BEHIND_ITERS",
    "STRAGGLER_STALL_FACTOR", "STRAGGLER_STALL_MIN_S",
    "TERMINAL_PHASES",
]

#: Committed barrier-alignment acceptance bound (seconds): the measured
#: per-host offset spread across common synced barriers must stay under
#: this for a merge to be trusted — asserted by the real multi-process
#: tests.  Localhost barrier release skew is ~ms; 250 ms leaves head-
#: room for loaded CI hosts while still catching a mis-paired barrier
#: (which skews by whole fit-lengths).
FLEET_SKEW_BOUND_S = 0.25

#: Committed analytic-vs-compiled collective-bytes agreement band
#: (|ratio - 1| <= 10%), the FLOPS_AGREEMENT_RTOL discipline applied to
#: comm: the model and the HLO share one convention (per-device result
#: bytes, loop bodies once), so the kmeans/gmm fit programs match to
#: the byte on CPU — the band absorbs backend/version HLO variation,
#: and a breach is a REPORTED finding, never silently trusted.
COMM_AGREEMENT_RTOL = 0.10

#: Straggler decision rules, committed (the repo's pre-registration
#: discipline).  A host flags:
#: * ``slow``   — rows_per_sec < RATE_FACTOR x the fleet median,
#: * ``behind`` — iteration trails the fleet leader by >= BEHIND_ITERS,
#: * ``stalled`` — it is silent for longer than
#:   max(STALL_FACTOR x the fleet median beat interval, STALL_MIN_S)
#:   (the floor keeps sub-second CPU fits from flagging on scheduler
#:   jitter) AND either trails the leader, or — under an EXPLICIT
#:   ``now`` (a live monitor's wall clock, ISSUE 19 fix) — its last
#:   beat is not a TERMINAL one.  Post-hoc reads (``now`` defaulted to
#:   the newest record) keep the behind-only rule: every host of a
#:   completed fleet is "old", and flagging them all would make every
#:   post-mortem read as a mass stall.  A live read is different: a
#:   host at the leader iteration whose last phase is mid-fit and that
#:   has gone silent past the window IS stalled (the whole fleet being
#:   paused must not read healthy), while a host whose last beat is
#:   terminal (:data:`TERMINAL_PHASES`) finished its fit and never
#:   flags.
STRAGGLER_RATE_FACTOR = 0.5
STRAGGLER_BEHIND_ITERS = 2
STRAGGLER_STALL_FACTOR = 3.0
STRAGGLER_STALL_MIN_S = 1.0

#: Heartbeat phases that mark a host's fit COMPLETE (the end-of-fit
#: completion beats: ``finished`` from the host-loop/stream engines,
#: ``fit`` from the one-dispatch completion record).  A terminal last
#: beat means silence is success, not a stall.
TERMINAL_PHASES = ("fit", "finished")


# ------------------------------------------------------------- loading

def expand_fleet_paths(paths) -> List[str]:
    """Resolve CLI inputs into trace/heartbeat files: a directory
    expands to its sorted ``*.jsonl`` members, a glob pattern to its
    matches, a file to itself.  Raises :class:`TraceReadError` when an
    input names nothing (the exit-2 contract)."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        p = str(p)
        if os.path.isdir(p):
            hits = sorted(glob.glob(os.path.join(p, "*.jsonl")))
            if not hits:
                raise TraceReadError(f"{p}: directory holds no .jsonl "
                                     f"files")
            out.extend(hits)
        elif glob.has_magic(p):
            hits = sorted(glob.glob(p))
            if not hits:
                raise TraceReadError(f"{p}: glob matched no files")
            out.extend(hits)
        else:
            if not os.path.exists(p):
                raise TraceReadError(f"cannot read trace file {p}: "
                                     f"no such file")
            out.append(p)
    seen = set()
    uniq = []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq


def sniff_stream(path) -> str:
    """Cheap first-line content sniff: ``'trace'`` (a JSON object with
    ``"kind"`` — header/span/event records), ``'heartbeat'`` (a JSON
    object with ``"ts"`` and no ``"kind"``), else ``'unknown'``.  The
    ONE classification rule both CLIs use to tell co-located telemetry
    files apart (``obs.tracing`` and ``obs.heartbeat`` sinks naturally
    share a directory): each CLI skips the OTHER family and keeps
    ``'unknown'`` for its strict reader — a garbage file must classify
    as malformed (exit 2), never be silently dropped as "the other
    kind"."""
    try:
        with open(path) as f:
            first = f.readline()
        rec = json.loads(first)
    except (OSError, ValueError):
        return "unknown"
    if not isinstance(rec, dict):
        return "unknown"
    if "kind" in rec:
        return "trace"
    if "ts" in rec:
        return "heartbeat"
    return "unknown"


def load_trace(path) -> dict:
    """One host's trace stream: ``{"path", "header", "records",
    "process_index", "process_count", "host", "wall0"}``.  Identity is
    read from the header record (r17 format) or the first span/event's
    stamps; a stream carrying neither still loads (``process_index``
    None) — single-stream analyses work, fleet merges then key off
    file order."""
    records = _trace.read_jsonl(path)
    header = next((r for r in records if r.get("kind") == "header"), None)
    body = [r for r in records if r.get("kind") in ("span", "event")]
    src = header if header and "process_index" in header else \
        next((r for r in body if "process_index" in r), {})
    return {
        "path": str(path),
        "header": header,
        "records": body,
        "process_index": src.get("process_index"),
        "process_count": src.get("process_count"),
        "host": src.get("host"),
        "wall0": (header or {}).get("wall0"),
    }


def _barriers(stream: dict) -> List[dict]:
    """The stream's SYNCED fleet-barrier events, in occurrence order
    (only a barrier that really crossed processes anchors clocks; the
    single-process/simulated emission is a marker, not a sync)."""
    out = []
    for r in stream["records"]:
        if r.get("kind") == "event" and r.get("name") == "fleet.barrier":
            attrs = r.get("attrs", {}) or {}
            if attrs.get("synced"):
                out.append(r)
    return out


# ------------------------------------------------------------- merging

def merge_traces(paths_or_streams) -> dict:
    """Merge per-host trace streams into one clock-aligned timeline.

    Accepts paths (str/PathLike, dirs/globs expanded) or pre-loaded
    :func:`load_trace` dicts.  Returns::

        {"hosts":   [{process_index, host, path, offset_s, records}...],
         "align":   "single" | "barrier" | "wall",
         "barriers": <common synced barriers used>,
         "skew_bound_s": <measured drift bound; None under 'wall'>,
         "ntp_delta_s":  <wall-vs-barrier clock disagreement; info>,
         "records": [aligned span/event records, t-sorted]}

    Aligned records are COPIES stamped ``fleet_merged`` (their
    ``t0``/``t1`` live on the reference host's clock; chrome export
    tracks by ``process_index``).  Raises :class:`TraceReadError` for
    malformed streams, duplicate process indices, or clock-unalignable
    inputs (multiple hosts, no synced barriers, no wall anchors)."""
    streams = []
    for item in (paths_or_streams if isinstance(paths_or_streams,
                                                (list, tuple))
                 else [paths_or_streams]):
        if isinstance(item, dict):
            streams.append(item)
        else:
            for p in expand_fleet_paths(item):
                streams.append(load_trace(p))
    if not streams:
        raise TraceReadError("no trace streams to merge")
    # Stable identity per stream: stamped index, else file order.
    for i, s in enumerate(streams):
        if s.get("process_index") is None:
            s["process_index"] = i
        if not s.get("host"):
            s["host"] = f"host{s['process_index']}"
    idxs = [s["process_index"] for s in streams]
    if len(set(idxs)) != len(idxs):
        dupes = sorted({i for i in idxs if idxs.count(i) > 1})
        raise TraceReadError(
            f"duplicate process_index {dupes} across trace streams — "
            f"merging two files from the same process double-counts it")
    streams.sort(key=lambda s: s["process_index"])
    ref = streams[0]

    align = "single"
    barriers_used = 0
    skew_bound: Optional[float] = None
    ntp_delta: Optional[float] = None
    offsets: Dict[int, float] = {ref["process_index"]: 0.0}
    if len(streams) > 1:
        per_host = [_barriers(s) for s in streams]
        m = min(len(b) for b in per_host)
        if m >= 1:
            # Tag sequences must agree position-by-position: SPMD hosts
            # execute the same barriers in the same order; a mismatch
            # means the streams are from different runs.
            tags = [[(b.get("attrs") or {}).get("tag") for b in bs[:m]]
                    for bs in per_host]
            if any(t != tags[0] for t in tags[1:]):
                raise TraceReadError(
                    "clock-unalignable: fleet.barrier tag sequences "
                    f"disagree across hosts ({tags}) — streams are not "
                    f"from one run")
            align = "barrier"
            barriers_used = m
            ref_t = [b["t0"] for b in per_host[0]]
            skew_bound = 0.0
            for s, bs in zip(streams[1:], per_host[1:]):
                per_b = [ref_t[j] - bs[j]["t0"] for j in range(m)]
                offsets[s["process_index"]] = per_b[0]
                skew_bound = max(skew_bound,
                                 max(abs(o - per_b[0]) for o in per_b))
            if ref["wall0"] is not None and all(
                    s["wall0"] is not None for s in streams[1:]):
                ntp_delta = max(
                    (abs((s["wall0"] + bs[0]["t0"])
                         - (ref["wall0"] + ref_t[0]))
                     for s, bs in zip(streams[1:], per_host[1:])),
                    default=0.0)
        else:
            if any(s["wall0"] is None for s in streams):
                raise TraceReadError(
                    "clock-unalignable: streams share no synced "
                    "fleet.barrier event and lack wall-clock headers")
            align = "wall"
            for s in streams[1:]:
                offsets[s["process_index"]] = s["wall0"] - ref["wall0"]

    merged: List[dict] = []
    hosts = []
    for s in streams:
        off = offsets[s["process_index"]]
        hosts.append({"process_index": s["process_index"],
                      "host": s["host"], "path": s.get("path"),
                      "offset_s": off, "records": len(s["records"])})
        for r in s["records"]:
            r2 = dict(r)
            r2["t0"] = r["t0"] + off
            if r.get("t1") is not None:
                r2["t1"] = r["t1"] + off
            r2.setdefault("process_index", s["process_index"])
            r2.setdefault("host", s["host"])
            r2["fleet_merged"] = True
            merged.append(r2)
    merged.sort(key=lambda r: r["t0"])
    return {"hosts": hosts, "align": align, "barriers": barriers_used,
            "skew_bound_s": skew_bound, "ntp_delta_s": ntp_delta,
            "records": merged}


def format_fleet_summary(merged: dict) -> str:
    """One operator-facing block describing a merged timeline: host
    roster with clock offsets, the alignment rule used, and its
    measured skew bound."""
    lines = [f"fleet timeline: {len(merged['hosts'])} host"
             f"{'s' if len(merged['hosts']) != 1 else ''}, "
             f"{len(merged['records'])} records, "
             f"align={merged['align']}"
             + (f" ({merged['barriers']} barriers)"
                if merged["align"] == "barrier" else "")]
    if merged["skew_bound_s"] is not None:
        lines[0] += f", skew_bound={merged['skew_bound_s'] * 1e3:.3f}ms"
    if merged.get("ntp_delta_s") is not None:
        lines[0] += f", wall_delta={merged['ntp_delta_s'] * 1e3:.1f}ms"
    lines.append(f"  {'proc':>4} {'host':<20} {'offset ms':>12} "
                 f"{'records':>8}")
    for h in merged["hosts"]:
        lines.append(f"  {h['process_index']:>4} {h['host'][:20]:<20} "
                     f"{h['offset_s'] * 1e3:>12.3f} {h['records']:>8}")
    return "\n".join(lines)


# ---------------------------------------------------------- heartbeats

def read_heartbeats(path) -> List[dict]:
    """Heartbeat JSONL -> records.  Tolerant of trailing torn lines (a
    live fit's sink may be mid-write — the fleet-status use case) but
    classifies a file with NO parseable record as malformed."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as e:
        raise TraceReadError(f"cannot read heartbeat file {path}: {e}") \
            from e
    records = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            if i == len(lines) - 1:
                continue                # torn tail of a live writer
            raise TraceReadError(
                f"{path}:{i + 1}: not a JSON record ({e.msg})") from e
        if not isinstance(rec, dict) or "ts" not in rec:
            raise TraceReadError(
                f"{path}:{i + 1}: not a heartbeat record (missing 'ts')")
        records.append(rec)
    if not records:
        raise TraceReadError(f"{path}: no heartbeat records")
    return records


def merge_heartbeats(paths) -> List[dict]:
    """All hosts' heartbeat records, ts-sorted.  Heartbeats are merged
    on their wall clocks (records carry ``ts``): straggler thresholds
    are seconds-scale, far above same-fleet NTP skew; identity comes
    from each record's own stamps (falling back to file order)."""
    out: List[dict] = []
    for i, p in enumerate(expand_fleet_paths(paths)):
        for rec in read_heartbeats(p):
            rec = dict(rec)
            rec.setdefault("process_index", i)
            rec.setdefault("host", f"host{i}")
            out.append(rec)
    out.sort(key=lambda r: r.get("ts", 0.0))
    return out


def _median(vals: Sequence[float]) -> Optional[float]:
    """True median (midpoint-averaged for even counts) — NOT the
    nearest-rank rule the histograms use: on a 2-host fleet nearest
    rank degenerates to one host's own value, which would let that host
    define the 'fleet' it is compared against and never flag."""
    vals = sorted(vals)
    if not vals:
        return None
    mid = len(vals) // 2
    if len(vals) % 2:
        return vals[mid]
    return 0.5 * (vals[mid - 1] + vals[mid])


def straggler_report(records: List[dict], *, now: Optional[float] = None,
                     rate_factor: float = STRAGGLER_RATE_FACTOR,
                     behind_iters: int = STRAGGLER_BEHIND_ITERS,
                     stall_factor: float = STRAGGLER_STALL_FACTOR,
                     stall_min_s: float = STRAGGLER_STALL_MIN_S) -> dict:
    """Per-host progress/liveness/lag over merged heartbeat records,
    with the committed straggler rules (module docstring).  ``now``
    defaults to the newest record's ``ts`` (post-hoc analysis); a live
    monitor passes ``time.time()``.  Returns ``{"hosts": [row...],
    "flagged": [process_index...], "healthy": bool, ...}`` — the
    payload ``fleet-status`` renders and ROADMAP item 1's elastic loop
    consumes."""
    by_host: Dict[int, List[dict]] = {}
    names: Dict[int, str] = {}
    for r in records:
        idx = int(r.get("process_index", 0))
        by_host.setdefault(idx, []).append(r)
        names.setdefault(idx, str(r.get("host", f"host{idx}")))
    if not by_host:
        raise TraceReadError("no heartbeat records to report on")
    # An EXPLICIT now is a live monitor's wall clock; the default is
    # post-hoc analysis anchored to the newest record.  The stall rule
    # below is stricter under a live clock (ISSUE 19 fix): a paused
    # fleet must not read healthy just because nobody is behind.
    live = now is not None
    if now is None:
        now = max(r.get("ts", 0.0) for r in records)

    rows = []
    for idx in sorted(by_host):
        recs = sorted(by_host[idx], key=lambda r: r.get("ts", 0.0))
        beats = [r for r in recs if not r.get("tick")]
        iters = [r["iteration"] for r in recs if "iteration" in r]
        rates = [r["rows_per_sec"] for r in beats
                 if r.get("rows_per_sec")]
        ts = [r["ts"] for r in beats]
        intervals = [b - a for a, b in zip(ts, ts[1:]) if b > a]
        rows.append({
            "process_index": idx, "host": names[idx],
            "beats": len(beats), "ticks": len(recs) - len(beats),
            "phase": recs[-1].get("phase"),
            "iteration": max(iters) if iters else None,
            "inertia": recs[-1].get("inertia"),
            "rows_per_sec": _median(rates),
            "beat_interval_s": _median(intervals),
            "ts": recs[-1].get("ts"),
            "last_age_s": max(0.0, now - recs[-1].get("ts", now)),
            "flags": [],
        })

    lead = max((r["iteration"] for r in rows
                if r["iteration"] is not None), default=None)
    fleet_rate = _median([r["rows_per_sec"] for r in rows
                          if r["rows_per_sec"]])
    fleet_interval = _median([r["beat_interval_s"] for r in rows
                              if r["beat_interval_s"]])
    for r in rows:
        behind = (lead - r["iteration"]
                  if lead is not None and r["iteration"] is not None
                  else 0)
        r["behind"] = behind
        if behind >= behind_iters:
            r["flags"].append("behind")
        if len(rows) > 1 and r["rows_per_sec"] and fleet_rate \
                and r["rows_per_sec"] < rate_factor * fleet_rate:
            r["flags"].append("slow")
        stall_after = max(stall_factor * (fleet_interval or 0.0),
                          stall_min_s)
        # Post-hoc (default now): behind-only, so a completed fleet's
        # uniformly-old beats stay silent.  Live (explicit now): a host
        # whose last beat is MID-FIT and silent past the window is
        # stalled even at the leader iteration — the live-but-paused
        # fleet the ISSUE 19 autopilot must see; terminal completion
        # beats (TERMINAL_PHASES) exempt finished hosts.
        mid_fit = r["phase"] not in TERMINAL_PHASES
        if (behind > 0 or (live and mid_fit)) \
                and r["last_age_s"] > stall_after:
            r["flags"].append("stalled")
    flagged = [r["process_index"] for r in rows if r["flags"]]
    return {"hosts": rows, "flagged": flagged,
            "healthy": not flagged, "now": now,
            "fleet": {"leader_iteration": lead,
                      "median_rows_per_sec": fleet_rate,
                      "median_beat_interval_s": fleet_interval},
            "thresholds": {"rate_factor": rate_factor,
                           "behind_iters": behind_iters,
                           "stall_factor": stall_factor,
                           "stall_min_s": stall_min_s}}


def format_fleet_status(report: dict) -> str:
    """The ``fleet-status`` table: one row per host —
    progress (iteration/phase), throughput, liveness, lag flags."""
    f = report["fleet"]
    head = (f"fleet status: {len(report['hosts'])} host"
            f"{'s' if len(report['hosts']) != 1 else ''}, leader at "
            f"iteration {f['leader_iteration']}, "
            f"{'HEALTHY' if report['healthy'] else 'STRAGGLERS: ' + str(report['flagged'])}")
    lines = [head,
             f"  {'proc':>4} {'host':<18} {'phase':<10} {'iter':>6} "
             f"{'behind':>6} {'rows/s':>10} {'beat s':>8} {'age s':>7}"
             f"  flags"]
    for r in report["hosts"]:
        rate = f"{r['rows_per_sec']:.0f}" if r["rows_per_sec"] else "-"
        beat = f"{r['beat_interval_s']:.3f}" \
            if r["beat_interval_s"] is not None else "-"
        it = r["iteration"] if r["iteration"] is not None else "-"
        lines.append(
            f"  {r['process_index']:>4} {r['host'][:18]:<18} "
            f"{str(r['phase'])[:10]:<10} {it:>6} {r['behind']:>6} "
            f"{rate:>10} {beat:>8} {r['last_age_s']:>7.2f}"
            f"  {','.join(r['flags']) or '-'}")
    return "\n".join(lines)


# ------------------------------------------------- collective accounting

def _ring_wire(result_bytes: float, group: int, collective: str) -> float:
    """Per-device interconnect bytes under the standard ring algorithm:
    an all-reduce moves ``2 (S-1)/S`` of its payload per device
    (reduce-scatter + all-gather halves), a plain all-gather
    ``(S-1)/S`` of its RESULT (each device receives every shard but its
    own).  Zero for a group of one."""
    if group <= 1:
        return 0.0
    if collective == "all-reduce":
        return 2.0 * (group - 1) / group * result_bytes
    return (group - 1) / group * result_bytes


def comm_bytes_model(family: str = "kmeans", *, k: int, d: int,
                     data_shards: int = 1, model_shards: int = 1,
                     acc_bytes: int = 4, compute_sse: bool = True,
                     empty_cluster: str = "keep", cov_type: str = "diag",
                     n_members: int = 1, n_chunks: int = 1,
                     seeding_rounds: int = 0, seeding_cap: int = 0,
                     processes: int = 1, k_shard: int = 0,
                     chunk_rows: int = 0) -> dict:
    """The analytic collective-traffic bill of one fit (module
    docstring).  Site rows carry ``result_bytes`` (per-device, the
    XLA/HLO convention the cross-check uses), ``count`` (times the
    RUNNING fit pays it per iteration or per fit — a scan-body site
    appears once in HLO but ``n_chunks`` times per iteration), and
    ``wire_bytes_per_device`` (ring estimate, hardware budgeting).

    Totals: ``hlo_program_bytes`` — what the compiled FIT program's
    collective instructions should sum to (the
    :func:`comm_crosscheck` reference); ``per_iteration_bytes`` /
    ``per_fit_bytes`` — the running bill.  ``empty_cluster='resample'``
    is modeled as 'keep' (its conditional Gumbel refill collectives are
    outside the committed model — documented, not pretended).

    ``k_shard`` (ISSUE 16, with ``model_shards > 1``) switches the
    kmeans-family bill to the K-SHARDED tier: the statistics psums stay
    sharded on the model axis (one (k/M, D) block over the DATA axis
    only — the term that made dense TP traffic scale with full k), the
    per-dispatch centroid-table gather disappears (the step consumes
    its sharded block directly), and the headline per-iteration
    collective becomes the scan-bodied (distance, index) pair
    all-reduce — two ``pmin`` legs of ``chunk_rows`` f32 + i32 over the
    model axis, ``n_chunks`` times per iteration.  Pass ``chunk_rows``
    (the scan chunk size) to size it; unlike the dense TP path — whose
    per-chunk minima gathers ride a program documented as
    modeled-to-the-table — the pair all-reduce IS the committed wire
    cost of the k-sharded tier, so it is in the model."""
    S, M = int(data_shards), int(model_shards)
    group = S * M
    R = int(n_members)
    k_pad = -(-int(k) // M) * M if M > 1 else int(k)
    kl = k_pad // M                                     # per-shard rows
    sites: List[dict] = []

    def site(name, collective, result_bytes, *, scope, count=1,
             grp=group, in_program=True):
        sites.append({
            "site": name, "collective": collective,
            "result_bytes": float(result_bytes), "scope": scope,
            "count": count, "group": grp, "in_program": in_program,
            "wire_bytes_per_device": _ring_wire(result_bytes, grp,
                                                collective)})

    kshard = bool(k_shard) and M > 1
    if family in ("kmeans", "spherical", "bisecting", "minibatch"):
        if kshard:
            # K-sharded tier (ISSUE 16): each model shard psums ONLY
            # its own (k/M, D) statistics block over the data axis —
            # the model axis is the output sharding, not a reduction.
            site("estep.psum_sums", "all-reduce", R * kl * d * acc_bytes,
                 scope="iteration", grp=S)
            site("estep.psum_counts", "all-reduce", R * kl * acc_bytes,
                 scope="iteration", grp=S)
            # The pair select replacing the dense minima gather: two
            # pmin legs (f32 global-min distance + i32 masked global
            # index) per scan-bodied chunk over the model axis.
            site("estep.pmin_assign_pair", "all-reduce",
                 R * chunk_rows * (acc_bytes + 4), scope="iteration",
                 count=n_chunks, grp=M)
        else:
            site("estep.psum_sums", "all-reduce",
                 R * k_pad * d * acc_bytes, scope="iteration")
            site("estep.psum_counts", "all-reduce", R * k_pad * acc_bytes,
                 scope="iteration")
        if compute_sse:
            site("estep.psum_sse", "all-reduce", R * acc_bytes,
                 scope="iteration")
        if empty_cluster == "farthest":
            # Per-shard farthest candidates: (dist f32, index s64,
            # point) gathered over every device, plus the winner
            # broadcast pair the update phase gathers (measured shape
            # set on the r17 CPU probe).
            far = (group * R * (acc_bytes + 8 + d * acc_bytes)
                   + group * (acc_bytes + d * acc_bytes))
            site("estep.gather_farthest", "all-gather", far,
                 scope="iteration")
    elif family == "gmm":
        site("estep.psum_resp", "all-reduce", R * k_pad * acc_bytes,
             scope="iteration")
        site("estep.psum_xsum", "all-reduce", R * k_pad * d * acc_bytes,
             scope="iteration")
        if cov_type in ("diag", "spherical"):
            # The spherical E pass accumulates the same (k, D)-shaped
            # second-moment table as diag (measured on the r17 CPU HLO
            # probe; the spherical reduction to one variance per
            # component happens in the M-step, after the psum).
            site("estep.psum_x2sum", "all-reduce",
                 R * k_pad * d * acc_bytes, scope="iteration")
        elif cov_type == "full":
            site("estep.psum_scatter", "all-reduce",
                 R * k_pad * d * d * acc_bytes, scope="iteration")
        elif cov_type == "tied":
            # Tied pools one (D, D) scatter per iteration (the pooled
            # covariance's data-dependent half rides the E pass)...
            site("estep.psum_scatter_tied", "all-reduce",
                 R * d * d * acc_bytes, scope="iteration")
        site("estep.psum_loglik", "all-reduce", R * acc_bytes,
             scope="iteration")
        site("fit.psum_weight_total", "all-reduce", acc_bytes,
             scope="dispatch")
        if cov_type == "tied":
            # ...and additionally pays the loop-INVARIANT total-scatter
            # pass once per fit, as its own program (make_total_scatter
            # _fn) — outside the fit-program cross-check.
            site("fit.psum_total_scatter", "all-reduce",
                 d * d * acc_bytes, scope="fit", in_program=False)
    else:
        raise ValueError(f"unknown family {family!r}")

    if family in ("kmeans", "spherical", "bisecting", "minibatch") \
            and M > 1 and not kshard:
        # TP composition: the per-dispatch (k_pad, D) centroid-table
        # gather over the model axis.  (The per-chunk minima gathers of
        # the TP assignment path are chunk-shaped and scan-bodied; they
        # are deliberately OUTSIDE the committed model — TP fit
        # programs are documented as modeled-to-the-table, and the
        # cross-check tests run the DP programs the headline pays.)
        site("tp.gather_centroid_table", "all-gather",
             k_pad * d * acc_bytes, scope="dispatch", grp=M)

    if seeding_rounds and seeding_cap:
        # k-means|| cross-shard top-k combine: per round, all-gathers of
        # per-shard candidate (score, index, row) tables over the data
        # axis (parallel.distributed lines ~578-580).  Separate program
        # (the init pipeline), so not in the fit-program cross-check.
        per_round = (S * seeding_cap * acc_bytes           # scores
                     + S * seeding_cap * acc_bytes         # indices
                     + S * seeding_cap * d * acc_bytes)    # rows
        site("seed.gather_topk", "all-gather", per_round, scope="fit",
             count=seeding_rounds, grp=S, in_program=False)
    if processes > 1:
        site("data.process_allgather_counts", "all-gather",
             processes * 8, scope="dataset", grp=processes,
             in_program=False)

    per_iter = sum(s["result_bytes"] * s["count"] for s in sites
                   if s["scope"] == "iteration")
    per_fit = sum(s["result_bytes"] * s["count"] for s in sites
                  if s["scope"] in ("dispatch", "fit", "dataset"))
    program = sum(s["result_bytes"] for s in sites if s["in_program"])
    wire_iter = sum(s["wire_bytes_per_device"] * s["count"]
                    for s in sites if s["scope"] == "iteration")
    return {"family": family, "k": k, "k_pad": k_pad, "d": d,
            "data_shards": S, "model_shards": M, "acc_bytes": acc_bytes,
            "n_members": R, "k_shard": int(k_shard) if kshard else 0,
            "sites": sites,
            "per_iteration_bytes": per_iter,
            "per_fit_bytes": per_fit,
            "hlo_program_bytes": program,
            "wire_bytes_per_device_per_iteration": wire_iter}


def comm_crosscheck(model: dict, record,
                    rtol: float = COMM_AGREEMENT_RTOL) -> dict:
    """Analytic-vs-compiled collective bytes for one fit program:
    ``ratio`` = measured (``CostRecord.collective_bytes``) over the
    model's ``hlo_program_bytes``; ``agree`` = within the committed
    band.  ``ratio=None`` (no HLO text on this backend, or a group of
    one where XLA elides the collectives) reports ``agree=None`` —
    unknown, never silently passed."""
    measured = getattr(record, "collective_bytes", None)
    expected = model["hlo_program_bytes"]
    ratio = (measured / expected
             if measured is not None and expected > 0 else None)
    return {"analytic_bytes": expected, "measured_bytes": measured,
            "collectives": getattr(record, "collectives", None),
            "ratio": ratio,
            "agree": (None if ratio is None
                      else bool(abs(ratio - 1.0) <= rtol)),
            "rtol": rtol}


def format_comm_table(model: dict, crosscheck: Optional[dict] = None
                      ) -> str:
    """Fixed-width rendering of the analytic comm bill (+ the measured
    cross-check line when one ran) — the ``dryrun_multichip`` /
    ``trace summarize`` artifact."""
    lines = [f"collective traffic (analytic, {model['family']} "
             f"k={model['k']} d={model['d']} "
             f"S={model['data_shards']}x{model['model_shards']}):",
             f"  {'site':<28} {'collective':<12} {'bytes':>10} "
             f"{'count':>6} {'scope':<10} {'wire/dev':>10}"]
    for s in model["sites"]:
        lines.append(
            f"  {s['site']:<28} {s['collective']:<12} "
            f"{s['result_bytes']:>10.0f} {s['count']:>6} "
            f"{s['scope']:<10} {s['wire_bytes_per_device']:>10.0f}")
    lines.append(
        f"  per-iteration {model['per_iteration_bytes']:.0f} B "
        f"(wire/dev {model['wire_bytes_per_device_per_iteration']:.0f} "
        f"B); per-fit extras {model['per_fit_bytes']:.0f} B; "
        f"fit-program collectives {model['hlo_program_bytes']:.0f} B")
    if crosscheck is not None:
        m = crosscheck["measured_bytes"]
        r = crosscheck["ratio"]
        lines.append(
            f"  measured (compiled HLO): "
            f"{f'{m:.0f} B' if m is not None else '-'} "
            f"ratio={f'{r:.3f}' if r is not None else '-'} "
            f"agree={crosscheck['agree']} "
            f"(band ±{crosscheck['rtol']:.0%})")
    return "\n".join(lines)
