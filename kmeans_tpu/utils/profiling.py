"""Timing and profiling hooks.

The reference's only instrumentation is wall-clock ``time.time()`` pairs
around ``fit`` (kmeans_spark.py:427-429, :575-579) with a derived
avg-time-per-iteration.  Here timing is a first-class utility with proper
device synchronization (``block_until_ready`` — JAX dispatch is async, so
naive wall-clock under-measures), warmup exclusion (the reference times cold,
including JVM/compile warmup, kmeans_spark.py:575), and an optional
``jax.profiler`` trace context for TPU timeline capture.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax

from kmeans_tpu.obs import metrics_registry as _metrics
from kmeans_tpu.obs import trace as _obs_trace


class Timer:
    """Accumulating wall-clock timer with device sync."""

    def __init__(self):
        self.total = 0.0
        self.count = 0

    @contextlib.contextmanager
    def measure(self, sync_on=None):
        start = time.perf_counter()
        yield
        if sync_on is not None:
            jax.block_until_ready(sync_on)
        self.total += time.perf_counter() - start
        self.count += 1

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0


@contextlib.contextmanager
def trace(log_dir: Optional[str]):
    """``jax.profiler`` trace scope; no-op when log_dir is None."""
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


# --------------------------------------------------------- dispatch log
# Host->device dispatch accounting for the latency-sensitive paths: call
# sites that cross the host/device boundary (a jitted call whose result
# the host consumes, or a device_get) note themselves here, so tests and
# harnesses can assert structural properties like "the device k-means||
# pipeline is O(1) dispatches in the round count" (ISSUE 2) without
# depending on jax internals.
#
# Since ISSUE 11 the canonical store is the obs metrics registry: every
# noted dispatch increments ``dispatch.<label>`` in
# ``obs.metrics_registry.REGISTRY`` and (when a tracer is active) lands
# as an instant ``dispatch.note`` event on the span timeline.  The
# ``log_dispatches`` scope list is the COMPATIBILITY SHIM for the
# existing structural pins (``log.count(label)``): a scoped view over
# the same notes, unchanged surface.

_DISPATCH_LOG: Optional[list] = None


def note_dispatch(label: str) -> None:
    """Record one host->device dispatch: increments the registry's
    ``dispatch.<label>`` counter, emits a span-timeline event when a
    tracer is active, and appends to the active ``log_dispatches``
    scope (the legacy list shim).  Instrumented call sites pass a
    stable label (e.g. ``'kmeans||/round'``) so counts group."""
    if _DISPATCH_LOG is not None:
        _DISPATCH_LOG.append(label)
    _metrics.REGISTRY.counter(f"dispatch.{label}").inc()
    _obs_trace.event("dispatch.note", label=label)


@contextlib.contextmanager
def log_dispatches():
    """Collect dispatch labels noted by instrumented call sites.

    Usage::

        with log_dispatches() as log:
            kmeans_parallel_init(X, k, seed)
        assert log.count("kmeans||/device-pipeline") == 1

    Nested scopes shadow (the inner scope collects; the outer resumes
    afterwards), matching how the tests isolate measurements.  The
    global accounting moved to ``obs.metrics_registry`` (``dispatch.*``
    counters, process-lifetime); this scope remains the isolated-
    measurement shim over the same ``note_dispatch`` stream."""
    global _DISPATCH_LOG
    prev, _DISPATCH_LOG = _DISPATCH_LOG, []
    try:
        yield _DISPATCH_LOG
    finally:
        _DISPATCH_LOG = prev


# --------------------------------------------------- phase decomposition
# Shared harness for splitting a fused device loop's per-iteration cost
# into phases (ISSUE 3 / VERDICT weak #8: "decompose by measurement, not
# assertion").  A fused program cannot be timed phase-by-phase from the
# host — XLA fuses and overlaps everything — so the decomposition runs a
# LADDER of cumulative-prefix programs (phase 1 only; phases 1-2; the
# full body ...), measures each rung with the same measurement callable,
# and attributes each phase the per-rep DIFFERENCE between its rung and
# the previous one.  Reps interleave across rungs so a host-drift window
# moves every rung together (the BASELINE.md cross-variant rule), and
# differences are taken per rep before the median.


def measure_phase_ladder(rungs, *, reps: int = 5):
    """Measure a cumulative-phase ladder; returns per-phase costs.

    ``rungs`` is an ordered list of ``(label, measure)`` pairs where
    ``measure()`` returns the cost (seconds) of the program running all
    phases up to and including ``label`` — typically a marginal
    per-iteration measurement so dispatch latency is already cancelled.
    The first rung's phase cost is its own measurement; each later
    phase's cost is the per-rep difference to the previous rung,
    clamped at 0 in ``seconds`` (a negative difference is measurement
    noise).  ``spread`` is computed from the UNCLAMPED per-rep
    differences so the clamp can never hide the noise it absorbs: a
    rung whose differences are all-noise reports ``seconds`` 0 (or
    near it) with ``spread`` inf — never a fake zero-cost,
    zero-variance phase.

    Returns a list of dicts: ``{"phase", "seconds", "cumulative",
    "spread"}`` with ``spread`` the (max-min)/median rule of the
    repo's publication bar (inf when the median is non-positive but
    the reps vary; 0 only when the reps are identically zero).
    """
    import numpy as np

    labels = [label for label, _ in rungs]
    samples = {label: [] for label in labels}
    for _ in range(reps):
        for label, measure in rungs:
            samples[label].append(float(measure()))
    out = []
    prev = None
    for label in labels:
        cur = np.asarray(samples[label])
        raw = cur if prev is None else cur - prev
        med_raw = float(np.median(raw))
        span = float(raw.max() - raw.min())
        if med_raw > 0:
            spread = span / med_raw
        else:
            spread = float("inf") if span > 0 else 0.0
        out.append({"phase": label,
                    "seconds": max(med_raw, 0.0),
                    "cumulative": float(np.median(cur)),
                    "spread": spread})
        prev = cur
    return out


#: Decision rule of the phase table (committed BEFORE the hardware run,
#: the repo's pre-registration discipline): a phase owning at least this
#: share of the measured step is "actionable" — it becomes the next
#: schedule target (>= half of the headline's idle ~30%).  Anything
#: smaller is pinned as part of the measured ceiling.
PHASE_DECISION_SHARE = 0.15


def phase_ceiling_table(ladder, *, flops_per_iter=None,
                        peak_tflops=None, cost_record=None,
                        comm_model=None,
                        decision_share: float = PHASE_DECISION_SHARE):
    """Turn a ``measure_phase_ladder`` result into the publishable
    MEASURED-CEILING table (ISSUE 8c): one row per phase with

    * ``ms`` — the phase's marginal cost,
    * ``share`` — its fraction of the full measured pass,
    * ``implied_ceiling_speedup`` — ``full / (full - phase)``: the whole-
      pass speedup IF this phase were completely free (perfectly hidden
      behind another unit) — the honest upper bound any schedule attack
      on that phase can buy,
    * ``implied_ceiling_mfu`` — the MFU the pass would reach at that
      ceiling (None without ``flops_per_iter``/``peak_tflops``),
    * ``actionable`` — the committed decision rule: ``share >=
      decision_share`` (default 15%, >= half the idle ~30%) marks the
      phase as the next schedule target.

    The full pass is the LAST rung's cumulative median (the complete
    statistics body); rows carry the ladder's ``spread`` through so a
    noisy phase can never silently pass the decision rule unflagged.

    Roofline join (ISSUE 12): with ``cost_record`` (a captured
    :class:`~kmeans_tpu.obs.cost.CostRecord` of the measured program)
    each row additionally carries ``analytic_flops`` (the hand
    formula, when ``flops_per_iter`` is given), ``ai`` (XLA
    flops/bytes-accessed), and ``mfu_analytic`` (analytic flops over
    the full measured pass vs the pinned peak; None off-accelerator) —
    so every BASELINE row that embeds this table is roofline-attributed
    without a second measurement.

    Comm join (ISSUE 13): with ``comm_model`` (an
    ``obs.fleet.comm_bytes_model`` dict) the LAST row — the full
    measured pass, the one that pays the collectives — additionally
    carries ``comm_bytes_per_iter`` (analytic per-device collective
    result bytes per iteration) and ``comm_wire_bytes_per_device``
    (ring-algorithm interconnect estimate), so the table answers "how
    much of this phase is the fleet talking" without a second model
    run; ``format_phase_table`` renders them as a trailing comm line.
    """
    import numpy as np  # noqa: F811 — mirror measure_phase_ladder

    full = float(ladder[-1]["cumulative"])
    roofline = None
    if cost_record is not None and flops_per_iter:
        from kmeans_tpu.obs.cost import roofline_fields
        roofline = roofline_fields(flops_per_iter, full, cost_record,
                                   peak_tflops)
    rows = []
    for r in ladder:
        sec = float(r["seconds"])
        share = sec / full if full > 0 else 0.0
        remaining = max(full - sec, 1e-12)
        speedup = full / remaining if full > 0 else 1.0
        mfu = None
        if flops_per_iter and peak_tflops and full > 0:
            mfu = (flops_per_iter / remaining) / (peak_tflops * 1e12)
        row = {
            "phase": r["phase"],
            "ms": sec * 1e3,
            "share": share,
            "spread": r["spread"],
            "implied_ceiling_speedup": speedup,
            "implied_ceiling_mfu": mfu,
            "actionable": bool(share >= decision_share),
        }
        if roofline is not None:
            row.update(roofline)
        rows.append(row)
    if comm_model is not None and rows:
        rows[-1]["comm_bytes_per_iter"] = \
            comm_model["per_iteration_bytes"]
        rows[-1]["comm_wire_bytes_per_device"] = \
            comm_model["wire_bytes_per_device_per_iteration"]
    return rows


def sanitize_json(obj):
    """Recursively replace non-finite floats with None: strict JSON has
    no inf/nan, but a noise-only phase reports ``spread=inf`` by design
    (``measure_phase_ladder`` — never a fake zero-variance phase).  The
    shared sanitizer of every artifact that embeds ladder rows
    (``benchmarks.bench_phases``, exp_headline_decomposition)."""
    import numpy as np  # noqa: F811 — mirror measure_phase_ladder

    if isinstance(obj, dict):
        return {k: sanitize_json(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [sanitize_json(v) for v in obj]
    if isinstance(obj, float) and not np.isfinite(obj):
        return None
    return obj


# ---------------------------------------------- recompilation sentinel
# Runtime twin of the static cache-key lint rule (ISSUE 10): the linter
# proves every cache key SPANS its builder's knobs; the sentinel proves
# a warmed path actually REUSES its compiled entries.  It generalizes
# the r11 "zero new cache entries across repeat same-shape serving
# calls" one-off into a reusable guard: snapshot every package
# compile-cache's keys, run the body, and fail loudly on growth.

#: Modules force-imported before cache discovery, so the sentinel sees
#: every package compile cache even when the caller imported none of
#: them directly.  Discovery itself is dynamic (any LRUCache module
#: attribute in any loaded kmeans_tpu module), so a future cache is
#: covered the moment its module loads.
_CACHE_MODULES = (
    "kmeans_tpu.models.kmeans",      # _STEP_CACHE, _AUTO_CACHE
    "kmeans_tpu.models.gmm",         # _STEP_CACHE (EM families)
    "kmeans_tpu.models.init",        # _PIPE_CACHE (kmeans|| pipeline)
)


class RecompilationError(AssertionError):
    """A compile cache grew inside a ``recompilation_sentinel`` scope:
    some call path re-keyed (and re-compiled) a program the warm path
    should have reused — the r13 duplicate-compile class at runtime."""


def compile_caches() -> dict:
    """Every module-level :class:`~kmeans_tpu.utils.cache.LRUCache` in
    the loaded package, as ``{'module.attr': cache}`` (deduplicated by
    object identity — re-exports keep their defining name)."""
    import importlib
    import sys

    from kmeans_tpu.utils.cache import LRUCache

    for name in _CACHE_MODULES:
        importlib.import_module(name)
    out = {}
    seen_ids = set()
    for name in sorted(n for n in sys.modules
                       if n.startswith("kmeans_tpu")):
        mod = sys.modules.get(name)
        if mod is None:
            continue
        for attr, val in sorted(vars(mod).items()):
            if isinstance(val, LRUCache) and id(val) not in seen_ids:
                seen_ids.add(id(val))
                out[f"{name}.{attr}"] = val
    return out


@contextlib.contextmanager
def recompilation_sentinel(allowed_new: int = 0):
    """Assert zero compile-cache growth across the ``with`` body.

    Usage (the repeat-same-shape serving/predict guard)::

        model.predict(X)                     # warm the caches
        with recompilation_sentinel():
            model.predict(X)                 # must reuse every entry
            model.predict(X)

    Yields a dict record; on exit ``record['new']`` maps cache names to
    the keys added inside the scope (empty on the healthy path) and
    ``record['caches']`` names every cache watched.  More than
    ``allowed_new`` total new entries raises :class:`RecompilationError`
    naming each offending cache and key — the message is the debugging
    artifact, so it carries the actual keys, not just counts.
    """
    caches = compile_caches()
    before = {name: set(c.keys()) for name, c in caches.items()}
    record = {"new": {}, "caches": sorted(caches)}
    yield record
    new = {}
    total = 0
    for name, cache in caches.items():
        added = [k for k in cache.keys() if k not in before[name]]
        if added:
            new[name] = added
            total += len(added)
    record["new"] = new
    # Timeline twin of the growth check (ISSUE 11 satellite): every new
    # key the sentinel observed becomes a zero-length ``compile`` span
    # naming the cache, so a sentinel violation is visible on the
    # chrome://tracing timeline at the moment the scope closed even
    # when the miss itself ran before tracing was installed.
    tr = _obs_trace.get_tracer()
    if tr is not None:
        for name, keys in sorted(new.items()):
            for k in keys:
                tr.instant_span("compile", cache=name,
                                key=repr(k)[:160], via="sentinel")
    if total > allowed_new:
        lines = [f"  {name}: +{len(keys)} entries:" + "".join(
            f"\n    {repr(k)[:120]}" for k in keys)
            for name, keys in sorted(new.items())]
        raise RecompilationError(
            f"{total} new compile-cache entr"
            f"{'y' if total == 1 else 'ies'} inside a "
            f"recompilation_sentinel scope (allowed {allowed_new}) — a "
            f"warm same-shape path re-keyed a compiled program:\n"
            + "\n".join(lines))


def timed_call(fn, *args, warmup: int = 1, iters: int = 3):
    """(mean_seconds, last_result) of fn(*args), excluding warmup runs."""
    result = None
    for _ in range(warmup):
        result = jax.block_until_ready(fn(*args))
    start = time.perf_counter()
    for _ in range(iters):
        result = jax.block_until_ready(fn(*args))
    return (time.perf_counter() - start) / iters, result
