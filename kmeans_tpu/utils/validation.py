"""Parameter and numerical validation.

Mirrors the reference's validation surface: constructor checks raising
``ValueError`` (``_validate_parameters``, kmeans_spark.py:49-56 — k, max_iter,
tolerance positive), all-finite checks on the initial sample
(kmeans_spark.py:79-80) and on every iteration's new centroids
(kmeans_spark.py:289-290).
"""

from __future__ import annotations

import numpy as np


def validate_params(k: int, max_iter: int, tolerance: float) -> None:
    """Raise ValueError on non-positive hyperparameters (kmeans_spark.py:49-56)."""
    if k <= 0:
        raise ValueError(f"k must be positive, got {k}")
    if max_iter <= 0:
        raise ValueError(f"max_iter must be positive, got {max_iter}")
    if tolerance <= 0:
        raise ValueError(f"tolerance must be positive, got {tolerance}")


def check_finite_array(arr, message: str) -> None:
    """Raise ValueError if the array contains NaN/Inf (kmeans_spark.py:79/289)."""
    if not np.all(np.isfinite(np.asarray(arr))):
        raise ValueError(message)
