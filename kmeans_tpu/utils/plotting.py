"""Benchmark plot artifacts.

Reproduces the reference's speedup-graph generator (kmeans_spark.py:594-619):
matplotlib Agg, ideal (y=x) vs actual curves, markers and labels to match.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, Sequence

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402


def save_speedup_graph(shard_counts: Sequence[int],
                       speedups: Dict[int, float], path) -> Path:
    """Ideal-vs-actual speedup plot (kmeans_spark.py:601-617 layout)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    xs = np.array(list(shard_counts))
    actual = np.array([speedups[n] for n in shard_counts])

    plt.figure(figsize=(10, 6))
    plt.plot(xs, xs, "b-", marker="o", linewidth=2, markersize=8,
             label="Ideal")
    plt.plot(xs, actual, color="orange", marker="s", linewidth=2,
             markersize=8, label="Actual")
    plt.xlabel("Number of Shards", fontsize=12)
    plt.ylabel("Speedup", fontsize=12)
    plt.title("Speedup vs Number of Shards", fontsize=14, fontweight="bold")
    plt.legend(fontsize=11)
    plt.grid(True, alpha=0.3)
    plt.xticks(xs)
    plt.savefig(path, dpi=150, bbox_inches="tight")
    plt.close()
    return path
