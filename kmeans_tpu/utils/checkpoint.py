"""Model checkpoint / resume.

The reference has NO model serialization of any kind (SURVEY.md §5: centroids
live only as an in-memory attribute, kmeans_spark.py:44/307).  This module is
the deliberate cheap superset the survey recommends: fitted state (centroids,
SSE history, hyperparameters, iteration counter) round-trips through a single
``.npz`` file, enabling mid-training resume via ``KMeans.fit(..., resume=...)``
as well as fitted-model save/load.

Fault-tolerance contract (ISSUE 4):

* **Atomic writes** — temp file + ``os.replace``; a crashed writer can
  never leave a torn file at the checkpoint path itself.
* **Last-good rotation** — ``save_state_rotating`` keeps the previous
  checkpoint at ``<path>.prev`` before replacing ``<path>``, so even a
  checkpoint that was corrupted AFTER being written (disk fault, torn
  copy off the machine) leaves a valid predecessor to resume from.
* **Loud corruption** — ``load_state`` raises
  :class:`CheckpointCorruptError` naming the file for any
  truncated/torn/non-checkpoint ``.npz`` instead of surfacing a zipfile
  traceback; ``load_state_with_fallback`` then falls back to ``.prev``.
* **Version gate** — a ``__format_version__`` NEWER than this build is
  rejected with an actionable message (upgrade, don't KeyError); an
  older one with its own message (re-save with a matching build).
"""

from __future__ import annotations

import json
import os
import zipfile
from pathlib import Path
from typing import Any, Dict, Tuple

import numpy as np

FORMAT_VERSION = 1


class CheckpointCorruptError(ValueError):
    """A checkpoint file exists but cannot be parsed (truncated write,
    torn copy, or not a kmeans_tpu checkpoint).  Carries ``.path``."""

    def __init__(self, path, cause: str):
        self.path = Path(path)
        super().__init__(
            f"checkpoint {self.path} is truncated or corrupt ({cause}); "
            f"if a last-good rotation exists, resume from "
            f"{self.path.name}.prev (fit(resume=<path>) does this "
            f"automatically)")


def _normalize(path) -> Path:
    """np.savez appends '.npz' to suffix-less paths; make load agree."""
    path = Path(path)
    return path if path.suffix == ".npz" else path.with_name(path.name
                                                             + ".npz")


def prev_path(path) -> Path:
    """The last-good rotation slot for ``path`` (``<name>.npz.prev``)."""
    p = _normalize(path)
    return p.with_name(p.name + ".prev")


def save_state(path, state: Dict[str, Any]) -> None:
    """Write a checkpoint dict; arrays as npz payloads, rest as JSON.

    The write is ATOMIC (temp file in the same directory + ``os.replace``):
    a concurrent or crashed-midway writer can never leave a torn file for a
    reader to load (r1 VERDICT #5 — multi-host shared-filesystem safety)."""
    path = _normalize(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    arrays = {k: np.asarray(v) for k, v in state.items()
              if isinstance(v, np.ndarray)}
    meta = {k: v for k, v in state.items() if k not in arrays}
    meta["__format_version__"] = FORMAT_VERSION
    tmp = path.with_name(f".{path.name}.{os.getpid()}.tmp")
    try:
        with open(tmp, "wb") as f:
            np.savez(f, __meta__=json.dumps(meta), **arrays)
        os.replace(tmp, path)
    finally:
        tmp.unlink(missing_ok=True)


def save_state_rotating(path, state: Dict[str, Any]) -> None:
    """Atomic write with last-good rotation: the existing checkpoint (if
    any) moves to ``<path>.prev`` before the new one lands at ``path``.

    Used by the auto-checkpointing fits (``checkpoint_every=N``): a
    checkpoint that later proves unreadable still leaves its predecessor
    — one segment older, still on the bit-exact trajectory — for
    ``fit(resume=<path>)`` to fall back to.  Both renames are
    ``os.replace`` (atomic on POSIX); the worst a crash between them can
    produce is a missing ``path`` with a valid ``.prev``, which the
    fallback loader handles."""
    path = _normalize(path)
    if path.exists():
        os.replace(path, prev_path(path))
    save_state(path, state)


def save_state_primary(path, state: Dict[str, Any], tag: str,
                       rotate: bool = False) -> None:
    """Multi-host-safe checkpoint write, shared by every model's
    ``save``: only process 0 writes — N identical concurrent writers to
    one shared-filesystem path race (r1 VERDICT #5) — and a
    cross-process barrier (named by ``tag``) orders the write before any
    process returns, so a following ``load`` on any host with access to
    the path sees the complete file.  ``rotate=True`` applies the
    last-good ``.prev`` rotation (the segmented-fit writer)."""
    import jax

    from kmeans_tpu.parallel.multihost import is_primary
    if is_primary():
        (save_state_rotating if rotate else save_state)(path, state)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def load_state(path) -> Dict[str, Any]:
    return _load_state_at(_normalize(path))


def _load_state_at(path: Path) -> Dict[str, Any]:
    """Load an EXACT path (no .npz normalization — also serves the
    ``.prev`` rotation slot), translating every parse-level failure into
    a :class:`CheckpointCorruptError` naming the file."""
    try:
        with np.load(path, allow_pickle=False) as z:
            if "__meta__" not in z.files:
                raise CheckpointCorruptError(
                    path, "missing __meta__ record — not a kmeans_tpu "
                          "checkpoint")
            raw_meta = str(z["__meta__"])
            arrays = {k: z[k] for k in z.files if k != "__meta__"}
    except (zipfile.BadZipFile, EOFError, OSError, KeyError,
            ValueError) as e:
        # np.load surfaces torn/garbage files as BadZipFile OR plain
        # ValueError depending on how much of the magic survived; both
        # become the one clear corruption error.  FileNotFoundError (a
        # missing file is not a corrupt one) and our own classification
        # pass through.
        if isinstance(e, (FileNotFoundError, CheckpointCorruptError)):
            raise
        raise CheckpointCorruptError(path, f"{type(e).__name__}: {e}") \
            from e
    try:
        state: Dict[str, Any] = json.loads(raw_meta)
    except json.JSONDecodeError as e:
        raise CheckpointCorruptError(path, f"unparseable __meta__: {e}") \
            from e
    ver = state.pop("__format_version__", None)
    _check_version(path, ver)           # version errors are NOT corruption
    state.update(arrays)
    return state


def _check_version(path, ver) -> None:
    if not isinstance(ver, int):
        raise CheckpointCorruptError(
            path, f"missing or malformed __format_version__ ({ver!r})")
    if ver > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint {Path(path)} uses format version {ver}, but this "
            f"kmeans_tpu build supports up to {FORMAT_VERSION}: it was "
            f"written by a NEWER kmeans_tpu — upgrade this installation "
            f"(or re-save the model with a build <= {FORMAT_VERSION})")
    if ver < FORMAT_VERSION:
        raise ValueError(
            f"checkpoint {Path(path)} uses obsolete format version {ver} "
            f"(< supported minimum {FORMAT_VERSION}); re-save it with the "
            f"kmeans_tpu build that wrote it, then load here")


def load_state_with_fallback(path) -> Tuple[Dict[str, Any], bool]:
    """Load ``path``; on a corrupt (or missing-but-rotated) checkpoint,
    fall back to the last-good ``<path>.prev`` rotation.

    Returns ``(state, used_fallback)`` — the caller decides how loudly
    to warn.  A version error never falls back (the ``.prev`` was
    written by the same build); when BOTH files are unreadable the
    primary file's error propagates with the fallback failure noted."""
    try:
        return load_state(path), False
    except (CheckpointCorruptError, FileNotFoundError) as primary_err:
        prev = prev_path(path)
        if not prev.exists():
            raise
        try:
            return _load_state_at(prev), True
        except (CheckpointCorruptError, FileNotFoundError) as e:
            raise CheckpointCorruptError(
                path, f"{primary_err}; last-good fallback {prev} also "
                      f"unreadable ({e})") from e
