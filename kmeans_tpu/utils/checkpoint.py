"""Model checkpoint / resume.

The reference has NO model serialization of any kind (SURVEY.md §5: centroids
live only as an in-memory attribute, kmeans_spark.py:44/307).  This module is
the deliberate cheap superset the survey recommends: fitted state (centroids,
SSE history, hyperparameters, iteration counter) round-trips through a single
``.npz`` file, enabling mid-training resume via ``KMeans.fit(..., resume=...)``
as well as fitted-model save/load.

Fault-tolerance contract (ISSUE 4):

* **Atomic writes** — temp file + ``os.replace``; a crashed writer can
  never leave a torn file at the checkpoint path itself.
* **Last-good rotation** — ``save_state_rotating`` keeps the previous
  checkpoint at ``<path>.prev`` before replacing ``<path>``, so even a
  checkpoint that was corrupted AFTER being written (disk fault, torn
  copy off the machine) leaves a valid predecessor to resume from.
* **Loud corruption** — ``load_state`` raises
  :class:`CheckpointCorruptError` naming the file for any
  truncated/torn/non-checkpoint ``.npz`` instead of surfacing a zipfile
  traceback; ``load_state_with_fallback`` then falls back to ``.prev``.
* **Version gate** — a ``__format_version__`` NEWER than this build is
  rejected with an actionable message (upgrade, don't KeyError); an
  older one with its own message (re-save with a matching build).

Elastic-resume contract (ISSUE 5):

* **Canonical, unsharded state** — every checkpoint stores the fitted
  state in topology-independent form: host ``numpy`` arrays at their
  REAL shapes (``(k, D)`` centroid/mean tables, never the model-axis
  padded ``(k_pad, ...)`` a particular TP layout commits to).  A
  ``fit(resume=<path>)`` on ANY mesh size / TP sharding re-pads and
  re-shards the canonical state for the resuming topology — the cost is
  one gather at save time (already paid: states are host arrays) and
  one re-shard at resume (the same ``device_put`` a fresh fit pays).
* **Topology metadata** — ``topology_meta()`` stamps the mesh shape the
  checkpoint was WRITTEN on (data/model shards), the jax version, the
  compute dtype, and the format version into the JSON meta block;
  ``describe_checkpoint`` (the ``python -m kmeans_tpu ckpt-info``
  backend) reads it without constructing a model.
"""

from __future__ import annotations

import json
import os
import zipfile
from pathlib import Path
from typing import Any, Dict, Tuple

import numpy as np

from kmeans_tpu.obs import trace as _obs_trace

FORMAT_VERSION = 1


class CheckpointCorruptError(ValueError):
    """A checkpoint file exists but cannot be parsed (truncated write,
    torn copy, or not a kmeans_tpu checkpoint).  Carries ``.path``."""

    def __init__(self, path, cause: str):
        self.path = Path(path)
        super().__init__(
            f"checkpoint {self.path} is truncated or corrupt ({cause}); "
            f"if a last-good rotation exists, resume from "
            f"{self.path.name}.prev (fit(resume=<path>) does this "
            f"automatically)")


def _normalize(path) -> Path:
    """np.savez appends '.npz' to suffix-less paths; make load agree."""
    path = Path(path)
    return path if path.suffix == ".npz" else path.with_name(path.name
                                                             + ".npz")


def prev_path(path) -> Path:
    """The last-good rotation slot for ``path`` (``<name>.npz.prev``)."""
    p = _normalize(path)
    return p.with_name(p.name + ".prev")


def save_state(path, state: Dict[str, Any]) -> None:
    """Write a checkpoint dict; arrays as npz payloads, rest as JSON.

    The write is ATOMIC (temp file in the same directory + ``os.replace``):
    a concurrent or crashed-midway writer can never leave a torn file for a
    reader to load (r1 VERDICT #5 — multi-host shared-filesystem safety)."""
    path = _normalize(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    arrays = {k: np.asarray(v) for k, v in state.items()
              if isinstance(v, np.ndarray)}
    meta = {k: v for k, v in state.items() if k not in arrays}
    meta["__format_version__"] = FORMAT_VERSION
    tmp = path.with_name(f".{path.name}.{os.getpid()}.tmp")
    with _obs_trace.span("checkpoint.save", path=str(path)):
        try:
            with open(tmp, "wb") as f:
                np.savez(f, __meta__=json.dumps(meta), **arrays)
            os.replace(tmp, path)
        finally:
            tmp.unlink(missing_ok=True)


def save_state_rotating(path, state: Dict[str, Any]) -> None:
    """Atomic write with last-good rotation: the existing checkpoint (if
    any) moves to ``<path>.prev`` before the new one lands at ``path``.

    Used by the auto-checkpointing fits (``checkpoint_every=N``): a
    checkpoint that later proves unreadable still leaves its predecessor
    — one segment older, still on the bit-exact trajectory — for
    ``fit(resume=<path>)`` to fall back to.  Both renames are
    ``os.replace`` (atomic on POSIX); the worst a crash between them can
    produce is a missing ``path`` with a valid ``.prev``, which the
    fallback loader handles."""
    path = _normalize(path)
    if path.exists():
        os.replace(path, prev_path(path))
    save_state(path, state)


def save_state_primary(path, state: Dict[str, Any], tag: str,
                       rotate: bool = False) -> None:
    """Multi-host-safe checkpoint write, shared by every model's
    ``save``: only process 0 writes — N identical concurrent writers to
    one shared-filesystem path race (r1 VERDICT #5) — and a
    cross-process barrier (named by ``tag``) orders the write before any
    process returns, so a following ``load`` on any host with access to
    the path sees the complete file.  ``rotate=True`` applies the
    last-good ``.prev`` rotation (the segmented-fit writer)."""
    import jax

    from kmeans_tpu.parallel.multihost import is_primary
    if is_primary():
        (save_state_rotating if rotate else save_state)(path, state)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def load_state(path) -> Dict[str, Any]:
    return _load_state_at(_normalize(path))


def _parse_npz(path: Path, materialize: bool):
    """Shared parse of a checkpoint ``.npz``: returns
    ``(meta_dict, arrays)`` with every parse-level failure translated
    into a :class:`CheckpointCorruptError` naming the file and the
    format version gate applied.  ``materialize=False`` reads ONLY the
    JSON ``__meta__`` member (``np.load`` is lazy per member; the zip
    central directory at the file's tail still catches torn writes) and
    returns ``arrays=None`` — the one corruption-classification rule
    serving both the full loader and the metadata-only ``ckpt-info``
    path (review r10)."""
    try:
        with np.load(path, allow_pickle=False) as z:
            if "__meta__" not in z.files:
                raise CheckpointCorruptError(
                    path, "missing __meta__ record — not a kmeans_tpu "
                          "checkpoint")
            raw_meta = str(z["__meta__"])
            arrays = {k: z[k] for k in z.files if k != "__meta__"} \
                if materialize else None
    except (zipfile.BadZipFile, EOFError, OSError, KeyError,
            ValueError) as e:
        # np.load surfaces torn/garbage files as BadZipFile OR plain
        # ValueError depending on how much of the magic survived; both
        # become the one clear corruption error.  FileNotFoundError (a
        # missing file is not a corrupt one) and our own classification
        # pass through.
        if isinstance(e, (FileNotFoundError, CheckpointCorruptError)):
            raise
        raise CheckpointCorruptError(path, f"{type(e).__name__}: {e}") \
            from e
    try:
        meta: Dict[str, Any] = json.loads(raw_meta)
    except json.JSONDecodeError as e:
        raise CheckpointCorruptError(path, f"unparseable __meta__: {e}") \
            from e
    ver = meta.pop("__format_version__", None)
    _check_version(path, ver)           # version errors are NOT corruption
    return meta, arrays


def _load_state_at(path: Path) -> Dict[str, Any]:
    """Load an EXACT path (no .npz normalization — also serves the
    ``.prev`` rotation slot)."""
    with _obs_trace.span("checkpoint.restore", path=str(path)):
        state, arrays = _parse_npz(path, materialize=True)
        state.update(arrays)
    return state


def _check_version(path, ver) -> None:
    if not isinstance(ver, int):
        raise CheckpointCorruptError(
            path, f"missing or malformed __format_version__ ({ver!r})")
    if ver > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint {Path(path)} uses format version {ver}, but this "
            f"kmeans_tpu build supports up to {FORMAT_VERSION}: it was "
            f"written by a NEWER kmeans_tpu — upgrade this installation "
            f"(or re-save the model with a build <= {FORMAT_VERSION})")
    if ver < FORMAT_VERSION:
        raise ValueError(
            f"checkpoint {Path(path)} uses obsolete format version {ver} "
            f"(< supported minimum {FORMAT_VERSION}); re-save it with the "
            f"kmeans_tpu build that wrote it, then load here")


def load_state_with_fallback(path) -> Tuple[Dict[str, Any], bool]:
    """Load ``path``; on a corrupt (or missing-but-rotated) checkpoint,
    fall back to the last-good ``<path>.prev`` rotation.

    Returns ``(state, used_fallback)`` — the caller decides how loudly
    to warn.  A version error never falls back (the ``.prev`` was
    written by the same build); when BOTH files are unreadable the
    primary file's error propagates with the fallback failure noted."""
    try:
        return load_state(path), False
    except (CheckpointCorruptError, FileNotFoundError) as primary_err:
        prev = prev_path(path)
        if not prev.exists():
            raise
        try:
            return _load_state_at(prev), True
        except (CheckpointCorruptError, FileNotFoundError) as e:
            raise CheckpointCorruptError(
                path, f"{primary_err}; last-good fallback {prev} also "
                      f"unreadable ({e})") from e


# ------------------------------------------------- topology metadata


def topology_meta(mesh=None, model_shards=None, dtype=None) -> Dict[str, Any]:
    """The metadata block every checkpoint carries (ISSUE 5): the mesh
    shape the state was written on, the TP (model-axis) layout, the
    compute dtype, the jax version, and the format version — all
    JSON-serializable.  The block is INFORMATIONAL: resume never
    requires the shapes to match (state is canonical/unsharded), but
    the operator-facing ``ckpt-info`` command and the cross-mesh tests
    read it to know what topology a checkpoint came from."""
    import jax
    data_shards = None
    if mesh is not None:
        from kmeans_tpu.parallel.mesh import mesh_shape
        data_shards, model_shards = mesh_shape(mesh)
    return {
        "meta_format_version": FORMAT_VERSION,
        "meta_jax_version": jax.__version__,
        "meta_mesh_data_shards": data_shards,
        "meta_mesh_model_shards": (int(model_shards)
                                   if model_shards is not None else None),
        "meta_dtype": str(dtype) if dtype is not None else None,
    }


def _read_meta_at(path: Path) -> Dict[str, Any]:
    """Parse ONLY the JSON ``__meta__`` member of a checkpoint (no
    array materialization — a multi-GB state describes in
    milliseconds).  Torn/truncated writes still surface as
    :class:`CheckpointCorruptError` via the zip central directory at
    the file's tail; per-array corruption with an intact directory is
    only caught by a full ``load_state`` (which ``fit(resume=...)``
    performs anyway)."""
    meta, _ = _parse_npz(path, materialize=False)
    return meta


def describe_checkpoint(path) -> Dict[str, Any]:
    """Operator-facing summary of a checkpoint (the ``ckpt-info``
    backend): model class, cluster count, completed iteration, the
    topology metadata block, and whether the ``.prev`` last-good
    rotation exists and its metadata reads.  Never constructs a model
    and never materializes the array payload (``_read_meta_at`` — a
    multi-GB checkpoint describes in milliseconds); works on
    checkpoints from any family.  A corrupt/missing PRIMARY file is
    reported (``primary_error``) with the summary taken from ``.prev``
    when that still reads — the torn-checkpoint debugging surface."""
    path = _normalize(path)
    prev = prev_path(path)
    out: Dict[str, Any] = {"path": str(path), "primary_error": None,
                           "prev_exists": prev.exists(),
                           "prev_loads": None, "source": None}
    state = None
    try:
        state = _read_meta_at(path)
        out["source"] = "primary"
    except (CheckpointCorruptError, FileNotFoundError, ValueError) as e:
        out["primary_error"] = str(e)
    if out["prev_exists"]:
        try:
            prev_state = _read_meta_at(prev)
            out["prev_loads"] = True
            if state is None:
                state = prev_state
                out["source"] = "prev"
        except (CheckpointCorruptError, ValueError) as e:
            out["prev_loads"] = False
            out["prev_error"] = str(e)
    if state is None:
        return out
    k = state.get("k", state.get("n_components"))
    out.update({
        "model_class": state.get("model_class"),
        "k": int(k) if k is not None else None,
        "iteration": int(state.get("iterations_run",
                                   state.get("n_iter_", 0))),
        "format_version": int(state.get("meta_format_version",
                                        FORMAT_VERSION)),
        "jax_version": state.get("meta_jax_version"),
        "dtype": state.get("meta_dtype", state.get("dtype")),
        "written_on_mesh": {
            "data_shards": state.get("meta_mesh_data_shards"),
            "model_shards": state.get("meta_mesh_model_shards"),
        },
    })
    return out


def classify_resume(path) -> Dict[str, Any]:
    """Typed resume classification for the orchestration layer
    (ISSUE 19): is ``path`` worth handing to ``fit(resume=...)``, and
    through which rotation?

    Returns ``{"resumable", "source", "iteration", "detail"}`` where
    ``source`` is ``"primary"`` (file loads), ``"prev"`` (primary
    torn/missing but the ``.prev`` last-good rotation reads — exactly
    the fallback ``load_state_with_fallback`` will take), or ``None``
    (nothing loads: both torn, or no checkpoint yet).  Built on
    :func:`describe_checkpoint`, so a multi-GB checkpoint classifies
    in milliseconds without materializing arrays."""
    desc = describe_checkpoint(path)
    source = desc.get("source")
    if source == "prev" and desc.get("prev_loads") is False:
        source = None
    return {
        "resumable": source is not None,
        "source": source,
        "iteration": desc.get("iteration"),
        "detail": desc,
    }
