"""Model checkpoint / resume.

The reference has NO model serialization of any kind (SURVEY.md §5: centroids
live only as an in-memory attribute, kmeans_spark.py:44/307).  This module is
the deliberate cheap superset the survey recommends: fitted state (centroids,
SSE history, hyperparameters, iteration counter) round-trips through a single
``.npz`` file, enabling mid-training resume via ``KMeans.fit(..., resume=...)``
as well as fitted-model save/load.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Dict

import numpy as np

FORMAT_VERSION = 1


def _normalize(path) -> Path:
    """np.savez appends '.npz' to suffix-less paths; make load agree."""
    path = Path(path)
    return path if path.suffix == ".npz" else path.with_name(path.name
                                                             + ".npz")


def save_state(path, state: Dict[str, Any]) -> None:
    """Write a checkpoint dict; arrays as npz payloads, rest as JSON.

    The write is ATOMIC (temp file in the same directory + ``os.replace``):
    a concurrent or crashed-midway writer can never leave a torn file for a
    reader to load (r1 VERDICT #5 — multi-host shared-filesystem safety)."""
    path = _normalize(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    arrays = {k: np.asarray(v) for k, v in state.items()
              if isinstance(v, np.ndarray)}
    meta = {k: v for k, v in state.items() if k not in arrays}
    meta["__format_version__"] = FORMAT_VERSION
    tmp = path.with_name(f".{path.name}.{os.getpid()}.tmp")
    try:
        with open(tmp, "wb") as f:
            np.savez(f, __meta__=json.dumps(meta), **arrays)
        os.replace(tmp, path)
    finally:
        tmp.unlink(missing_ok=True)


def save_state_primary(path, state: Dict[str, Any], tag: str) -> None:
    """Multi-host-safe checkpoint write, shared by every model's
    ``save``: only process 0 writes — N identical concurrent writers to
    one shared-filesystem path race (r1 VERDICT #5) — and a
    cross-process barrier (named by ``tag``) orders the write before any
    process returns, so a following ``load`` on any host with access to
    the path sees the complete file."""
    import jax

    from kmeans_tpu.parallel.multihost import is_primary
    if is_primary():
        save_state(path, state)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def load_state(path) -> Dict[str, Any]:
    with np.load(_normalize(path), allow_pickle=False) as z:
        state: Dict[str, Any] = json.loads(str(z["__meta__"]))
        ver = state.pop("__format_version__", None)
        if ver != FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint version: {ver}")
        for k in z.files:
            if k != "__meta__":
                state[k] = z[k]
    return state
