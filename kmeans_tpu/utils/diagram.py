"""Rendered architecture-diagram + one-page report artifacts.

Artifact-level parity with the reference's two binary documents
(SURVEY.md header inventory): ``architecture_diagram-K-means_with_
spark.jpg`` (a driver/worker dataflow flowchart) and
``Distributed_KMeans_Report.pdf`` (one page: problem formulation,
parallelization strategy, performance).  Unlike the reference — whose
artifacts were produced out-of-band (its requirements.txt lists
reportlab as "optional report generation" but never imports it) — both
are REGENERATED from code: ``python -m kmeans_tpu report``.
"""

from __future__ import annotations

from pathlib import Path

_LAYERS = [
    ("L4  Harness + CLI",
     "suite.py (narrative A–E, real exit codes) · benchmarks · "
     "bench.py · cli fit · pytest (8-device CPU mesh + real "
     "2-process run)"),
    ("L3  Algorithm API",
     "KMeans · MiniBatch (reassignment) · Bisecting · Spherical · "
     "GaussianMixture (diag/spherical/tied/full) ·\ninit strategies · "
     "checkpoint/resume · streaming fit/predict/transform · metrics"),
    ("L2  Distributed primitives",
     "Mesh (data × model) · ShardedDataset · shard_map SPMD step + "
     "psum/all_gather ·\non-device while_loop fits · multihost "
     "process-local loading"),
    ("L1  Compute kernels",
     "fused assign+reduce (matmul-form distances, one-hot scatter, "
     "SSE, farthest) as chunked lax.scan · software-pipelined "
     "Pallas/Mosaic kernel (fold-into-MXU, manual argmin)"),
]

_FLOW = [
    ("points sharded on\nthe data axis\n(resident all fit)", 0),
    ("fused chunk kernel:\ndistances → argmin →\none-hot scatter "
     "(MXU)", 1),
    ("dense (k, D+1)\naccumulator + SSE\nper shard", 2),
    ("ONE lax.psum over\nthe mesh → replicated\nglobal stats", 3),
    ("centroid update +\nconvergence check\n(host or in-loop)", 4),
]


def _require_matplotlib():
    """matplotlib is an optional dependency (like the reference, whose
    requirements.txt lists it for the speedup plot): fail with a
    pointed message, not a bare ImportError."""
    try:
        import matplotlib  # noqa: F401
    except ImportError:
        raise ImportError(
            "the report/diagram artifacts need matplotlib "
            "(pip install matplotlib) — the library itself does not"
        ) from None


def render_architecture(path) -> Path:
    """Render the layer map + per-iteration dataflow to a PNG.

    The visual analogue of the reference's architecture JPG: its
    driver→executor→shuffle→driver round trip becomes the one-psum SPMD
    step (docs/ARCHITECTURE.md's ASCII layer map, rendered)."""
    _require_matplotlib()
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from matplotlib.patches import FancyArrowPatch, FancyBboxPatch

    fig, (ax_l, ax_f) = plt.subplots(
        2, 1, figsize=(11, 8.2), height_ratios=[4, 1.6])
    fig.suptitle("kmeans_tpu — TPU-native distributed K-Means framework",
                 fontsize=14, fontweight="bold")

    colors = ["#cfe3f7", "#d8f0d3", "#fbe6c2", "#f3d1d4"]
    ax_l.set_xlim(0, 10)
    ax_l.set_ylim(0, len(_LAYERS) * 1.15)
    ax_l.axis("off")
    for i, (title, body) in enumerate(_LAYERS):
        y = (len(_LAYERS) - 1 - i) * 1.15
        ax_l.add_patch(FancyBboxPatch(
            (0.15, y + 0.08), 9.7, 1.0,
            boxstyle="round,pad=0.02", linewidth=1.2,
            edgecolor="#444444", facecolor=colors[i]))
        ax_l.text(0.35, y + 0.85, title, fontsize=11, fontweight="bold",
                  va="top")
        ax_l.text(0.55, y + 0.52, body, fontsize=8.5, va="top", wrap=True)
    ax_l.set_title("Layer map (SURVEY.md §1 → TPU-native re-design)",
                   fontsize=10, loc="left")

    ax_f.set_xlim(0, 10)
    ax_f.set_ylim(0, 2)
    ax_f.axis("off")
    ax_f.set_title("One Lloyd iteration = one jitted SPMD step (the "
                   "reference's broadcast/shuffle/collect round-trip "
                   "collapses into a single psum)", fontsize=10,
                   loc="left")
    w = 1.72
    for text, i in _FLOW:
        x = 0.15 + i * (w + 0.25)
        ax_f.add_patch(FancyBboxPatch(
            (x, 0.35), w, 1.25, boxstyle="round,pad=0.02",
            linewidth=1.0, edgecolor="#444444", facecolor="#eeeeee"))
        ax_f.text(x + w / 2, 0.97, text, fontsize=7.6, ha="center",
                  va="center")
        if i:
            ax_f.add_patch(FancyArrowPatch(
                (x - 0.23, 0.97), (x + 0.0, 0.97),
                arrowstyle="-|>", mutation_scale=14, color="#333333"))

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(path, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return path


def render_report(path, *, diagram: Path = None,
                  speedup: Path = None) -> Path:
    """One-page PDF report: problem formulation, parallelization
    strategy, measured performance — the content class of the
    reference's ``Distributed_KMeans_Report.pdf``, with this repo's
    measured numbers, regenerated from code."""
    _require_matplotlib()
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.image as mpimg
    import matplotlib.pyplot as plt

    fig = plt.figure(figsize=(8.5, 11))
    fig.text(0.5, 0.965, "kmeans_tpu: TPU-Native Distributed K-Means",
             ha="center", fontsize=16, fontweight="bold")
    fig.text(0.5, 0.945, "Project report (regenerated by "
             "`python -m kmeans_tpu report`)", ha="center", fontsize=9,
             style="italic")

    body = (
        "Problem formulation.  Partition n points in R^D into k clusters "
        "minimizing the within-cluster sum of squared\ndistances (SSE), at "
        "scales where one machine's memory and FLOPs are insufficient "
        "(headline: 10M x 128, k=1024).\n"
        "\n"
        "Parallelization strategy.  Points are sharded across a device "
        "mesh's data axis and stay resident for the whole\nfit; centroids "
        "are replicated (or sharded on a second model axis when k*D is "
        "large).  Each iteration is ONE jitted\nSPMD step: every shard "
        "scans its chunks through a fused assign+reduce kernel (distances "
        "in matmul form on the\nMXU, running argmin, one-hot scatter-sum) "
        "into a dense (k, D+1) accumulator, and a single lax.psum "
        "replicates\nthe global statistics.  The reference's per-iteration "
        "broadcast -> per-point Python closures -> keyed shuffle ->\n"
        "driver collect round-trip collapses into that one collective; "
        "with host_loop=False the entire fit (convergence\ntest included) "
        "is a single dispatch.  A hand-scheduled Pallas/Mosaic kernel "
        "serves the large-k win region.\n"
        "\n"
        "Performance (TPU v5e, 1 chip, steady-state; BASELINE.md).  "
        "Headline 10M x 128, k=1024: ~38.5 ms/iteration =\n3.3e10 "
        "points*dims/s/chip (~12,000x an idealized 8-worker scaling of "
        "the reference's measured per-point executor\nloop), ~69-70% MFU "
        "of the chip's bf16 peak.  Final SSE matches a float64 oracle to "
        "~3e-6 relative; centroid\nparity with scikit-learn to 1e-4 "
        "(sorted centroids, shared init).  Strong scaling across mesh "
        "sizes reproduces the\nreference's speedup-graph capability "
        "(artifacts/speedup_graph.png).")
    fig.text(0.06, 0.915, body, fontsize=8.3, va="top", family="serif")

    y0 = 0.50
    if diagram is not None and Path(diagram).exists():
        ax = fig.add_axes([0.07, y0 - 0.33, 0.86, 0.36])
        ax.imshow(mpimg.imread(diagram))
        ax.axis("off")
    if speedup is not None and Path(speedup).exists():
        ax = fig.add_axes([0.25, 0.015, 0.5, 0.16])
        ax.imshow(mpimg.imread(speedup))
        ax.axis("off")

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(path, format="pdf", bbox_inches="tight")
    plt.close(fig)
    return path


def main(argv=None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu report",
        description="Regenerate the architecture diagram + project "
                    "report artifacts")
    parser.add_argument("--out-dir", default="artifacts")
    args = parser.parse_args(argv)
    out = Path(args.out_dir)
    diagram = render_architecture(out / "architecture_diagram.png")
    print(f"wrote {diagram}")
    report = render_report(out / "kmeans_tpu_report.pdf",
                           diagram=diagram,
                           speedup=out / "speedup_graph.png")
    print(f"wrote {report}")
    return 0
