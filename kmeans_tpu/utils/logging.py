"""Per-iteration observability.

Reproduces the reference's print-based metrics surface (SURVEY.md §5):
startup config echo (kmeans_spark.py:262-263), per-iteration line with SSE /
max shift / cluster sizes with an explicit flush (kmeans_spark.py:296-304),
convergence announcement (:311), empty-cluster and SSE-rise warnings
(:192, :285).  For large k the full cluster-size list is summarized instead
of printed verbatim (the reference prints all k sizes, which is unreadable
at k=1024).
"""

from __future__ import annotations

import sys
from typing import Optional, Sequence


class IterationLogger:
    def __init__(self, verbose: bool = True, max_sizes_listed: int = 32):
        self.verbose = verbose
        self.max_sizes_listed = max_sizes_listed

    def _emit(self, msg: str) -> None:
        if self.verbose:
            print(msg)
            sys.stdout.flush()          # kmeans_spark.py:264/304 flushes too

    def startup(self, k: int, max_iter: int, tolerance: float,
                compute_sse: bool) -> None:
        self._emit(f"Starting K-Means with k={k}, max_iter={max_iter}, "
                   f"tolerance={tolerance}")
        self._emit("SSE computation: "
                   + ("ENABLED" if compute_sse else
                      "DISABLED (for performance)"))

    def _sizes_repr(self, sizes: Sequence[int]) -> str:
        if len(sizes) <= self.max_sizes_listed:
            return str([int(s) for s in sizes])
        import numpy as np
        a = np.asarray(sizes)
        return (f"[k={len(sizes)}: min={a.min()}, median={int(np.median(a))}, "
                f"max={a.max()}, empty={int((a == 0).sum())}]")

    def iteration(self, iteration: int, max_shift: float,
                  sizes: Sequence[int], sse: Optional[float]) -> None:
        if sse is not None:           # format matches kmeans_spark.py:299-303
            self._emit(f"Iteration {iteration + 1}: SSE = {sse:.4f}, "
                       f"Max Shift = {max_shift:.6f}, "
                       f"Cluster Sizes = {self._sizes_repr(sizes)}")
        else:
            self._emit(f"Iteration {iteration + 1}: "
                       f"Max Shift = {max_shift:.6f}, "
                       f"Cluster Sizes = {self._sizes_repr(sizes)}")

    def converged(self, iterations: int) -> None:
        self._emit(f"Converged after {iterations} iterations")

    def restart(self, restart: int, total: int, inertia: float,
                winner: bool = False) -> None:
        tag = "best of" if winner else "of"
        self._emit(f"Restart {restart + 1} {tag} {total}: "
                   f"final inertia = {inertia:.4f}")

    def warn_empty(self, n_empty: int) -> None:
        self._emit(f"  WARNING: {n_empty} empty cluster(s) detected. "
                   "Reinitializing...")

    def warn_reassign(self, n: int) -> None:
        self._emit(f"  WARNING: {n} low-count center(s) reassigned from "
                   "the current batch")

    def warn_sse_increase(self, prev: float, cur: float) -> None:
        self._emit(f"  WARNING: SSE increased from {prev:.4f} to {cur:.4f}")
