"""Deterministic fault injection for the fault-tolerance layer (ISSUE 4).

Every recovery claim in this repo is *proved* by re-running the real code
path under an injected, seeded failure — never by mocking the code under
test.  This module is the one place those injections live:

* :class:`TransientIOError` — the canonical retryable error.  The retry
  machinery (``data.io.retry_call`` / ``resilient_blocks``) treats any
  ``OSError`` as transient; tests raise this subclass so a retried
  failure is distinguishable from a real environment error.
* :class:`SimulatedPreemption` — what an injected "kill" raises.  It
  deliberately does NOT subclass ``OSError``: a preemption must never be
  swallowed by an IO retry loop.
* ``fail_first_attempts(fn, k)`` — wrap any callable (a shard
  ``read_rows``, a segment dispatch) so its first ``k`` invocations
  raise; deterministic, counted.
* ``flaky_blocks(make_blocks, ...)`` — a block stream whose Nth block
  read fails the first K times it is attempted (across epochs AND
  across retry replays), then succeeds forever.
* ``poison_blocks(make_blocks, ...)`` — NaN-poison one block of every
  epoch, exercising the ``on_nonfinite`` quarantine policy.
* ``inject_kill_after_iteration(j)`` — arm the checkpoint-boundary
  hook: the fit engines call :func:`on_checkpoint` immediately AFTER
  each rotating checkpoint write, and the armed hook raises
  :class:`SimulatedPreemption` once the boundary iteration reaches
  ``j`` — the deterministic stand-in for a TPU preemption landing
  between segments.
* ``inject_oom_on_segment(j)`` — arm the segment-dispatch hook: the
  device-loop fit engines call :func:`on_segment_dispatch` immediately
  before dispatching each segment, and the armed hook raises
  :class:`SimulatedOOM` (message-compatible with XLA's
  ``RESOURCE_EXHAUSTED``) the first ``times`` times segment ``j`` is
  attempted — proving the OOM chunk-backoff recovery (ISSUE 5) through
  the real dispatch loop, not a mock.
* ``inject_replica_kill(fleet, replica)`` — arm a serving-fleet chaos
  kill (ISSUE 17): the replica's pre-dispatch fault hook counts
  dispatches and kills the replica after ``after_dispatches`` — the
  in-flight request fails through the engine's dispatch guard and the
  micro-batch queue's per-member isolation, and the fleet router must
  re-dispatch it on a survivor with ZERO failed requests.
* ``inject_host_kill(process_index, after_iteration=)`` — the fleet
  variant of ``inject_kill_after_iteration`` (ISSUE 19): same
  checkpoint-boundary registry, but the armed hook fires ONLY on the
  process whose fleet identity (``obs.identity.identity()``) matches
  ``process_index`` — so every worker of an autopilot fleet can arm the
  same shared fault spec and exactly one host dies.
* ``inject_launch_failures(n)`` — arm the launch-attempt hook: the
  orchestrator's launcher calls :func:`on_launch` immediately before
  every worker spawn, and the armed hook raises
  :class:`SimulatedLaunchFailure` for the first ``n`` attempts — the
  deterministic stand-in for a flaky scheduler/allocator, driving the
  autopilot's bounded exponential launch backoff through the real
  spawn path.
* ``inject_update_failure(...)`` — arm the serve-and-learn update-step
  hook (ISSUE 20): the learner calls :func:`on_update_step` right
  before each ``partial_fit`` batch of an in-place online update, and
  the armed hook raises :class:`SimulatedUpdateFailure` — proving
  through the real update path that a failed update NEVER touches the
  serving model (the clone dies, the engine keeps serving last-good).
* ``inject_quality_regression(...)`` — arm the post-update evaluation
  hook (ISSUE 20): the learner calls :func:`on_update_eval` with the
  measured post/pre score ratio when it judges an applied update, and
  the armed hook overrides the ratio past the committed regression
  threshold — driving the snapshot-restore rollback through the real
  evaluation/restore/swap path, no mocks.

All state is explicit (closures / context managers); nothing here is
active unless a test arms it, and the hooks cost one empty-list check
per checkpoint in production.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Iterable, List, Optional

import numpy as np

__all__ = [
    "TransientIOError", "SimulatedPreemption", "SimulatedOOM",
    "SimulatedLaunchFailure", "SimulatedUpdateFailure",
    "on_checkpoint", "on_segment_dispatch", "on_launch",
    "on_update_step", "on_update_eval",
    "inject_kill_after_iteration", "inject_oom_on_segment",
    "inject_checkpoint_delay", "inject_replica_kill",
    "inject_host_kill", "inject_launch_failures",
    "inject_update_failure", "inject_quality_regression",
    "fail_first_attempts", "flaky_blocks", "poison_blocks",
]


class TransientIOError(IOError):
    """A retryable (injected) IO failure — an ``OSError`` subclass, so
    the production retry machinery handles it exactly like a real flaky
    read on the 7-10 MB/s tunnel."""


class SimulatedPreemption(RuntimeError):
    """Injected kill at a checkpoint boundary.  NOT an ``OSError``:
    preemptions must propagate out of the fit, never be retried."""


class SimulatedLaunchFailure(RuntimeError):
    """Injected worker-launch failure (ISSUE 19).  NOT an ``OSError``
    either: the launcher classifies it through its own typed retry
    policy (bounded deterministic exponential backoff), never through
    an IO retry loop."""


class SimulatedUpdateFailure(RuntimeError):
    """Injected failure inside a serve-and-learn in-place update
    (ISSUE 20).  NOT an ``OSError``: an update failure is classified by
    the learner's own typed policy (record the failed attempt, keep the
    serving model on last-good), never by an IO retry loop."""


class SimulatedOOM(RuntimeError):
    """Injected device out-of-memory at a segment dispatch.  A
    ``RuntimeError`` whose message carries XLA's ``RESOURCE_EXHAUSTED``
    tag — the exact classification surface the production backoff
    (``models.fault_tolerance.is_oom_error``) matches real
    ``XlaRuntimeError`` OOMs on, so the injected failure exercises the
    same detection path as a real one."""

    def __init__(self, segment: int, chunk: int):
        self.segment = segment
        self.chunk = chunk
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected device OOM dispatching "
            f"segment {segment} at chunk {chunk}")


# --------------------------------------------------------------- hooks

# Checkpoint-boundary hook registry.  The fit engines call
# ``on_checkpoint(iteration, path)`` right after every successful
# rotating checkpoint write (segment boundary on the device loops,
# every-N iteration on the host loops, epoch boundary on the streamed
# fits).  Hooks are (callable, lock-free append/remove) — production
# pays one truthiness check.
_CHECKPOINT_HOOKS: List[Callable[[int, object], None]] = []
_HOOK_LOCK = threading.Lock()


def on_checkpoint(iteration: int, path) -> None:
    """Fire the checkpoint-boundary hooks (called by the fit engines
    AFTER the checkpoint for ``iteration`` completed iterations is
    durably on disk — so a hook that kills the process models a
    preemption whose last checkpoint is valid)."""
    if _CHECKPOINT_HOOKS:
        for hook in list(_CHECKPOINT_HOOKS):
            hook(iteration, path)


@contextlib.contextmanager
def inject_kill_after_iteration(j: int):
    """Arm a one-shot kill: the FIRST checkpoint boundary whose
    completed-iteration count is >= ``j`` raises
    :class:`SimulatedPreemption`.  One-shot so the resumed fit (same
    process, hook still armed would otherwise re-kill) runs to
    completion; re-enter the context to kill again.  Yields a dict with
    the observed kill iteration (``fired_at``, None if never fired)."""
    record = {"fired_at": None}

    def hook(iteration: int, path) -> None:
        if record["fired_at"] is None and iteration >= j:
            record["fired_at"] = iteration
            raise SimulatedPreemption(
                f"injected preemption after iteration {iteration} "
                f"(armed at {j}); last checkpoint: {path}")

    with _HOOK_LOCK:
        _CHECKPOINT_HOOKS.append(hook)
    try:
        yield record
    finally:
        with _HOOK_LOCK:
            if hook in _CHECKPOINT_HOOKS:
                _CHECKPOINT_HOOKS.remove(hook)


@contextlib.contextmanager
def inject_checkpoint_delay(seconds: float, *, after_iteration: int = 0):
    """Arm a deterministic SLOW-HOST injection (ISSUE 13): every
    checkpoint boundary whose completed-iteration count is
    >= ``after_iteration`` sleeps ``seconds`` before returning to the
    fit loop — the stand-in for a host whose per-iteration work is
    slower than the fleet's (page-cache misses, a noisy neighbor, a
    failing NIC).  Run a fit with ``checkpoint_every=1`` and the delay
    stretches every iteration on THIS process only, so merged
    heartbeats show the lagging boundary cadence and rows/s skew the
    straggler report must flag.  Yields a record dict with ``fired``
    (boundary count delayed)."""
    import time

    record = {"fired": 0}

    def hook(iteration: int, path) -> None:
        if iteration >= after_iteration:
            record["fired"] += 1
            time.sleep(seconds)

    with _HOOK_LOCK:
        _CHECKPOINT_HOOKS.append(hook)
    try:
        yield record
    finally:
        with _HOOK_LOCK:
            if hook in _CHECKPOINT_HOOKS:
                _CHECKPOINT_HOOKS.remove(hook)


@contextlib.contextmanager
def inject_host_kill(process_index: int, *, after_iteration: int = 0):
    """Arm a one-shot, HOST-TARGETED kill (ISSUE 19): the first
    checkpoint boundary whose completed-iteration count is
    >= ``after_iteration`` raises :class:`SimulatedPreemption` — but
    only on the process whose fleet identity
    (``obs.identity.identity()['process_index']``) equals
    ``process_index``.  Every worker of a fleet can therefore arm the
    SAME shared fault spec and exactly one host dies, mid-segment, with
    its last rotating checkpoint durably on disk (the hook registry
    fires after the write).  Yields a record dict with ``fired_at``
    (the kill iteration on the targeted host; None elsewhere/never)."""
    from kmeans_tpu.obs.identity import identity

    record = {"fired_at": None}

    def hook(iteration: int, path) -> None:
        if record["fired_at"] is None and iteration >= after_iteration \
                and identity()["process_index"] == process_index:
            record["fired_at"] = iteration
            raise SimulatedPreemption(
                f"injected host kill on process {process_index} after "
                f"iteration {iteration} (armed at {after_iteration}); "
                f"last checkpoint: {path}")

    with _HOOK_LOCK:
        _CHECKPOINT_HOOKS.append(hook)
    try:
        yield record
    finally:
        with _HOOK_LOCK:
            if hook in _CHECKPOINT_HOOKS:
                _CHECKPOINT_HOOKS.remove(hook)


# Launch-attempt hook registry (ISSUE 19): the orchestrator's launcher
# calls ``on_launch(process_index, attempt)`` immediately BEFORE every
# worker spawn (inside its typed backoff try block, so an injected
# failure takes exactly the retry path a real scheduler flake would).
_LAUNCH_HOOKS: List[Callable[[int, int], None]] = []


def on_launch(process_index: int, attempt: int) -> None:
    """Fire the launch-attempt hooks (called by the orchestrator's
    launcher right before spawning worker ``process_index``, on its
    ``attempt``-th try).  Production cost: one truthiness check."""
    if _LAUNCH_HOOKS:
        for hook in list(_LAUNCH_HOOKS):
            hook(process_index, attempt)


@contextlib.contextmanager
def inject_launch_failures(n: int):
    """Arm a deterministic launch flake: the first ``n`` launch
    attempts (counted fleet-wide, across workers and retries) raise
    :class:`SimulatedLaunchFailure`, then every later attempt passes.
    With ``n < launch retry budget`` the autopilot's bounded
    exponential backoff recovers; with ``n >=`` budget it must raise
    its typed give-up error.  Yields a record dict with ``fired``
    (failures raised) and ``attempts`` ((process_index, attempt) pairs
    seen)."""
    record = {"fired": 0, "attempts": []}

    def hook(process_index: int, attempt: int) -> None:
        record["attempts"].append((process_index, attempt))
        if record["fired"] < n:
            record["fired"] += 1
            raise SimulatedLaunchFailure(
                f"injected launch failure {record['fired']}/{n} "
                f"(worker {process_index}, attempt {attempt})")

    with _HOOK_LOCK:
        _LAUNCH_HOOKS.append(hook)
    try:
        yield record
    finally:
        with _HOOK_LOCK:
            if hook in _LAUNCH_HOOKS:
                _LAUNCH_HOOKS.remove(hook)


# Segment-dispatch hook registry (ISSUE 5): the device-loop fit engines
# call ``on_segment_dispatch(segment, chunk)`` immediately BEFORE each
# segment dispatch (inside the OOM-backoff try block, so an injected
# RESOURCE_EXHAUSTED takes exactly the recovery path a real one would).
_SEGMENT_HOOKS: List[Callable[[int, int], None]] = []


def on_segment_dispatch(segment: int, chunk: int) -> None:
    """Fire the segment-dispatch hooks (called by the device-loop fit
    engines right before dispatching segment ``segment`` with scan
    chunk ``chunk``).  Production cost: one truthiness check."""
    if _SEGMENT_HOOKS:
        for hook in list(_SEGMENT_HOOKS):
            hook(segment, chunk)


@contextlib.contextmanager
def inject_oom_on_segment(j: int, times: int = 1):
    """Arm a deterministic device-OOM injection: the first ``times``
    dispatch attempts of segment ``j`` raise :class:`SimulatedOOM`
    (counted across backoff retries, so ``times=1`` proves one halving
    recovers and ``times > max backoffs`` proves the bounded-attempts
    re-raise).  Yields a record dict with ``fired`` (count) and
    ``chunks`` (the chunk size each attempt was about to dispatch
    with)."""
    record = {"fired": 0, "chunks": []}

    def hook(segment: int, chunk: int) -> None:
        if segment == j and record["fired"] < times:
            record["fired"] += 1
            record["chunks"].append(chunk)
            raise SimulatedOOM(segment, chunk)

    with _HOOK_LOCK:
        _SEGMENT_HOOKS.append(hook)
    try:
        yield record
    finally:
        with _HOOK_LOCK:
            if hook in _SEGMENT_HOOKS:
                _SEGMENT_HOOKS.remove(hook)


# Serve-and-learn hook registries (ISSUE 20).  The learner calls
# ``on_update_step(model_id, batch_index)`` right before feeding each
# reservoir batch to the working clone's ``partial_fit`` (inside the
# learner's try block, so an injected failure takes exactly the
# record-and-keep-serving path a real one would), and
# ``on_update_eval(model_id, ratio)`` when judging an applied update
# against the committed regression threshold — armed hooks may OVERRIDE
# the measured post/pre score ratio, forcing the rollback branch
# through the real restore + atomic-swap code.
_UPDATE_HOOKS: List[Callable[[str, int], None]] = []
_UPDATE_EVAL_HOOKS: List[Callable[[str, Optional[float]],
                                  Optional[float]]] = []


def on_update_step(model_id: str, batch_index: int) -> None:
    """Fire the update-step hooks (called by the serve-and-learn
    actuator right before batch ``batch_index`` of an in-place update
    for ``model_id``).  Production cost: one truthiness check."""
    if _UPDATE_HOOKS:
        for hook in list(_UPDATE_HOOKS):
            hook(model_id, batch_index)


def on_update_eval(model_id: str, ratio):
    """Fire the post-update evaluation hooks: each armed hook receives
    (and may override) the post/pre score ratio the learner measured;
    the last hook's return value is what the committed regression rule
    judges.  Production cost: one truthiness check."""
    if _UPDATE_EVAL_HOOKS:
        for hook in list(_UPDATE_EVAL_HOOKS):
            ratio = hook(model_id, ratio)
    return ratio


@contextlib.contextmanager
def inject_update_failure(model_id: Optional[str] = None, *,
                          on_batch: int = 0, times: int = 1):
    """Arm a deterministic in-place-update failure: the first ``times``
    times the serve-and-learn actuator reaches ``partial_fit`` batch
    ``on_batch`` of an update for ``model_id`` (any model when None),
    :class:`SimulatedUpdateFailure` is raised from the real update
    path.  The learner must record the failed attempt and leave the
    serving model bit-identical on last-good — the chaos tests pin
    zero failed serving requests while this is armed.  Yields a record
    dict with ``fired`` (count) and ``models`` (the model ids hit)."""
    record = {"fired": 0, "models": []}

    def hook(mid: str, batch_index: int) -> None:
        if model_id is not None and mid != model_id:
            return
        if batch_index == on_batch and record["fired"] < times:
            record["fired"] += 1
            record["models"].append(mid)
            raise SimulatedUpdateFailure(
                f"injected update failure for model {mid!r} at batch "
                f"{batch_index} (failure {record['fired']}/{times})")

    with _HOOK_LOCK:
        _UPDATE_HOOKS.append(hook)
    try:
        yield record
    finally:
        with _HOOK_LOCK:
            if hook in _UPDATE_HOOKS:
                _UPDATE_HOOKS.remove(hook)


@contextlib.contextmanager
def inject_quality_regression(model_id: Optional[str] = None, *,
                              ratio: float = 10.0, times: int = 1):
    """Arm a deterministic post-update quality regression: the first
    ``times`` evaluations of an applied update for ``model_id`` (any
    model when None) report ``ratio`` as the post/pre score ratio —
    far past the committed :data:`~kmeans_tpu.serving.learn
    .REGRESSION_RATIO` by default — regardless of what the traffic
    measured, so the learner's rollback-to-last-good runs through the
    real snapshot-restore + atomic-swap path.  Yields a record dict
    with ``fired`` (count) and ``measured`` (the ratios that were
    overridden, None entries for updates whose traffic gave no score
    reading)."""
    record = {"fired": 0, "measured": []}

    def hook(mid: str, measured):
        if model_id is not None and mid != model_id:
            return measured
        if record["fired"] < times:
            record["fired"] += 1
            record["measured"].append(measured)
            return float(ratio)
        return measured

    with _HOOK_LOCK:
        _UPDATE_EVAL_HOOKS.append(hook)
    try:
        yield record
    finally:
        with _HOOK_LOCK:
            if hook in _UPDATE_EVAL_HOOKS:
                _UPDATE_EVAL_HOOKS.remove(hook)


@contextlib.contextmanager
def inject_replica_kill(fleet, replica=None, *, after_dispatches: int = 0):
    """Arm a deterministic serving-replica kill (ISSUE 17 chaos run):
    the armed ``fault_hook`` — called by the engine's pre-dispatch
    guard on EVERY dispatch path (direct, queued batch, packed) —
    counts dispatch attempts, and once ``after_dispatches`` have been
    allowed through it calls ``fleet.kill_replica`` on the replica
    performing the NEXT one, so that dispatch (and every later one on
    the victim) is refused with ``ReplicaDeadError``.  A queued batch
    in flight at that moment fails through the micro-batch queue's
    per-member isolation, and the fleet router re-dispatches each
    member on a surviving replica — the chaos test pins zero failed
    requests.  ``replica`` names a specific victim; the default arms
    EVERY serving replica and kills whichever one crosses the
    threshold first (robust to the router concentrating traffic — the
    kill lands on a replica that actually holds work).  Yields a
    record dict with ``dispatches`` (attempts seen fleet-wide),
    ``killed`` (bool) and ``replica`` (the victim's name; the armed
    target's when a specific one was named)."""
    if replica is None:
        targets = [r for r in fleet._replicas if r.state == "serving"] \
            or list(fleet._replicas)
    else:
        targets = [fleet._replica(replica)]
    record = {"dispatches": 0, "killed": False,
              "replica": targets[0].name if len(targets) == 1 else None}

    def hook(rep, model_id, op) -> None:
        record["dispatches"] += 1
        if not record["killed"] \
                and record["dispatches"] > after_dispatches:
            record["killed"] = True
            record["replica"] = rep.name
            fleet.kill_replica(rep.name)

    for t in targets:
        t.fault_hook = hook
    try:
        yield record
    finally:
        for t in targets:
            t.fault_hook = None


# ------------------------------------------------------------ callables

def fail_first_attempts(fn: Callable, k: int,
                        exc_factory: Callable[[int], BaseException]
                        = None) -> Callable:
    """Wrap ``fn`` so its first ``k`` invocations raise (then it passes
    through forever).  The wrapper carries a ``.state`` dict with
    ``'calls'`` (total invocations) and ``'failures'`` (raised so far)
    counters — the "fail-first-K-dispatch-attempts" injection point.
    Deterministic: no randomness, the attempt counter is the only
    state."""
    if exc_factory is None:
        exc_factory = lambda i: TransientIOError(  # noqa: E731
            f"injected transient failure (attempt {i + 1}/{k})")
    state = {"calls": 0, "failures": 0}

    def wrapped(*args, **kwargs):
        i = state["calls"]
        state["calls"] += 1
        if i < k:
            state["failures"] += 1
            raise exc_factory(i)
        return fn(*args, **kwargs)

    wrapped.state = state
    return wrapped


# -------------------------------------------------------- block streams

def flaky_blocks(make_blocks: Callable[[], Iterable], *,
                 fail_block: int, fail_times: int,
                 exc_factory: Optional[Callable[[int], BaseException]]
                 = None) -> Callable[[], Iterable]:
    """A ``make_blocks`` whose block ``fail_block`` (0-based position
    within each epoch) raises the first ``fail_times`` times that
    position is READ — counted across epochs and across retry replays,
    so with ``io_retries >= fail_times`` the fit recovers and with
    fewer it must surface the error.  The wrapper carries
    ``.state['failures']`` for assertions."""
    if exc_factory is None:
        exc_factory = lambda i: TransientIOError(  # noqa: E731
            f"injected flaky read of block {fail_block} "
            f"(failure {i + 1}/{fail_times})")
    state = {"failures": 0}

    def make():
        def gen():
            for pos, item in enumerate(make_blocks()):
                if pos == fail_block and state["failures"] < fail_times:
                    i = state["failures"]
                    state["failures"] += 1
                    raise exc_factory(i)
                yield item
        return gen()

    make.state = state
    return make


def poison_blocks(make_blocks: Callable[[], Iterable], *,
                  block: int, value: float = np.nan,
                  row: int = 0, col: Optional[int] = 0, rows: int = 1,
                  from_epoch: int = 0) -> Callable[[], Iterable]:
    """A ``make_blocks`` that poisons block ``block`` (0-based position)
    with ``value`` — the deterministic stand-in for a corrupted
    streamed block.  Two injection shapes:

    * ``col=<int>`` (default): a ``rows``-high column slab
      ``b[row:row+rows, col] = value`` — with the NaN default this
      proves the ``on_nonfinite='error'|'skip'`` quarantine policy.
    * ``col=None``: a full-width slab ``b[row:row+rows, :] = value`` —
      with a huge FINITE value (e.g. ``2e38``) the block passes the IO
      finite check but the identically-poisoned rows land in one
      cluster and overflow the f32 device accumulator, driving the
      FIT's trajectory non-finite: the deterministic trigger for the
      divergence-rollback path (ISSUE 5), which the IO quarantine must
      NOT intercept.

    ``from_epoch=N`` delays the poison until the (0-based) Nth
    invocation of ``make_blocks`` — a fit healthy for several epochs
    (accumulating checkpoints) then hit mid-fit, so the rollback has a
    last-good state to restore.  The source items are never mutated
    (each poisoned block is a copy); the wrapper carries
    ``.state['epochs']`` for assertions."""
    state = {"epochs": 0}

    def make():
        epoch = state["epochs"]
        state["epochs"] += 1

        def gen():
            for pos, item in enumerate(make_blocks()):
                if pos != block or epoch < from_epoch:
                    yield item
                    continue
                if isinstance(item, tuple):
                    b, w = item
                else:
                    b, w = item, None
                b = np.array(b, copy=True)
                if col is None:
                    b[row: row + rows, :] = value
                else:
                    b[row: row + rows, col] = value
                yield b if w is None else (b, w)
        return gen()

    make.state = state
    return make
