"""Utilities: validation, iteration logging, checkpointing, profiling."""

from kmeans_tpu.utils.validation import validate_params, check_finite_array
from kmeans_tpu.utils.logging import IterationLogger
from kmeans_tpu.utils import checkpoint
from kmeans_tpu.utils.profiling import Timer

__all__ = [
    "validate_params",
    "check_finite_array",
    "IterationLogger",
    "checkpoint",
    "Timer",
]
