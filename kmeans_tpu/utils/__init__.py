"""Utilities: validation, iteration logging, checkpointing, profiling,
determinism checking (debug)."""

from kmeans_tpu.utils.validation import validate_params, check_finite_array
from kmeans_tpu.utils.logging import IterationLogger
from kmeans_tpu.utils import checkpoint
from kmeans_tpu.utils.profiling import Timer
from kmeans_tpu.utils.debug import check_determinism

__all__ = [
    "validate_params",
    "check_finite_array",
    "IterationLogger",
    "checkpoint",
    "Timer",
    "check_determinism",
]
