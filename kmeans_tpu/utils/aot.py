"""Portable AOT executable cache + the compilation-cache ladder (ISSUE 15).

The measured time-to-first-iteration window (docs/PERFORMANCE.md "Time
to first iteration") decomposes into a transfer term and a ~3.5 s/program
compile term — and the compile term is paid again by every fresh process:
an elastic resize (ROADMAP item 1), a serving restart, a second bench
run.  This module is the warm-start layer that removes it:

* :func:`enable_compilation_cache` — the FIRST rung: jax's persistent
  compilation cache (promoted out of ``benchmarks.py`` where it was
  bench-only since r2), now library-level with the
  ``KMEANS_TPU_COMPILE_CACHE`` env knob and called by bench and CLI
  alike.  Same-machine recompiles become disk hits.
* :class:`AOTStore` + :func:`wrap` — the SECOND rung: on the first call
  of any ``*_STEP_CACHE``-class program (the moment the arguments — and
  therefore the exact avals/shardings — exist), the program is lowered,
  compiled and SERIALIZED (``jax.experimental.serialize_executable``,
  the ``jax.export``-era AOT surface) to an on-disk artifact keyed by
  (cache name, in-memory cache key, argument signature, jax/jaxlib
  version, backend fingerprint).  A later process — including a resumed
  fit on a fresh host — deserializes and LOADS the executable instead of
  trace+compile: the TTFI compile row collapses to artifact-read
  milliseconds, visible on the span timeline as
  ``compile(via='aot-load')``.

Degrade contract (the ``obs/cost.py`` discipline): a backend whose PJRT
client cannot serialize executables yields ``available=False`` in
:meth:`AOTStore.stats` with ONE warning — fits run exactly as before,
never fail, never silently pretend the cache worked.  A corrupted or
version-skewed artifact is a counted fallback (``aot.fallback`` metric +
warning) that re-enters trace+compile — NEVER a wrong program: artifacts
are looked up by content hash of the full key AND the stored key fields
are re-verified against the expectation on load.

Key discipline (the ``aot-key`` lint rule): every artifact write derives
its key through :func:`artifact_key` — the one constructor that starts
from the SAME in-memory ``_STEP_CACHE`` key the compiled entry lives
under and appends the version/backend fields.  A hand-rolled key missing
a component is the r14 cache-key incident class, across processes.

Trust note: artifacts embed a pickled treedef pair (the executable's
in/out trees).  The store directory is therefore in the same trust
domain as checkpoints — load artifacts only from directories you would
load a checkpoint from.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import warnings
import zipfile
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from kmeans_tpu.obs import metrics_registry as _metrics
from kmeans_tpu.obs import trace as _obs_trace

__all__ = ["enable_compilation_cache", "aot_supported", "AOTStore",
           "artifact_key", "configure", "deactivate", "active_store",
           "wrap", "aot_dir_for", "describe_dir", "FORMAT"]

FORMAT = "kmeans_tpu.aot.v1"

#: Artifact file extension (one serialized executable per file).
_EXT = ".aotx"


# ------------------------------------------------- compilation cache

_COMPILE_CACHE_SET = False


def enable_compilation_cache() -> Optional[str]:
    """Persistent XLA/Mosaic compilation cache (r2 VERDICT #6), the
    first rung of the warm-start ladder — promoted from the bench-only
    ``benchmarks.py`` setup (ISSUE 15 satellite) so EVERY fit entry
    point (bench, ``fit``/``warm``/``serve`` CLIs, library users calling
    this) shares it.

    Directory resolution: ``KMEANS_TPU_COMPILE_CACHE`` (the library
    knob) > ``JAX_COMPILATION_CACHE_DIR`` (jax's own) > the
    ``/tmp/kmeans_tpu_jax_cache`` default.  An EMPTY value for either
    env knob opts out (cold-compile measurement).  Idempotent; returns
    the directory in effect (None when opted out)."""
    global _COMPILE_CACHE_SET
    import jax
    cache = os.environ.get("KMEANS_TPU_COMPILE_CACHE")
    if cache is None:
        cache = os.environ.get("JAX_COMPILATION_CACHE_DIR",
                               "/tmp/kmeans_tpu_jax_cache")
    if not cache:
        return None
    if not _COMPILE_CACHE_SET:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
        _COMPILE_CACHE_SET = True
    return cache


# ------------------------------------------------- backend capability

_SUPPORTED: Optional[Tuple[bool, str]] = None
_SUPPORT_LOCK = threading.Lock()


def aot_supported() -> Tuple[bool, str]:
    """(supported, reason): can this backend serialize AND reload a
    compiled executable?  Probed ONCE per process with a trivial
    program; cached.  ``reason`` names the failing step on degraded
    backends — the ``available=False`` surface the store and the
    ``warm`` CLI (exit 2) report."""
    global _SUPPORTED
    with _SUPPORT_LOCK:
        if _SUPPORTED is not None:
            return _SUPPORTED
        import jax
        try:
            from jax.experimental import serialize_executable as se
            fn = jax.jit(lambda x: x + 1)
            comp = fn.lower(jax.numpy.zeros((2,))).compile()
            payload, in_tree, out_tree = se.serialize(comp)
            pickle.dumps((in_tree, out_tree))
            se.deserialize_and_load(payload, in_tree, out_tree)
            _SUPPORTED = (True, "ok")
        except Exception as e:  # noqa: BLE001 — capability probe
            _SUPPORTED = (False, f"{type(e).__name__}: {e}")
        return _SUPPORTED


def _backend_fingerprint() -> Dict[str, object]:
    """The backend fields of every artifact key: an executable compiled
    for one platform/topology must never load on another."""
    import jax
    dev = jax.devices()[0]
    return {
        "platform": jax.default_backend(),
        "device_kind": str(getattr(dev, "device_kind", "?")),
        "device_count": int(jax.device_count()),
        "process_count": int(jax.process_count()),
    }


# --------------------------------------------------------- key fields

def _norm_key(key) -> object:
    """A JSON-stable normalization of an in-memory ``_STEP_CACHE`` key:
    tuples recurse; a ``jax.sharding.Mesh`` becomes its (axis, size)
    shape plus device kind (two processes with the same topology must
    produce the SAME normalized key — ``repr(mesh)`` embeds device ids
    and would defeat cross-process reuse); everything else reprs."""
    if isinstance(key, tuple):
        return [_norm_key(k) for k in key]
    from jax.sharding import Mesh
    if isinstance(key, Mesh):
        dev = next(iter(key.devices.flat))
        return ["mesh", [[str(n), int(s)] for n, s in key.shape.items()],
                str(getattr(dev, "device_kind", "?"))]
    return repr(key)


def _shard_sig(sh) -> object:
    """Sharding component of an argument signature.  NamedShardings
    reduce to (axis sizes, spec) — deliberately WITHOUT device ids or
    memory kind, so a ``jax.ShapeDtypeStruct`` warm-up signature
    (prelude overlap, ISSUE 15c) matches the real arrays' and two
    processes on the same topology agree."""
    if sh is None:
        return "host"
    from jax.sharding import NamedSharding
    if isinstance(sh, NamedSharding):
        return ("named",
                tuple((str(n), int(s))
                      for n, s in sh.mesh.shape.items()),
                str(sh.spec))
    return type(sh).__name__


def _sig_of(args) -> tuple:
    """Aval signature of a concrete argument tuple (shapes, dtypes,
    shardings) — what, together with the cache key, pins ONE compiled
    executable.  Works on real arrays and on ``ShapeDtypeStruct``s."""
    import jax
    sig = []
    for a in jax.tree_util.tree_leaves(args):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            sig.append((tuple(int(s) for s in a.shape), str(a.dtype),
                        _shard_sig(getattr(a, "sharding", None))))
        else:
            sig.append(("pyleaf", type(a).__name__))
    return tuple(sig)


def artifact_key(cache_name: str, key, sig) -> Dict[str, object]:
    """The CANONICAL AOT artifact key (the ``aot-key`` lint rule's
    blessed constructor — every ``store.put`` call site must build its
    key here): the in-memory cache identity (cache name + full
    ``_STEP_CACHE`` key, normalized) + the argument signature + jax /
    jaxlib versions + the backend fingerprint.  Dropping any component
    is the r14 cache-key incident class across processes: a stale or
    foreign executable served as this program."""
    import jax
    import jaxlib
    return {
        "format": FORMAT,
        "cache": str(cache_name),
        "key": _norm_key(key),
        "sig": _norm_key(tuple(sig)),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        **_backend_fingerprint(),
    }


def _digest(fields: Dict[str, object]) -> str:
    return hashlib.sha256(
        json.dumps(fields, sort_keys=True).encode()).hexdigest()[:40]


# -------------------------------------------------------------- store

class AOTStore:
    """Directory-backed store of serialized executables.

    ``root`` is the write (and first read) directory; ``read_dirs`` are
    additional lookup-only directories (e.g. the ``<ckpt>.aot``
    directory shipped next to a checkpoint being resumed); ``mirror``
    (when set) receives a copy of every write — the ship-next-to-
    checkpoints mechanism, so an elastic restart on a fresh host finds
    the executables beside the state it restores.

    Artifacts are single ``.aotx`` files (a zip of ``meta.json`` +
    ``trees.pkl`` + ``exe.bin``) written atomically (temp +
    ``os.replace``, the checkpoint discipline).  Loads re-verify the
    stored key fields against the expectation — a content-hash
    collision or a hand-renamed file can never serve a wrong program.
    """

    def __init__(self, root, read_dirs=(), mirror=None):
        self.root = Path(root)
        self.read_dirs: List[Path] = [Path(d) for d in read_dirs]
        self.mirror: Optional[Path] = Path(mirror) if mirror else None
        self._lock = threading.Lock()
        self.counts = {"loaded": 0, "built": 0, "saved": 0,
                       "fallbacks": 0, "call_fallbacks": 0}

    # ------------------------------------------------------- bookkeeping
    def _count(self, what: str) -> None:
        with self._lock:
            self.counts[what] += 1
        _metrics.REGISTRY.counter(f"aot.{what}").inc()

    def stats(self) -> dict:
        ok, reason = aot_supported()
        with self._lock:
            counts = dict(self.counts)
        return {"root": str(self.root),
                "read_dirs": [str(d) for d in self.read_dirs],
                "mirror": str(self.mirror) if self.mirror else None,
                "available": ok, "reason": reason, **counts}

    def add_read_dir(self, path) -> None:
        p = Path(path)
        if p not in self.read_dirs:
            self.read_dirs.append(p)

    def set_mirror(self, path) -> None:
        self.mirror = Path(path) if path else None

    # ------------------------------------------------------------ paths
    def _candidates(self, digest: str) -> List[Path]:
        dirs = [self.root] + self.read_dirs
        if self.mirror is not None:
            dirs.append(self.mirror)
        return [d / (digest + _EXT) for d in dirs]

    # ------------------------------------------------------------- put
    def put(self, fields: Dict[str, object], compiled) -> bool:
        """Serialize ``compiled`` under ``fields``
        (:func:`artifact_key` output — the lint-enforced constructor).
        Returns False (counted, warned once) on an unserializable
        backend; raises nothing into the fit path."""
        ok, reason = aot_supported()
        if not ok:
            _warn_once(f"AOT executable cache unavailable on this "
                       f"backend ({reason}); fits run with in-process "
                       f"compiles only (available=False)")
            return False
        from jax.experimental import serialize_executable as se
        try:
            payload, in_tree, out_tree = se.serialize(compiled)
            blob = pickle.dumps((in_tree, out_tree))
        except Exception as e:  # noqa: BLE001 — degrade, never fail a fit
            self._count("fallbacks")
            _warn_once(f"AOT serialize failed ({type(e).__name__}: {e}); "
                       f"continuing without a cached executable")
            return False
        digest = _digest(fields)
        meta = json.dumps(fields, sort_keys=True)
        for target in ([self.root] + ([self.mirror] if self.mirror
                                      else [])):
            try:
                target.mkdir(parents=True, exist_ok=True)
                path = target / (digest + _EXT)
                tmp = target / f".{digest}.{os.getpid()}.tmp"
                try:
                    with zipfile.ZipFile(tmp, "w") as z:
                        z.writestr("meta.json", meta)
                        z.writestr("trees.pkl", blob)
                        z.writestr("exe.bin", payload)
                    os.replace(tmp, path)
                finally:
                    tmp.unlink(missing_ok=True)
            except OSError as e:
                self._count("fallbacks")
                warnings.warn(f"AOT artifact write to {target} failed "
                              f"({e}); executable stays in-process only",
                              UserWarning, stacklevel=2)
                return False
        self._count("saved")
        return True

    # ------------------------------------------------------------- get
    def get(self, fields: Dict[str, object]):
        """Deserialize-and-load the executable stored under ``fields``,
        or None (a miss, or a counted fallback for corrupt/skewed
        artifacts — the caller then trace+compiles, never a wrong
        program)."""
        ok, _ = aot_supported()
        if not ok:
            return None
        digest = _digest(fields)
        expect = json.loads(json.dumps(fields, sort_keys=True))
        for path in self._candidates(digest):
            if not path.exists():
                continue
            try:
                with zipfile.ZipFile(path) as z:
                    meta = json.loads(z.read("meta.json"))
                    if meta != expect:
                        raise ValueError(
                            f"key fields mismatch (stored "
                            f"jax={meta.get('jax')} "
                            f"platform={meta.get('platform')}, expected "
                            f"jax={expect.get('jax')} "
                            f"platform={expect.get('platform')})")
                    in_tree, out_tree = pickle.loads(z.read("trees.pkl"))
                    payload = z.read("exe.bin")
                from jax.experimental import serialize_executable as se
                loaded = se.deserialize_and_load(payload, in_tree,
                                                 out_tree)
                self._count("loaded")
                return loaded
            except Exception as e:  # noqa: BLE001 — fall back to compile
                self._count("fallbacks")
                warnings.warn(
                    f"AOT artifact {path} unusable "
                    f"({type(e).__name__}: {e}); falling back to "
                    f"trace+compile", UserWarning, stacklevel=2)
                return None
        return None


def _warn_once(msg: str, _seen: set = set()) -> None:  # noqa: B006
    """One warning per distinct degrade message per process — visible,
    never spammy (a fit dispatches hundreds of programs)."""
    if msg not in _seen:
        _seen.add(msg)
        warnings.warn(msg, UserWarning, stacklevel=3)


# ----------------------------------------------------- active store

_STORE: Optional[AOTStore] = None
_ENV_CHECKED = False


def configure(root, read_dirs=(), mirror=None) -> Optional[AOTStore]:
    """Install the process-wide AOT store (``root=None`` uninstalls).
    The env twin is ``KMEANS_TPU_AOT_CACHE=<dir>`` — picked up lazily on
    the first compile-cache miss, so library users get the cache without
    code changes."""
    global _STORE, _ENV_CHECKED
    _ENV_CHECKED = True
    _STORE = AOTStore(root, read_dirs=read_dirs, mirror=mirror) \
        if root else None
    return _STORE


def deactivate() -> None:
    configure(None)


def active_store() -> Optional[AOTStore]:
    """The installed store; initializes from ``KMEANS_TPU_AOT_CACHE``
    exactly once when nothing was configured programmatically."""
    global _ENV_CHECKED
    if _STORE is None and not _ENV_CHECKED:
        env = os.environ.get("KMEANS_TPU_AOT_CACHE")
        if env:
            return configure(env)
        _ENV_CHECKED = True
    return _STORE


def aot_dir_for(ckpt_path) -> Path:
    """The artifact directory shipped NEXT TO a checkpoint
    (``model.npz`` -> ``model.npz.aot/``): what an elastic restart on a
    fresh host ships together with the state, so resume skips the
    compile column entirely."""
    from kmeans_tpu.utils.checkpoint import _normalize
    p = _normalize(ckpt_path)
    return p.with_name(p.name + ".aot")


def on_checkpoint_path(ckpt_path) -> None:
    """Fit-prelude hook (``AutoCheckpointMixin._check_ckpt``): with a
    store active, mirror every artifact written during this fit into the
    checkpoint's sibling ``.aot`` directory."""
    store = active_store()
    if store is not None and ckpt_path is not None:
        store.set_mirror(aot_dir_for(ckpt_path))


def on_resume_path(ckpt_path) -> None:
    """Resume hook (``AutoCheckpointMixin._resolve_resume``): with a
    store active, the checkpoint's sibling ``.aot`` directory joins the
    read path — a fresh host resuming a shipped checkpoint loads the
    shipped executables instead of compiling."""
    store = active_store()
    if store is not None and ckpt_path is not None:
        store.add_read_dir(aot_dir_for(ckpt_path))


def describe_dir(path) -> dict:
    """Operator-facing summary of an artifact directory (the
    ``ckpt-info`` ``aot`` block): artifact count/bytes and the distinct
    (cache, platform, jax) triples present — readable without jax
    device init (pure zip/json)."""
    p = Path(path)
    out = {"path": str(p), "exists": p.is_dir(), "artifacts": 0,
           "bytes": 0, "programs": [], "unreadable": 0}
    if not out["exists"]:
        return out
    seen = set()
    for f in sorted(p.glob(f"*{_EXT}")):
        out["artifacts"] += 1
        out["bytes"] += f.stat().st_size
        try:
            with zipfile.ZipFile(f) as z:
                meta = json.loads(z.read("meta.json"))
            seen.add((meta.get("cache", "?"), meta.get("platform", "?"),
                      meta.get("jax", "?")))
        except Exception:  # noqa: BLE001 — a torn artifact still counts
            out["unreadable"] += 1
    out["programs"] = [{"cache": c, "platform": pl, "jax": j}
                      for c, pl, j in sorted(seen)]
    return out


# ----------------------------------------------------------- wrapper

class _AOTProgram:
    """Per-signature AOT front of one compiled-cache entry.

    On the first call for each argument signature: try the store
    (``compile(via='aot-load')`` span), else lower+compile explicitly
    (``compile(via='aot-build')`` span) and serialize the result.  The
    explicit build moves the XLA executable build OUT of the first
    ``dispatch`` span and into the ``compile`` phase — which is what
    makes the TTFI compile row an honest before/after instrument for
    this attack.  Every failure path falls back to the wrapped jitted
    function (counted), so behavior is bit-identical to the unwrapped
    entry by construction — the AOT-off parity oracle."""

    def __init__(self, fn, cache_name: str, key, store: AOTStore):
        self._fn = fn
        self._cache = cache_name
        self._key = key
        self._store = store
        self._exes: dict = {}
        self._elock = threading.Lock()

    # Delegation keeps the jit surface (.lower, .__name__, ...) visible
    # to the cost-capture wrapper stacked outside this one.
    def __getattr__(self, name):
        return getattr(self._fn, name)

    def _ensure(self, args):
        sig = _sig_of(args)
        with self._elock:
            hit = self._exes.get(sig)
        if hit is not None:
            return hit
        exe = None
        fields = artifact_key(self._cache, self._key, sig)
        loaded = self._store.get(fields)
        if loaded is not None:
            with _obs_trace.span("compile", cache=self._cache,
                                 key=repr(self._key)[:160],
                                 via="aot-load"):
                exe = loaded
        else:
            try:
                with _obs_trace.span("compile", cache=self._cache,
                                     key=repr(self._key)[:160],
                                     via="aot-build"):
                    compiled = self._fn.lower(*args).compile()
                self._store._count("built")
                self._store.put(fields, compiled)
                exe = compiled
            except Exception as e:  # noqa: BLE001 — jit path still works
                self._store._count("call_fallbacks")
                _warn_once(f"AOT explicit compile failed for "
                           f"{self._cache} ({type(e).__name__}: {e}); "
                           f"using the in-process jit path")
                exe = self._fn
        with self._elock:
            self._exes[sig] = exe
        return exe

    def warm(self, *arg_structs) -> None:
        """Pre-resolve the executable for an argument signature given as
        ``jax.ShapeDtypeStruct``s (sharding-carrying) — the prelude-
        overlap entry point: load-or-compile runs NOW, concurrently with
        the staged ingest, and the later real call is a dict hit.
        Never raises into the fit prelude."""
        try:
            self._ensure(arg_structs)
        except Exception as e:  # noqa: BLE001 — warming is best-effort
            self._store._count("call_fallbacks")
            _warn_once(f"AOT warm-up failed for {self._cache} "
                       f"({type(e).__name__}: {e})")

    def __call__(self, *args, **kwargs):
        if kwargs:
            return self._fn(*args, **kwargs)
        exe = self._ensure(args)
        if exe is self._fn:
            return self._fn(*args)
        try:
            return exe(*args)
        except (TypeError, ValueError) as e:
            # Argument/sharding layout the compiled executable cannot
            # accept (e.g. differently-committed arrays): permanent,
            # counted fallback for this signature — correctness first.
            self._store._count("call_fallbacks")
            _warn_once(f"AOT executable call fell back to jit for "
                       f"{self._cache} ({type(e).__name__}: {e})")
            with self._elock:
                self._exes[_sig_of(args)] = self._fn
            return self._fn(*args)


def wrap(cache_name: str, key, value):
    """The ``LRUCache.get_or_create`` MISS hook (the cost-capture
    pattern): with a store active, wrap each callable member of the
    fresh entry in an :class:`_AOTProgram`; with none, return ``value``
    untouched — the disabled path is one None check, and tier-1 runs
    with it disabled (the AOT-off parity oracle)."""
    store = active_store()
    if store is None:
        return value
    if isinstance(value, tuple):
        return tuple(_AOTProgram(v, cache_name, key, store)
                     if callable(v) else v for v in value)
    if callable(value):
        return _AOTProgram(value, cache_name, key, store)
    return value
