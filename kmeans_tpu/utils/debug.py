"""Determinism / reproducibility checking.

The reference's closest analogue to a race detector is its numerical
sanitizers plus one DELIBERATE nondeterminism: the empty-cluster resample
is time-seeded (``seed=int(time.time())``, kmeans_spark.py:195-196), so
identical runs can diverge.  This framework makes every path deterministic
(derived seeds, fixed reduction orders within a given mesh/chunk
configuration) — and this module provides the checker that PROVES it for a
given setup, the SPMD equivalent of running a data-race detector over a
parallel program.

What it checks: two independent fits with identical configuration must
produce bit-identical centroid trajectories, SSE histories, and labels.
What it deliberately does NOT promise: bit-identity ACROSS different
meshes/chunk sizes (psum/accumulation order changes — compare those with a
tolerance instead; see tests/test_distributed.py's invariance tests).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np


class DeterminismReport(dict):
    """Dict with a readable summary (keys: deterministic, runs, details)."""

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        status = "DETERMINISTIC" if self["deterministic"] else "DIVERGED"
        return f"<{status} over {self['runs']} runs: {self['details']}>"


def check_determinism(model_factory: Callable[[], object], X,
                      *, runs: int = 2,
                      sample_weight: Optional[np.ndarray] = None
                      ) -> DeterminismReport:
    """Fit ``runs`` fresh models from ``model_factory`` on the same data and
    compare full trajectories bit-for-bit.

    ``model_factory`` must build a NEW, identically-configured model each
    call (e.g. ``lambda: KMeans(k=8, seed=0, verbose=False)``).  Works
    for the K-Means family AND :class:`GaussianMixture` (r4).  Returns a
    report; ``report["deterministic"]`` is the verdict, and
    ``report["details"]`` names the first field that diverged — per
    family, see ``_snapshot`` (K-Means: centroids/sse_history/
    iterations/labels; GMM: means/covariances/weights/lower_bound/
    iterations/labels).
    """
    if runs < 2:
        raise ValueError(f"runs must be >= 2, got {runs}")
    X = np.asarray(X)
    ref = None
    for r in range(runs):
        model = model_factory()
        if getattr(model, "verbose", False):
            raise ValueError("use verbose=False models (log output is not "
                             "part of the determinism contract)")
        fit_kwargs = {}
        if sample_weight is not None:
            import inspect
            if "sample_weight" not in inspect.signature(
                    model.fit).parameters:
                raise ValueError(
                    f"{type(model).__name__}.fit does not accept "
                    "sample_weight; omit it for this model")
            fit_kwargs["sample_weight"] = sample_weight
        model.fit(X.copy(), **fit_kwargs)
        snap = _snapshot(model, X)
        if ref is None:
            ref = snap
            continue
        for field, val in snap.items():
            a = np.asarray(ref[field])
            b = np.asarray(val)
            if a.shape != b.shape or not np.array_equal(a, b):
                where = ""
                if a.shape == b.shape and a.ndim:
                    bad = np.flatnonzero((a != b).reshape(-1))
                    where = f" (first mismatch at flat index {bad[0]})"
                elif not a.ndim:
                    where = f": {a} vs {b}"
                return DeterminismReport(
                    deterministic=False, runs=r + 1,
                    details=f"{field} diverged on run {r}{where}")
    return DeterminismReport(deterministic=True, runs=runs,
                             details="all trajectories bit-identical")


def _snapshot(model, X) -> dict:
    """Bit-comparable trajectory snapshot, per model family (the K-Means
    estimators expose centroids/sse_history; GaussianMixture its EM
    parameters — r4: the checker covers the mixture family too)."""
    if hasattr(model, "centroids"):              # K-Means family
        return {
            "centroids": np.asarray(model.centroids).copy(),
            "sse_history": np.asarray(model.sse_history,
                                      dtype=np.float64),
            "iterations": model.iterations_run,
            "labels": np.asarray(model.predict(X)).copy(),
        }
    return {                                     # GaussianMixture family
        "means": np.asarray(model.means_).copy(),
        "covariances": np.asarray(model.covariances_).copy(),
        "weights": np.asarray(model.weights_).copy(),
        "lower_bound": np.float64(model.lower_bound_),
        "iterations": model.n_iter_,
        "labels": np.asarray(model.predict(X)).copy(),
    }
