"""Bounded LRU cache for compiled step functions.

The models key their jitted ``shard_map`` programs by everything that
forces a rebuild (mesh, chunk, mode, k, ...).  Unbounded dicts were a
slow leak for long-lived services: every distinct block shape streamed
through ``predict_stream``/``transform_stream`` compiled and pinned a
new executable for the process lifetime (r3 VERDICT weak #7).  A small
LRU bound keeps hot entries (move-to-end on hit) and lets XLA
executables for cold shapes be garbage-collected; fit loops hold a local
reference to their function, so eviction mid-fit is harmless.
"""

from __future__ import annotations

import os
import sys
from collections import OrderedDict

from kmeans_tpu.obs import cost as _obs_cost
from kmeans_tpu.obs import trace as _obs_trace


def _aot_wrap(name, key, value):
    """ISSUE 15: hand a fresh compile-cache entry to the AOT executable
    layer (``utils.aot.wrap``) — lazily, so this module keeps its
    light import surface: ``utils.aot`` (which imports jax) is touched
    only when it was already configured programmatically (module
    imported) or the ``KMEANS_TPU_AOT_CACHE`` env knob is set.  With
    neither, a cache miss costs one sys.modules lookup + one env get —
    the AOT-off parity-oracle path."""
    mod = sys.modules.get("kmeans_tpu.utils.aot")
    if mod is None:
        if not os.environ.get("KMEANS_TPU_AOT_CACHE"):
            return value
        from kmeans_tpu.utils import aot as mod
    return mod.wrap(name, key, value)


class LRUCache:
    """Minimal ordered-dict LRU with the mapping surface the models use
    (``in`` / ``[]`` / assignment / ``len``).

    ``name`` labels the cache in telemetry: every ``get_or_create``
    MISS — the event where a program gets (re)built — is recorded as a
    ``compile`` span naming the cache and key when a tracer is active
    (ISSUE 11: the ``_STEP_CACHE``-class compile hook), so unexpected
    recompiles appear on the timeline with their provenance, the same
    classification the recompilation sentinel enforces at runtime.
    ``compile_spans=False`` opts a cache out — for caches whose factory
    is NOT a program build (the ``_AUTO_CACHE`` measurement cache runs
    two full training steps; labeling that ``compile`` would inflate
    the TTFI compile row on exactly the high-RTT platforms the
    artifact targets).
    """

    def __init__(self, maxsize: int = 64, name: str = None,
                 compile_spans: bool = True):
        if int(maxsize) < 1:
            raise ValueError(f"maxsize must be >= 1, got {maxsize}")
        self.maxsize = int(maxsize)
        self.name = name
        self.compile_spans = bool(compile_spans)
        self._d: OrderedDict = OrderedDict()

    def get_or_create(self, key, factory):
        """Return the cached value, building it with ``factory()`` on a
        miss.  The models use THIS (not check-then-get) so a concurrent
        eviction between the check and the read can never raise — the
        worst race outcome is a duplicate compile, exactly like the old
        unbounded dict."""
        try:
            value = self._d[key]           # single atomic read
        except KeyError:
            if self.compile_spans and _obs_trace.active():
                with _obs_trace.span("compile",
                                     cache=self.name or "cache",
                                     key=repr(key)[:160]):
                    value = factory()
            else:
                value = factory()
            if self.compile_spans:
                # AOT executable cache (ISSUE 15): with a store active,
                # each callable member is fronted by a per-signature
                # load-or-compile-and-serialize wrapper — applied FIRST
                # so the cost wrapper below stays outermost and its
                # one-shot analysis still observes every call.
                # Measurement caches (compile_spans=False) opt out of
                # all three hooks together.
                value = _aot_wrap(self.name or "cache", key, value)
                # Device-cost capture (ISSUE 12): with a cost collector
                # active, the freshly built program(s) are wrapped for
                # one-shot AOT analysis on their first call; with none
                # installed this is a single None check returning the
                # value untouched.  Measurement caches
                # (compile_spans=False) opt out alongside the span.
                value = _obs_cost.instrument(self.name or "cache", key,
                                             value)
            self[key] = value
            return value
        try:
            self._d.move_to_end(key)
        except KeyError:
            pass            # evicted concurrently; value is still valid
        return value

    def __contains__(self, key) -> bool:
        return key in self._d

    def __getitem__(self, key):
        self._d.move_to_end(key)
        return self._d[key]

    def __setitem__(self, key, value) -> None:
        self._d[key] = value
        self._d.move_to_end(key)
        while len(self._d) > self.maxsize:
            self._d.popitem(last=False)

    def __len__(self) -> int:
        return len(self._d)

    def keys(self):
        """Snapshot of the current keys (insertion/recency order) —
        what ``profiling.recompilation_sentinel`` diffs to assert that
        repeat same-shape calls add zero compiled entries."""
        return list(self._d.keys())

    def clear(self) -> None:
        self._d.clear()
