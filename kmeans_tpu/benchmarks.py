"""Benchmark harness for the BASELINE.json north-star configs.

Measures steady-state iteration throughput (points*dims/sec/chip) for each
config, with compile/warmup excluded (the reference times cold,
kmeans_spark.py:575-579 — SURVEY.md §6 flags this) and synchronization via
scalar transfer (block_until_ready is not a reliable barrier on tunneled
PJRT platforms).

Configs (BASELINE.json): make_blobs 10k x 2 k=5 · blobs 1M x 16 k=64 ·
uniform 10M x 128 k=1024 (headline) · MNIST-shaped 60k x 784 k=10 ·
GloVe-shaped 400k x 100 k=3000.  The image has no network access, so the
MNIST/GloVe configs use distribution-matched synthetic data (pixel-like
clipped mixtures / heavy-tailed embedding clouds) at the exact shapes.

Run: ``python -m kmeans_tpu bench [--configs small,blobs1m] [--iters N]``
Each config prints one JSON line; a markdown table row set is printed at the
end for BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict

import numpy as np


def enable_compilation_cache() -> None:
    """Persistent XLA/Mosaic compilation cache (r2 VERDICT #6): the
    marginal method compiles TWO while_loop programs per config, and on
    the tunneled platform each remote compile can cost tens of seconds
    on a slow compile-service day (breakdown in docs/PERFORMANCE.md;
    experiments/exp_compile_time.py reproduces it).  The cache removes
    recompiles across processes/runs entirely.  Opt out with
    JAX_COMPILATION_CACHE_DIR="" (cold-compile measurement).  Lives in
    the package (not the repo-root bench.py script) so installed users
    get it too."""
    import os

    import jax
    cache = os.environ.get("JAX_COMPILATION_CACHE_DIR",
                           "/tmp/kmeans_tpu_jax_cache")
    if cache:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
        _log(f"bench: compilation cache at {cache}")


def measure_marginal(time_small, time_big, reps: int = 3):
    """The measurement protocol shared by BOTH harnesses (bench.py and
    bench_config): ``reps`` interleaved (small, big) wall-time pairs —
    interleaving keeps each marginal internally consistent under slow
    environment drift (r1 VERDICT #8) — reduced to the MEDIAN marginal
    (one noisy pair must not decide, r3 fix) with the (max-min)/median
    relative spread reported alongside.  Returns (margin, spread,
    margins)."""
    margins = []
    for _ in range(reps):
        ts = time_small()
        tb = time_big()
        margins.append(max(tb - ts, 1e-9))
    margin = float(np.median(margins))
    spread = (max(margins) - min(margins)) / margin
    return margin, spread, margins


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_config_data(name: str, rng: np.random.Generator) -> np.ndarray:
    from kmeans_tpu.data.synthetic import make_blobs, make_uniform
    if name == "small":        # make_blobs 10k x 2, k=5 (reference-scale)
        return make_blobs(10_000, 5, 2, random_state=42,
                          dtype=np.float32)[0]
    if name == "blobs1m":      # 1M x 16, k=64
        return make_blobs(1_000_000, 64, 16, random_state=42,
                          dtype=np.float32)[0]
    if name == "uniform10m":   # headline: 10M x 128, k=1024
        return make_uniform(10_000_000, 128, random_state=42)
    if name == "mnist":        # MNIST-shaped: 60k x 784 pixels in [0, 1]
        centers = rng.uniform(0, 1, size=(10, 784)).astype(np.float32)
        labels = rng.integers(0, 10, size=60_000)
        X = centers[labels] + 0.15 * rng.standard_normal(
            (60_000, 784)).astype(np.float32)
        return np.clip(X, 0.0, 1.0)
    if name == "glove":        # GloVe-shaped: 400k x 100, heavy-tailed
        X = rng.standard_t(df=4, size=(400_000, 100)).astype(np.float32)
        return X / np.sqrt((X * X).mean())
    raise ValueError(f"unknown config {name!r}")


CONFIG_K = {"small": 5, "blobs1m": 64, "uniform10m": 1024, "mnist": 10,
            "glove": 3000}
DEFAULT_CONFIGS = ["small", "blobs1m", "mnist", "glove", "uniform10m"]


def bench_config(name: str, iters: int, mode: str) -> Dict:
    import jax
    from kmeans_tpu.parallel import distributed as dist
    from kmeans_tpu.parallel.mesh import make_mesh, mesh_shape
    from kmeans_tpu.parallel.sharding import (choose_chunk_size,
                                              shard_points)

    rng = np.random.default_rng(42)
    X = make_config_data(name, rng)
    n, d = X.shape
    k = CONFIG_K[name]
    if mode == "auto":
        from kmeans_tpu.ops.pallas_kernels import resolve_auto
        mode = resolve_auto(n, d, k)
    mesh = make_mesh()
    data_shards, model_shards = mesh_shape(mesh)
    chunk = choose_chunk_size(-(-n // data_shards), k, d)
    points, weights = shard_points(X, mesh, chunk)
    init = X[rng.choice(n, size=k, replace=False)]
    cents = jax.device_put(dist.pad_centroids(init, model_shards),
                           dist.centroid_sharding(mesh))

    # Marginal method (same as bench.py): per-iteration cost is the time
    # difference between a 2-iteration and a (2+iters)-iteration on-device
    # while_loop fit — one dispatch each, which cancels dispatch/tunnel
    # round-trip latency exactly.  A per-dispatch loop would add the full
    # host->device RTT (~100 ms on tunneled platforms) to every iteration.
    def build(max_iter: int):
        return dist.make_fit_fn(mesh, chunk_size=chunk, mode=mode, k_real=k,
                                max_iter=max_iter, tolerance=0.0,
                                empty_policy="keep")

    # Pre-placed seed schedules ('keep': unused by the program), one per
    # program length — transferring them inside the timed window would
    # add an O(iters) host->device copy to only the BIG side of each
    # marginal pair and bias the measurement.
    _seed_cache: Dict[int, object] = {}

    def seeds_for(n_seeds: int):
        if n_seeds not in _seed_cache:
            _seed_cache[n_seeds] = jax.device_put(
                np.zeros((n_seeds,), np.uint32))
        return _seed_cache[n_seeds]

    def timed(fit_fn, n_seeds) -> tuple:
        seeds = seeds_for(n_seeds)
        start = time.perf_counter()
        out = fit_fn(points, weights, cents, seeds)
        int(out[1])                                  # n_iters -> sync barrier
        return time.perf_counter() - start, out

    fit_small = build(2)
    t0 = time.perf_counter()
    timed(fit_small, 2)
    _log(f"[{name}] compile+warmup(2-iter) {time.perf_counter() - t0:.1f}s")

    # Adaptive: grow the iteration gap until the marginal time rises above
    # the dispatch-latency noise floor (~50 ms on tunneled platforms).
    # The grow/stop decision uses the MEDIAN of 3 interleaved pairs (r1
    # VERDICT #8) — r3 fix: deciding on a single pair let one noise spike
    # stop the growth early and mis-report a measurable config as
    # noise-limited.  The cap is high — the 5x growth stops at the first
    # measured gap >= 50k iterations — because a while_loop's compile
    # time does not depend on its trip count; only sub-µs/iter configs
    # stay unmeasurable.
    out_big = None
    while True:
        fit_big = build(2 + iters)
        _, out_big = timed(fit_big, 2 + iters)       # compile + warm
        margin, spread, _ = measure_marginal(
            lambda: timed(fit_small, 2)[0],
            lambda: timed(fit_big, 2 + iters)[0])
        if margin > 0.05 or iters >= 50_000:
            break
        iters *= 5
        _log(f"[{name}] marginal below noise floor; retrying with "
             f"iters={iters}")
    noise_limited = margin <= 0.05              # same floor as the loop
    if noise_limited:
        _log(f"[{name}] WARNING: marginal time ({margin:.3f}s over "
             f"{iters} iters) is within dispatch-latency noise — "
             f"per-iteration numbers are unmeasurable at this size and are "
             f"reported as null")
    per_iter = margin / iters
    sse = float(np.asarray(out_big[2])[-1])          # last-iteration SSE
    n_chips = max(1, len(jax.devices()))
    result = {
        "config": name, "n": n, "d": d, "k": k, "mode": mode,
        "iters": iters,
        "ms_per_iter": None if noise_limited else round(per_iter * 1e3, 4),
        "throughput_pd_per_sec_per_chip": None if noise_limited else
        round(n * d / per_iter / n_chips, 1),
        "spread": None if noise_limited else round(spread, 3),
        "sse": sse,
        "noise_limited": noise_limited,
    }
    print(json.dumps(result), flush=True)
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="kmeans_tpu benchmarks")
    parser.add_argument("--configs", default=",".join(DEFAULT_CONFIGS))
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument("--mode", default="auto",
                        help="auto | matmul | matmul_bf16 | pallas | "
                             "pallas_bf16")
    args = parser.parse_args(argv)

    enable_compilation_cache()

    results = []
    for name in args.configs.split(","):
        try:
            results.append(bench_config(name.strip(), args.iters,
                                        args.mode))
        except Exception as e:           # noqa: BLE001 — keep suite going
            _log(f"[{name}] FAILED: {e}")

    _log("\n| config | N | D | k | ms/iter | points*dims/s/chip |")
    _log("|---|---|---|---|---|---|")
    for r in results:
        tput = r["throughput_pd_per_sec_per_chip"]
        nl = tput is None
        _log(f"| {r['config']} | {r['n']:,} | {r['d']} | {r['k']} | "
             f"{'(noise-limited)' if nl else r['ms_per_iter']} | "
             f"{'(noise-limited)' if nl else format(tput, '.3e')} |")
    return 0 if results else 1


if __name__ == "__main__":
    sys.exit(main())
