"""Benchmark harness for the BASELINE.json north-star configs.

Measures steady-state iteration throughput (points*dims/sec/chip) for each
config, with compile/warmup excluded (the reference times cold,
kmeans_spark.py:575-579 — SURVEY.md §6 flags this) and synchronization via
scalar transfer (block_until_ready is not a reliable barrier on tunneled
PJRT platforms).

Configs (BASELINE.json): make_blobs 10k x 2 k=5 · blobs 1M x 16 k=64 ·
uniform 10M x 128 k=1024 (headline) · MNIST-shaped 60k x 784 k=10 ·
GloVe-shaped 400k x 100 k=3000.  The image has no network access, so the
MNIST/GloVe configs use distribution-matched synthetic data (pixel-like
clipped mixtures / heavy-tailed embedding clouds) at the exact shapes.

Run: ``python -m kmeans_tpu bench [--configs small,blobs1m] [--iters N]``
Each config prints one JSON line; a markdown table row set is printed at the
end for BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict

import numpy as np


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_config_data(name: str, rng: np.random.Generator) -> np.ndarray:
    from kmeans_tpu.data.synthetic import make_blobs, make_uniform
    if name == "small":        # make_blobs 10k x 2, k=5 (reference-scale)
        return make_blobs(10_000, 5, 2, random_state=42,
                          dtype=np.float32)[0]
    if name == "blobs1m":      # 1M x 16, k=64
        return make_blobs(1_000_000, 64, 16, random_state=42,
                          dtype=np.float32)[0]
    if name == "uniform10m":   # headline: 10M x 128, k=1024
        return make_uniform(10_000_000, 128, random_state=42)
    if name == "mnist":        # MNIST-shaped: 60k x 784 pixels in [0, 1]
        centers = rng.uniform(0, 1, size=(10, 784)).astype(np.float32)
        labels = rng.integers(0, 10, size=60_000)
        X = centers[labels] + 0.15 * rng.standard_normal(
            (60_000, 784)).astype(np.float32)
        return np.clip(X, 0.0, 1.0)
    if name == "glove":        # GloVe-shaped: 400k x 100, heavy-tailed
        X = rng.standard_t(df=4, size=(400_000, 100)).astype(np.float32)
        return X / np.sqrt((X * X).mean())
    raise ValueError(f"unknown config {name!r}")


CONFIG_K = {"small": 5, "blobs1m": 64, "uniform10m": 1024, "mnist": 10,
            "glove": 3000}
DEFAULT_CONFIGS = ["small", "blobs1m", "mnist", "glove", "uniform10m"]


def bench_config(name: str, iters: int, mode: str) -> Dict:
    import jax
    from kmeans_tpu.models.kmeans import _get_step_fns
    from kmeans_tpu.parallel import distributed as dist
    from kmeans_tpu.parallel.mesh import make_mesh, mesh_shape
    from kmeans_tpu.parallel.sharding import (choose_chunk_size,
                                              shard_points)

    rng = np.random.default_rng(42)
    X = make_config_data(name, rng)
    n, d = X.shape
    k = CONFIG_K[name]
    mesh = make_mesh()
    data_shards, model_shards = mesh_shape(mesh)
    chunk = choose_chunk_size(-(-n // data_shards), k, d)
    points, weights = shard_points(X, mesh, chunk)
    init = X[rng.choice(n, size=k, replace=False)]
    cents = jax.device_put(dist.pad_centroids(init, model_shards),
                           dist.centroid_sharding(mesh))
    step_fn, _ = _get_step_fns(mesh, chunk, mode)

    t0 = time.perf_counter()
    float(step_fn(points, weights, cents).sse)       # compile + first step
    _log(f"[{name}] compile+first step {time.perf_counter() - t0:.1f}s")
    float(step_fn(points, weights, cents).sse)       # steady-state warm

    start = time.perf_counter()
    for _ in range(iters):
        stats = step_fn(points, weights, cents)
        sse = float(stats.sse)                       # sync barrier
    per_iter = (time.perf_counter() - start) / iters
    n_chips = max(1, len(jax.devices()))
    result = {
        "config": name, "n": n, "d": d, "k": k, "mode": mode,
        "iters": iters, "ms_per_iter": round(per_iter * 1e3, 2),
        "throughput_pd_per_sec_per_chip": round(n * d / per_iter / n_chips,
                                                1),
        "sse": sse,
    }
    print(json.dumps(result), flush=True)
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="kmeans_tpu benchmarks")
    parser.add_argument("--configs", default=",".join(DEFAULT_CONFIGS))
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument("--mode", default="matmul",
                        help="matmul | matmul_bf16 | pallas | pallas_bf16")
    args = parser.parse_args(argv)

    results = []
    for name in args.configs.split(","):
        try:
            results.append(bench_config(name.strip(), args.iters,
                                        args.mode))
        except Exception as e:           # noqa: BLE001 — keep suite going
            _log(f"[{name}] FAILED: {e}")

    _log("\n| config | N | D | k | ms/iter | points*dims/s/chip |")
    _log("|---|---|---|---|---|---|")
    for r in results:
        _log(f"| {r['config']} | {r['n']:,} | {r['d']} | {r['k']} | "
             f"{r['ms_per_iter']} | {r['throughput_pd_per_sec_per_chip']:.3e}"
             f" |")
    return 0 if results else 1


if __name__ == "__main__":
    sys.exit(main())
