"""Benchmark harness for the BASELINE.json north-star configs.

Measures steady-state iteration throughput (points*dims/sec/chip) for each
config, with compile/warmup excluded (the reference times cold,
kmeans_spark.py:575-579 — SURVEY.md §6 flags this) and synchronization via
scalar transfer (block_until_ready is not a reliable barrier on tunneled
PJRT platforms).

Configs (BASELINE.json): make_blobs 10k x 2 k=5 · blobs 1M x 16 k=64 ·
uniform 10M x 128 k=1024 (headline) · MNIST-shaped 60k x 784 k=10 ·
GloVe-shaped 400k x 100 k=3000.  The image has no network access, so the
MNIST/GloVe configs use distribution-matched synthetic data (pixel-like
clipped mixtures / heavy-tailed embedding clouds) at the exact shapes.

Run: ``python -m kmeans_tpu bench [--configs small,blobs1m] [--iters N]``
Each config prints one JSON line; a markdown table row set is printed at the
end for BASELINE.md.

``--model kmeans|gmm|minibatch|bisecting|spherical`` (ISSUE 2 satellite)
selects the model family: ``kmeans`` runs the BASELINE.json configs as
before; the other four run that family's ONE-DISPATCH device fit through
the same marginal protocol at a family-scaled shape, so BASELINE.md can
publish ≤5%-spread rows for every family the repo ships.  Every row also
carries an ``init`` column — the warm one-dispatch k-means|| seeding cost
at the row's shape (plus the legacy engine's cost on the kmeans rows), so
the ISSUE 2 before/after is a pinned bench number, not prose.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List

import numpy as np


def enable_compilation_cache() -> None:
    """Persistent XLA/Mosaic compilation cache (r2 VERDICT #6).

    ISSUE 15 satellite: the implementation moved to
    ``utils.aot.enable_compilation_cache`` — library-level, with the
    ``KMEANS_TPU_COMPILE_CACHE`` env knob, called by the CLI fits too —
    so the first rung of the warm-start ladder stopped being
    bench-only.  This delegator keeps the bench surface (and its log
    line)."""
    from kmeans_tpu.utils.aot import enable_compilation_cache as enable
    cache = enable()
    if cache:
        _log(f"bench: compilation cache at {cache}")


def measure_marginal(time_small, time_big, reps: int = 3):
    """The measurement protocol shared by BOTH harnesses (bench.py and
    bench_config): ``reps`` interleaved (small, big) wall-time pairs —
    interleaving keeps each marginal internally consistent under slow
    environment drift (r1 VERDICT #8) — reduced to the MEDIAN marginal
    (one noisy pair must not decide, r3 fix) with the (max-min)/median
    relative spread reported alongside.  Returns (margin, spread,
    margins)."""
    margins = []
    for _ in range(reps):
        ts = time_small()
        tb = time_big()
        margins.append(max(tb - ts, 1e-9))
    margin = float(np.median(margins))
    spread = (max(margins) - min(margins)) / margin
    return margin, spread, margins


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_config_data(name: str, rng: np.random.Generator) -> np.ndarray:
    from kmeans_tpu.data.synthetic import make_blobs, make_uniform
    if name == "small":        # make_blobs 10k x 2, k=5 (reference-scale)
        return make_blobs(10_000, 5, 2, random_state=42,
                          dtype=np.float32)[0]
    if name == "blobs1m":      # 1M x 16, k=64
        return make_blobs(1_000_000, 64, 16, random_state=42,
                          dtype=np.float32)[0]
    if name == "uniform10m":   # headline: 10M x 128, k=1024
        return make_uniform(10_000_000, 128, random_state=42)
    if name == "mnist":        # MNIST-shaped: 60k x 784 pixels in [0, 1]
        centers = rng.uniform(0, 1, size=(10, 784)).astype(np.float32)
        labels = rng.integers(0, 10, size=60_000)
        X = centers[labels] + 0.15 * rng.standard_normal(
            (60_000, 784)).astype(np.float32)
        return np.clip(X, 0.0, 1.0)
    if name == "glove":        # GloVe-shaped: 400k x 100, heavy-tailed
        X = rng.standard_t(df=4, size=(400_000, 100)).astype(np.float32)
        return X / np.sqrt((X * X).mean())
    raise ValueError(f"unknown config {name!r}")


CONFIG_K = {"small": 5, "blobs1m": 64, "uniform10m": 1024, "mnist": 10,
            "glove": 3000}
DEFAULT_CONFIGS = ["small", "blobs1m", "mnist", "glove", "uniform10m"]


def published_row(n: int, d: int, k: int):
    """The matching BASELINE.json.published row, or None outside a repo
    checkout — the bench then simply reports absolutes, it never fails
    (r5: the published table became machine-readable; comparing each
    run against it catches silent regressions AND tunnel-drift
    windows).  Exact (n, d, k) first; a (d, k) match only when unique —
    the table holds two (128, 1024) rows (headline 10M + 2M sanity), so
    shape alone must not silently pick one by JSON order (review r5)."""
    import json as _json
    from pathlib import Path
    try:
        doc = _json.loads((Path(__file__).parent.parent
                           / "BASELINE.json").read_text())
        rows = doc["published"]["rows"]
        exact = [r for r in rows
                 if (int(r["n"]), int(r["d"]), int(r["k"])) == (n, d, k)]
        if exact:
            return exact[0]
        shape = [r for r in rows if (int(r["d"]), int(r["k"])) == (d, k)]
        return shape[0] if len(shape) == 1 else None
    except (OSError, KeyError, TypeError, ValueError):
        pass
    return None


def bench_init(X, k: int, *, seed: int = 0, reps: int = 5):
    """Warm k-means|| seeding cost at a shape: (device_s, legacy_s) —
    median of ``reps`` warm calls each (first call per engine compiles
    and is discarded).  The 'init' column of every published row: the
    ISSUE 2 tentpole's before/after as a pinned number."""
    from kmeans_tpu.models.init import kmeans_parallel_init

    out = []
    for device in (True, False):
        kmeans_parallel_init(X, k, seed, device=device)     # compile/warm
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            kmeans_parallel_init(X, k, seed, device=device)
            times.append(time.perf_counter() - t0)
        out.append(float(np.median(times)))
    return out[0], out[1]


#: Family-scaled shapes for the non-KMeans model rows.  CPU-safe sizes —
#: on TPU hardware the same harness runs unchanged and the published
#: BASELINE rows record the platform alongside the number.
MODEL_SPECS = {
    "gmm": dict(n=200_000, d=32, k=32),
    "gmm_full": dict(n=100_000, d=16, k=16),
    "minibatch": dict(n=500_000, d=32, k=64, batch=4096),
    "bisecting": dict(n=100_000, d=16, k=8),
    "spherical": dict(n=200_000, d=32, k=64),
}

#: bf16 peak TFLOP/s per backend — the MFU denominator (the rate "f32"
#: dots execute at on the MXU; exp_glove_mfu.py precedent).  Backends
#: without an entry publish ``step_mfu = None`` but always record
#: ``flops_per_iter``.  Since ISSUE 12 the hand FLOP formulas below are
#: CROSS-CHECKED against XLA's own per-program cost analysis
#: (``obs.cost``): an MFU row is publishable only while the analytic
#: and XLA-reported flops agree within the committed 10% band
#: (``obs.cost.FLOPS_AGREEMENT_RTOL``; ``BENCH_COST=1`` /
#: ``cost-report`` emit the comparison) — a mismatch is a reported
#: finding, never a silently trusted numerator.
PEAK_TFLOPS = {"tpu": 197.0}


def gmm_flops_per_iter(n: int, d: int, k: int,
                       cov_type: str = "diag") -> float:
    """Real FLOPs of one EM iteration's E pass — the MFU numerator
    (padding waste gets no credit, the repo's MFU definition).

    diag/spherical: two log-density + two moment matmuls, 2·N·D·k each.
    full: the batched density transform ("cd,kde->cke") and the scatter
    moment ("ck,cd,ce->kde") at 2·N·k·D² each, plus the N·k·D-order
    xsum/quad terms.  tied: one N×D² transform + the 2·N·D·k
    cross/xsum matmuls."""
    if cov_type in ("diag", "spherical"):
        return 8.0 * n * d * k
    if cov_type == "full":
        return 4.0 * n * k * d * d + 4.0 * n * d * k
    if cov_type == "tied":
        return 2.0 * n * d * d + 4.0 * n * d * k
    raise ValueError(f"unknown covariance type {cov_type!r}")


def kmeans_flops_per_iter(n: int, d: int, k: int) -> float:
    """Real FLOPs of one Lloyd iteration: 2·N·D·k distance matmul +
    2·N·D·k one-hot scatter matmul (padding waste gets no credit — the
    repo's MFU definition, docs/PERFORMANCE.md)."""
    return 4.0 * n * d * k


def step_mfu(flops_per_iter: float, sec_per_iter: float):
    """Measured-FLOPs/peak for the current backend, or None when no
    peak is pinned for it (the CPU container) — the >40%-MFU tentpole
    target as a machine-readable column, not prose."""
    import jax
    peak = PEAK_TFLOPS.get(jax.default_backend())
    if peak is None or not sec_per_iter > 0:
        return None
    return flops_per_iter / sec_per_iter / (peak * 1e12)


def bench_model(model: str, iters: int) -> Dict:
    """Marginal per-iteration cost of a non-KMeans family's ONE-DISPATCH
    fit (host_loop=False — gmm EM loop, minibatch Sculley loop, the new
    spherical projected loop, bisecting's per-split device 2-means),
    via the repo's estimator-level marginal: median of 5 interleaved
    (max_iter=2, max_iter=2+T) whole-fit wall-time pairs with a fixed
    deterministic init, which cancels upload/init/compile/labels exactly.
    Adds the ``init`` column (``bench_init``) at the same shape."""
    import jax

    from kmeans_tpu.models import (BisectingKMeans, GaussianMixture,
                                   MiniBatchKMeans, SphericalKMeans)

    spec = MODEL_SPECS[model]
    n, d, k = spec["n"], spec["d"], spec["k"]
    rng = np.random.default_rng(42)
    X = (rng.standard_normal((n, d))
         + 4.0 * rng.integers(0, 4, size=(n, 1))).astype(np.float32)
    init = X[np.sort(rng.choice(n, size=k, replace=False))]

    def make(mi: int):
        if model in ("gmm", "gmm_full"):
            return GaussianMixture(
                n_components=k,
                covariance_type="full" if model == "gmm_full" else "diag",
                max_iter=mi, tol=0.0, seed=0, init_params="random",
                host_loop=False, verbose=False)
        if model == "minibatch":
            return MiniBatchKMeans(
                k=k, batch_size=spec["batch"], max_iter=mi,
                tolerance=1e-30, seed=0, init=init, host_loop=False,
                compute_labels=False, verbose=False)
        if model == "bisecting":
            return BisectingKMeans(
                k=k, max_iter=mi, tolerance=1e-30, seed=0,
                host_loop=False, compute_labels=False, verbose=False)
        return SphericalKMeans(
            k=k, max_iter=mi, tolerance=1e-30, seed=0, init=init,
            host_loop=False, empty_cluster="keep", compute_labels=False,
            verbose=False)

    # The KMeans families re-fit a PRE-CACHED dataset so the per-fit
    # constant (upload + shard) stays out of the timed window's noise;
    # GMM uploads per fit (no public cache) — its margin cancels it.
    ds = X if model.startswith("gmm") else make(2).cache(X)

    def timed(mi: int) -> float:
        t0 = time.perf_counter()
        make(mi).fit(ds)
        return time.perf_counter() - t0

    # Iteration accounting: bisecting runs (k-1) splits of max_iter inner
    # Lloyd iterations each, so the marginal covers T*(k-1) iterations.
    iter_scale = (k - 1) if model == "bisecting" else 1

    timed(2)                                        # compile + warm
    timed(2)                                        # second warm (cache)
    # Ramp on the MEASURED MEDIAN margin, never a single probe:
    # estimator-level fits carry a seconds-scale constant (upload/init/
    # dispatch) whose run-to-run noise on a shared host can inflate one
    # probe several-fold and fake a sufficient gap (first-cut failure
    # mode of this harness: a 184 ms true margin passed a 1.5 s bar).
    TARGET, CAP = 1.5, 20_000
    margin = spread = None
    for attempt in range(4):
        timed(2 + iters)                            # compile the big side
        margin, spread, _ = measure_marginal(
            lambda: timed(2), lambda: timed(2 + iters), reps=5)
        if spread <= 0.05 or iters >= CAP or attempt == 3:
            # attempt==3 guard: NEVER update iters after the final
            # measurement — per_iter divides the measured margin by the
            # iters it was measured at (review: the unguarded variant
            # could publish margin/new_iters, up to 25x too small).
            break
        if margin < TARGET:
            per_iter0 = max(margin / iters, 1e-9)
            iters = int(min(CAP, min(iters * 25,
                                     max(TARGET / per_iter0,
                                         iters * 4))))
            _log(f"[{model}] spread {spread * 100:.0f}% with margin "
                 f"{margin * 1e3:.0f} ms; retrying with iters={iters}")
        else:
            _log(f"[{model}] spread {spread * 100:.0f}% at a sufficient "
                 f"margin (host drift); re-measuring")
    per_iter = margin / (iters * iter_scale)
    init_dev_s, init_legacy_s = bench_init(X, k)
    n_chips = max(1, len(jax.devices()))
    result = {
        "config": f"{model} {n}x{d} k={k}",
        "model": model, "n": n, "d": d, "k": k,
        "iters": iters,
        "ms_per_iter": round(per_iter * 1e3, 4),
        "throughput_pd_per_sec_per_chip": round(n * d / per_iter / n_chips,
                                                1),
        "spread": round(spread, 3),
        "indicative_only": bool(spread > 0.05),
        "init_kmeanspp_s": round(init_dev_s, 4),
        "init_kmeanspp_legacy_s": round(init_legacy_s, 4),
        "platform": jax.default_backend(),
    }
    if model.startswith("gmm"):
        # step MFU column (ISSUE 3 satellite): the >40% tentpole target
        # as a machine-readable number on the mixture rows.  estep_path_
        # records which chunk schedule the measured fit actually ran.
        ct = "full" if model == "gmm_full" else "diag"
        flops = gmm_flops_per_iter(n, d, k, ct)
        mfu = step_mfu(flops, per_iter)
        result["flops_per_iter"] = flops
        result["step_mfu"] = None if mfu is None else round(mfu, 4)
        result["estep_path"] = ("pipelined" if make(2)._resolve_pipeline()
                                else "serial")
    print(json.dumps(result), flush=True)
    return result


def bench_config(name: str, iters: int, mode: str) -> Dict:
    import jax
    from kmeans_tpu.parallel import distributed as dist
    from kmeans_tpu.parallel.mesh import make_mesh, mesh_shape
    from kmeans_tpu.parallel.sharding import (choose_chunk_size,
                                              shard_points)

    rng = np.random.default_rng(42)
    X = make_config_data(name, rng)
    n, d = X.shape
    k = CONFIG_K[name]
    if mode == "auto":
        from kmeans_tpu.ops.pallas_kernels import resolve_auto
        mode = resolve_auto(n, d, k)
    mesh = make_mesh()
    data_shards, model_shards = mesh_shape(mesh)
    chunk = choose_chunk_size(-(-n // data_shards), k, d)
    points, weights = shard_points(X, mesh, chunk)
    init = X[rng.choice(n, size=k, replace=False)]
    cents = jax.device_put(dist.pad_centroids(init, model_shards),
                           dist.centroid_sharding(mesh))

    # Marginal method (same as bench.py): per-iteration cost is the time
    # difference between a 2-iteration and a (2+iters)-iteration on-device
    # while_loop fit — one dispatch each, which cancels dispatch/tunnel
    # round-trip latency exactly.  A per-dispatch loop would add the full
    # host->device RTT (~100 ms on tunneled platforms) to every iteration.
    def build(max_iter: int):
        return dist.make_fit_fn(mesh, chunk_size=chunk, mode=mode, k_real=k,
                                max_iter=max_iter, tolerance=0.0,
                                empty_policy="keep")

    # Pre-placed seed schedules ('keep': unused by the program), one per
    # program length — transferring them inside the timed window would
    # add an O(iters) host->device copy to only the BIG side of each
    # marginal pair and bias the measurement.
    _seed_cache: Dict[int, object] = {}

    def seeds_for(n_seeds: int):
        if n_seeds not in _seed_cache:
            _seed_cache[n_seeds] = jax.device_put(
                np.zeros((n_seeds,), np.uint32))
        return _seed_cache[n_seeds]

    def timed(fit_fn, n_seeds) -> tuple:
        seeds = seeds_for(n_seeds)
        start = time.perf_counter()
        out = fit_fn(points, weights, cents, seeds)
        int(out[1])                                  # n_iters -> sync barrier
        return time.perf_counter() - start, out

    fit_small = build(2)
    t0 = time.perf_counter()
    timed(fit_small, 2)                              # compile
    t_small, _ = timed(fit_small, 2)                 # warm dispatch floor
    _log(f"[{name}] compile+warmup(2-iter) {time.perf_counter() - t0:.1f}s"
         f" (dispatch floor {t_small * 1e3:.0f} ms)")

    # Adaptive gap: the marginal must rise far enough above the per-pair
    # dispatch noise (~±25 ms on tunneled platforms) that the PUBLISHED
    # spread is <= ~5% — i.e. a BIG-run wall time of ~1.5 s, not merely
    # a margin above the 50 ms noise floor (r3 published 44-47% spreads
    # for the sub-5 ms-marginal glove/small rows; r4 fix per the repo's
    # own methodology bar).  Growth is steered by the big run's DIRECT
    # wall time with the measured dispatch floor subtracted — a marginal
    # at the noise floor is garbage and once projected a 2M-iteration
    # (~18 min) dispatch that CRASHED the TPU worker (r4, observed) —
    # and clamped to 25x per step, so dispatches stay at seconds.  Stop
    # decisions use the MEDIAN of 5 interleaved pairs (r1 VERDICT #8).
    # A spread failure at a SUFFICIENT gap (projection says the current
    # T already suffices — i.e. a tunnel-drift burst) re-measures
    # without growing; if the spread still exceeds 5% after the retry
    # budget, the row is published flagged ``indicative_only``.
    TARGET_BIG, ITER_CAP = 1.5, 2_000_000
    RAMP_BUDGET, SPREAD_BUDGET = 8, 2

    out_big = None
    ramp = spread_tries = 0
    built_iters = None
    margin = spread = None
    while True:
        if built_iters != iters:
            fit_big = build(2 + iters)
            t_big, out_big = timed(fit_big, 2 + iters)   # compile/load
            built_iters = iters
            if t_big >= TARGET_BIG / 2:
                # Near/over target: confirm with a warm run (the first
                # call's trace/cache-load overhead could fake a pass).
                t_big, _ = timed(fit_big, 2 + iters)
            if t_big < TARGET_BIG and iters < ITER_CAP \
                    and ramp < RAMP_BUDGET:
                ramp += 1
                # Dispatch-floor-corrected projection: t_big/(2+iters)
                # alone is pure dispatch latency for tiny configs and
                # would burn the whole budget in underestimates.
                per_iter = max((t_big - t_small) / (2 + iters), 1e-9)
                iters = int(min(ITER_CAP,
                                min(iters * 25,
                                    max(TARGET_BIG / per_iter,
                                        iters * 5))))
                _log(f"[{name}] big run {t_big * 1e3:.0f} ms below the "
                     f"{TARGET_BIG:.1f} s target; retrying with "
                     f"iters={iters}")
                continue
        margin, spread, _ = measure_marginal(
            lambda: timed(fit_small, 2)[0],
            lambda: timed(fit_big, 2 + iters)[0], reps=5)
        if spread <= 0.05 or iters >= ITER_CAP \
                or spread_tries >= SPREAD_BUDGET:
            break
        spread_tries += 1
        est = max(margin, 1e-9) / iters
        proj = 1.4 * TARGET_BIG / est
        if proj > iters * 1.2:
            iters = int(min(ITER_CAP, min(iters * 25, proj)))
            _log(f"[{name}] spread {spread * 100:.0f}% above the 5% bar "
                 f"with an undersized gap; retrying with iters={iters}")
        else:
            _log(f"[{name}] spread {spread * 100:.0f}% from tunnel drift "
                 f"at a sufficient gap; re-measuring")
    noise_limited = margin <= 0.05
    indicative = (not noise_limited) and spread > 0.05
    if noise_limited:
        _log(f"[{name}] WARNING: marginal time ({margin:.3f}s over "
             f"{iters} iters) is within dispatch-latency noise — "
             f"per-iteration numbers are unmeasurable at this size and are "
             f"reported as null")
    elif indicative:
        _log(f"[{name}] WARNING: spread {spread * 100:.0f}% exceeds the "
             f"5% publication bar after {spread_tries} retries "
             f"(tunnel drift) — row flagged indicative_only")
    per_iter = margin / iters
    sse = float(np.asarray(out_big[2])[-1])          # last-iteration SSE
    n_chips = max(1, len(jax.devices()))
    result = {
        "config": name, "n": n, "d": d, "k": k, "mode": mode,
        "iters": iters,
        "ms_per_iter": None if noise_limited else round(per_iter * 1e3, 4),
        "throughput_pd_per_sec_per_chip": None if noise_limited else
        round(n * d / per_iter / n_chips, 1),
        "spread": None if noise_limited else round(spread, 3),
        "sse": sse,
        "noise_limited": noise_limited,
        "indicative_only": indicative,
    }
    # The 'init' column (ISSUE 2): warm one-dispatch k-means|| seeding
    # cost at this shape, device pipeline vs the legacy per-round engine.
    try:
        init_dev_s, init_legacy_s = bench_init(X, k)
        result["init_kmeanspp_s"] = round(init_dev_s, 4)
        result["init_kmeanspp_legacy_s"] = round(init_legacy_s, 4)
    except Exception as e:           # noqa: BLE001 — init column is extra
        _log(f"[{name}] init column skipped: {e}")
    pub = published_row(n, d, k)
    if pub is not None and pub.get("mode") != mode:
        # A matmul run compared against the published pallas row would
        # warn 'regression' for a mode choice, not a regression
        # (review r5): published rows record the auto-resolved mode.
        _log(f"[{name}] published row is mode={pub.get('mode')!r}; this "
             f"run is {mode!r} — vs_published comparison skipped")
        pub = None
    if pub is not None and not noise_limited:
        # Same-shape check against the published table (per-row n may
        # differ, so compare per-point-dim throughput, not ms).  Guarded
        # like the lookup: a malformed row must never crash a bench that
        # just spent minutes measuring (review r5).
        try:
            tput_pub = float(pub["pts_dims_per_s_chip"])
            ratio = result["throughput_pd_per_sec_per_chip"] / tput_pub \
                if tput_pub > 0 else None
        except (KeyError, TypeError, ValueError):
            ratio = None
        if ratio is not None:
            result["published_pts_dims_per_s_chip"] = tput_pub
            result["vs_published"] = round(ratio, 3)
            if abs(ratio - 1.0) > 0.2:
                _log(f"[{name}] WARNING: {ratio:.2f}x the published "
                     f"BASELINE.json row (r{pub.get('round')}, "
                     f"{pub.get('measured')}) — regression, improvement, "
                     f"or tunnel-drift window; re-run before publishing")
    print(json.dumps(result), flush=True)
    return result


def bench_gmm_pipeline(n: int, d: int, k: int, iters: int = 20,
                       reps: int = 5, cov_type: str = "diag") -> Dict:
    """Pipelined-vs-serial GMM E-step benchmark (the ISSUE 3 tentpole's
    before/after): the one-dispatch diag EM loop with ``pipeline=1``
    (software-pipelined chunk schedule) vs ``pipeline=0`` (the serial
    four-phase oracle), measured the only way cross-variant numbers are
    trusted here — per-rep INTERLEAVED marginal pairs with the
    published speedup the median of per-rep ratios (the r6
    stream-overlap rule: a sequential series-vs-series design measured
    1.8x and 0.7x for the same binary across two drift windows).

    Publishes ms/iter for both schedules, the overlap speedup, and the
    ``step_mfu`` column (None off-TPU; ``flops_per_iter`` always
    recorded) — the >40%-MFU tentpole target at 2M x 128 k=256 diag as
    one JSON line.  ``BENCH_GMM=1 python bench.py`` drives it with
    those hardware defaults (CPU proxy scales down)."""
    import jax

    from kmeans_tpu.models import GaussianMixture

    rng = np.random.default_rng(42)
    X = (rng.standard_normal((n, d))
         + 4.0 * rng.integers(0, 4, size=(n, 1))).astype(np.float32)

    def make(mi: int, pipeline: int) -> "GaussianMixture":
        return GaussianMixture(
            n_components=k, covariance_type=cov_type, max_iter=mi,
            tol=0.0, seed=0, init_params="random", host_loop=False,
            pipeline=pipeline, verbose=False)

    def timed(mi: int, pipeline: int) -> float:
        t0 = time.perf_counter()
        make(mi, pipeline).fit(X)
        return time.perf_counter() - t0

    for p in (0, 1):                         # compile + warm all 4 programs
        timed(2, p), timed(2 + iters, p)
    # Ramp the gap on the measured pipelined margin until it clears the
    # estimator-level constant's noise (the bench_model discipline).
    TARGET, CAP = 1.5, 20_000
    for attempt in range(4):
        margin, spread, _ = measure_marginal(
            lambda: timed(2, 1), lambda: timed(2 + iters, 1), reps=3)
        if margin >= TARGET or iters >= CAP or attempt == 3:
            break
        per_iter0 = max(margin / iters, 1e-9)
        iters = int(min(CAP, min(iters * 25,
                                 max(TARGET / per_iter0, iters * 4))))
        _log(f"[gmm-pipeline] margin {margin * 1e3:.0f} ms below "
             f"{TARGET:.1f} s; retrying with iters={iters}")
        timed(2 + iters, 0), timed(2 + iters, 1)        # compile big side

    m0s, m1s = [], []
    for rep in range(reps + 1):
        m0 = max(timed(2 + iters, 0) - timed(2, 0), 1e-9)
        m1 = max(timed(2 + iters, 1) - timed(2, 1), 1e-9)
        if rep == 0:
            continue                          # burn-in pair
        m0s.append(m0)
        m1s.append(m1)
        _log(f"[gmm-pipeline] rep {rep}/{reps}: serial "
             f"{m0 / iters * 1e3:.2f} ms/iter, pipelined "
             f"{m1 / iters * 1e3:.2f} ms/iter, speedup {m0 / m1:.3f}x")
    ratios = sorted(a / b for a, b in zip(m0s, m1s))
    speedup = float(np.median(ratios))
    ratio_spread = (max(ratios) - min(ratios)) / speedup
    p0 = float(np.median(m0s)) / iters
    p1 = float(np.median(m1s)) / iters
    flops = gmm_flops_per_iter(n, d, k, cov_type)
    mfu0, mfu1 = step_mfu(flops, p0), step_mfu(flops, p1)
    _log(f"[gmm-pipeline] serial {p0 * 1e3:.2f} ms/iter"
         + (f" ({mfu0:.1%} MFU)" if mfu0 else "")
         + f"; pipelined {p1 * 1e3:.2f} ms/iter"
         + (f" ({mfu1:.1%} MFU)" if mfu1 else "")
         + f"; speedup {speedup:.3f}x (ratio spread "
         f"{ratio_spread * 100:.0f}%)")
    result = {
        "metric": f"gmm_estep_pipeline_N{n}_D{d}_k{k}_{cov_type}",
        "value": round(p1 * 1e3, 4),
        "unit": "ms/iter (one-dispatch EM, pipelined schedule)",
        "serial_ms_per_iter": round(p0 * 1e3, 4),
        "pipelined_ms_per_iter": round(p1 * 1e3, 4),
        "overlap_speedup": round(speedup, 4),
        "overlap_speedup_spread": round(ratio_spread, 3),
        "indicative_only": bool(ratio_spread > 0.05),
        "iters_gap": iters,
        "flops_per_iter": flops,
        "step_mfu_serial": None if mfu0 is None else round(mfu0, 4),
        "step_mfu": None if mfu1 is None else round(mfu1, 4),
        "target_mfu_at_2Mx128_k256": 0.40,
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
    }
    print(json.dumps(result), flush=True)
    return result


def _lloyd_bench_setup(n: int, d: int, k: int, seed: int = 42,
                       mesh=None):
    """Shared staging of the Lloyd schedule/rung benches: a sharded
    uniform dataset + a fixed explicit init (identical across variants,
    so the marginal compares SCHEDULES, never init luck)."""
    from kmeans_tpu.models.kmeans import KMeans

    rng = np.random.default_rng(seed)
    X = rng.uniform(-1, 1, size=(n, d)).astype(np.float32)
    init = X[np.sort(rng.choice(n, size=k, replace=False))].copy()
    staging = KMeans(k=k, verbose=False, mesh=mesh)
    ds = staging.cache(X)
    return ds, init


def _timed_lloyd_fit(ds, init, k: int, mi: int, *, mode: str,
                     pipeline: int, **extra) -> float:
    """Wall seconds of one whole-fit dispatch (estimator level, so the
    measured program is exactly what `KMeans(distance_mode=, pipeline=)`
    ships; the fixed-iteration tolerance keeps both sides honest).
    ``extra`` overrides estimator knobs — the large-k bench routes
    through here with ``k_shard``/``assign``/``host_loop`` (the routed
    steps are per-iteration host-loop programs, so the comparison pins
    ``host_loop=True`` on BOTH sides)."""
    from kmeans_tpu.models.kmeans import KMeans

    kw = dict(k=k, max_iter=mi, tolerance=1e-30, seed=0, init=init,
              compute_sse=False, compute_labels=False,
              empty_cluster="keep", host_loop=False, verbose=False,
              distance_mode=mode, pipeline=pipeline)
    kw.update(extra)
    m = KMeans(**kw)
    m._eager_labels = False
    t0 = time.perf_counter()
    m.fit(ds)
    return time.perf_counter() - t0


def _interleaved_lloyd_pair(ds, init, k, iters, reps, a_kw, b_kw,
                            label_a: str, label_b: str, tag: str):
    """Per-rep interleaved (2, 2+iters) marginal PAIRS for two Lloyd
    variants -> (per_iter_a, per_iter_b, ratios a/b sorted).  The only
    way cross-variant numbers are trusted here (the r6 drift rule)."""
    for kw in (a_kw, b_kw):                  # compile + warm all 4
        _timed_lloyd_fit(ds, init, k, 2, **kw)
        _timed_lloyd_fit(ds, init, k, 2 + iters, **kw)
    mas, mbs = [], []
    for rep in range(reps + 1):
        ma = max(_timed_lloyd_fit(ds, init, k, 2 + iters, **a_kw)
                 - _timed_lloyd_fit(ds, init, k, 2, **a_kw), 1e-9)
        mb = max(_timed_lloyd_fit(ds, init, k, 2 + iters, **b_kw)
                 - _timed_lloyd_fit(ds, init, k, 2, **b_kw), 1e-9)
        if rep == 0:
            continue                          # burn-in pair
        mas.append(ma)
        mbs.append(mb)
        _log(f"[{tag}] rep {rep}/{reps}: {label_a} "
             f"{ma / iters * 1e3:.2f} ms/iter, {label_b} "
             f"{mb / iters * 1e3:.2f} ms/iter, ratio {ma / mb:.3f}x")
    ratios = sorted(a / b for a, b in zip(mas, mbs))
    return (float(np.median(mas)) / iters, float(np.median(mbs)) / iters,
            ratios)


def bench_lloyd_pipeline(n: int, d: int, k: int, iters: int = 20,
                         reps: int = 5) -> Dict:
    """Pipelined-vs-serial Lloyd E-step benchmark (the ISSUE 8 tentpole's
    before/after, the bench_gmm_pipeline twin on the flagship path): the
    one-dispatch K-Means loop with ``pipeline=1`` (two-stage chunk
    schedule, distance matmul of chunk i overlapping the argmin +
    scatter epilogue of chunk i-1) vs ``pipeline=0`` (the serial
    bit-exact oracle), per-rep INTERLEAVED marginal pairs, speedup = the
    median of per-rep ratios.  Publishes ms/iter for both schedules and
    the ``step_mfu`` column (None off-TPU; ``flops_per_iter`` always
    recorded).  Committed decision rule: the pipelined schedule is
    adopted into accelerator-'auto' only at >= 5% measured speedup on
    the headline shape; a CPU regression is a publishable measured
    rejection (the r8 precedent — 'auto' already resolves serial
    there)."""
    import jax

    ds, init = _lloyd_bench_setup(n, d, k)
    p0, p1, ratios = _interleaved_lloyd_pair(
        ds, init, k, iters, reps,
        dict(mode="matmul", pipeline=0), dict(mode="matmul", pipeline=1),
        "serial", "pipelined", "lloyd-pipeline")
    speedup = float(np.median(ratios))
    ratio_spread = (max(ratios) - min(ratios)) / speedup
    flops = kmeans_flops_per_iter(n, d, k)
    mfu0, mfu1 = step_mfu(flops, p0), step_mfu(flops, p1)
    _log(f"[lloyd-pipeline] serial {p0 * 1e3:.2f} ms/iter"
         + (f" ({mfu0:.1%} MFU)" if mfu0 else "")
         + f"; pipelined {p1 * 1e3:.2f} ms/iter"
         + (f" ({mfu1:.1%} MFU)" if mfu1 else "")
         + f"; speedup {speedup:.3f}x (ratio spread "
         f"{ratio_spread * 100:.0f}%)")
    result = {
        "metric": f"lloyd_pipeline_N{n}_D{d}_k{k}",
        "value": round(p1 * 1e3, 4),
        "unit": "ms/iter (one-dispatch Lloyd, pipelined schedule)",
        "serial_ms_per_iter": round(p0 * 1e3, 4),
        "pipelined_ms_per_iter": round(p1 * 1e3, 4),
        "overlap_speedup": round(speedup, 4),
        "overlap_speedup_spread": round(ratio_spread, 3),
        "indicative_only": bool(ratio_spread > 0.05),
        "iters_gap": iters,
        "flops_per_iter": flops,
        "step_mfu_serial": None if mfu0 is None else round(mfu0, 4),
        "step_mfu": None if mfu1 is None else round(mfu1, 4),
        "adopt_rule": ">=1.05x at the headline shape flips "
                      "accelerator-'auto' to pipelined; CPU 'auto' "
                      "stays serial either way",
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
    }
    print(json.dumps(result), flush=True)
    return result


def bench_bf16_guard(n: int, d: int, k: int, iters: int = 20,
                     reps: int = 5) -> Dict:
    """Guarded-bf16 training rung benchmark (ISSUE 8): the one-dispatch
    Lloyd loop under ``distance_mode='matmul_bf16_guarded'`` vs the f32
    'matmul' class, per-rep interleaved marginal pairs — PLUS the two
    acceptance properties published alongside the time: (1) the guarded
    fit's centroids are BIT-equal to the f32 fit's (the by-construction
    contract, asserted every run, never sampled), and (2) the
    corrected-rows audit (``bf16_guard_corrected_rows_``) is recorded —
    a bf16-rate number without its audit row is not a publishable
    result here.  Committed decision rule: >= 5% measured speedup at
    the headline shape to recommend the rung (hardware row; on CPU the
    'f32' matmul already runs the same scalar units, so a ~1.0x or
    regression is the expected measured outcome — published either
    way)."""
    import jax

    from kmeans_tpu.models.kmeans import KMeans

    ds, init = _lloyd_bench_setup(n, d, k)
    # Acceptance property first (cheap, and a failed property makes the
    # timing meaningless): bit parity + audit at a real iteration count.
    pin_kw = dict(k=k, max_iter=8, tolerance=1e-30, seed=0, init=init,
                  compute_sse=False, compute_labels=False,
                  empty_cluster="keep", host_loop=False, verbose=False)
    m_f32 = KMeans(distance_mode="matmul", **pin_kw)
    m_f32._eager_labels = False
    m_f32.fit(ds)
    m_g = KMeans(distance_mode="matmul_bf16_guarded", **pin_kw)
    m_g._eager_labels = False
    m_g.fit(ds)
    parity = bool(np.array_equal(m_f32.centroids, m_g.centroids)
                  and m_f32.iterations_run == m_g.iterations_run)
    corrected = m_g.bf16_guard_corrected_rows_
    # The pin fit may converge before max_iter (a zero-shift fixed point
    # beats even tolerance=1e-30) — the per-iteration rate divides by
    # the iterations that actually ran, never the cap.
    pin_iters = max(m_g.iterations_run, 1)
    if not parity:
        raise AssertionError(
            "guarded bf16 rung broke bit parity with the f32 class — "
            "do not publish a rate for a wrong answer")
    p0, p1, ratios = _interleaved_lloyd_pair(
        ds, init, k, iters, reps,
        dict(mode="matmul", pipeline=0),
        dict(mode="matmul_bf16_guarded", pipeline=0),
        "f32", "bf16-guarded", "bf16-guard")
    speedup = float(np.median(ratios))
    ratio_spread = (max(ratios) - min(ratios)) / speedup
    flops = kmeans_flops_per_iter(n, d, k)
    mfu1 = step_mfu(flops, p1)
    _log(f"[bf16-guard] f32 {p0 * 1e3:.2f} ms/iter; guarded "
         f"{p1 * 1e3:.2f} ms/iter; speedup {speedup:.3f}x (spread "
         f"{ratio_spread * 100:.0f}%); corrected_rows {corrected} over "
         f"{pin_iters} iters of {n} rows; parity {parity}")
    result = {
        "metric": f"bf16_guard_N{n}_D{d}_k{k}",
        "value": round(p1 * 1e3, 4),
        "unit": "ms/iter (one-dispatch Lloyd, guarded bf16 distances)",
        "f32_ms_per_iter": round(p0 * 1e3, 4),
        "guarded_ms_per_iter": round(p1 * 1e3, 4),
        "guard_speedup": round(speedup, 4),
        "guard_speedup_spread": round(ratio_spread, 3),
        "indicative_only": bool(ratio_spread > 0.05),
        "iters_gap": iters,
        "centroid_bit_parity": parity,
        "corrected_rows": corrected,
        "corrected_rows_pin_iters": pin_iters,
        "corrected_rows_frac": round(corrected / (pin_iters * n), 6),
        "flops_per_iter": flops,
        "step_mfu": None if mfu1 is None else round(mfu1, 4),
        "adopt_rule": ">=1.05x at the headline shape with the "
                      "corrected-rows audit published",
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
    }
    print(json.dumps(result), flush=True)
    return result


def _large_k_capture_fit(ds, init, k: int, extra: dict, mesh=None):
    """One short (3-iteration) fit under the cost collector: returns
    ``(model, records)`` — the records join ``plan_fit`` for the
    predicted-vs-observed HBM row, the model carries the parity
    inputs (``inertia_``, ``centroids``) and the resolved route."""
    from kmeans_tpu.models.kmeans import KMeans
    from kmeans_tpu.obs import cost as cost_mod

    m = KMeans(k=k, max_iter=3, tolerance=1e-30, seed=0, init=init,
               compute_sse=True, compute_labels=False,
               empty_cluster="keep", host_loop=True, verbose=False,
               distance_mode="matmul", pipeline=0, mesh=mesh, **extra)
    m._eager_labels = False
    with cost_mod.collecting() as col:
        m.fit(ds)
    return m, col.records()


def bench_large_k(n: int, d: int, ks, iters: int = 8,
                  reps: int = 3, model_shards: int = 0) -> Dict:
    """Massive-k scaling curve (ISSUE 16 tentpole artifact:
    ``BENCH_LARGEK=1 python bench.py``): ms/iter vs k at FIXED N x D
    for the dense Lloyd oracle vs the routed large-k tier, one row per
    k.  The route is what the mesh affords — ``k_shard=model_shards``
    (TP-sharded centroid table, pair all-reduce assignment) on a
    model-sharded mesh, ``assign='two_level'`` (coarse-cell candidate
    routing) on a data-parallel one — and each row records what the
    planner's 'auto' rule would have resolved at that shape, so the
    published curve and the shipping default are comparable.

    Method: per-rep INTERLEAVED (2, 2+iters) marginal pairs (the r6
    drift rule, median-of-ratios, <= 5% spread bar published per row).
    Both sides run the per-iteration host loop — the routed steps are
    host-loop programs by construction (member tables / stats gathers
    rebuild between iterations), so a device-loop dense side would
    conflate dispatch amortization with the tier's actual per-iteration
    cost.  Each row also carries the parity oracle from a short
    same-init fit pair (k-shard: centroid maxdiff, bit-exact expected;
    two-level: SSE relative gap — labels may differ inside the
    candidate-set contract, docs/ANALYSIS.md) and the planner's
    predicted table/peak bytes with XLA-observed peak joined when the
    backend reports it."""
    import jax

    from kmeans_tpu.obs import memory as memory_mod
    from kmeans_tpu.parallel.mesh import make_mesh, mesh_shape

    # model_shards > 0 builds a TP mesh explicitly (BENCH_MODEL_SHARDS)
    # — that is what flips the route to the k-sharded table on hosts
    # whose default mesh is data-only.
    mesh = make_mesh(model=model_shards) if model_shards else make_mesh()
    data_shards, model_shards = mesh_shape(mesh)
    if model_shards > 1:
        route = "k_shard"
        routed_kw = dict(k_shard=model_shards, assign="dense")
    else:
        route = "two_level"
        routed_kw = dict(k_shard=0, assign="two_level")
    dense_kw = dict(k_shard=0, assign="dense")
    rows = []
    for k in ks:
        ds, init = _lloyd_bench_setup(n, d, k, mesh=mesh)
        # Parity + plan capture first (cheap; a broken route makes the
        # timing meaningless).  Same init on both sides.
        m_dense, recs_dense = _large_k_capture_fit(ds, init, k,
                                                   dense_kw, mesh=mesh)
        m_routed, recs_routed = _large_k_capture_fit(ds, init, k,
                                                     routed_kw, mesh=mesh)
        maxdiff = float(np.max(np.abs(
            np.asarray(m_dense.centroids, np.float64)
            - np.asarray(m_routed.centroids, np.float64))))
        sse_gap = float(m_routed.inertia_ / m_dense.inertia_ - 1.0)
        if route == "k_shard" and maxdiff != 0.0:
            raise AssertionError(
                f"k-sharded step broke bit parity with the dense TP "
                f"oracle at k={k} (centroid maxdiff {maxdiff:.3e}) — "
                f"do not publish a rate for a wrong answer")
        plan_dense = memory_mod.plan_fit(
            "kmeans", n, d, k, data_shards=data_shards,
            model_shards=model_shards, chunk=ds.chunk, k_shard=0,
            records=recs_dense)
        plan_routed = memory_mod.plan_fit(
            "kmeans", n, d, k, data_shards=data_shards,
            model_shards=model_shards, chunk=ds.chunk,
            k_shard=model_shards if route == "k_shard" else 0,
            records=recs_routed)
        # What the shipping 'auto' rule resolves to at this shape (the
        # planner consults live allocator stats; unreported backends
        # resolve dense — recorded so the curve says which rows the
        # default would actually route).
        from kmeans_tpu.models.kmeans import KMeans
        probe = KMeans(k=k, seed=0, verbose=False, mesh=mesh)
        auto_ks, auto_asg = probe._resolve_large_k(
            ds, data_shards, model_shards, ds.chunk)
        p0, p1, ratios = _interleaved_lloyd_pair(
            ds, init, k, iters, reps,
            dict(mode="matmul", pipeline=0, host_loop=True, mesh=mesh,
                 **dense_kw),
            dict(mode="matmul", pipeline=0, host_loop=True, mesh=mesh,
                 **routed_kw),
            "dense", route, f"large-k:{k}")
        speedup = float(np.median(ratios))
        spread = (max(ratios) - min(ratios)) / speedup
        row = {
            "metric": f"large_k_N{n}_D{d}_k{k}",
            "value": round(p1 * 1e3, 4),
            "unit": f"ms/iter (routed large-k tier: {route})",
            "k": k, "n": n, "d": d, "chunk": ds.chunk,
            "route": route,
            "dense_ms_per_iter": round(p0 * 1e3, 4),
            "routed_ms_per_iter": round(p1 * 1e3, 4),
            "dense_over_routed": round(speedup, 4),
            "ratio_spread": round(spread, 3),
            "indicative_only": bool(spread > 0.05),
            "iters_gap": iters,
            "centroid_maxdiff": maxdiff,
            "sse_rel_gap": round(sse_gap, 8),
            "auto_resolution": {"k_shard": auto_ks, "assign": auto_asg},
            "predicted_table_bytes_dense":
                plan_dense["components"]["table_bytes"],
            "predicted_table_bytes_routed":
                plan_routed["components"]["table_bytes"],
            "predicted_peak_bytes_dense":
                plan_dense["predicted_peak_bytes"],
            "predicted_peak_bytes_routed":
                plan_routed["predicted_peak_bytes"],
            "observed_peak_bytes_dense":
                plan_dense["observed_peak_bytes"],
            "observed_peak_bytes_routed":
                plan_routed["observed_peak_bytes"],
            "platform": jax.default_backend(),
            "n_devices": len(jax.devices()),
        }
        if route == "two_level":
            row["coarse_cells"], row["nprobe"] = \
                m_routed._two_level_params()
            tl = m_routed._two_level_route_
            row["candidate_width"] = int(tl[1].shape[1]) if tl else None
        _log(f"[large-k] k={k}: dense {p0 * 1e3:.2f} ms/iter, {route} "
             f"{p1 * 1e3:.2f} ms/iter, dense/routed {speedup:.3f}x "
             f"(spread {spread * 100:.0f}%), sse_gap {sse_gap:+.2e}, "
             f"auto -> k_shard={auto_ks} assign={auto_asg!r}")
        print(json.dumps(row), flush=True)
        rows.append(row)
    _log("\n| k | dense ms/iter | routed ms/iter | dense/routed | "
         "spread | predicted peak B/dev (dense -> routed) |")
    _log("|---|---|---|---|---|---|")
    for r in rows:
        _log(f"| {r['k']:,} | {r['dense_ms_per_iter']} | "
             f"{r['routed_ms_per_iter']} | {r['dense_over_routed']}x | "
             f"{r['ratio_spread'] * 100:.0f}% | "
             f"{r['predicted_peak_bytes_dense']:,} -> "
             f"{r['predicted_peak_bytes_routed']:,} |")
    summary = {
        "metric": f"large_k_curve_N{n}_D{d}",
        "value": rows[-1]["routed_ms_per_iter"] if rows else None,
        "unit": "ms/iter (routed large-k tier at the largest k)",
        "route": route,
        "ks": list(ks),
        "rows": rows,
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
    }
    print(json.dumps(summary), flush=True)
    return summary


#: Chunk-geometry re-sweep candidates of the BENCH_PHASES mode: the
#: measured 32768-131072 plateau (swept at 2M, docs/PERFORMANCE.md) plus
#: one rung below and one above, so a plateau SHIFT at the 10M shape is
#: observable in either direction.
PHASE_SWEEP_CHUNKS = (16384, 32768, 65536, 131072, 262144)


def bench_phases(n: int, d: int, k: int, *, gap: int = 20, reps: int = 5,
                 chunks=None, skip_sweep: bool = False) -> Dict:
    """The measured per-phase ceiling table + chunk-geometry re-sweep
    (ISSUE 8c — `BENCH_PHASES=1 python bench.py`): runs the r8
    cumulative-prefix phase ladder (distance -> +argmin -> +scatter/
    psum; ``make_estep_phase_fn`` + ``measure_phase_ladder``) at the
    given shape and emits ``phase_ceiling_table``'s publishable rows
    (phase ms, share, implied ceiling if that phase were free, the
    committed >= 15% decision rule), then re-derives the scan-chunk
    plateau AT THIS SHAPE via full-step marginals per candidate chunk
    (the 32768-131072 plateau was swept at 2M; the 10M committed chunk
    had never been re-derived — committed rule: adopt any >= 3% plateau
    shift).  One JSON line carries both tables."""
    import jax

    from kmeans_tpu.parallel import distributed as dist
    from kmeans_tpu.parallel.mesh import make_mesh, mesh_shape
    from kmeans_tpu.parallel.sharding import (choose_chunk_size,
                                              shard_points)
    from kmeans_tpu.utils.profiling import (measure_phase_ladder,
                                            phase_ceiling_table)

    backend = jax.default_backend()
    mesh = make_mesh()
    data_shards, model_shards = mesh_shape(mesh)
    committed = choose_chunk_size(-(-n // data_shards), k, d)
    rng = np.random.default_rng(42)
    X = rng.uniform(-1, 1, size=(n, d)).astype(np.float32)
    pts, w = shard_points(X, mesh, committed)
    cents = jax.device_put(
        dist.pad_centroids(X[:k].copy(), model_shards),
        dist.centroid_sharding(mesh))

    # --- phase ladder (marginal between 2- and (2+gap)-iteration chains)
    fns = {}
    for ph in dist.ESTEP_PHASES:
        fns[ph] = {m: dist.make_estep_phase_fn(
            mesh, chunk_size=committed, n_iters=m, phase=ph)
            for m in (2, 2 + gap)}
        for m in (2, 2 + gap):
            float(fns[ph][m](pts, w, cents))          # compile + warm

    def marginal(ph):
        def measure():
            t0 = time.perf_counter()
            float(fns[ph][2](pts, w, cents))
            t_small = time.perf_counter() - t0
            t0 = time.perf_counter()
            float(fns[ph][2 + gap](pts, w, cents))
            return max(time.perf_counter() - t0 - t_small, 1e-9) / gap
        return measure

    ladder = measure_phase_ladder(
        [(ph, marginal(ph)) for ph in dist.ESTEP_PHASES], reps=reps)
    flops = kmeans_flops_per_iter(n, d, k)
    peak = PEAK_TFLOPS.get(backend)
    # Device-cost join (ISSUE 12): AOT-analyze the measured full-stats
    # program so every ceiling row carries analytic_flops/ai/
    # mfu_analytic, and the XLA-vs-analytic agreement publishes next to
    # the measured table (per-chunk on both sides — XLA counts loop
    # bodies once).
    from kmeans_tpu.obs import cost as obs_cost
    cost_rec = obs_cost.analyze_jitted(
        fns[dist.ESTEP_PHASES[-1]][2 + gap], pts, w, cents,
        cache="bench.phases", key=f"N{n}_D{d}_k{k}_chunk{committed}")
    agreement = obs_cost.crosscheck(
        obs_cost.analytic_step_flops("kmeans", n=n, d=d, k=k,
                                     chunk=committed,
                                     n_devices=data_shards),
        cost_rec)
    table = phase_ceiling_table(ladder, flops_per_iter=flops,
                                peak_tflops=peak, cost_record=cost_rec)
    full = ladder[-1]["cumulative"]
    for row in table:
        _log(f"[phases] {row['phase']:9s} {row['ms']:8.3f} ms "
             f"({row['share']:5.1%}; ceiling if free "
             f"{row['implied_ceiling_speedup']:.3f}x; "
             f"{'ACTIONABLE' if row['actionable'] else 'pinned'}; "
             f"spread {row['spread']:.0%})")
    mfu = step_mfu(flops, full)
    _log(f"[phases] full stats pass {full * 1e3:.3f} ms/iter"
         + (f" = {mfu:.1%} MFU" if mfu else ""))

    # --- chunk-geometry re-sweep at THIS shape (full-step marginals)
    sweep_rows = []
    if not skip_sweep:
        cands = [c for c in (chunks or PHASE_SWEEP_CHUNKS)
                 if c <= -(-n // data_shards)]
        if committed not in cands:
            cands.append(committed)
        seeds_s = np.zeros((2,), np.uint32)
        seeds_b = np.zeros((2 + gap,), np.uint32)
        fits = {}
        for c in sorted(cands):
            pts_c, w_c = shard_points(X, mesh, c)
            pair = {}
            for mi, seeds in ((2, seeds_s), (2 + gap, seeds_b)):
                fn = dist.make_fit_fn(
                    mesh, chunk_size=c, mode="matmul", k_real=k,
                    max_iter=mi, tolerance=1e-30, empty_policy="keep",
                    history_sse=False)
                out = fn(pts_c, w_c, cents, seeds)
                int(out[1])                            # compile + warm
                pair[mi] = fn
            fits[c] = (pts_c, w_c, pair)

        def timed_chunk(c, mi):
            pts_c, w_c, pair = fits[c]
            seeds = seeds_s if mi == 2 else seeds_b
            t0 = time.perf_counter()
            out = pair[mi](pts_c, w_c, cents, seeds)
            int(out[1])
            return time.perf_counter() - t0

        samples = {c: [] for c in fits}
        for _ in range(reps):                         # interleaved
            for c in sorted(fits):
                samples[c].append(
                    max(timed_chunk(c, 2 + gap) - timed_chunk(c, 2),
                        1e-9) / gap)
        for c in sorted(fits):
            med = float(np.median(samples[c]))
            span = max(samples[c]) - min(samples[c])
            sweep_rows.append({"chunk": c, "ms_per_iter": med * 1e3,
                               "spread": span / med if med > 0 else 0.0,
                               "committed": c == committed})
            _log(f"[phases] chunk {c:7d}: {med * 1e3:.3f} ms/iter "
                 f"(spread {span / med:.0%})"
                 + ("  <- committed" if c == committed else ""))
        best = min(sweep_rows, key=lambda r: r["ms_per_iter"])
        base = next(r for r in sweep_rows if r["committed"])
        shift = base["ms_per_iter"] / best["ms_per_iter"] - 1.0
        _log(f"[phases] chunk re-sweep: best {best['chunk']} vs "
             f"committed {committed} ({shift:+.1%}; adopt rule >= 3%)")

    result = {
        "metric": f"lloyd_phase_ceiling_N{n}_D{d}_k{k}",
        "value": round(full * 1e3, 4),
        "unit": "ms/iter (XLA stats pass; ladder shares in table)",
        "chunk": committed,
        "ladder": ladder,
        "ceiling_table": table,
        "cost": cost_rec.to_dict(),
        "flops_agreement": agreement,
        "chunk_sweep": sweep_rows,
        "decision_rules": {
            "phase_actionable_share": 0.15,
            "pipelined_vs_serial_adopt": 1.05,
            "bf16_guard_adopt": 1.05,
            "chunk_resweep_adopt_shift": 0.03,
        },
        "flops_per_iter": flops,
        "step_mfu": None if mfu is None else round(mfu, 4),
        "platform": backend,
        "n_devices": len(jax.devices()),
    }

    from kmeans_tpu.utils.profiling import sanitize_json
    print(json.dumps(sanitize_json(result), default=float), flush=True)
    return result


def bench_obs(n: int, d: int, k: int, iters: int = 20,
              reps: int = 5, artifact_path=None) -> Dict:
    """Telemetry-overhead benchmark (ISSUE 11: ``BENCH_OBS=1 python
    bench.py``): the same fit measured obs-OFF vs obs-ON (tracing +
    heartbeat active), per-rep INTERLEAVED marginal pairs, overhead =
    the median of per-rep on/off ratios.  Two rows because the cost
    model differs:

    * ``device`` — the one-dispatch loop: a handful of spans per fit
      (segment/dispatch/compile) regardless of iteration count — the
      headline path's cost.
    * ``host`` — the per-iteration host loop: one dispatch span + one
      heartbeat record PER ITERATION — the telemetry-dense worst case
      the committed rule is judged on.

    Committed decision rule (pre-registered, the repo discipline):
    median obs-on overhead <= 1% (ratio <= 1.01) on the 200k x 32 k=64
    CPU proxy (or the headline shape on hardware) keeps the default
    span set; a measured breach demotes the per-iteration host-loop
    span to coarse-grained (segment-level only) — published either way.

    Also produces the TTFI ARTIFACT: one cold-cache traced fit whose
    span-derived time-to-first-iteration table (the
    ``phase_ceiling_table`` schema) is printed and, with
    ``artifact_path``, written as the trace JSONL the ``trace
    summarize`` CLI re-derives it from."""
    import jax

    from kmeans_tpu.models.kmeans import KMeans
    from kmeans_tpu.obs import heartbeat as heartbeat_scope
    from kmeans_tpu.obs import trace as trace_mod
    from kmeans_tpu.obs.report import (format_phase_table,
                                       time_to_first_iteration)

    ds, init = _lloyd_bench_setup(n, d, k)

    def timed_fit(mi: int, host_loop: bool) -> float:
        m = KMeans(k=k, max_iter=mi, tolerance=1e-30, seed=0, init=init,
                   compute_sse=False, compute_labels=False,
                   empty_cluster="keep", host_loop=host_loop,
                   verbose=False)
        m._eager_labels = False
        t0 = time.perf_counter()
        m.fit(ds)
        return time.perf_counter() - t0

    def timed_obs(mi: int, host_loop: bool) -> float:
        with trace_mod.tracing(), \
                heartbeat_scope(callback=lambda rec: None):
            return timed_fit(mi, host_loop)

    rows = {}
    for path_name, host_loop in (("device", False), ("host", True)):
        offs, ons = [], []
        for rep in range(reps + 1):
            off = max(timed_fit(2 + iters, host_loop)
                      - timed_fit(2, host_loop), 1e-9)
            on = max(timed_obs(2 + iters, host_loop)
                     - timed_obs(2, host_loop), 1e-9)
            if rep == 0:
                continue                       # burn-in pair
            offs.append(off)
            ons.append(on)
            _log(f"[obs:{path_name}] rep {rep}/{reps}: off "
                 f"{off / iters * 1e3:.3f} ms/iter, on "
                 f"{on / iters * 1e3:.3f} ms/iter, ratio "
                 f"{on / off:.4f}x")
        ratios = sorted(o / f for o, f in zip(ons, offs))
        overhead = float(np.median(ratios))
        spread = (max(ratios) - min(ratios)) / overhead
        rows[path_name] = {
            "off_ms_per_iter": round(float(np.median(offs))
                                     / iters * 1e3, 4),
            "on_ms_per_iter": round(float(np.median(ons))
                                    / iters * 1e3, 4),
            "overhead_ratio": round(overhead, 4),
            "overhead_spread": round(spread, 3),
            "indicative_only": bool(spread > 0.05),
            "within_1pct_rule": bool(overhead <= 1.01),
        }
        _log(f"[obs:{path_name}] median overhead "
             f"{overhead:.4f}x (spread {spread * 100:.0f}%)")

    # TTFI artifact: a cold-cache traced fit (odd chunk -> fresh step-
    # cache keys, forgy -> a real seed span) at the SAME shape.  Fit
    # from the dataset's retained HOST copy, so the table's place/stage
    # rows measure a real upload — np.asarray(ds.points) would instead
    # pull the padded device buffer back over the link first (review
    # finding).
    with trace_mod.tracing() as tr:
        m = KMeans(k=k, max_iter=3, tolerance=1e-30, seed=0,
                   init="forgy", compute_sse=False, compute_labels=False,
                   empty_cluster="keep", host_loop=False,
                   chunk_size=max(1009, k), verbose=False)
        m._eager_labels = False
        m.fit(ds.host)
    ttfi = time_to_first_iteration(tr.records())
    _log(format_phase_table(ttfi, title=f"ttfi (cold-cache, {n}x{d} "
                                        f"k={k})"))
    if artifact_path is not None:
        tr.write_jsonl(artifact_path)
        _log(f"[obs] trace artifact written to {artifact_path} "
             f"(re-derive: python -m kmeans_tpu trace summarize "
             f"{artifact_path})")

    from kmeans_tpu.utils.profiling import sanitize_json
    result = {
        "metric": f"obs_overhead_N{n}_D{d}_k{k}",
        "value": rows["host"]["overhead_ratio"],
        "unit": "obs-on/obs-off wall ratio (per-iteration host loop)",
        "paths": rows,
        "iters_gap": iters,
        "decision_rule": "<=1.01 median keeps the default span set; a "
                         "breach demotes per-iteration spans to "
                         "segment-level (coarse) — published either "
                         "way",
        "ttfi": sanitize_json(ttfi),
        "trace_artifact": str(artifact_path) if artifact_path else None,
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
    }
    print(json.dumps(sanitize_json(result)), flush=True)
    return result


def bench_cost(n: int, d: int, k: int, *, gmm_n: int = None,
               gmm_d: int = None, gmm_k: int = None) -> List[Dict]:
    """Device-cost observability benchmark (ISSUE 12: ``BENCH_COST=1
    python bench.py``): analytic-vs-XLA FLOPs and predicted-vs-observed
    peak-memory rows for the kmeans and gmm-diag step programs, one
    JSON line each — the BASELINE.md/json artifact rows.

    Each family's fit runs under the real step-cache capture path
    (``obs.report.device_cost_report``), so the analyzed program is
    exactly what ``fit`` dispatches.  COMMITTED DECISION RULE
    (pre-registered): at the hardware headline shape 10M x 128 k=1024
    the analytic and XLA-reported FLOPs must agree within the 10% band
    (``obs.cost.FLOPS_AGREEMENT_RTOL``) for the MFU rows to keep their
    hand-formula numerator; a breach is published as a finding and the
    MFU rows switch to the XLA-reported numerator.  CPU rows publish
    the same comparison now at the scaled proxy shapes.  The
    predicted-vs-observed peak ratio has no pass/fail bar — the planner
    is advisory — but ships on every row so drift is visible."""
    import jax

    from kmeans_tpu.obs.report import device_cost_report

    specs = {"kmeans": dict(n=n, d=d, k=k),
             "gmm": dict(n=gmm_n or n, d=gmm_d or d,
                         k=gmm_k or max(2, k // 2))}
    rep = device_cost_report(("kmeans", "gmm"), specs=specs)
    rows = []
    for row, plan in zip(rep["rows"], rep["plans"]):
        observed = row.get("peak_bytes")
        predicted = plan["predicted_peak_bytes"]
        out = {
            "metric": f"device_cost_{row['family']}_N{row['n']}"
                      f"_D{row['d']}_k{row['k']}",
            "value": row.get("ratio"),
            "unit": "x (XLA-reported flops / analytic flops, one "
                    "chunk of the step program)",
            "family": row["family"],
            "n": row["n"], "d": row["d"], "k": row["k"],
            "chunk": row["chunk"],
            "available": row["available"],
            "reported_flops": row.get("flops"),
            "analytic_flops": row.get("analytic_flops"),
            "flops_agree_10pct": row.get("agree"),
            "ai": row.get("ai"),
            "bytes_accessed": row.get("bytes_accessed"),
            "observed_peak_bytes": observed,
            "predicted_peak_bytes": predicted,
            "predicted_vs_observed": (round(predicted / observed, 3)
                                      if observed else None),
            "decision_rule": "analytic flops within 10% of XLA at "
                             "10M x 128 k=1024 keeps the hand-formula "
                             "MFU numerator; a breach is published and "
                             "MFU switches to the XLA numerator",
            "error": row.get("error"),
            "platform": jax.default_backend(),
            "n_devices": len(jax.devices()),
        }
        print(json.dumps(out), flush=True)
        rows.append(out)
    return rows


def bench_stream(n: int, d: int, k: int, block_rows: int, epochs: int,
                 path=None, prefetch: int = 2) -> Dict:
    """Streamed-epoch benchmark: `fit_stream` epoch cost with the
    double-buffered pipeline ON (``prefetch``) vs OFF (0), plus the
    in-memory device-loop iteration at the same shape for context.

    Method (the repo's marginal protocol): per-epoch cost is the median
    of 5 interleaved marginals between a 1-epoch and a (1+epochs)-epoch
    ``fit_stream`` (fixed explicit init, tolerance~0, 'keep' policy —
    no early convergence), which cancels the init/setup/compile share
    exactly; ``measure_marginal`` reports the (max-min)/median spread
    for the <=5% publication bar.  Blocks come off disk through
    ``iter_npy_blocks`` (mmap), so the measured quantity includes the
    real read + decode + host->device transfer per block — the costs
    the prefetcher exists to overlap.  The dataset .npy is written once
    (seeded) and reused.
    """
    import os
    import tempfile

    import jax
    from kmeans_tpu.data.io import iter_npy_blocks
    from kmeans_tpu.models.kmeans import KMeans

    if path is None:
        path = os.path.join(tempfile.gettempdir(),
                            f"kmeans_tpu_stream_{n}x{d}.npy")
    if os.path.exists(path):
        # A stale explicit BENCH_STREAM_PATH must never silently
        # benchmark a different shape than the published metric name
        # claims (the default path embeds n x d; an override bypasses
        # that guard).
        shape = np.load(path, mmap_mode="r").shape
        if shape != (n, d):
            raise ValueError(
                f"BENCH_STREAM dataset {path} has shape {shape}, not "
                f"({n}, {d}) — delete it or point BENCH_STREAM_PATH at "
                f"a matching file")
    else:
        _log(f"[stream] writing {path} ({n * d * 4 / 1e9:.2f} GB) ...")
        rng = np.random.default_rng(42)
        out = np.lib.format.open_memmap(path, mode="w+",
                                        dtype=np.float32, shape=(n, d))
        step = max(1, min(block_rows, 1 << 22))
        for lo in range(0, n, step):
            hi = min(lo + step, n)
            out[lo:hi] = rng.uniform(-1.0, 1.0,
                                     size=(hi - lo, d)).astype(np.float32)
        out.flush()
        del out

    mm = np.load(path, mmap_mode="r")
    rng = np.random.default_rng(7)
    init = np.asarray(mm[np.sort(rng.choice(n, size=k, replace=False))],
                      dtype=np.float32)
    del mm

    def run(pf: int, n_epochs: int) -> float:
        km = KMeans(k=k, max_iter=n_epochs, tolerance=1e-30, seed=0,
                    init=init, empty_cluster="keep", compute_sse=False,
                    verbose=False)
        start = time.perf_counter()
        km.fit_stream(iter_npy_blocks(path, block_rows), d=d,
                      prefetch=pf)
        elapsed = time.perf_counter() - start
        assert km.iterations_run == n_epochs
        return elapsed

    # INTERLEAVED variant comparison (the BASELINE.md rule for every
    # cross-variant number: both settings must see the same host-drift
    # window).  Each rep measures one (small, big) marginal pair per
    # prefetch setting back-to-back; the published overlap speedup is
    # the median of the PER-REP ratios, so slow drift that moves both
    # settings together cancels — a sequential prefetch-0-series-then-
    # prefetch-2-series design measured 1.8x and 0.7x for the SAME
    # binary across two drift windows on a shared host.
    for pf in (0, prefetch):
        run(pf, 1)
        run(pf, 1 + epochs)                      # warm both programs
    m0s, m2s = [], []
    reps = 5
    for rep in range(reps + 1):
        m0 = max(run(0, 1 + epochs) - run(0, 1), 1e-9)
        m2 = max(run(prefetch, 1 + epochs) - run(prefetch, 1), 1e-9)
        if rep == 0:
            continue                             # burn-in pair (outlier)
        m0s.append(m0)
        m2s.append(m2)
        _log(f"[stream] rep {rep}/{reps}: prefetch0 "
             f"{m0 / epochs:.3f} s/epoch, prefetch{prefetch} "
             f"{m2 / epochs:.3f} s/epoch, speedup {m0 / m2:.2f}x")
    ratios = sorted(a / b for a, b in zip(m0s, m2s))
    speedup = float(np.median(ratios))
    ratio_spread = (max(ratios) - min(ratios)) / speedup
    p0 = float(np.median(m0s)) / epochs
    p2 = float(np.median(m2s)) / epochs
    s0 = (max(m0s) - min(m0s)) / float(np.median(m0s))
    s2 = (max(m2s) - min(m2s)) / float(np.median(m2s))
    _log(f"[stream] prefetch=0: {p0:.3f} s/epoch (spread "
         f"{s0 * 100:.0f}%); prefetch={prefetch}: {p2:.3f} s/epoch "
         f"(spread {s2 * 100:.0f}%); overlap speedup {speedup:.2f}x "
         f"(ratio spread {ratio_spread * 100:.0f}%)")

    # In-memory device-loop iteration at the same shape (the published
    # per-config method) — quantifies what streaming costs over a
    # device-resident fit when the data DOES fit.
    in_mem = None
    try:
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from kmeans_tpu.parallel import distributed as dist
        from kmeans_tpu.parallel.mesh import (DATA_AXIS, make_mesh,
                                              mesh_shape)
        from kmeans_tpu.parallel.sharding import choose_chunk_size
        mesh = make_mesh()
        data_shards, model_shards = mesh_shape(mesh)
        chunk = choose_chunk_size(-(-n // data_shards), k, d)
        n_pad = -(-n // (data_shards * chunk)) * (data_shards * chunk)
        gen = jax.jit(
            lambda key: (jax.random.uniform(key, (n_pad, d), jnp.float32,
                                            -1.0, 1.0),
                         (jnp.arange(n_pad) < n).astype(jnp.float32)),
            out_shardings=(NamedSharding(mesh, P(DATA_AXIS, None)),
                           NamedSharding(mesh, P(DATA_AXIS))))
        points, weights = gen(jax.random.PRNGKey(42))
        cents = jax.device_put(dist.pad_centroids(init, model_shards),
                               dist.centroid_sharding(mesh))

        def build(mi):
            return dist.make_fit_fn(mesh, chunk_size=chunk, mode="matmul",
                                    k_real=k, max_iter=mi, tolerance=0.0,
                                    empty_policy="keep")

        def timed(fn, mi):
            seeds = jax.device_put(np.zeros((mi,), np.uint32))
            t0 = time.perf_counter()
            out = fn(points, weights, cents, seeds)
            int(out[1])
            return time.perf_counter() - t0

        f_s, f_b = build(2), build(2 + epochs)
        timed(f_s, 2), timed(f_b, 2 + epochs)          # compile
        m, sp, _ = measure_marginal(lambda: timed(f_s, 2),
                                    lambda: timed(f_b, 2 + epochs),
                                    reps=5)
        in_mem = m / epochs
        _log(f"[stream] in-memory device loop: {in_mem * 1e3:.1f} ms/iter"
             f" (spread {sp * 100:.0f}%)")
    except Exception as e:                 # noqa: BLE001 — context only
        _log(f"[stream] in-memory comparison skipped: {e}")

    result = {
        # Same publication rule as bench_config: rows whose spread
        # exceeds the 5% bar are flagged, never silently published.
        # The bar is applied to the RATIO spread — the published
        # comparison — since absolute epoch times on a shared host
        # carry the drift the interleaving exists to cancel.
        "indicative_only": bool(ratio_spread > 0.05),
        "metric": f"kmeans_stream_epoch_N{n}_D{d}_k{k}",
        "value": round(p2, 4),
        "unit": "s/epoch (streamed, prefetch on)",
        "prefetch": prefetch,
        "block_rows": block_rows,
        "epochs_gap": epochs,
        "prefetch0_s_per_epoch": round(p0, 4),
        "prefetch_s_per_epoch": round(p2, 4),
        "overlap_speedup": round(speedup, 3),
        "overlap_speedup_spread": round(ratio_spread, 3),
        "spread_prefetch0": round(s0, 3),
        "spread_prefetch": round(s2, 3),
        "in_memory_ms_per_iter": (round(in_mem * 1e3, 3)
                                  if in_mem else None),
        "stream_overhead_vs_in_memory": (round(p0 / in_mem, 2)
                                         if in_mem else None),
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
    }
    print(json.dumps(result), flush=True)
    return result


def bench_checkpoint_segments(n: int, d: int, k: int, iters: int,
                              every: int, reps: int = 5) -> Dict:
    """Segmented-dispatch cost (ISSUE 4): a ``checkpoint_every=N``
    device-loop fit vs the single-dispatch oracle at the same shape.

    The segmented fit pays ``ceil(iters/N) - 1`` extra dispatches plus
    per-boundary host round trips (centroid pull + re-put) and one
    rotating atomic ``.npz`` write per segment.  Method: the repo's
    interleaved per-rep protocol — each rep times one (oracle,
    segmented) FULL-fit pair back-to-back (fixed explicit init,
    tolerance~0, 'keep' policy, so both run exactly ``iters``
    iterations; both programs compiled and warmed first), and the
    published overhead is the median of the per-rep ratios so shared-
    host drift cancels.  Checkpoints go to a fresh temp dir (local
    disk; a network filesystem adds its own write latency on top).
    """
    import os
    import tempfile

    import jax
    from kmeans_tpu.models.kmeans import KMeans

    rng = np.random.default_rng(42)
    X = rng.uniform(-1.0, 1.0, size=(n, d)).astype(np.float32)
    init = X[np.sort(rng.choice(n, size=k, replace=False))].copy()

    def run(ck_every, path) -> "KMeans":
        km = KMeans(k=k, max_iter=iters, tolerance=1e-30, seed=0,
                    init=init, empty_cluster="keep", compute_sse=False,
                    host_loop=False, verbose=False)
        kwargs = ({"checkpoint_every": ck_every, "checkpoint_path": path}
                  if ck_every else {})
        km.fit(X, **kwargs)
        assert km.iterations_run == iters
        return km

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "bench_ckpt.npz")
        run(0, None)                               # compile oracle
        run(every, path)                           # compile all segments
        o_s, s_s = [], []
        for rep in range(reps + 1):
            t0 = time.perf_counter()
            run(0, None)
            o = time.perf_counter() - t0
            t0 = time.perf_counter()
            seg_km = run(every, path)
            s = time.perf_counter() - t0
            if rep == 0:
                continue                           # burn-in pair
            o_s.append(o)
            s_s.append(s)
            _log(f"[ckpt] rep {rep}/{reps}: oracle {o / iters * 1e3:.2f} "
                 f"ms/iter, every={every} {s / iters * 1e3:.2f} ms/iter, "
                 f"overhead {(s / o - 1) * 100:.1f}%")
    ratios = sorted(s / o for s, o in zip(s_s, o_s))
    overhead = float(np.median(ratios))
    ratio_spread = (max(ratios) - min(ratios)) / overhead
    segments = -(-iters // every)
    result = {
        "indicative_only": bool(ratio_spread > 0.05),
        "metric": f"kmeans_ckpt_overhead_N{n}_D{d}_k{k}_every{every}",
        "value": round(overhead, 4),
        "unit": "x (segmented fit wall / single-dispatch oracle wall)",
        "checkpoint_every": every,
        "iters": iters,
        "segments": segments,
        "extra_dispatches": segments - 1,
        "oracle_ms_per_iter": round(
            float(np.median(o_s)) / iters * 1e3, 3),
        "segmented_ms_per_iter": round(
            float(np.median(s_s)) / iters * 1e3, 3),
        "overhead_ratio_spread": round(ratio_spread, 3),
        "checkpoint_segments_observed": seg_km.checkpoint_segments_,
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
    }
    print(json.dumps(result), flush=True)
    return result


def bench_cross_mesh_resume(n: int, d: int, k: int, iters: int,
                            every: int, reps: int = 5) -> Dict:
    """Elastic-resume cost (ISSUE 5): what topology portability adds —
    one canonical gather at save (already a host ``numpy`` state: the
    rotating ``.npz`` write IS the gather) and one re-shard at resume
    (checkpoint load + re-pad for the new mesh + device placement +
    the first segment dispatch, program pre-compiled).

    Method: fit with ``checkpoint_every`` on a mesh over ALL devices,
    then resume the checkpoint on a HALF-width mesh (the preempted
    slice coming back smaller — the elasticity scenario).  Per rep:
    ``save_ms`` times one rotating checkpoint write; ``resume_ms``
    times ``fit(resume=path)`` end-to-end on the half mesh for ONE
    segment of further iterations (both meshes' programs compiled and
    warmed first).  Medians published; single-device platforms skip
    (no second topology to resume on)."""
    import os
    import tempfile

    import jax
    from kmeans_tpu.models.kmeans import KMeans
    from kmeans_tpu.parallel.mesh import make_mesh
    from kmeans_tpu.utils import checkpoint as ckpt

    n_dev = len(jax.devices())
    if n_dev < 2:
        result = {"metric": "cross_mesh_resume", "skipped":
                  "needs >= 2 devices for two topologies"}
        print(json.dumps(result), flush=True)
        return result
    mesh_w = make_mesh(data=n_dev, model=1)
    mesh_r = make_mesh(data=n_dev // 2, model=1,
                       devices=jax.devices()[: n_dev // 2])
    rng = np.random.default_rng(42)
    X = rng.uniform(-1.0, 1.0, size=(n, d)).astype(np.float32)
    init = X[np.sort(rng.choice(n, size=k, replace=False))].copy()
    kw = dict(k=k, tolerance=1e-30, seed=0, init=init,
              empty_cluster="keep", compute_sse=False, host_loop=False,
              verbose=False)

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "bench_xmesh.npz")
        writer = KMeans(max_iter=iters, mesh=mesh_w, **kw)
        writer.fit(X, checkpoint_every=every, checkpoint_path=path)
        # Warm the resume mesh's program (same segment length).
        KMeans(max_iter=every, mesh=mesh_r, **kw).fit(X)
        save_s, resume_s = [], []
        for rep in range(reps + 1):
            t0 = time.perf_counter()
            ckpt.save_state_rotating(path, writer._state_dict())
            sv = time.perf_counter() - t0
            res = KMeans(max_iter=iters + every, mesh=mesh_r, **kw)
            t0 = time.perf_counter()
            res.fit(X, resume=path)
            rs = time.perf_counter() - t0
            if rep == 0:
                continue                              # burn-in
            save_s.append(sv)
            resume_s.append(rs)
            _log(f"[xmesh] rep {rep}/{reps}: save {sv * 1e3:.1f} ms, "
                 f"resume-on-{n_dev // 2}-way {rs * 1e3:.1f} ms "
                 f"({res.iterations_run - iters} iters run)")
        assert res.iterations_run > iters     # the resume really continued
    result = {
        "metric": f"cross_mesh_resume_N{n}_D{d}_k{k}",
        "value": round(float(np.median(resume_s)) * 1e3, 2),
        "unit": "ms (load + re-shard + one further segment on the "
                "half-width mesh)",
        "write_mesh_data_shards": n_dev,
        "resume_mesh_data_shards": n_dev // 2,
        "save_ms": round(float(np.median(save_s)) * 1e3, 2),
        "segment_iters": every,
        "platform": jax.default_backend(),
        "n_devices": n_dev,
    }
    print(json.dumps(result), flush=True)
    return result


def bench_serving(n: int, d: int, k: int,
                  batch_sizes=(1, 8, 64, 512), reps: int = 5,
                  max_wait_ms: float = 2.0) -> List[Dict]:
    """Serving latency/QPS harness (ISSUE 6): micro-batched dispatch vs
    sequential per-request dispatch at 1/8/64/512-request batch sizes.

    One K-Means model is fitted at (n, d, k) and held resident in a
    :class:`~kmeans_tpu.serving.ServingEngine`; per batch size B each
    rep runs one INTERLEAVED pair — a batched wave (B concurrent
    single-row ``submit`` calls coalesced by the micro-batch queue,
    wave wall = last ``result()``) back-to-back with a sequential wave
    (B direct ``engine.predict`` calls, one dispatch each) — and the
    published speedup is the median of per-rep ratios (the repo's
    drift-cancelling protocol).  Warm path throughout: models resident,
    bucket shapes pre-compiled; what is measured is dispatch + padding
    + queue overhead, which is exactly what serving pays per request.

    p50/p99 latencies are per-request submit->result times over extra
    latency-only batched waves (the batching TIMER is part of the
    number: a lone request waits up to ``max_wait_ms`` for co-batchable
    traffic — the documented latency floor of the ``submit`` path).
    QPS = B / median batched-wave wall.  Emits one JSON line per batch
    size; returns the rows.
    """
    import jax

    from kmeans_tpu.models.kmeans import KMeans
    from kmeans_tpu.serving import ServingEngine

    rng = np.random.default_rng(42)
    X = rng.uniform(-1.0, 1.0, size=(n, d)).astype(np.float32)
    init = X[np.sort(rng.choice(n, size=k, replace=False))].copy()
    km = KMeans(k=k, max_iter=5, seed=0, init=init,
                empty_cluster="keep", verbose=False).fit(X)
    pool = rng.uniform(-1.0, 1.0, size=(4096, d)).astype(np.float32)

    engine = ServingEngine(max_wait_ms=max_wait_ms)
    engine.add_model("bench", km)
    engine.warmup()
    _log(f"[serve] resident k={k} d={d}, buckets={engine.buckets}, "
         f"max_wait_ms={max_wait_ms}, backend={jax.default_backend()}")

    def batched_wave(B: int):
        """B concurrent single-row requests through the queue; returns
        (wall, per-request latencies)."""
        rows = [pool[i % pool.shape[0]][None, :] for i in range(B)]
        t0 = time.perf_counter()
        submits, futs = [], []
        for r in rows:
            submits.append(time.perf_counter())
            futs.append(engine.submit("bench", r))
        lats = []
        for t_sub, f in zip(submits, futs):
            f.result(timeout=60.0)
            lats.append(time.perf_counter() - t_sub)
        return time.perf_counter() - t0, lats

    def sequential_wave(B: int) -> float:
        t0 = time.perf_counter()
        for i in range(B):
            engine.predict("bench", pool[i % pool.shape[0]][None, :])
        return time.perf_counter() - t0

    results = []
    for B in batch_sizes:
        batched_wave(B)                    # burn-in pair per size
        sequential_wave(B)
        tb_s, ts_s, lat = [], [], []
        for rep in range(reps):
            tb, lats = batched_wave(B)
            ts = sequential_wave(B)
            tb_s.append(tb)
            ts_s.append(ts)
            lat.extend(lats)
            _log(f"[serve] B={B} rep {rep + 1}/{reps}: batched "
                 f"{tb * 1e3:.2f} ms, sequential {ts * 1e3:.2f} ms "
                 f"({ts / tb:.2f}x)")
        # Extra latency-only waves so p99 has samples at small B.
        for _ in range(max(0, -(-128 // B) - reps)):
            _, lats = batched_wave(B)
            lat.extend(lats)
        ratios = sorted(t / b for t, b in zip(ts_s, tb_s))
        speedup = float(np.median(ratios))
        spread = (max(ratios) - min(ratios)) / speedup
        tb_med = float(np.median(tb_s))
        lat = np.asarray(sorted(lat))
        row = {
            "metric": f"serving_latency_B{B}_k{k}_D{d}",
            "batch_requests": B,
            "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
            "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
            "n_latency_samples": int(lat.size),
            "qps": round(B / tb_med, 1),
            "batched_wave_ms": round(tb_med * 1e3, 3),
            "sequential_wave_ms": round(
                float(np.median(ts_s)) * 1e3, 3),
            "speedup_vs_sequential": round(speedup, 3),
            "speedup_spread": round(spread, 3),
            "indicative_only": bool(spread > 0.05),
            "max_wait_ms": max_wait_ms,
            "platform": jax.default_backend(),
            "n_devices": len(jax.devices()),
        }
        print(json.dumps(row), flush=True)
        results.append(row)
    st = engine.stats()
    _log(f"[serve] dispatches={st['dispatches']}, batch_fill="
         f"{st['batch_fill']}")
    engine.close()
    return results


def bench_quality(n: int, d: int, k: int, *, reps: int = 5,
                  batch: int = 512, waves: int = 8) -> Dict:
    """Serving-quality monitoring overhead (ISSUE 14): monitoring-ON
    vs monitoring-OFF serving throughput, interleaved per-rep — the
    r15 telemetry-overhead discipline applied to the drift monitor.

    One K-Means model is fitted at (n, d, k) and held resident in TWO
    engines on ONE shared mesh (so the identity-keyed ``_cents_dev``
    placement cache never thrashes between them): ``quality=True``
    (the monitor fed per dispatch, windows closing mid-run) and
    ``quality=False`` (the blind r11 engine).  Per rep one interleaved
    pair runs ``waves`` direct ``call`` dispatches of ``batch`` rows
    through each engine; the published overhead is the median of
    per-rep on/off ratios.  Committed rule: <= 1.01 median overhead
    keeps monitoring on for that platform's ``quality='auto'``
    resolution; a breach resolves 'auto' to OFF there (the r8/r13
    'auto' discipline — the rejection is published, the knob stays).
    Outcome on the 2-core CPU proxy: BREACH (~1.1-1.2x — a 512-row
    local dispatch costs under 1 ms, so the ~0.1 ms cold-cache numpy
    feed is visible), hence 'auto' = off on CPU; accelerators keep ON
    (a tunneled dispatch pays 70-100 ms RTT — the same feed is
    < 0.2%), hardware row pinned.  Labels bit-equality on/off is
    asserted IN-BENCH every run (the obs=0 parity contract)."""
    import jax

    from kmeans_tpu.models.kmeans import KMeans
    from kmeans_tpu.parallel.mesh import make_mesh
    from kmeans_tpu.serving import ServingEngine

    rng = np.random.default_rng(42)
    X = rng.uniform(-1.0, 1.0, size=(n, d)).astype(np.float32)
    init = X[np.sort(rng.choice(n, size=k, replace=False))].copy()
    km = KMeans(k=k, max_iter=5, seed=0, init=init,
                empty_cluster="keep", verbose=False,
                compute_sse=True).fit(X)
    pool = rng.uniform(-1.0, 1.0, size=(max(batch, 4096), d)) \
        .astype(np.float32)

    mesh = make_mesh()
    # ONE fitted model shared by both engines (neither mutates it; the
    # per-engine state lives on the ResidentModel wrappers): a deepcopy
    # twin would duplicate the retained training dataset — ~1 GB at
    # the accelerator default shape — purely for registration.
    eng_on = ServingEngine(mesh=mesh, quality=True, start=False)
    eng_off = ServingEngine(mesh=mesh, quality=False, start=False)
    eng_on.add_model("q", km)
    eng_off.add_model("q", km)
    eng_on.warmup()
    eng_off.warmup()
    _log(f"[quality] resident k={k} d={d}, batch={batch}, "
         f"waves={waves}, window={eng_on._quality_window}, "
         f"backend={jax.default_backend()}")

    block = pool[:batch]
    np.testing.assert_array_equal(eng_on.call("q", block),
                                  eng_off.call("q", block))

    n_blocks = max(1, pool.shape[0] // batch)

    def wave(engine) -> float:
        t0 = time.perf_counter()
        for i in range(waves):
            j = (i % n_blocks) * batch
            engine.call("q", pool[j: j + batch])
        return time.perf_counter() - t0

    wave(eng_on)                            # burn-in pair
    wave(eng_off)
    ratios = []
    for rep in range(reps):
        t_on = wave(eng_on)
        t_off = wave(eng_off)
        ratios.append(t_on / t_off)
        _log(f"[quality] rep {rep + 1}/{reps}: on {t_on * 1e3:.2f} ms, "
             f"off {t_off * 1e3:.2f} ms ({ratios[-1]:.4f}x)")
    overhead = float(np.median(ratios))
    spread = (max(ratios) - min(ratios)) / overhead
    status = eng_on.quality_status()["q"]
    row = {
        "metric": f"serving_quality_overhead_N{n}_D{d}_k{k}",
        "overhead_ratio": round(overhead, 4),
        "overhead_spread": round(spread, 3),
        "indicative_only": bool(spread > 0.05),
        "within_1pct_rule": bool(overhead <= 1.01),
        "rule": "<=1.01 median on/off keeps quality='auto' ON for "
                "this platform; breach resolves 'auto' to off there "
                "(published either way)",
        "batch": batch, "waves": waves, "reps": reps,
        "windows_closed": status["windows"],
        "drift_events": status["events"],
        "labels_bitequal": True,            # asserted above
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
    }
    print(json.dumps(row), flush=True)
    eng_on.close()
    eng_off.close()
    return row


def _fleet_open_loop(fleet, pool, rate_qps: float, n_reqs: int) -> Dict:
    """One open-loop level against a fleet (the r12 protocol, compact):
    a dispatcher submits single-row requests at scheduled instants
    ``t0 + i/rate`` without waiting for completions, and latency is
    measured from the SCHEDULED arrival — no coordinated omission.
    Returns achieved qps / p99 / failed count."""
    import queue as queue_mod
    import threading

    done_q = queue_mod.Queue()
    lats, failed = [], [0]
    lock = threading.Lock()

    def waiter():
        while True:
            item = done_q.get()
            if item is None:
                return
            sched, fut = item
            try:
                fut.result(timeout=120.0)
            except Exception:       # noqa: BLE001 — counted, not noise
                with lock:
                    failed[0] += 1
                continue
            t = time.perf_counter()
            with lock:
                lats.append(t - sched)

    waiters = []
    for _ in range(4):
        w = threading.Thread(target=waiter)
        w.start()
        waiters.append(w)
    interval = 1.0 / rate_qps
    t0 = time.perf_counter()
    for i in range(n_reqs):
        sched = t0 + i * interval
        now = time.perf_counter()
        if sched > now:
            time.sleep(sched - now)
        done_q.put((sched, fleet.submit("bench",
                                        pool[i % pool.shape[0]][None, :])))
    for _ in waiters:
        done_q.put(None)
    for w in waiters:
        w.join()
    wall = time.perf_counter() - t0
    lats = np.sort(np.asarray(lats))
    return {
        "qps": (n_reqs - failed[0]) / wall,
        "p99_ms": (float(np.percentile(lats, 99)) * 1e3
                   if lats.size else None),
        "failed": failed[0],
    }


def bench_fleet(n: int, d: int, k: int, *, reps: int = 5,
                replicas=(1, 2), open_reqs: int = 192,
                batch: int = 256, waves: int = 16,
                shed_burst: int = 96, max_inflight: int = 8,
                max_wait_ms: float = 2.0) -> List[Dict]:
    """Serving-fleet benchmark (ISSUE 17): router overhead, the 1->N
    replica open-loop QPS/p99 scaling curve, shed behaviour at the
    committed admission bound, and replica prewarm cost.

    One K-Means model is fitted at (n, d, k); every fleet shares ONE
    mesh and the ONE fitted model object, so the identity-keyed
    ``_cents_dev`` placement and the compiled programs are shared and
    parity with a single engine is structural (asserted in-bench).

    Four row families, all interleaved per-rep where a ratio is
    published (the repo's drift-cancelling protocol):

    * ``fleet_router_overhead`` — direct ``engine.call`` vs routed
      ``fleet.call`` (R=1) batched waves, median per-rep ratio.
      Committed rule: <= 1.05 median overhead, else the row publishes
      as a rejection (the router would not be earning its keep at one
      replica and direct dispatch should be the single-replica path).
    * ``fleet_serving_R{R}`` — open-loop (coordinated-omission-free)
      QPS and p99 at a committed offered rate (0.3x the measured
      direct-dispatch capacity — deliberately inside capacity so the
      property under test is routing, not saturation) for each R.
      ``failed == 0`` is asserted EVERY rep.  On this CPU container
      the in-process replicas share one backend, so QPS(R) is flat by
      construction and the published property is "replication adds no
      loss"; real scaling needs one mesh per replica — hardware row
      pinned (docs/PERFORMANCE.md).
    * ``fleet_shed_at_bound`` — a submit burst against R=2 with
      ``max_inflight`` admission: sheds are explicit
      (``FleetOverloadError``) and counted; ``served + shed ==
      offered`` is asserted (zero silent drops), and the registry's
      ``fleet.shed`` counter must equal the observed sheds.
    * ``fleet_prewarm`` — ``add_replica(prewarm=True)`` wall vs the
      first replica's initial warmup: the r19 shared-compile-cache
      economics of growing the fleet while serving.
    """
    import jax

    from kmeans_tpu.models.kmeans import KMeans
    from kmeans_tpu.parallel.mesh import make_mesh
    from kmeans_tpu.serving import (FleetOverloadError, ServingEngine,
                                    ServingFleet)

    rng = np.random.default_rng(42)
    X = rng.uniform(-1.0, 1.0, size=(n, d)).astype(np.float32)
    init = X[np.sort(rng.choice(n, size=k, replace=False))].copy()
    km = KMeans(k=k, max_iter=5, seed=0, init=init,
                empty_cluster="keep", verbose=False).fit(X)
    pool = rng.uniform(-1.0, 1.0, size=(4096, d)).astype(np.float32)
    mesh = make_mesh()
    backend = jax.default_backend()
    rows: List[Dict] = []

    # ---- router overhead (direct engine vs fleet at R=1) -------------
    eng = ServingEngine(mesh=mesh, quality=False, start=False)
    eng.add_model("bench", km)
    eng.warmup()
    fleet1 = ServingFleet(1, mesh=mesh, quality=False, start=False,
                          max_wait_ms=max_wait_ms)
    fleet1.add_model("bench", km)
    t0 = time.perf_counter()
    fleet1.warmup()
    initial_warm_s = time.perf_counter() - t0
    block = pool[:batch]
    np.testing.assert_array_equal(fleet1.predict("bench", block),
                                  eng.predict("bench", block))

    def wave(target) -> float:
        t0 = time.perf_counter()
        for i in range(waves):
            j = (i % (pool.shape[0] // batch)) * batch
            target.call("bench", pool[j: j + batch])
        return time.perf_counter() - t0

    wave(fleet1)                            # burn-in pair
    wave(eng)
    ratios = []
    for rep in range(reps):
        t_f = wave(fleet1)
        t_e = wave(eng)
        ratios.append(t_f / t_e)
        _log(f"[fleet] overhead rep {rep + 1}/{reps}: fleet "
             f"{t_f * 1e3:.2f} ms, direct {t_e * 1e3:.2f} ms "
             f"({ratios[-1]:.4f}x)")
    overhead = float(np.median(ratios))
    spread = (max(ratios) - min(ratios)) / overhead
    rows.append({
        "metric": f"fleet_router_overhead_k{k}_D{d}",
        "overhead_ratio": round(overhead, 4),
        "overhead_spread": round(spread, 3),
        "indicative_only": bool(spread > 0.05),
        "within_5pct_rule": bool(overhead <= 1.05),
        "rule": "<=1.05 median routed/direct keeps the router on the "
                "single-replica path; breach publishes as a rejection",
        "batch": batch, "waves": waves, "reps": reps,
        "labels_bitequal": True,            # asserted above
        "platform": backend, "n_devices": len(jax.devices()),
    })
    print(json.dumps(rows[-1]), flush=True)

    # Committed offered rate: 0.3x the measured direct single-row
    # capacity (inside capacity by construction — the scaling rows
    # measure routing, not saturation).
    for _ in range(8):
        fleet1.predict("bench", pool[:1])
    t0 = time.perf_counter()
    n_direct = 64
    for i in range(n_direct):
        fleet1.predict("bench", pool[i % pool.shape[0]][None, :])
    direct_s = (time.perf_counter() - t0) / n_direct
    rate = 0.3 / direct_s
    p99_bound_ms = max_wait_ms + 10 * direct_s * 1e3
    eng.close()
    fleet1.close()

    # ---- 1 -> N open-loop scaling curve ------------------------------
    for R in replicas:
        fleet = ServingFleet(R, mesh=mesh, quality=False,
                             max_wait_ms=max_wait_ms)
        fleet.add_model("bench", km)
        fleet.warmup()
        _fleet_open_loop(fleet, pool, rate, min(64, open_reqs))  # warm
        qps_s, p99_s = [], []
        for rep in range(reps):
            r = _fleet_open_loop(fleet, pool, rate, open_reqs)
            assert r["failed"] == 0, \
                f"open-loop rep {rep} failed {r['failed']} requests"
            qps_s.append(r["qps"])
            p99_s.append(r["p99_ms"])
            _log(f"[fleet] R={R} rep {rep + 1}/{reps}: "
                 f"{r['qps']:.1f} qps, p99 {r['p99_ms']:.2f} ms")
        qps_med = float(np.median(qps_s))
        p99_med = float(np.median(p99_s))
        qps_spread = (max(qps_s) - min(qps_s)) / qps_med
        p99_spread = (max(p99_s) - min(p99_s)) / p99_med
        st = fleet.stats()
        rows.append({
            "metric": f"fleet_serving_R{R}_k{k}_D{d}",
            "replicas": R,
            "offered_qps": round(rate, 1),
            "qps": round(qps_med, 1),
            "p99_ms": round(p99_med, 3),
            "p99_bound_ms": round(p99_bound_ms, 3),
            "p99_within_bound": bool(p99_med <= p99_bound_ms),
            "qps_spread": round(qps_spread, 3),
            "p99_spread": round(p99_spread, 3),
            "indicative_only": bool(max(qps_spread, p99_spread) > 0.05),
            "failed": 0,                    # asserted every rep
            "routes": st["routes"], "sheds": st["sheds"],
            "reqs_per_rep": open_reqs, "reps": reps,
            "platform": backend, "n_devices": len(jax.devices()),
        })
        print(json.dumps(rows[-1]), flush=True)
        if R == max(replicas):
            # ---- prewarm row: grow the serving fleet by one ----------
            name = fleet.add_replica(prewarm=True)
            prewarm_s = fleet.stats()["replicas"][name]["prewarm_s"]
            rows.append({
                "metric": f"fleet_prewarm_k{k}_D{d}",
                "prewarm_ms": round(prewarm_s * 1e3, 3),
                "initial_warmup_ms": round(initial_warm_s * 1e3, 3),
                "note": "add_replica shares the in-process compile "
                        "cache (and the AOT store when configured), so "
                        "growing is placement + probe cost, not "
                        "recompiles",
                "platform": backend, "n_devices": len(jax.devices()),
            })
            print(json.dumps(rows[-1]), flush=True)
        fleet.close()

    # ---- shed at the committed bound ---------------------------------
    obs_sheds0 = None
    fleet = ServingFleet(2, mesh=mesh, quality=False,
                         max_wait_ms=max_wait_ms,
                         max_inflight=max_inflight)
    fleet.add_model("bench", km)
    fleet.warmup()
    obs_sheds0 = fleet.stats()["sheds"]
    futs, shed = [], 0
    for i in range(shed_burst):
        try:
            fut = fleet.submit("bench",
                               pool[i % pool.shape[0]][None, :])
            futs.append((time.perf_counter(), fut))
        except FleetOverloadError:
            shed += 1
    served_lats = []
    for t_sub, f in futs:
        f.result(timeout=120.0)
        served_lats.append(time.perf_counter() - t_sub)
    ok = len(futs)
    assert ok + shed == shed_burst, \
        f"silent drop: {ok} served + {shed} shed != {shed_burst} offered"
    st = fleet.stats()
    assert st["sheds"] - obs_sheds0 == shed, \
        f"registry sheds {st['sheds'] - obs_sheds0} != observed {shed}"
    rows.append({
        "metric": f"fleet_shed_at_bound_k{k}_D{d}",
        "offered": shed_burst, "served": ok, "shed": shed,
        "shed_rate": round(shed / shed_burst, 3),
        "max_inflight": max_inflight,
        "served_p99_ms": round(
            float(np.percentile(np.asarray(served_lats), 99)) * 1e3, 3)
        if served_lats else None,
        "zero_silent_drops": True,          # asserted above
        "platform": backend, "n_devices": len(jax.devices()),
    })
    print(json.dumps(rows[-1]), flush=True)
    fleet.close()
    return rows


def bench_learn(n: int, d: int, k: int, *, reps: int = 5,
                batch: int = 512, waves: int = 32) -> Dict:
    """Serve-and-learn p99 excursion (ISSUE 20: ``BENCH_LEARN=1
    python bench.py``): per-request serving latency measured DURING an
    in-place online update vs a quiet engine, interleaved per-rep —
    the r15/r18 overhead discipline applied to the actuator.

    One MiniBatch model is held resident with ``learn`` on.  Each rep
    runs a QUIET wave (``waves`` direct ``call`` dispatches of
    ``batch`` rows, per-call latencies collected) and an UPDATE wave
    (the same traffic while a forced update — snapshot, clone
    ``partial_fit``, atomic swap — runs on a background thread; the
    wave's traffic itself feeds the reservoir, so the measured path is
    the real one including the reservoir copy).  The published
    excursion is the median of per-rep p99(update)/p99(quiet) ratios.
    Committed rule: :data:`~kmeans_tpu.serving.learn.
    LEARN_P99_EXCURSION_BOUND` (3x) — the update runs off the dispatch
    lock, so anything past scheduler noise means update work leaked
    into the serve path.  ZERO failed requests is asserted IN-BENCH
    (the chaos contract: an update must never fail a serving
    request)."""
    import threading

    import jax

    from kmeans_tpu.models.minibatch import MiniBatchKMeans
    from kmeans_tpu.parallel.mesh import make_mesh
    from kmeans_tpu.serving import ServingEngine
    from kmeans_tpu.serving.learn import LEARN_P99_EXCURSION_BOUND

    rng = np.random.default_rng(42)
    X = rng.uniform(-1.0, 1.0, size=(n, d)).astype(np.float32)
    mb = MiniBatchKMeans(k=k, max_iter=10, seed=0, batch_size=4096,
                         verbose=False).fit(X)
    pool = rng.uniform(-1.0, 1.0,
                       size=(max(batch * 8, 4096), d)).astype(np.float32)

    mesh = make_mesh()
    eng = ServingEngine(mesh=mesh, quality=True, start=False,
                        learn={"batch_rows": batch, "min_rows": batch,
                               "max_batches": 2, "cooldown_windows": 0,
                               "update_budget": reps + 2,
                               "reservoir_rows": batch * 8})
    eng.add_model("learn", mb)
    eng.warmup()
    ln = eng._residents["learn"].learner
    _log(f"[learn] resident k={k} d={d}, batch={batch}, waves={waves}, "
         f"backend={jax.default_backend()}")

    n_blocks = pool.shape[0] // batch
    failed = [0]

    def wave(start: int) -> np.ndarray:
        lats = np.empty(waves)
        for i in range(waves):
            j = ((start + i) % n_blocks) * batch
            t0 = time.perf_counter()
            try:
                eng.call("learn", pool[j: j + batch])
            except Exception:   # noqa: BLE001 — the contract IS zero
                failed[0] += 1  # failed requests; count, don't mask
            lats[i] = time.perf_counter() - t0
        return lats

    wave(0)                                     # burn-in (incl. reservoir)
    ln.update_now(force=True, reason="bench-warm")   # warm the update step
    ratios, applied = [], 0
    for rep in range(reps):
        quiet = wave(rep)
        upd_dec = [None]

        def updater():
            upd_dec[0] = ln.update_now(force=True, reason="bench")

        t = threading.Thread(target=updater)
        t.start()
        busy = wave(rep + reps)
        t.join(timeout=120.0)
        if upd_dec[0] is not None and upd_dec[0]["action"] == "update":
            applied += 1
        p99_q = float(np.percentile(quiet, 99))
        p99_u = float(np.percentile(busy, 99))
        ratios.append(p99_u / p99_q)
        _log(f"[learn] rep {rep + 1}/{reps}: quiet p99 "
             f"{p99_q * 1e3:.2f} ms, update p99 {p99_u * 1e3:.2f} ms "
             f"({ratios[-1]:.3f}x, "
             f"{'applied' if upd_dec[0] else 'skipped'})")
    assert failed[0] == 0, f"{failed[0]} serving requests failed " \
        "during update waves (the never-fail contract)"
    excursion = float(np.median(ratios))
    spread = (max(ratios) - min(ratios)) / excursion
    status = ln.status()
    row = {
        "metric": f"serve_learn_p99_excursion_N{n}_D{d}_k{k}",
        "excursion_ratio": round(excursion, 3),
        "excursion_spread": round(spread, 3),
        "indicative_only": bool(spread > 0.05),
        "within_bound": bool(excursion <= LEARN_P99_EXCURSION_BOUND),
        "rule": f"<= {LEARN_P99_EXCURSION_BOUND}x median p99 "
                "update-wave/quiet-wave; a breach means update work "
                "leaked into the dispatch path",
        "batch": batch, "waves": waves, "reps": reps,
        "updates_applied": status["updates_applied"],
        "updates_in_measured_waves": applied,
        "rollbacks": len(status["rollbacks"]),
        "failed_requests": 0,               # asserted above
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
    }
    print(json.dumps(row), flush=True)
    eng.close()
    return row


def bench_sweep(n: int, d: int, k_values, n_init: int,
                max_iter: int, reps: int = 3) -> Dict:
    """Sweep-vs-sequential benchmark (ISSUE 7 acceptance row): the
    batched multi-k sweep (`KMeans.sweep`, one vmapped fit dispatch for
    every (k, restart) member) against the sequential per-member oracle
    (`sweep(batched=0)`, one device-loop fit + one scoring pass per
    member), at identical work: same cached dataset, same seeds, same
    fixed iteration count (tolerance 0 so no member converges early —
    the FLOPs comparison stays honest).

    Method: both paths are warmed (compiles cached), then ``reps``
    INTERLEAVED (batched, sequential) wall-time pairs reduce to the
    median of per-rep ratios with the (max-min)/median spread — the
    repo's drift-cancelling protocol.  The row also publishes the
    padding economics: batched FLOPs ≈ n_members · cost(k_max) vs
    Σ cost(k_m) sequential — ``wasted_flops_factor`` is that ratio, the
    price the one-dispatch form pays for its dispatch/batching wins
    (break-even discussion in docs/PERFORMANCE.md "Batched k sweeps").

    DECISION RULE (committed now): CPU proxy acceptance is batched
    >= 2x sequential wall-clock at 200k x 32, k ∈ {2..17}, n_init=2;
    hardware (10M x 128 on the tunneled chip, where each sequential
    member pays the ~70-100 ms dispatch RTT and a fresh compile per
    distinct k) is pinned at >= 3x, else the row publishes as a
    measured rejection and ``sweep`` documents ``batched=0`` as the
    default for that platform."""
    import jax

    from kmeans_tpu.models.kmeans import KMeans

    # ``k_values`` is an already-parsed k list (bench.py feeds it the
    # CLI's half-open 'lo:hi[:step]' / comma grammar via parse_k_range,
    # so a bench config reproduces verbatim through the sweep
    # subcommand).
    ks = tuple(int(k) for k in k_values)
    if not ks:
        raise ValueError("bench_sweep: empty k range")
    from kmeans_tpu.data.synthetic import make_blobs
    X = make_blobs(n, max(ks[len(ks) // 2], 2), d, random_state=42,
                   dtype=np.float32)[0]

    def model():
        # tolerance below any real shift: every member runs max_iter
        # (fixed work on both paths; the reference's stress-bench
        # semantics).
        return KMeans(k=ks[-1], max_iter=max_iter, tolerance=1e-30,
                      seed=0, n_init=n_init, empty_cluster="keep",
                      verbose=False)

    ds = model().cache(X)

    def run_batched():
        return model().sweep(ds, k_range=ks, criterion="inertia")

    def run_sequential():
        return model().sweep(ds, k_range=ks, criterion="inertia",
                             batched=0)

    _log(f"[sweep] warming both paths (N={n} D={d} k={ks[0]}..{ks[-1]} "
         f"n_init={n_init} max_iter={max_iter}, "
         f"{len(ks) * n_init} members)...")
    res_b = run_batched()                      # compile + warm
    res_s = run_sequential()
    if res_b.selected_k != res_s.selected_k:
        _log(f"[sweep] WARNING: batched selected k={res_b.selected_k} "
             f"!= sequential k={res_s.selected_k}")

    tb_s, ts_s = [], []
    for rep in range(reps):
        t0 = time.perf_counter()
        run_batched()
        tb_s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_sequential()
        ts_s.append(time.perf_counter() - t0)
        _log(f"[sweep] rep {rep + 1}/{reps}: batched {tb_s[-1]:.3f}s, "
             f"sequential {ts_s[-1]:.3f}s ({ts_s[-1] / tb_s[-1]:.2f}x)")
    ratios = sorted(t / b for t, b in zip(ts_s, tb_s))
    speedup = float(np.median(ratios))
    spread = (max(ratios) - min(ratios)) / speedup
    members = len(ks) * n_init
    waste = members * ks[-1] / (n_init * sum(ks))
    target = 2.0 if jax.default_backend() == "cpu" else 3.0
    row = {
        "metric": f"sweep_vs_sequential_N{n}_D{d}_k{ks[0]}-{ks[-1]}"
                  f"_ninit{n_init}",
        "n": n, "d": d, "k_lo": ks[0], "k_hi": ks[-1],
        "n_init": n_init, "members": members, "max_iter": max_iter,
        "batched_s": round(float(np.median(tb_s)), 3),
        "sequential_s": round(float(np.median(ts_s)), 3),
        "speedup": round(speedup, 2),
        "spread": round(spread, 3),
        "indicative_only": bool(spread > 0.05),
        "dispatches_batched": int(res_b.n_dispatches),
        "dispatches_sequential": int(res_s.n_dispatches),
        "wasted_flops_factor": round(waste, 2),
        "selected_k": int(res_b.selected_k),
        "decision_target_x": target,
        "decision_passed": bool(speedup >= target),
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
    }
    print(json.dumps(row), flush=True)
    return row


# --------------------------------------------------------------- TTFI

def _ttfi_payload(records, wall_s: float) -> Dict:
    """One traced fit -> its TTFI table + the prelude-window overlap
    figures: ``window_s`` is the measured wall of the pre-first-
    dispatch work (place/stage/compile span envelope), ``serial_s`` the
    sum of those phases' SELF times — ``window_s < serial_s`` is the
    measured proof that ingest and compile ran concurrently (ISSUE
    15c's committed overlap rule)."""
    from kmeans_tpu.obs.report import time_to_first_iteration
    table = time_to_first_iteration(records)
    spans = [r for r in records if r.get("kind") == "span"]
    fd = min((s for s in spans if s["name"] == "dispatch"),
             key=lambda s: s["t0"], default=None)
    window = serial = None
    if fd is not None:
        # Up to the first dispatch's END (the revised ttfi_ladder
        # rule): a serial fit's explicit aot-build compile span nests
        # INSIDE the first dispatch, and the window must cover it or
        # the serial stage-then-compile wall under-measures.
        fd_end = fd["t1"] if fd.get("t1") is not None else fd["t0"]
        pre = [s for s in spans
               if s["name"] in ("place", "stage", "compile")
               and s["t0"] <= fd_end and s.get("t1") is not None]
        if pre:
            window = max(s["t1"] for s in pre) - min(s["t0"] for s in pre)
            serial = sum(r["ms"] for r in table
                         if r["phase"] in ("place", "stage",
                                           "compile")) / 1e3
    phases = {r["phase"]: r["ms"] for r in table}
    return {"table": table, "wall_s": wall_s,
            "ttfi_s": sum(r["ms"] for r in table) / 1e3,
            "compile_ms": phases.get("compile"),
            "first_dispatch_ms": phases.get("first_dispatch"),
            "stage_ms": (phases.get("stage", 0.0)
                         + phases.get("place", 0.0)),
            "window_s": window, "serial_s": serial}


def ttfi_child() -> None:
    """Subprocess body of ``bench_ttfi`` (a FRESH process is the only
    honest cold/AOT-warm boundary): two traced fits at the configured
    shape — the first is this process's cold (or AOT-warm, when the
    shared store is populated) row, the second the same-process warm
    row — printed as one ``TTFI_JSON`` line."""
    import os

    from kmeans_tpu.obs import trace as obs_trace
    from kmeans_tpu.models.kmeans import KMeans
    from kmeans_tpu.utils import aot
    from kmeans_tpu.utils.profiling import sanitize_json
    cfg = json.loads(os.environ["KMEANS_TPU_TTFI_CFG"])
    if cfg.get("compile_cache"):
        enable_compilation_cache()
    store = aot.configure(cfg["aot_dir"]) if cfg.get("aot_dir") else None
    rng = np.random.default_rng(0)
    X = rng.normal(size=(cfg["n"], cfg["d"])).astype(np.float32)

    def run_fit(trace_path=None):
        model = KMeans(k=cfg["k"], max_iter=cfg["max_iter"],
                       tolerance=1e-12, seed=0, verbose=False,
                       host_loop=False, empty_cluster="keep",
                       bucket="auto", overlap=cfg["overlap"],
                       ingest=cfg.get("ingest", "auto"))
        t0 = time.perf_counter()
        with obs_trace.tracing(trace_path) as tr:
            model.fit(X)
        return (time.perf_counter() - t0, tr.records(),
                float(np.float64(model.centroids).sum()))

    # Only the FIRST fit writes the trace artifact — it is the
    # cold/AOT-warm row the bench-diff TTFI guard reads; the second
    # fit is the same-process-warm row, reported but not persisted.
    wall1, recs1, sum1 = run_fit(cfg.get("trace_path"))
    wall2, recs2, sum2 = run_fit()
    out = {"first": _ttfi_payload(recs1, wall1),
           "second": _ttfi_payload(recs2, wall2),
           "centroid_sum": sum1, "centroid_sum_warm": sum2,
           "aot": store.stats() if store else None}
    print("TTFI_JSON " + json.dumps(sanitize_json(out)), flush=True)


#: Committed decision rules (pre-registered, the repo's publication
#: discipline): an AOT-warm second process's TTFI compile row must cost
#: <= this fraction of the cold process's; the overlapped prelude's
#: measured window must be < its serial phase sum.
TTFI_AOT_COMPILE_MAX_RATIO = 0.10


def _ttfi_spawn(cfg: Dict) -> Dict:
    """Run one ``ttfi_child`` subprocess and parse its payload."""
    import os
    import subprocess

    env = dict(os.environ)
    env["KMEANS_TPU_TTFI_CFG"] = json.dumps(cfg)
    env.pop("KMEANS_TPU_AOT_CACHE", None)   # cfg decides, not ambient env
    if not cfg.get("compile_cache"):
        # The COLD row must be genuinely cold: jax reads
        # JAX_COMPILATION_CACHE_DIR natively, so an ambient value (set
        # by docs/bench habits) would turn the cold compile into a
        # persistent-cache disk hit and corrupt the committed
        # AOT<=10%-of-cold baseline (review finding).
        env["JAX_COMPILATION_CACHE_DIR"] = ""
        env.pop("KMEANS_TPU_COMPILE_CACHE", None)
    proc = subprocess.run(
        [sys.executable, "-c",
         "from kmeans_tpu.benchmarks import ttfi_child; ttfi_child()"],
        env=env, capture_output=True, text=True, timeout=1800)
    for line in proc.stdout.splitlines():
        if line.startswith("TTFI_JSON "):
            return json.loads(line[len("TTFI_JSON "):])
    raise RuntimeError(
        f"TTFI child produced no payload (exit {proc.returncode}):\n"
        f"{proc.stderr[-2000:]}")


def bench_ttfi(n: int, d: int, k: int, *, max_iter: int = 4,
               aot_dir: str = None, artifact_dir: str = "artifacts",
               overlap_reps: int = 3) -> List[Dict]:
    """BENCH_TTFI=1: measured cold / warm / AOT-warm / overlap
    time-to-first-iteration rows (ISSUE 15 acceptance).

    Four fresh-process runs against one shared AOT store:

    * **cold** — empty store; the TTFI compile row carries the real
      XLA build (``compile(via='aot-build')`` spans).
    * **warm** — the SAME process's second fit (in-memory caches):
      zero compile time, the standing-fleet bound.
    * **aot-warm** — a SECOND process against the populated store:
      compile row = ``via='aot-load'`` deserialize time; committed
      rule ``<= TTFI_AOT_COMPILE_MAX_RATIO`` x cold.
    * **overlap** — a third process, fresh store, ``overlap=1``:
      staged ingest runs in the producer thread while this thread
      builds; committed rule measured window < serial phase sum.

    Rows print as bench JSON lines (bench-diff-comparable); the cold
    and AOT-warm traces land in ``artifact_dir`` for the bench-diff
    TTFI guard."""
    import os
    import tempfile

    os.makedirs(artifact_dir, exist_ok=True)
    aot_dir = aot_dir or tempfile.mkdtemp(prefix="kmeans_tpu_aot_")
    base = {"n": n, "d": d, "k": k, "max_iter": max_iter,
            "compile_cache": False, "overlap": 0, "aot_dir": aot_dir}
    shape = f"N{n}_D{d}_k{k}"

    _log(f"bench: TTFI cold process (store {aot_dir})...")
    cold = _ttfi_spawn({**base, "trace_path":
                        os.path.join(artifact_dir, "trace_ttfi_cold.jsonl")})
    _log(f"bench: TTFI AOT-warm process...")
    warm2 = _ttfi_spawn({**base, "trace_path":
                         os.path.join(artifact_dir,
                                      "trace_ttfi_aotwarm.jsonl")})
    # The overlap row compares MEASURED walls, not self-time sums: an
    # interleaved (serial, overlapped) pair of fresh-store processes
    # per rep — the serial child's place/stage/compile span envelope IS
    # the stage-then-compile serial wall (overlap=0 runs them
    # sequentially), the overlapped child's envelope is the concurrent
    # wall — reduced to medians (the repo's interleaved-pairs method;
    # thread contention moves single runs ~10% on a shared CPU).
    ov_runs, ov_windows, ser_windows = [], [], []
    for i in range(overlap_reps):
        _log(f"bench: TTFI overlap pair {i + 1}/{overlap_reps} "
             f"(fresh stores)...")
        ser = _ttfi_spawn({**base, "overlap": 0,
                           "aot_dir": tempfile.mkdtemp(
                               prefix="kmeans_tpu_aot_ser_")})
        ovl = _ttfi_spawn({**base, "overlap": 1,
                           "aot_dir": tempfile.mkdtemp(
                               prefix="kmeans_tpu_aot_ov_")})
        ov_runs.append(ovl)
        ser_windows.append(ser["first"]["window_s"])
        ov_windows.append(ovl["first"]["window_s"])
    ov_sorted, ser_sorted = sorted(ov_windows), sorted(ser_windows)
    overlap = ov_runs[ov_windows.index(
        ov_sorted[len(ov_sorted) // 2])]
    ov_window = ov_sorted[len(ov_sorted) // 2]
    ov_serial = ser_sorted[len(ser_sorted) // 2]

    parity = cold["centroid_sum"] == warm2["centroid_sum"] \
        == overlap["centroid_sum"]
    c_cold = cold["first"]["compile_ms"] or 0.0
    c_aot = warm2["first"]["compile_ms"] or 0.0
    ratio = c_aot / c_cold if c_cold > 0 else None
    rows = [
        {"metric": f"ttfi_cold_{shape}", **_row_of(cold["first"]),
         "aot_built": cold["aot"]["built"]},
        {"metric": f"ttfi_warm_sameproc_{shape}",
         **_row_of(cold["second"])},
        {"metric": f"ttfi_aot_warm_{shape}", **_row_of(warm2["first"]),
         "aot_loaded": warm2["aot"]["loaded"],
         "compile_vs_cold": round(ratio, 4) if ratio is not None
         else None,
         "rule": f"compile <= {TTFI_AOT_COMPILE_MAX_RATIO} x cold",
         "rule_pass": bool(ratio is not None
                           and ratio <= TTFI_AOT_COMPILE_MAX_RATIO)},
        {"metric": f"ttfi_overlap_{shape}",
         **_row_of(overlap["first"]),
         "overlap_window_s": round(ov_window, 4),
         "serial_wall_s": round(ov_serial, 4),
         "overlap_window_reps": [round(w, 4) for w in ov_sorted],
         "serial_wall_reps": [round(s, 4) for s in ser_sorted],
         "overlap_speedup": (round(ov_serial / ov_window, 3)
                             if ov_window else None),
         "spread": (round((ov_sorted[-1] - ov_sorted[0])
                          / ov_window, 3) if ov_window else None),
         "rule": "median overlapped window < median serial "
                 "stage-then-compile wall",
         "rule_pass": bool(ov_window < ov_serial)},
    ]
    for r in rows:
        r["bit_parity_across_processes"] = parity
        print(json.dumps(r), flush=True)
    _log("\n| row | ttfi s | compile ms | first_dispatch ms | rule |")
    _log("|---|---|---|---|---|")
    for r in rows:
        _log(f"| {r['metric']} | {r['ttfi_s']:.3f} | "
             f"{r['compile_ms'] if r['compile_ms'] is not None else '-'}"
             f" | {r['first_dispatch_ms']:.1f} | "
             f"{r.get('rule', '-')}"
             f"{' PASS' if r.get('rule_pass') else ''} |")
    return rows


def _row_of(payload: Dict) -> Dict:
    return {"ttfi_s": round(payload["ttfi_s"], 4),
            "wall_s": round(payload["wall_s"], 3),
            "compile_ms": (round(payload["compile_ms"], 2)
                           if payload["compile_ms"] is not None
                           else None),
            "stage_ms": round(payload["stage_ms"], 2),
            "first_dispatch_ms": round(payload["first_dispatch_ms"], 2)}


# ------------------------------------------------------------- INGEST

#: Committed adoption rule (ISSUE 18, the r8/r12 measured-adopt
#: discipline): the slabbed placement joins ``ingest='auto'`` only where
#: its measured mono/slab placement-wall ratio on the >= 1 GB proxy
#: reaches this bar; below it 'auto' would keep the mono oracle.
INGEST_ADOPT_RATIO = 1.2

#: Committed memory rule (ISSUE 18d), saved-copy form: the streamed
#: ``from_npy`` child must shave at least this fraction of the proxy
#: file's bytes off the load-whole-file child's host high-water
#: (``naive_maxrss - stream_maxrss >= fraction x file_bytes``) — the
#: measured proof that streaming never materialises the full-file host
#: copy, i.e. the host-side high-water is O(slab) in the *data* term.
#: An absolute maxrss ratio is the wrong committed form on the CPU
#: proxy, where the device buffers themselves live in host RAM and
#: dominate both children identically; the r22 run measured the saved
#: bytes at 0.98x the file size (1008 of 1025 MB), exactly the
#: full-copy elimination this rule pins.
INGEST_STREAM_SAVED_MIN_FRACTION = 0.8


def ingest_child() -> None:
    """Subprocess body of ``bench_ingest`` (fresh processes are the
    honest allocator/RSS boundary).  Tasks, via KMEANS_TPU_INGEST_CFG:

    * ``pairs`` — interleaved (mono, slab) placement walls of an
      in-memory (n, d) float32 matrix on the full-device mesh,
      per-array checksums for the bit-parity column.
    * ``mem_naive`` — ``np.load`` the whole ``.npy`` file, then place:
      the O(rows) host high-water baseline.
    * ``mem_stream`` — ``from_npy`` streamed ingest of the same file:
      the O(slab) high-water contender.

    Each prints one ``INGEST_JSON`` line with its measurements plus the
    process's ``ru_maxrss``."""
    import os
    import resource

    from kmeans_tpu.parallel.mesh import make_mesh
    from kmeans_tpu.parallel.sharding import to_device
    cfg = json.loads(os.environ["KMEANS_TPU_INGEST_CFG"])
    mesh = make_mesh()
    chunk = cfg.get("chunk") or 65536

    def checksum(ds):
        return [float(np.float64(np.asarray(ds.points)).sum()),
                float(np.float64(np.asarray(ds.weights)).sum())]

    out: Dict = {"task": cfg["task"]}
    if cfg["task"] == "pairs":
        rng = np.random.default_rng(0)
        X = rng.random((cfg["n"], cfg["d"]), dtype=np.float32)
        walls = {"mono": [], "slab": []}
        sums = {}
        for _ in range(cfg.get("reps", 3)):
            for mode in ("mono", "slab"):
                t0 = time.perf_counter()
                ds = to_device(X, mesh, chunk, np.float32, ingest=mode)
                ds.points.block_until_ready()
                ds.weights.block_until_ready()
                walls[mode].append(time.perf_counter() - t0)
                sums[mode] = checksum(ds)
                del ds
        out.update(mono_s=walls["mono"], slab_s=walls["slab"],
                   parity=sums["mono"] == sums["slab"])
    else:
        from kmeans_tpu.data.io import from_npy
        if cfg["task"] == "mem_naive":
            X = np.load(cfg["path"])
            ds = to_device(X, mesh, chunk, np.float32, ingest="slab")
        else:                                          # mem_stream
            ds = from_npy(cfg["path"], mesh, chunk_size=chunk,
                          ingest="slab")
        ds.points.block_until_ready()
        out["checksum"] = checksum(ds)
    out["maxrss_mb"] = resource.getrusage(
        resource.RUSAGE_SELF).ru_maxrss / 1024.0
    print("INGEST_JSON " + json.dumps(out), flush=True)


def _ingest_spawn(cfg: Dict) -> Dict:
    """Run one ``ingest_child`` subprocess and parse its payload."""
    import os
    import subprocess

    env = dict(os.environ)
    env["KMEANS_TPU_INGEST_CFG"] = json.dumps(cfg)
    proc = subprocess.run(
        [sys.executable, "-c",
         "from kmeans_tpu.benchmarks import ingest_child; "
         "ingest_child()"],
        env=env, capture_output=True, text=True, timeout=1800)
    for line in proc.stdout.splitlines():
        if line.startswith("INGEST_JSON "):
            return json.loads(line[len("INGEST_JSON "):])
    raise RuntimeError(
        f"ingest child produced no payload (exit {proc.returncode}):\n"
        f"{proc.stderr[-2000:]}")


def bench_ingest(n: int, d: int, *, k: int = 64, max_iter: int = 4,
                 reps: int = 3, chunk: int = None,
                 artifact_dir: str = "artifacts") -> List[Dict]:
    """BENCH_INGEST=1: the staged-ingest decision rows (ISSUE 18).

    * ``ingest_ratio`` — interleaved mono/slab placement walls of the
      >= 1 GB proxy in one fresh process, medians + the committed
      ``INGEST_ADOPT_RATIO`` adoption verdict (honest rejection below
      the bar) and the bit-parity column.
    * ``ingest_overlap`` — fresh-process (serial, overlapped) TTFI
      pairs with the platform's RESOLVED ``'auto'`` ingest mode (the
      shipping path: mono on CPU after the r22 rejection, slab on
      accelerators): the measured window < serial stage-then-compile
      wall PASS row, plus the re-measured place/stage share of TTFI.
    * ``ingest_host_highwater`` — load-whole-file vs streamed
      ``from_npy`` children over the same >= 1 GB ``.npy``; committed
      rule (saved-copy form): ``naive_maxrss - stream_maxrss >=
      INGEST_STREAM_SAVED_MIN_FRACTION x file_bytes``.
    * ``ingest_plan_1e9`` — the 1e9-row weak-scaling config DECLARED
      through ``obs.memory.plan_fit``/``plan_ingest`` (no device on
      earth holds it otherwise): per-device resident bytes + slab
      geometry at 256 shards, with the fits-16-GB-HBM verdict.
    """
    import os
    import tempfile

    from kmeans_tpu.obs.memory import plan_fit, plan_ingest

    os.makedirs(artifact_dir, exist_ok=True)
    shape = f"N{n}_D{d}"
    bytes_total = n * d * 4

    _log(f"bench: INGEST pairs process ({bytes_total / 2**30:.2f} GiB "
         f"proxy, {reps} interleaved reps)...")
    pairs = _ingest_spawn({"task": "pairs", "n": n, "d": d,
                           "chunk": chunk, "reps": reps})
    mono = sorted(pairs["mono_s"])[len(pairs["mono_s"]) // 2]
    slab = sorted(pairs["slab_s"])[len(pairs["slab_s"]) // 2]
    ratio = mono / slab if slab else None

    # The overlap row measures the SHIPPING ingest mode — what
    # resolve_ingest('auto') picks for this platform (mono on CPU after
    # the r22 rejection, slab on accelerators).  Forcing 'slab' on a
    # platform that just rejected it would stack the double-buffer
    # staging threads on top of the overlap producer and measure a
    # configuration nothing ships.
    from kmeans_tpu.parallel.sharding import resolve_ingest
    ov_mode = resolve_ingest("auto")
    _log(f"bench: INGEST overlap pairs (fresh processes, "
         f"ingest={ov_mode})...")
    tn, td = max(200_000, n // 8), d
    tbase = {"n": tn, "d": td, "k": k, "max_iter": max_iter,
             "compile_cache": False, "ingest": ov_mode}
    ov_windows, ser_windows, ov_runs = [], [], []
    for i in range(reps):
        ser = _ttfi_spawn({**tbase, "overlap": 0,
                           "aot_dir": tempfile.mkdtemp(
                               prefix="kmeans_tpu_ing_ser_")})
        ovl = _ttfi_spawn({**tbase, "overlap": 1,
                           "aot_dir": tempfile.mkdtemp(
                               prefix="kmeans_tpu_ing_ov_")})
        ov_runs.append(ovl)
        ser_windows.append(ser["first"]["window_s"])
        ov_windows.append(ovl["first"]["window_s"])
    ov_sorted, ser_sorted = sorted(ov_windows), sorted(ser_windows)
    ov_window = ov_sorted[len(ov_sorted) // 2]
    ov_serial = ser_sorted[len(ser_sorted) // 2]
    ov_med = ov_runs[ov_windows.index(ov_window)]
    stage_share = (ov_med["first"]["stage_ms"]
                   / (ov_med["first"]["ttfi_s"] * 1e3)
                   if ov_med["first"]["ttfi_s"] else None)

    _log("bench: INGEST host high-water children (.npy proxy)...")
    with tempfile.TemporaryDirectory(prefix="kmeans_tpu_ing_") as td_:
        path = os.path.join(td_, "proxy.npy")
        rng = np.random.default_rng(0)
        np.save(path, rng.random((n, d), dtype=np.float32))
        naive = _ingest_spawn({"task": "mem_naive", "path": path,
                               "chunk": chunk})
        stream = _ingest_spawn({"task": "mem_stream", "path": path,
                                "chunk": chunk})
    rss_ratio = stream["maxrss_mb"] / naive["maxrss_mb"] \
        if naive["maxrss_mb"] else None
    saved_mb = naive["maxrss_mb"] - stream["maxrss_mb"]
    file_mb = bytes_total / 2**20
    saved_frac = saved_mb / file_mb if file_mb else None

    plan = plan_fit("kmeans", 1_000_000_000, 64, 1024,
                    data_shards=256, chunk=65536)
    iplan = plan_ingest(1_000_000_000, 64, data_shards=256,
                        chunk=65536)
    hbm = 16 << 30
    rows = [
        {"metric": f"ingest_ratio_{shape}", "ingest": "slab",
         "mono_s": round(mono, 4), "slab_s": round(slab, 4),
         "ratio": round(ratio, 3) if ratio else None,
         "reps_mono_s": [round(v, 4) for v in sorted(pairs["mono_s"])],
         "reps_slab_s": [round(v, 4) for v in sorted(pairs["slab_s"])],
         "bit_parity": pairs["parity"],
         "rule": f"adopt slab into 'auto' at >= "
                 f"{INGEST_ADOPT_RATIO} x mono/slab",
         "rule_pass": bool(ratio is not None
                           and ratio >= INGEST_ADOPT_RATIO)},
        {"metric": f"ingest_overlap_N{tn}_D{td}_k{k}",
         "ingest": ov_mode, **_row_of(ov_med["first"]),
         "overlap_window_s": round(ov_window, 4),
         "serial_wall_s": round(ov_serial, 4),
         "ttfi_stage_share": (round(stage_share, 4)
                              if stage_share is not None else None),
         "rule": "median overlapped window < median serial "
                 "stage-then-compile wall",
         "rule_pass": bool(ov_window < ov_serial)},
        {"metric": f"ingest_host_highwater_{shape}", "ingest": "slab",
         "naive_maxrss_mb": round(naive["maxrss_mb"], 1),
         "stream_maxrss_mb": round(stream["maxrss_mb"], 1),
         "rss_ratio": round(rss_ratio, 3) if rss_ratio else None,
         "saved_mb": round(saved_mb, 1),
         "saved_file_frac": (round(saved_frac, 3)
                             if saved_frac is not None else None),
         "file_mb": round(file_mb, 1),
         "parity": naive["checksum"] == stream["checksum"],
         "rule": f"naive - stream maxrss >= "
                 f"{INGEST_STREAM_SAVED_MIN_FRACTION} x file bytes "
                 f"(streamed never holds the full-file host copy)",
         "rule_pass": bool(saved_frac is not None and
                           saved_frac >=
                           INGEST_STREAM_SAVED_MIN_FRACTION)},
        {"metric": "ingest_plan_1e9_D64_k1024", "ingest": "slab",
         "declared": True, "data_shards": 256,
         "resident_gb": round(
             plan["predicted_resident_bytes"] / 2**30, 2),
         "peak_gb": round(plan["predicted_peak_bytes"] / 2**30, 2),
         "slab_mb": round(iplan["slab_bytes"] / 2**20, 1),
         "slabs_per_host_shard": iplan["slabs"],
         "total_tb": round(iplan["total_bytes"] / 2**40, 2),
         "rule": "per-device peak fits 16 GB HBM",
         "rule_pass": bool(plan["predicted_peak_bytes"] < hbm)},
    ]
    for r in rows:
        print(json.dumps(r), flush=True)
    _log("\n| row | key figures | rule |")
    _log("|---|---|---|")
    for r in rows:
        fig = ", ".join(f"{k_}={v}" for k_, v in r.items()
                        if k_ not in ("metric", "rule", "rule_pass")
                        and not isinstance(v, (list, dict)))
        _log(f"| {r['metric']} | {fig} | {r.get('rule', '-')}"
             f"{' PASS' if r.get('rule_pass') else ' FAIL'} |")
    return rows


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="kmeans_tpu benchmarks")
    parser.add_argument("--configs", default=",".join(DEFAULT_CONFIGS))
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument("--mode", default="auto",
                        help="auto | matmul | matmul_bf16 | pallas | "
                             "pallas_bf16")
    parser.add_argument("--model", default="kmeans",
                        help="kmeans | " + " | ".join(sorted(MODEL_SPECS))
                        + " | all (non-kmeans families run their "
                        "one-dispatch fit at a family-scaled shape)")
    args = parser.parse_args(argv)

    enable_compilation_cache()

    if args.model != "kmeans":
        models = sorted(MODEL_SPECS) if args.model == "all" \
            else [m.strip() for m in args.model.split(",")]
        results = []
        for m in models:
            if m not in MODEL_SPECS:
                _log(f"[{m}] unknown model; options: kmeans, all, "
                     f"{sorted(MODEL_SPECS)}")
                continue
            try:
                results.append(bench_model(m, args.iters))
            except Exception as e:       # noqa: BLE001 — keep suite going
                _log(f"[{m}] FAILED: {e}")
        _log("\n| model | N | D | k | ms/iter | step MFU | "
             "init kmeans|| s (device/legacy) | spread |")
        _log("|---|---|---|---|---|---|---|---|")
        for r in results:
            mfu = r.get("step_mfu")
            _log(f"| {r['model']} | {r['n']:,} | {r['d']} | {r['k']} | "
                 f"{r['ms_per_iter']} | "
                 f"{'-' if mfu is None else format(mfu, '.1%')} | "
                 f"{r['init_kmeanspp_s']} / "
                 f"{r['init_kmeanspp_legacy_s']} | {r['spread']} |")
        return 0 if results else 1

    results = []
    for name in args.configs.split(","):
        try:
            results.append(bench_config(name.strip(), args.iters,
                                        args.mode))
        except Exception as e:           # noqa: BLE001 — keep suite going
            _log(f"[{name}] FAILED: {e}")

    _log("\n| config | N | D | k | ms/iter | points*dims/s/chip |")
    _log("|---|---|---|---|---|---|")
    for r in results:
        tput = r["throughput_pd_per_sec_per_chip"]
        nl = tput is None
        _log(f"| {r['config']} | {r['n']:,} | {r['d']} | {r['k']} | "
             f"{'(noise-limited)' if nl else r['ms_per_iter']} | "
             f"{'(noise-limited)' if nl else format(tput, '.3e')} |")
    return 0 if results else 1


if __name__ == "__main__":
    sys.exit(main())
