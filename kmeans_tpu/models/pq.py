"""Batched product-quantization codebook trainer (ISSUE 16).

Product quantization (Jégou et al., PAMI 2011) splits the feature space
into ``m`` contiguous subspaces and learns an independent k-means
codebook per subspace; a vector is stored as its ``m`` per-subspace
codeword indices (``m`` bytes at the classic k=256), and distances to
compressed vectors are answered by per-subspace lookup-table sums (ADC
— asymmetric distance computation).

The trainer is the r12 model axis doing new work: the ``m`` independent
subspace k-means problems stack on the multi-fit member axis with
PER-MEMBER ROWS (``parallel.distributed.make_multi_fit_fn(
member_points=True)`` — each member trains against its own column
slice), so ONE device dispatch trains every codebook.  Each member's
trajectory is bit-identical to a standalone fit of that subspace (the
member axis is a batch dimension of every kernel; pinned by
tests/test_large_k.py).

The serving side (``adc_assign``) answers nearest-centroid queries
against a PQ-compressed table with the r13 bf16 error-model discipline
(``ops.assign.BF16_GUARD_RTOL``): the f32-rate ADC sum decides every
query whose argmin margin clears the guard rtol of its distance scale,
and flagged near-ties re-resolve against the exactly-decoded table —
labels bit-equal to the exact decoded-table argmin BY CONSTRUCTION,
with the quantization residual (ADC distance == exact distance to the
DECODED row) as the one documented approximation.  The serving engine
routes ``quantize='pq'`` residents through it.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from kmeans_tpu.ops.assign import BF16_GUARD_RTOL
from kmeans_tpu.parallel import distributed as dist
from kmeans_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh, \
    mesh_shape
from kmeans_tpu.parallel.sharding import choose_chunk_size
from kmeans_tpu.models.init import resolve_init
from kmeans_tpu.utils.cache import LRUCache
from kmeans_tpu.utils.validation import check_finite_array

__all__ = ["ProductQuantizer", "default_subspaces"]

# The batched codebook-trainer programs, keyed like kmeans._STEP_CACHE
# entries (mesh + every static that forces a rebuild).
_PQ_CACHE = LRUCache(16, name="pq._PQ_CACHE")


def default_subspaces(d: int) -> int:
    """Largest m <= 8 dividing d (PQ needs equal contiguous slices);
    1 when d is prime to 2..8 — PQ degenerates to plain VQ there."""
    for m in range(min(8, d), 0, -1):
        if d % m == 0:
            return m
    return 1  # pragma: no cover — m=1 always divides


class ProductQuantizer:
    """m independent per-subspace k-means codebooks, trained in ONE
    batched dispatch on the multi-fit member axis.

    Parameters: ``m`` subspaces ('auto': largest divisor of d up to 8),
    ``k`` codewords per subspace (<= 256 keeps codes at one byte each),
    and the familiar fit knobs.  ``empty_cluster`` is pinned to 'keep'
    (the ``member_points`` contract: a subspace codeword with no mass
    keeps its old value — the sklearn-encoder behavior).

    Fitted attributes: ``codebooks_`` (m, k, d_sub), ``n_iters_`` (m,),
    ``subspace_inertias_`` (m,) — each member's true final inertia on
    its own subspace — and ``counts_`` (m, k).
    """

    def __init__(self, m="auto", k: int = 256, max_iter: int = 25,
                 tolerance: float = 1e-4, seed: int = 42, *,
                 init="k-means++", dtype=None,
                 mesh=None, chunk_size: Optional[int] = None,
                 verbose: bool = False):
        if m != "auto" and int(m) < 1:
            raise ValueError(f"m must be 'auto' or an int >= 1, got {m}")
        self.m = m if m == "auto" else int(m)
        if int(k) < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.k = int(k)
        self.max_iter = int(max_iter)
        self.tolerance = float(tolerance)
        self.seed = int(seed)
        self.init = init
        requested = np.dtype(dtype) if dtype is not None \
            else np.dtype(np.float32)
        self.dtype = np.dtype(jax.dtypes.canonicalize_dtype(requested))
        self.mesh = mesh
        self.chunk_size = chunk_size
        self.verbose = verbose
        self.codebooks_: Optional[np.ndarray] = None
        self.n_iters_: Optional[np.ndarray] = None
        self.subspace_inertias_: Optional[np.ndarray] = None
        self.counts_: Optional[np.ndarray] = None
        self.plan_: Optional[dict] = None
        self.m_: Optional[int] = None
        self.d_: Optional[int] = None
        self.d_sub_: Optional[int] = None

    # ------------------------------------------------------------- fit

    def _resolve_mesh(self):
        if self.mesh is None:
            self.mesh = make_mesh()
        return self.mesh

    def _member_seeds(self, m: int) -> List[int]:
        """One derived init/refill seed per subspace — the restart-seed
        discipline (distinct streams, deterministic in ``seed``)."""
        return [int(s) for s in
                np.random.SeedSequence(self.seed).generate_state(m)]

    def fit(self, X) -> "ProductQuantizer":
        X = np.asarray(X, dtype=self.dtype)
        if X.ndim != 2:
            raise ValueError(f"X must be 2-D (n, D), got shape {X.shape}")
        check_finite_array(X, "Data contains NaN or Inf values")
        n, d = X.shape
        m = default_subspaces(d) if self.m == "auto" else self.m
        if d % m:
            raise ValueError(
                f"m={m} must divide d={d} into equal contiguous "
                f"subspaces (PQ's split; pad the features or pick a "
                f"divisor)")
        if n < self.k:
            raise ValueError(f"Not enough data points ({n}) to train "
                             f"{self.k} codewords per subspace")
        d_sub = d // m
        mesh = self._resolve_mesh()
        data_shards, model_shards = mesh_shape(mesh)
        chunk = self.chunk_size or choose_chunk_size(
            -(-n // data_shards), max(self.k, model_shards), d_sub)
        # Pre-dispatch HBM fit-check (the r16 planner; also the
        # large-k lint rule's guard): each member's E-step materializes
        # a (chunk, k) tile, m of them concurrently under vmap.
        from kmeans_tpu.obs.memory import plan_fit
        self.plan_ = plan_fit(
            "kmeans", n, d_sub, self.k, data_shards=data_shards,
            model_shards=model_shards, dtype=str(self.dtype),
            chunk=chunk)

        sub = np.ascontiguousarray(
            X.reshape(n, m, d_sub).transpose(1, 0, 2))   # (m, n, d_sub)
        mult = data_shards * chunk
        n_pad = -(-n // mult) * mult
        pts = np.zeros((m, n_pad, d_sub), self.dtype)
        pts[:, :n] = sub
        wts = np.zeros(n_pad, self.dtype)
        wts[:n] = 1
        pts_dev = jax.device_put(
            pts, NamedSharding(mesh, P(None, DATA_AXIS, None)))
        wts_dev = jax.device_put(wts, NamedSharding(mesh, P(DATA_AXIS)))
        seeds = self._member_seeds(m)
        inits = np.stack([
            dist.pad_centroids(
                np.asarray(resolve_init(self.init, sub[j], self.k,
                                        seeds[j], validate=False),
                           np.float64).astype(self.dtype),
                model_shards)
            for j in range(m)])
        cents_dev = jax.device_put(
            inits, NamedSharding(mesh, P(None, MODEL_AXIS, None)))
        fit_fn = _PQ_CACHE.get_or_create(
            (mesh, chunk, self.k, m, self.max_iter,
             float(self.tolerance), "pqfit"),
            lambda: dist.make_multi_fit_fn(
                mesh, chunk_size=chunk, mode="matmul", k_real=self.k,
                max_iter=self.max_iter, tolerance=float(self.tolerance),
                empty_policy="keep", n_init=m, history_sse=True,
                return_all=True, member_points=True))
        out = jax.block_until_ready(fit_fn(
            pts_dev, wts_dev, cents_dev,
            np.stack([dist._empty_seed_array(s, 0, self.max_iter)
                      for s in seeds])))
        cents, n_iters, _sse, _shift, counts, finals = out
        self.codebooks_ = np.asarray(cents, np.float64).astype(self.dtype)
        self.n_iters_ = np.asarray(n_iters, np.int64)
        self.subspace_inertias_ = np.asarray(finals, np.float64)
        self.counts_ = np.asarray(counts, np.float64)
        self.m_, self.d_, self.d_sub_ = m, d, d_sub
        return self

    # ---------------------------------------------------- encode/decode

    def _check_fitted(self):
        if self.codebooks_ is None:
            raise ValueError("ProductQuantizer must be fitted first")

    def _code_dtype(self):
        return np.uint8 if self.k <= 256 else (
            np.uint16 if self.k <= 65536 else np.uint32)

    def encode(self, X) -> np.ndarray:
        """(n, d) rows -> (n, m) per-subspace codeword indices (exact
        f64 per-subspace argmin; ties to the lowest index, the dense
        argmin rule)."""
        self._check_fitted()
        X = np.asarray(X, np.float64)
        if X.ndim != 2 or X.shape[1] != self.d_:
            raise ValueError(f"X must be (n, {self.d_}), got {X.shape}")
        n = X.shape[0]
        codes = np.empty((n, self.m_), self._code_dtype())
        for j in range(self.m_):
            xj = X[:, j * self.d_sub_:(j + 1) * self.d_sub_]
            cb = np.asarray(self.codebooks_[j], np.float64)
            d2 = (np.sum(xj ** 2, axis=1)[:, None]
                  - 2.0 * xj @ cb.T + np.sum(cb ** 2, axis=1)[None, :])
            codes[:, j] = np.argmin(d2, axis=1)
        return codes

    def decode(self, codes) -> np.ndarray:
        """(n, m) codes -> (n, d) reconstruction (per-subspace codeword
        concatenation)."""
        self._check_fitted()
        codes = np.asarray(codes)
        return np.concatenate(
            [np.asarray(self.codebooks_[j], np.float64)[codes[:, j]]
             for j in range(self.m_)], axis=1)

    def compression_ratio(self) -> float:
        """Stored bytes per row, original vs coded."""
        self._check_fitted()
        return (self.d_ * self.dtype.itemsize) \
            / (self.m_ * np.dtype(self._code_dtype()).itemsize)

    # ------------------------------------------------------ ADC serving

    def adc_assign(self, queries, codes, *,
                   tie_rtol: float = BF16_GUARD_RTOL):
        """Nearest compressed-table row per query: ``(labels,
        n_corrected)``.

        The f32-rate ADC pass (per-subspace LUT + gathered sum — the
        fast path) decides every query whose argmin margin clears
        ``tie_rtol`` of its distance scale ``|q|^2 + max_i |row_i|^2``
        — the r13 bf16 error model, verbatim.  Flagged near-ties
        re-resolve by one exact f64 pass against the DECODED table, so
        labels equal the exact decoded-table argmin by construction;
        the quantization residual (decoded vs original rows) is the one
        approximation, and it is a property of the stored codes, not of
        this query path."""
        self._check_fitted()
        Q = np.asarray(queries, np.float64)
        if Q.ndim != 2 or Q.shape[1] != self.d_:
            raise ValueError(f"queries must be (n, {self.d_}), "
                             f"got {Q.shape}")
        codes = np.asarray(codes)
        decoded = self.decode(codes)                    # (t, d) exact f64
        # f32 fast path: LUTs and the gathered sum at serving rate.
        approx = np.zeros((Q.shape[0], codes.shape[0]), np.float32)
        for j in range(self.m_):
            qj = Q[:, j * self.d_sub_:(j + 1) * self.d_sub_] \
                .astype(np.float32)
            cb = np.asarray(self.codebooks_[j], np.float32)
            lut = (np.sum(qj ** 2, axis=1)[:, None]
                   - 2.0 * qj @ cb.T + np.sum(cb ** 2, axis=1)[None, :])
            approx += lut[:, codes[:, j]]
        order = np.argsort(approx, axis=1)[:, :2]
        best = order[:, 0].astype(np.int32)
        margin = (np.take_along_axis(approx, order[:, 1:2], axis=1)
                  - np.take_along_axis(approx, order[:, 0:1], axis=1)
                  )[:, 0]
        scale = np.sum(Q.astype(np.float32) ** 2, axis=1) \
            + np.float32(np.max(np.sum(decoded ** 2, axis=1)))
        near = np.flatnonzero(
            (margin <= tie_rtol * scale) | (codes.shape[0] < 2))
        if near.size:
            sub = Q[near]
            d2 = (np.sum(sub ** 2, axis=1)[:, None]
                  - 2.0 * sub @ decoded.T
                  + np.sum(decoded ** 2, axis=1)[None, :])
            best[near] = np.argmin(d2, axis=1).astype(np.int32)
        return best, int(near.size)

    # ---------------------------------------------------------- serving

    def fitted_state(self) -> dict:
        """Serving handle (the ISSUE 6 registry contract)."""
        self._check_fitted()
        return {
            "family": "pq",
            "model_class": type(self).__name__,
            "k": int(self.k),
            "d": int(self.d_),
            "dtype": self.dtype.str,
            "stackable": False,
            "normalize_inputs": False,
            "m": int(self.m_),
            "ops": ("encode",),
        }

    @classmethod
    def for_table(cls, table, *, m="auto", k: Optional[int] = None,
                  seed: int = 0, mesh=None, max_iter: int = 25):
        """Compress a fitted (k_table, d) centroid table: train the
        codebooks ON the table rows and encode them.  Returns
        ``(pq, codes)`` — the serving engine's ``quantize='pq'``
        ingredients."""
        table = np.asarray(table)
        kt, d = table.shape
        k_pq = int(k) if k is not None else min(256, max(2, kt // 4))
        pq = cls(m=m, k=min(k_pq, kt), seed=seed, mesh=mesh,
                 max_iter=max_iter, dtype=table.dtype).fit(table)
        return pq, pq.encode(table)
