"""TPU-native distributed K-Means estimator.

Re-designs the reference's ``class KMeans`` (kmeans_spark.py:19-352) for
JAX/TPU while preserving its behavioral contract:

* Constructor ``KMeans(k, max_iter, tolerance, seed, compute_sse)``
  (kmeans_spark.py:37-47) with the same validation errors (:49-56).
* ``fit`` semantics (kmeans_spark.py:239-319): seeded Forgy init with finite
  validation; per iteration assign -> update; optional SSE with monotonicity
  warning (>1e-6 rise, :283-286) — SSE measured against the iteration's
  STARTING centroids, exactly like the reference's second pass (:279 uses the
  pre-update broadcast); NaN/Inf hard error (:289-290); max-centroid-shift
  convergence (:293-313); per-iteration logging incl. cluster sizes
  (:296-304); empty-cluster recovery (:190-204).
* ``predict`` guard + argmin labels (kmeans_spark.py:321-352) — eager here
  (the reference returns a lazy RDD and unpersists its broadcast before
  evaluation, a latent bug; SURVEY.md §2.1 C9).
* Attributes ``centroids`` / ``sse_history`` / ``iterations_run`` — with
  ``iterations_run`` actually maintained (declared but never written in the
  reference, kmeans_spark.py:47; SURVEY.md §2.1).

Deliberate divergences (documented per SURVEY.md §7 stage 2):
* Empty-cluster resampling is DETERMINISTIC — seeded per iteration via
  ``np.random.default_rng([seed, iteration])`` instead of the reference's
  ``seed=int(time.time())`` (kmeans_spark.py:196).
* The reference's dead farthest-point policy (``_reinitialize_empty_cluster``,
  kmeans_spark.py:84-129) is implemented and LIVE (``empty_cluster=
  'farthest'``) — it costs nothing because the farthest point is fused into
  the assignment pass.

Execution model: data stays sharded on the mesh's data axis for the whole fit
(the ``rdd.cache()`` analogue, kmeans_spark.py:256); each iteration is ONE
jitted SPMD step (see parallel.distributed) returning replicated global
statistics; the host loop does only the O(k*D) centroid division, convergence
test, and logging — mirroring the reference's driver role (:181-188) minus
all the broadcast/shuffle/collect traffic.
"""

from __future__ import annotations

import contextlib
import time
from typing import List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kmeans_tpu.ops.assign import StepStats
from kmeans_tpu.parallel import distributed as dist
from kmeans_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh, mesh_shape
from kmeans_tpu.parallel.multihost import fleet_barrier
from kmeans_tpu.parallel.sharding import (ShardedDataset, choose_chunk_size,
                                          to_device)
from kmeans_tpu.models.init import resolve_init
from kmeans_tpu.models.fault_tolerance import AutoCheckpointMixin
from kmeans_tpu.obs import trace as obs_trace
from kmeans_tpu.obs import note_progress as obs_note_progress
from kmeans_tpu.utils.logging import IterationLogger
from kmeans_tpu.utils.validation import check_finite_array, validate_params
from kmeans_tpu.utils import checkpoint as ckpt

_EMPTY_POLICIES = ("resample", "farthest", "keep")


# _EpochReservoir (the Algorithm-R stream sampler) lives in models.init —
# shared by fit_stream's empty-cluster resampling and the streamed
# initializers.
from kmeans_tpu.models.init import _EpochReservoir

# shard_map step/predict functions, keyed by everything that forces a
# rebuild.  LRU-bounded: long-lived services streaming many distinct
# block shapes must not pin every compiled executable forever (r3
# VERDICT weak #7).  64 entries comfortably covers a working set of
# (mesh, chunk, mode, k) combinations; raise ``_STEP_CACHE.maxsize``
# for unusual multi-model processes.
from kmeans_tpu.utils.cache import LRUCache

_STEP_CACHE = LRUCache(64, name="kmeans._STEP_CACHE")


class DispatchLatencyHint(UserWarning):
    """One-time performance hint: per-iteration host dispatch dominates
    the fit on this platform (r4 VERDICT #6 — a default-config user on a
    high-latency link, e.g. a tunneled chip with ~70-100 ms RTT, would
    otherwise spend most of their wall time on dispatch without any
    signal)."""


# One-time hint bookkeeping + measurement caches for host_loop='auto'.
_HINTS_EMITTED: set = set()
_RTT_CACHE: dict = {}          # device-id tuple -> measured RTT seconds
# key -> measured step seconds.  compile_spans=False: the factory RUNS
# two training steps (a measurement, not a program build) — tracing it
# as 'compile' would inflate the TTFI compile row on high-RTT
# platforms, where host_loop='auto' actually probes (review finding).
_AUTO_CACHE = LRUCache(64, name="kmeans._AUTO_CACHE",
                       compile_spans=False)


def _hint_once(kind: str, msg: str) -> None:
    if kind not in _HINTS_EMITTED:
        _HINTS_EMITTED.add(kind)
        import warnings
        warnings.warn(msg, DispatchLatencyHint, stacklevel=4)


def _dispatch_rtt(mesh: Mesh) -> float:
    """Measured host->device->host round trip of a trivial jitted op on
    this mesh's first device (min of 3; cached per device set).  This is
    the per-iteration latency floor a host loop pays that a device-side
    ``lax.while_loop`` does not."""
    key = tuple(d.id for d in mesh.devices.flat)
    if key not in _RTT_CACHE:
        dev = list(mesh.devices.flat)[0]
        fn = jax.jit(lambda x: x + 1.0)
        x = jax.device_put(np.float32(0), dev)
        float(fn(x))                               # compile + warm
        reps = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(fn(x))                           # scalar transfer = barrier
            reps.append(time.perf_counter() - t0)
        _RTT_CACHE[key] = min(reps)
    return _RTT_CACHE[key]


def _get_step_fns(mesh: Mesh, chunk_size: int, mode: str,
                  pipeline: int = 0):
    # The base entry keys identically to the pre-ISSUE-8 entries, so
    # every serial caller (predict/score/serving paths) shares one
    # compile.  predict does not depend on the chunk schedule, so the
    # pipelined entry holds only its own step fn and REUSES the base
    # predict fn — never a second identical predict compile.
    step_fn, predict_fn = _STEP_CACHE.get_or_create(
        (mesh, chunk_size, mode),
        lambda: (
            dist.make_step_fn(mesh, chunk_size=chunk_size, mode=mode),
            dist.make_predict_fn(mesh, chunk_size=chunk_size, mode=mode),
        ))
    if pipeline:
        step_fn = _STEP_CACHE.get_or_create(
            (mesh, chunk_size, mode, pipeline),
            lambda: dist.make_step_fn(mesh, chunk_size=chunk_size,
                                      mode=mode, pipeline=pipeline))
    return step_fn, predict_fn


class KMeans(AutoCheckpointMixin):
    """Distributed K-Means on a TPU mesh (scikit-learn-style API).

    Parameters (first five = the reference's full config surface,
    kmeans_spark.py:37-47):

    k : number of clusters.
    max_iter : maximum iterations.
    tolerance : convergence threshold on the max centroid shift.
    seed : random seed (init AND deterministic empty-cluster resampling).
    compute_sse : record ``sse_history`` + emit monotonicity warnings.
        Unlike the reference — where this costs a second full data pass
        (kmeans_spark.py:237, README.md:39-41) — SSE is fused into the
        assignment pass, so the flag only controls bookkeeping.

    TPU-native extensions:

    init : 'forgy' (reference parity) | 'k-means++' | callable | (k,D) array.
    compute_labels : materialize ``labels_`` at the end of ``fit`` with one
        extra fused assignment pass (sklearn semantics; default True).
        ``False`` skips the pass AND releases the device-resident dataset —
        centroid-only workloads pay nothing for labels they never read
        (``labels_`` then raises; call ``predict(X)`` instead).  Mirrors
        sklearn's ``MiniBatchKMeans(compute_labels=...)``.
    n_init : number of independent restarts (sklearn-style; the reference
        draws once).  Restart 0 uses ``seed`` exactly (so n_init=1 is
        bit-identical to the reference trajectory); further restarts use
        seeds derived via ``np.random.SeedSequence(seed)``.  The winner is
        the restart whose FINAL centroids score the lowest inertia
        (one extra fused pass per restart).  With ``host_loop=False`` and an
        unsharded centroid table, all restarts run BATCHED in one dispatch —
        the restart axis is vmapped straight onto the MXU
        (parallel.distributed.make_multi_fit_fn).
    empty_cluster : 'resample' (reference live path, made deterministic) |
        'farthest' (reference's dead policy, made live) | 'keep'.
    dtype : compute dtype (default float32; float64 needs jax x64).
    mesh : a ``jax.sharding.Mesh``, or None to auto-build one over all
        devices with ``model_shards`` centroid shards.
    model_shards : size of the centroid-sharding (TP) axis for auto meshes.
    chunk_size : points per scan chunk (None = auto, VMEM-budgeted).
    distance_mode : 'auto' (default: the fused Pallas kernel on TPU
        hardware where it measures faster — k >= 512 and low lane-padding
        waste, see ops.pallas_kernels.pallas_preferred — else the XLA
        'matmul' path) | 'matmul' (MXU form) | 'matmul_bf16' | 'pallas' |
        'pallas_bf16' | 'direct' (exact; small problems) |
        'matmul_bf16_guarded' (ISSUE 8: the training twin of the serving
        bf16 fast path — the dominant distance matmul runs at bf16 input
        rate, and near-tie rows whose argmin margin is inside the bf16
        error band are re-resolved against a full-precision pass, so
        labels — and therefore sums, counts, centroids, shifts, and
        iteration counts — are BIT-equal to 'matmul' by construction;
        SSE/per-cluster-SSE read the winner's full-precision distance
        and land in the documented rtol class.  Data-parallel meshes
        only; `empty_cluster='farthest'` rejected (both pointed errors);
        `bf16_guard_corrected_rows_` audits the per-fit correction count
        on device-loop fits).
    bucket : 0 (default) | 'auto' | int — the fit-shape bucket (ISSUE
        15b, serving's batch-bucket discipline applied to training row
        counts).  0 pads the staged shard exactly to the shard/chunk
        multiple — the bit-parity oracle, identical to every fit before
        this knob existed.  'auto' pads up to the next committed bucket
        boundary (``parallel.sharding.bucket_rows``: {1, 1.25, 1.5,
        1.75} x 2^e rows, <= 25% padding worst-case) with the existing
        inert zero-weight sentinel rows, and derives the scan chunk
        from the BUCKETED count — so nearby dataset sizes commit to one
        padded shape and one compiled program, and a standing fleet
        (or a second-process AOT-cache hit, ``utils.aot``) accepts a
        new fit with zero compiles (``recompilation_sentinel`` pins a
        second same-bucket fit at zero new cache entries).  An int is
        an explicit boundary step: rows pad to the next multiple of it.
        Same-data results differ from ``bucket=0`` only in fp summation
        fold (the extra all-zero chunks), never semantics.
    overlap : 'auto' (default) | 0 | 1 — compile/ingest overlap (ISSUE
        15c): with 1, a fit on a host array stages the upload through
        the prefetch producer thread while THIS thread resolves the
        step programs — AOT-load (or trace+compile) concurrently with
        the transfer, so the two TTFI terms stop being serial.  The
        work and its arithmetic are identical (bit-exact parity with
        0 — only WHERE the prelude runs moves); 'auto' resolves 0 on
        CPU (both terms are small; keeps the serial trace shape) and 1
        on accelerators, where the transfer is the dominant TTFI term
        (docs/PERFORMANCE.md).
    ingest : 'auto' (default) | 'mono' | 'slab' — the host->device
        placement path (ISSUE 18): 'slab' groups device shards into
        HBM-planner-sized slabs uploaded double-buffered (slab i+1's
        host->device copy overlaps slab i's completion), 'mono' is the
        one-blocking-assembly parity oracle; the assembled array is
        byte-identical either way, so fits are bit-exact across modes.
        Both paths pad only the final shard's tail (no full-dataset
        host pad copy).  'auto' applies the committed BENCH_INGEST
        decision rule (docs/PERFORMANCE.md "Ingest pipeline").
    host_loop : True (reference per-iteration driver semantics: host-side
        f64 division, per-iteration logging, host empty-cluster policy) |
        False (the WHOLE fit as one device-side ``lax.while_loop``
        dispatch — no per-iteration host round trips) | 'auto' (default:
        host-loop behavior, but on platforms where one measured dispatch
        RTT exceeds 5 ms and 25% of a step it switches to the device
        loop when semantically interchangeable — verbose=False,
        base-class hooks, single process, and not 'resample' on a
        host-resident dataset — and otherwise emits a one-time
        :class:`DispatchLatencyHint`; see ``_resolve_host_loop``).
    pipeline : 'auto' (default) | 0 | 1 — the Lloyd E-step chunk
        schedule (ISSUE 8, the r8 GMM ``_chunked_epass`` discipline on
        the flagship path): 1 selects the software-pipelined two-stage
        scan that overlaps chunk i's distance matmul (MXU) with chunk
        i-1's argmin + one-hot scatter epilogue (VPU + MXU), 0 the
        serial body — the bit-exact parity oracle (the prefetch=0 /
        checkpoint_every=0 discipline; the schedules move WHERE work
        happens, never its arithmetic or fold order).  'auto' resolves
        per platform: serial on CPU (the carried (chunk, k) tile is pure
        extra memory traffic with no separate MXU/VPU to overlap — the
        r8 measured-rejection precedent, re-measured for Lloyd by
        ``bench_lloyd_pipeline``), pipelined on accelerators.  Pallas
        modes ignore it (the fused kernel owns its own overlap).
    verbose : reference-style per-iteration prints (kmeans_spark.py:296-304).

    Observability: after ``fit``, ``loop_path_`` records which engine ran
    ('host' | 'device' | 'device-multi') and ``auto_rtt_`` the dispatch
    RTT ``host_loop='auto'`` measured (None when no probe ran) — the
    fields the multichip dry-run artifact publishes (ISSUE 2 satellite:
    evidence that 'auto' measures the real RTT and takes the device path
    on high-latency platforms).  ``estep_path_`` records which chunk
    schedule the last fit ran ('pipelined' | 'serial');
    ``bf16_guard_corrected_rows_`` the guarded rung's corrected-row
    audit (None when the rung didn't run a device loop).
    """

    # Device-expressible subclass postprocess: None for plain Lloyd; a
    # subclass whose ``_postprocess_centroids`` has an exact device
    # equivalent (parallel.distributed._project_centroids) declares its
    # name here AND tags the method with ``_device_equivalent`` — that
    # pair is what lets host_loop=False/'auto' run it in one dispatch.
    _device_project: Optional[str] = None

    def __init__(self, k: int = 3, max_iter: int = 100,
                 tolerance: float = 1e-4, seed: int = 42,
                 compute_sse: bool = False, *,
                 init: Union[str, np.ndarray, callable] = "forgy",
                 n_init: int = 1,
                 compute_labels: bool = True,
                 empty_cluster: str = "resample",
                 dtype=None,
                 mesh: Optional[Mesh] = None,
                 model_shards: int = 1,
                 chunk_size: Optional[int] = None,
                 distance_mode: str = "auto",
                 host_loop: Union[bool, str] = "auto",
                 pipeline: Union[str, int] = "auto",
                 bucket: Union[str, int] = 0,
                 overlap: Union[str, int] = "auto",
                 ingest: str = "auto",
                 k_shard: Union[str, int] = "auto",
                 assign: str = "auto",
                 coarse_cells: Optional[int] = None,
                 nprobe: Optional[int] = None,
                 init_cap: Optional[int] = None,
                 verbose: bool = True):
        self.k = k
        self.max_iter = max_iter
        self.tolerance = tolerance
        self.seed = seed
        self.compute_sse = compute_sse
        self.init = init
        if isinstance(n_init, str):
            if n_init != "auto":
                raise ValueError(f"n_init must be an int >= 1 or 'auto', "
                                 f"got {n_init!r}")
            # sklearn's n_init='auto': 1 for the D^2-seeded inits (each
            # draw is already quality-controlled), ``_auto_n_init()`` for
            # plain random draws (forgy) — and for CALLABLE inits, which
            # get that many distinct seeds like sklearn's; explicit
            # arrays collapse to 1 in _restart_seeds.
            n_init = (1 if isinstance(init, str)
                      and init in ("k-means++", "kmeans++", "k-means||",
                                   "kmeans||") else self._auto_n_init())
        if int(n_init) < 1:
            raise ValueError(f"n_init must be >= 1, got {n_init}")
        self.n_init = int(n_init)
        self.compute_labels = compute_labels
        if empty_cluster not in _EMPTY_POLICIES:
            raise ValueError(f"empty_cluster must be one of {_EMPTY_POLICIES},"
                             f" got {empty_cluster!r}")
        self.empty_cluster = empty_cluster
        requested = np.dtype(dtype) if dtype is not None \
            else np.dtype(np.float32)
        # Canonicalize against the backend: without jax_enable_x64, float64
        # arrays are silently stored as float32 on device — declaring the
        # narrowed dtype up front keeps every dataset/model dtype check
        # consistent (and warns, instead of surprising at predict time).
        canonical = np.dtype(jax.dtypes.canonicalize_dtype(requested))
        if canonical != requested:
            import warnings
            warnings.warn(
                f"dtype {requested} requires jax_enable_x64; computing in "
                f"{canonical} instead (set jax.config.update("
                f"'jax_enable_x64', True) before constructing the model "
                f"for true {requested})", UserWarning, stacklevel=2)
            self.dtype = canonical
        else:
            # Keep the caller's exact instance when the value is unchanged:
            # sklearn.base.clone deepcopies params and then requires the
            # constructor to store them by IDENTITY.
            self.dtype = requested
        self.mesh = mesh
        self.model_shards = model_shards
        self.chunk_size = chunk_size
        if distance_mode == dist.GUARDED_MODE \
                and empty_cluster == "farthest":
            # Mirror the builder-level rejection at construction so the
            # knob combination fails before any data moves
            # (parallel.distributed._check_guarded has the long form).
            raise ValueError(
                "distance_mode='matmul_bf16_guarded' does not support "
                "empty_cluster='farthest' (the farthest-point policy is "
                "an argmax over min-distance VALUES, which the guarded "
                "rung reproduces only to ~1 ulp); use 'keep' or "
                "'resample'")
        self.distance_mode = distance_mode
        # Lloyd E-step chunk schedule (ISSUE 8; the GMM r8 knob grammar).
        if pipeline not in ("auto", 0, 1, True, False):
            raise ValueError(f"pipeline must be 'auto', 0, or 1; got "
                             f"{pipeline!r}")
        self.pipeline = pipeline if pipeline == "auto" else int(pipeline)
        # Fit-shape bucket + compile/ingest overlap (ISSUE 15; the
        # pipeline knob grammar: 0 is the bit-parity oracle).  Grammar
        # and target policy live in parallel.sharding — one definition
        # for both families and the CLI.
        from kmeans_tpu.parallel.sharding import check_bucket
        self.bucket = check_bucket(bucket)
        if overlap not in ("auto", 0, 1, True, False):
            raise ValueError(f"overlap must be 'auto', 0, or 1; got "
                             f"{overlap!r}")
        self.overlap = overlap if overlap == "auto" else int(overlap)
        # Ingest placement path (ISSUE 18): 'mono' is the bit-parity
        # oracle, 'slab' the staged double-buffered path; grammar in
        # parallel.sharding (one definition for both families, the
        # loaders, and the CLI).
        from kmeans_tpu.parallel.sharding import check_ingest
        self.ingest = check_ingest(ingest)
        # Massive-k tier (ISSUE 16).  Knob grammar follows the pipeline/
        # bucket convention: ``k_shard=0`` and ``assign='dense'`` are
        # the bit-exact dense parity oracles; 'auto' resolves per fit
        # against the r16 HBM planner (``_resolve_large_k``) and stays
        # dense whenever the backend reports no allocator stats (CPU),
        # so every committed oracle shape keeps the dense trajectory.
        if isinstance(k_shard, str):
            if k_shard != "auto":
                raise ValueError(f"k_shard must be 'auto' or an int >= 0, "
                                 f"got {k_shard!r}")
            self.k_shard = k_shard
        else:
            if int(k_shard) < 0:
                raise ValueError(f"k_shard must be >= 0, got {k_shard}")
            self.k_shard = int(k_shard)
        if assign not in ("auto", "dense", "two_level"):
            raise ValueError(f"assign must be 'auto', 'dense', or "
                             f"'two_level', got {assign!r}")
        self.assign = assign
        if coarse_cells is not None and int(coarse_cells) < 1:
            raise ValueError(f"coarse_cells must be >= 1 or None, "
                             f"got {coarse_cells}")
        self.coarse_cells = (None if coarse_cells is None
                             else int(coarse_cells))
        if nprobe is not None and int(nprobe) < 1:
            raise ValueError(f"nprobe must be >= 1 or None, got {nprobe}")
        self.nprobe = None if nprobe is None else int(nprobe)
        # k-means|| candidate-buffer capacity, threaded to the seeding
        # engine (models.init.kmeans_parallel_init) — None keeps the
        # committed clamp(2k, 256, 2048) default.
        if init_cap is not None and int(init_cap) < 1:
            raise ValueError(f"init_cap must be >= 1 or None, "
                             f"got {init_cap}")
        self.init_cap = None if init_cap is None else int(init_cap)
        if isinstance(host_loop, str):
            if host_loop != "auto":
                raise ValueError(f"host_loop must be True, False, or "
                                 f"'auto', got {host_loop!r}")
        else:
            # Normalize bool-likes (1/0/np.bool_) so the identity checks
            # in _resolve_host_loop can't silently route an explicit
            # choice to 'auto' (review r5).
            host_loop = bool(host_loop)
        self.host_loop = host_loop
        self.verbose = verbose

        self.centroids: Optional[np.ndarray] = None   # kmeans_spark.py:44
        self.loop_path_: Optional[str] = None         # 'host'|'device'|...
        self.auto_rtt_: Optional[float] = None        # measured by 'auto'
        # Massive-k resolution of the last fit (ISSUE 16): what the
        # k_shard/assign knobs resolved TO at the fit's shape (None
        # before any fit — the dry-run/ckpt-info artifact).
        self.k_shard_resolved_: Optional[int] = None
        self.assign_resolved_: Optional[str] = None
        # Set by _route_large_k when a large-k step is swapped in: both
        # large-k steps are per-iteration host-loop programs.
        self._force_host_loop = False
        # (coarse, members) routing tables of the last two-level fit —
        # reused by predict so serving shares the fit's coarse cells.
        self._two_level_route_ = None
        self._route_cache = None
        # Which chunk schedule the last fit IN THIS PROCESS ran
        # ('pipelined' | 'serial'; the GMM estep_path_ convention) and
        # the guarded bf16 rung's per-fit corrected-row audit (summed
        # over segments/restarts on device-loop fits; None when the
        # rung didn't run one — host loops don't surface the count).
        self.estep_path_: Optional[str] = None
        self.bf16_guard_corrected_rows_: Optional[int] = None
        # Fault-tolerance observability (ISSUE 4): transient-IO retries
        # consumed by the last fit's data path, streamed blocks
        # quarantined by on_nonfinite='skip', and checkpoint segments
        # executed under checkpoint_every=N.
        self.io_retries_used_: int = 0
        self.blocks_skipped_: int = 0
        self.checkpoint_segments_: Optional[int] = None
        # Elastic recovery observability (ISSUE 5): OOM chunk-backoff
        # count and the effective scan chunk the last device-loop fit
        # ended on (None when no device loop ran; equals the committed
        # chunk on healthy fits — `oom_backoffs_ > 0` is the backoff
        # signal), plus the active checkpoint path the divergence
        # rollback restores from.
        self.oom_backoffs_: int = 0
        self.effective_chunk_: Optional[int] = None
        self._active_ckpt_path = None
        # Warm-serving placement cache (ISSUE 6): (centroids-identity,
        # mesh, device table) — see ``_cents_dev``.
        self._cents_cache = None
        self.sse_history: List[float] = []            # kmeans_spark.py:45
        self.cluster_sizes_: Optional[np.ndarray] = None
        # Serving-quality reference profile restored from a checkpoint
        # (ISSUE 14); ``quality_profile()`` prefers the FRESH fitted
        # attrs when they exist (a loaded checkpoint has no
        # cluster_sizes_, which is exactly when this fallback carries
        # the fit-time reference window into the serving registry).
        self._quality_profile: Optional[dict] = None
        self.iter_times_: List[float] = []            # wall secs/iteration
        # Restart-sweep observability: winning restart index and the
        # per-restart final inertias — declared here (the counter-reset
        # lint discipline) so a pre-fit read is a defined 0/None, never
        # an AttributeError or a stale survivor from an earlier fit.
        self.best_restart_: int = 0
        self.restart_inertias_: Optional[np.ndarray] = None
        self._fit_ds = None                           # retained for labels_
        self._labels_cache: Optional[np.ndarray] = None
        # Rows THIS host processes per iteration (heartbeat rows_per_sec,
        # ISSUE 13); set by each fit prelude, cleared here so a reused
        # estimator never reports a previous fit's row count.
        self._progress_rows: Optional[int] = None
        validate_params(k, max_iter, tolerance)       # kmeans_spark.py:46
        self.iterations_run = 0                       # kmeans_spark.py:47
        # Internal: skip init-time full-array finite scans when the caller
        # (e.g. BisectingKMeans) already validated the data once.
        self._validate_init = True
        # Internal: inner/worker fits (e.g. BisectingKMeans' per-split
        # 2-means) skip the eager labels_ pass — the parent never reads it.
        self._eager_labels = True

    # ------------------------------------------------------------------ mesh

    def _mode(self, n: int, d: int) -> str:
        """Resolve distance_mode='auto' to a concrete mode for (n, d)
        data (ops.pallas_kernels.pallas_preferred holds the measured
        win-region rule); explicit modes pass through untouched."""
        if self.distance_mode != "auto":
            return self.distance_mode
        from kmeans_tpu.ops.pallas_kernels import resolve_auto
        return resolve_auto(n, d, self.k)

    def _resolve_pipeline(self, mode: Optional[str] = None) -> int:
        """Resolve the ``pipeline`` knob to the schedule that runs.

        The Pallas modes resolve to 0 whatever the knob says: the fused
        kernel owns its own overlap schedule, ``_local_stats`` never
        consults the flag there, and resolving 0 keeps the step-fn
        cache from holding two identical compiles of one program.

        The two schedules are bit-exact parity partners (pinned,
        tests/test_lloyd_pipeline.py), so 'auto' is purely a cost call
        — the r8 GMM rule: serial on CPU (the carried (chunk, k)
        distance tile is extra memory traffic with nothing to overlap;
        the Lloyd re-measure is ``bench_lloyd_pipeline``'s published
        row), pipelined on accelerators, where the schedule exists to
        fill the MXU during the argmin/scatter VPU phases — the
        measured ~3 ms -> 6.3 ms -> ~11 ms serialization of the XLA
        scan body (docs/PERFORMANCE.md "The remaining 30%"); the
        pinned hardware row's committed decision rule (>= 5% to adopt)
        flips accelerator-'auto' back to 0 if the overlap loses
        on-chip."""
        if mode is not None and mode in dist.PALLAS_MODES:
            return 0
        if self.pipeline == "auto":
            return 0 if jax.default_backend() == "cpu" else 1
        return int(self.pipeline)

    def _note_estep_path(self, mode: Optional[str] = None) -> int:
        """Set the ``estep_path_`` observability attr; returns the
        resolved pipeline flag (the GMM ``_note_estep_path``
        convention).  Records what actually runs, not what was asked
        for: the Pallas modes report 'fused-pallas' (the fused kernel's
        own overlap schedule — the knob is inert there), mirroring the
        minibatch path's honest 'serial'."""
        if mode is not None and mode in dist.PALLAS_MODES:
            self.estep_path_ = "fused-pallas"
            return 0
        p = self._resolve_pipeline(mode)
        self.estep_path_ = "pipelined" if p else "serial"
        return p

    def _resolve_mesh(self) -> Mesh:
        if self.mesh is None:
            self.mesh = make_mesh(model=self.model_shards)
        return self.mesh

    def _tile_k(self, n: int, d: int) -> int:
        """The per-row tile width the scan stages for this model: k for
        the matmul/pallas forms, k*D for 'direct' (its (chunk, k, D)
        difference tensor, ops/assign.py) — the width every chunk
        budget/clamp must be computed against (r5 review)."""
        return self.k * d if self._mode(n, d) == "direct" else self.k

    def _bucket_target(self, n: int) -> int:
        """Padded-row target of the fit-shape bucket (ISSUE 15b): the
        one committed policy in ``parallel.sharding.bucket_target``."""
        from kmeans_tpu.parallel.sharding import bucket_target
        return bucket_target(self.bucket, n)

    def _chunk_for(self, n: int, d: int) -> int:
        data_shards, model_shards = mesh_shape(self._resolve_mesh())
        # Chunk derives from the BUCKETED count, so every size in a
        # bucket commits to one (padded shape, chunk) and therefore one
        # compiled program (ISSUE 15b); bucket=0 leaves n untouched.
        n = self._bucket_target(n)
        return self.chunk_size or choose_chunk_size(
            -(-n // data_shards), max(self._tile_k(n, d), model_shards), d)

    def _eff_chunk(self, ds) -> int:
        """The dataset's chunk, clamped for this model's tile width
        (ShardedDataset.effective_chunk) — guards fits against datasets
        whose load-time k_hint undershot the real k."""
        return ds.effective_chunk(self._tile_k(ds.n, ds.d))

    def _setup(self, n: int, d: int):
        """Resolve mesh + chunk + step functions WITHOUT moving any data."""
        mesh = self._resolve_mesh()
        _, model_shards = mesh_shape(mesh)
        chunk = self._chunk_for(n, d)
        mode = self._mode(n, d)
        step_fn, predict_fn = _get_step_fns(mesh, chunk, mode,
                                            self._resolve_pipeline(mode))
        return mesh, model_shards, step_fn, predict_fn, chunk

    def cache(self, X, sample_weight=None) -> ShardedDataset:
        """Upload X once as a device-resident ShardedDataset (the
        ``rdd.cache()`` analogue, kmeans_spark.py:256).  Pass the result to
        ``fit``/``predict``/``score`` to skip re-uploading on every call.
        Optional ``sample_weight`` (n,) makes every statistic weighted."""
        X = np.asarray(X, dtype=self.dtype)
        if X.ndim != 2:
            raise ValueError(f"X must be 2-D (n, D), got shape {X.shape}")
        return to_device(X, self._resolve_mesh(),
                         self._chunk_for(*X.shape), self.dtype,
                         sample_weight=sample_weight,
                         explicit=self.chunk_size is not None,
                         min_rows=self._bucket_target(X.shape[0]),
                         ingest=self.ingest)

    def _dataset(self, X) -> ShardedDataset:
        """Accept an (n, D) array-like or an already-cached ShardedDataset."""
        if isinstance(X, ShardedDataset):
            if X.mesh is not None and self.mesh is not None \
                    and X.mesh is not self.mesh:
                raise ValueError(
                    "ShardedDataset was placed on a different mesh")
            if X.mesh is not None:
                self.mesh = X.mesh        # adopt the dataset's mesh
            if X.dtype != self.dtype:
                raise ValueError(f"ShardedDataset dtype {X.dtype} != model "
                                 f"dtype {self.dtype}")
            return X
        return self.cache(X)

    def _resolve_overlap(self) -> int:
        """Resolve the ``overlap`` knob (ISSUE 15c): serial on CPU
        (both TTFI terms are small there — keeps the default trace
        shape), overlapped on accelerators, where the staged transfer
        is the dominant term the compile should hide behind."""
        if self.overlap == "auto":
            return 0 if jax.default_backend() == "cpu" else 1
        return int(self.overlap)

    def _prepare(self, X, checkpoint_every: Optional[int] = None,
                 start_iter: int = 0):
        """Place the data; build (or fetch cached) step functions.

        Step functions are built for the dataset's OWN chunk size (its
        padding commits to it), which may differ from what ``_chunk_for``
        would pick for this model's k — clamped to a safe divisor when
        the load-time k_hint undershot this model's k
        (ShardedDataset.effective_chunk).

        Compile/ingest overlap (ISSUE 15c): with ``overlap`` resolved
        on and a HOST-array input (its shapes — and therefore the chunk
        and every program key — are known before any data moves), the
        upload runs in the prefetch producer thread while this thread
        resolves (and, with an AOT store active, loads-or-compiles) the
        programs.  ``checkpoint_every`` is the fit path's hint for
        which device-loop program to pre-warm (None: inference caller,
        step/predict only); ``start_iter`` is the resume offset, so a
        resumed fit warms the segment length it will actually dispatch
        (review finding)."""
        if self._resolve_overlap() and not isinstance(X, ShardedDataset) \
                and jax.process_count() == 1:
            prep = self._prepare_overlapped(X, checkpoint_every,
                                            start_iter)
            if prep is not None:
                return prep
        ds = self._dataset(X)
        mesh = self._resolve_mesh()
        _, model_shards = mesh_shape(mesh)
        mode = self._mode(ds.n, ds.d)
        step_fn, predict_fn = _get_step_fns(mesh, self._eff_chunk(ds), mode,
                                            self._resolve_pipeline(mode))
        return ds, mesh, model_shards, step_fn, predict_fn

    def _prepare_overlapped(self, X, checkpoint_every: Optional[int],
                            start_iter: int = 0):
        """The overlapped fit prelude: one-item prefetch producer stages
        the upload (``cache``; its 'place'/'stage' spans land on the
        producer tid) while the consumer thread resolves the step
        programs and pre-warms the AOT executables for the exact padded
        shapes the fit will dispatch.  Returns None when the input
        isn't a plain (n, D) host array — the serial path then applies
        its own validation — and falls back to the serial key
        derivation if the staged dataset ended up on a different chunk
        (cannot happen for self-cached data; defensive)."""
        from kmeans_tpu.data.prefetch import close_source, prefetch_iter
        X = np.asarray(X, dtype=self.dtype)
        if X.ndim != 2:
            return None
        n, d = X.shape
        mesh = self._resolve_mesh()
        _, model_shards = mesh_shape(mesh)
        mode = self._mode(n, d)
        chunk = self._chunk_for(n, d)
        pipeline = self._resolve_pipeline(mode)
        it = prefetch_iter([X], 1, stage=self.cache)
        try:
            step_fn, predict_fn = _get_step_fns(mesh, chunk, mode,
                                                pipeline)
            self._warm_aot(mesh, model_shards, n, d, chunk, mode,
                           pipeline, checkpoint_every, start_iter,
                           step_fn, predict_fn)
            ds = next(it)
        finally:
            close_source(it)
        if self._eff_chunk(ds) != chunk:  # pragma: no cover — defensive
            step_fn, predict_fn = _get_step_fns(
                mesh, self._eff_chunk(ds), mode, pipeline)
        return ds, mesh, model_shards, step_fn, predict_fn

    def _warm_aot(self, mesh, model_shards: int, n: int, d: int,
                  chunk: int, mode: str, pipeline: int,
                  checkpoint_every: Optional[int], start_iter: int,
                  step_fn, predict_fn) -> None:
        """Pre-resolve AOT executables for the shapes this fit will
        dispatch (ISSUE 15c), overlapping the load-or-compile with the
        staged ingest.  A no-op without an active AOT store (the cache
        entries are then plain jitted functions with no ``warm``).
        Signatures are built from sharding-carrying
        ``ShapeDtypeStruct``s that normalize identically to the real
        arrays (``utils.aot._shard_sig``)."""
        if not (hasattr(step_fn, "warm") or hasattr(predict_fn, "warm")):
            return
        from jax.sharding import NamedSharding
        data_shards, _ = mesh_shape(mesh)
        n_pad = -(-max(self._bucket_target(n), n)
                  // (data_shards * chunk)) * (data_shards * chunk)
        k_pad = -(-self.k // model_shards) * model_shards
        pts = jax.ShapeDtypeStruct(
            (n_pad, d), self.dtype,
            sharding=NamedSharding(mesh, P(DATA_AXIS, None)))
        wts = jax.ShapeDtypeStruct(
            (n_pad,), self.dtype,
            sharding=NamedSharding(mesh, P(DATA_AXIS)))
        cents = jax.ShapeDtypeStruct((k_pad, d), self.dtype,
                                     sharding=dist.centroid_sharding(mesh))
        # Warm only the programs THIS fit will dispatch: the per-
        # iteration step program is host-loop-only (a device-loop fit
        # never calls it), and the assignment program only runs when
        # the fit materializes labels_ — warming an unused program
        # would spend real compile seconds inside the TTFI window.
        if hasattr(step_fn, "warm") and self.host_loop is not False:
            step_fn.warm(pts, wts, cents)
        if hasattr(predict_fn, "warm") and self.compute_labels \
                and self._eager_labels:
            predict_fn.warm(pts, cents,
                            jax.ShapeDtypeStruct((), np.int32))
        # The one-dispatch training program, when this fit will
        # certainly take it (explicit host_loop=False, single restart):
        # the same key/builder AND the same first-segment length the
        # dispatch computes (_fit_on_device: seg is measured from the
        # RESUME offset — warming seg=max_iter for a resumed fit would
        # build a program the fit never dispatches, review finding).
        if self.host_loop is False and self.n_init == 1 \
                and checkpoint_every is not None:
            remaining = self.max_iter - start_iter
            seg = (min(checkpoint_every, remaining) if checkpoint_every
                   else remaining)
            if seg <= 0:
                return
            fit_fn = self._get_fit_fn(mesh, chunk, mode, seg, pipeline)
            if hasattr(fit_fn, "warm"):
                fit_fn.warm(pts, wts, cents,
                            jax.ShapeDtypeStruct((seg,), np.uint32))

    def _put_centroids(self, centroids: np.ndarray, mesh: Mesh,
                       model_shards: int) -> jax.Array:
        padded = dist.pad_centroids(
            centroids.astype(self.dtype), model_shards)
        return jax.device_put(padded, dist.centroid_sharding(mesh))

    def _cents_dev(self, mesh: Mesh, model_shards: int) -> jax.Array:
        """Warm device centroid table (ISSUE 6 satellite): the padded,
        device-placed fitted table, cached on the instance keyed by the
        ``centroids`` array IDENTITY and the mesh — repeated same-model
        inference calls (``predict``/``transform``/``score`` and every
        serving-engine dispatch) reuse ONE placement instead of paying
        a k x D host->device transfer per call.  ``fit`` re-assigns
        ``self.centroids`` with a fresh array every update, so the
        identity check invalidates naturally; in-place mutation of the
        fitted array is not a supported way to change a model (assign a
        new array, or re-fit)."""
        cents = self.centroids
        # getattr: states pickled before this cache existed restore
        # without the attribute.
        cache = getattr(self, "_cents_cache", None)
        if cache is not None and cache[0] is cents and cache[1] is mesh:
            return cache[2]
        dev = self._put_centroids(np.asarray(cents), mesh, model_shards)
        self._cents_cache = (cents, mesh, dev)
        return dev

    def fitted_state(self) -> dict:
        """Serving handle (ISSUE 6): the read-only description the
        serving engine needs to hold this model resident — family
        routing tag, table shape, dtype, whether same-shape instances
        may be PACKED on a batched model axis for one-dispatch
        mixed-model routing, whether inputs need row normalization
        (SphericalKMeans), and the ops the engine may queue for it.
        Raises before ``fit``."""
        if self.centroids is None:
            raise ValueError("Model must be fitted before serving")
        return {
            "family": "kmeans",
            "model_class": type(self).__name__,
            "k": int(self.k),
            "d": int(np.asarray(self.centroids).shape[1]),
            "dtype": np.dtype(self.dtype).str,
            # A two-level model routes through its own coarse/member
            # tables — it cannot ride the packed multi-model dense
            # dispatch (ISSUE 16).
            "stackable": self.assign != "two_level",
            "normalize_inputs": False,
            "assign": ("two_level" if self.assign == "two_level"
                       else "dense"),
            "ops": ("predict", "transform", "score_rows"),
        }

    def _profile_counts(self) -> Optional[np.ndarray]:
        """Training assignment mass per cluster for the quality
        profile's HISTOGRAM — the weighted cluster sizes the fit
        already materialized (MiniBatch overrides with its lifetime
        per-center counts)."""
        return self.cluster_sizes_

    def _profile_rows(self) -> Optional[float]:
        """Weighted row count behind ``inertia_`` — the score-per-row
        denominator and the profile's ``n_rows``.  Deliberately NOT
        ``sum(_profile_counts())``: MiniBatch's histogram mass is its
        lifetime ``_seen`` counts, whose total is rows PROCESSED
        (passes x batch) — dividing the full-dataset-scaled inertia
        estimate by that would deflate the drift reference by the
        number of passes (review finding: a healthy multi-pass
        MiniBatch model would read as permanently drifting)."""
        if self.cluster_sizes_ is None:
            return None
        total = float(np.asarray(self.cluster_sizes_, np.float64).sum())
        return total if total > 0 else None

    def _quality_rows(self, X) -> np.ndarray:
        """Rows in the geometry ``quality_profile(X=...)`` scores
        distances in (SphericalKMeans overrides with its row
        normalization, so the chordal-distance convention matches
        serving ``score_rows``)."""
        return np.asarray(X, np.float64)

    def quality_profile(self, X=None) -> Optional[dict]:
        """Fit-time serving-quality reference profile (ISSUE 14): the
        training assignment histogram, the training score-per-row
        (inertia/row — what the drift monitor's rolling serving SSE is
        compared against), and per-cluster SSE stats where the fit
        computed them (BisectingKMeans' ``cluster_sse_``).

        Sources, in order: an explicit ``X`` computes the profile
        against that data host-side (one ``predict`` pass + numpy
        distances — the reference-window override for a model whose
        training stats were lost); the fitted attrs (fresh after every
        ``fit``); the profile restored from checkpoint metadata (a
        loaded model carries its own reference window — the r10 meta
        block).  Returns None when none is available (e.g. a mid-fit
        segment checkpoint before sizes exist) — serving then runs the
        reference-free detector subset."""
        from kmeans_tpu.obs import drift as obs_drift
        if X is not None:
            if self.centroids is None:
                raise ValueError("Model must be fitted before building "
                                 "a quality profile from data")
            rows = self._quality_rows(X)
            labels = np.asarray(self.predict(X))
            cents = np.asarray(self.centroids, np.float64)
            d2 = np.sum((rows - cents[labels]) ** 2, axis=1)
            per_cluster = np.zeros(self.k, np.float64)
            np.add.at(per_cluster, labels, d2)
            return obs_drift.build_profile(
                family="kmeans", model_class=type(self).__name__,
                k=self.k,
                counts=np.bincount(labels, minlength=self.k),
                score_kind="sse", score_per_row=float(d2.mean()),
                per_cluster_sse=per_cluster,
                n_rows=float(labels.size))
        counts = self._profile_counts()
        if self.centroids is not None and counts is not None:
            inertia = self.inertia_
            rows = self._profile_rows()
            return obs_drift.build_profile(
                family="kmeans", model_class=type(self).__name__,
                k=self.k, counts=counts, score_kind="sse",
                score_per_row=(inertia / rows
                               if inertia is not None and rows
                               else None),
                per_cluster_sse=getattr(self, "cluster_sse_", None),
                n_rows=rows)
        return self._quality_profile

    # ------------------------------------------------------------------- fit

    def fit(self, X, y=None, *, sample_weight=None, resume=False,
            profile_dir: Optional[str] = None, checkpoint_every: int = 0,
            checkpoint_path=None) -> "KMeans":
        """Fit on (n, D) array-like or a cached ShardedDataset.
        Returns self (kmeans_spark.py:239-319).  ``y`` is ignored
        (sklearn estimator-protocol compatibility).

        ``sample_weight`` (n,) weights every statistic (sums, counts, SSE) —
        sklearn-style, beyond the reference.  ``resume=True`` continues from
        the current ``centroids`` / ``iterations_run`` (e.g. after
        ``KMeans.load``) instead of re-initializing — a capability the
        reference lacks (no checkpointing, SURVEY.md §5).  ``resume`` may
        also be a checkpoint PATH: the fitted state is loaded from it
        first — falling back to the last-good ``<path>.prev`` rotation
        (with a warning) when the file is torn/corrupt — and the fit
        continues from there.
        ``profile_dir`` captures a ``jax.profiler`` device trace of the fit
        (the reference's only instrumentation is wall-clock pairs,
        SURVEY.md §5); per-iteration wall times land in ``iter_times_``
        either way.

        ``checkpoint_every=N`` (with ``checkpoint_path``) auto-checkpoints
        the fit every N iterations with an atomic, rotating write
        (``utils.checkpoint.save_state_rotating``): the one-dispatch
        device loop becomes SEGMENTED — ceil(max_iter/N) dispatches with
        a checkpoint between segments — and the host loop checkpoints in
        place.  ``checkpoint_every=0`` (default) is bit-identical to the
        unsegmented fit (the parity oracle pinned by
        ``tests/test_faults.py``), and a kill+``fit(resume=path)`` resume
        at any boundary reproduces the uninterrupted trajectory
        bit-exactly.  Requires ``n_init=1`` (a restart sweep
        re-initializes; a partial sweep has no well-defined resume).
        Observability: ``checkpoint_segments_``.
        """
        from kmeans_tpu.utils import profiling
        resume = self._resolve_resume(resume)
        with profiling.trace(profile_dir):
            self._fit(X, sample_weight=sample_weight, resume=resume,
                      checkpoint_every=checkpoint_every,
                      checkpoint_path=checkpoint_path)
        # Materialize labels_ eagerly (sklearn semantics) — one extra fused
        # assignment pass, after which the device-resident dataset reference
        # is released so fit() never leaves HBM pinned.  Skipped when
        # ``compute_labels=False`` (centroid-only workloads).  Multi-host
        # process-local datasets materialize THIS process's own rows'
        # labels (predict's process-local contract, r3 VERDICT #4);
        # only hand-built global arrays without per-process layout info
        # fall back to an error.
        labelable = not isinstance(self._fit_ds, ShardedDataset) or \
            self._fit_ds.labelable
        if self.compute_labels and self._eager_labels and labelable:
            _ = self.labels_
        else:
            if not labelable:
                self._labels_error = (
                    "labels_ is not available for this multi-host fit "
                    "(unknown per-process layout); call predict on each "
                    "process's local rows")
            # compute_labels=False error state was set by _set_fit_data.
            self._fit_ds = None
        # Terminal completion beat (ISSUE 19): the host-loop engines'
        # last boundary beat is "iteration"/"checkpoint", which a LIVE
        # fleet-status read (explicit --now) would eventually flag as a
        # stall — this beat marks the fit DONE (obs.fleet
        # TERMINAL_PHASES), so a finished host reads finished, not
        # silent.
        obs_note_progress(self, phase="finished")
        return self

    def _set_fit_data(self, ds) -> None:
        """Point the lazy ``labels_`` machinery at new training data,
        clearing any stale error state a previous ``fit_stream`` left
        (ADVICE r1: a successful fit after fit_stream must not keep
        raising the 'not materialized' error).  ``compute_labels=False``
        opts the whole machinery out — sklearn's ``MiniBatchKMeans``
        semantics, uniformly across ``fit`` and ``partial_fit``."""
        if self.compute_labels:
            self._fit_ds, self._labels_cache = ds, None
            self._labels_error = None
        else:
            self._fit_ds, self._labels_cache = None, None
            self._labels_error = (
                "labels_ was not materialized because "
                "compute_labels=False; call predict(X) instead")

    def _apply_sample_weight(self, X, sample_weight):
        """Fold an explicit (n,) sample_weight into a fresh cached dataset
        (weights can only be attached at caching time)."""
        if sample_weight is None:
            return X
        if isinstance(X, ShardedDataset):
            raise ValueError("pass sample_weight when caching the "
                             "dataset, not on a pre-built ShardedDataset")
        return self.cache(X, sample_weight=sample_weight)

    def _auto_n_init(self) -> int:
        """``n_init='auto'`` resolution for random/callable inits.

        sklearn's rule: KMeans runs 10 full restarts; MiniBatchKMeans
        overrides this with 3 (it only SCORES candidate inits on one
        batch rather than running full restarts, so fewer draws suffice).
        Called from ``__init__`` — overrides must not touch instance
        state set after ``n_init``."""
        return 10

    def _restart_seeds(self) -> list:
        """Per-restart init seeds.  Restart 0 is ``seed`` itself (n_init=1
        stays bit-identical to the reference trajectory); the rest are
        SeedSequence-derived.  An explicit (k, D) init array makes every
        restart identical, so it collapses to one (sklearn does the same)."""
        if not isinstance(self.init, str) and not callable(self.init):
            return [self.seed]
        extra = np.random.SeedSequence(self.seed).generate_state(
            self.n_init - 1) if self.n_init > 1 else []
        return [self.seed] + [int(s) for s in extra]

    def _init_centroids(self, ds, seed: int,
                        k: Optional[int] = None) -> np.ndarray:
        # Forgy/k-means++/explicit init (kmeans_spark.py:58-82, :259).
        # ``k`` overrides ``self.k`` for sweep members — the SAME call a
        # standalone fit at that k makes, so member inits match their
        # standalone oracles exactly.
        centroids = resolve_init(self.init, ds, self.k if k is None else k,
                                 seed, validate=self._validate_init,
                                 cap=self.init_cap)
        return self._postprocess_centroids(
            np.asarray(centroids, dtype=np.float64)).astype(self.dtype)

    def _final_inertia(self, ds, mesh, model_shards, step_fn) -> float:
        """True SSE of the CURRENT centroids — one fused pass (sklearn's
        restart-selection rule; ``sse_history[-1]`` lags one iteration by
        reference semantics, kmeans_spark.py:279)."""
        stats = step_fn(ds.points, ds.weights, self._put_centroids(
            np.asarray(self.centroids), mesh, model_shards))
        return float(stats.sse)

    def _resolve_host_loop(self, ds, mesh, model_shards, step_fn) -> bool:
        """Resolve ``host_loop='auto'`` for this fit (r4 VERDICT #6).

        Explicit True/False pass through untouched (zero overhead).
        'auto' behaves like the host loop — the reference's per-iteration
        driver semantics — unless ONE measurement at fit start shows
        dispatch latency dominating: RTT > 5 ms absolute AND > 25% of a
        measured step (on a tunneled chip the RTT is ~70-100 ms,
        docs/PERFORMANCE.md).  Then, when the device loop is
        semantically interchangeable for this estimator — base-class
        Lloyd hooks, or a hook with a declared device equivalent
        (SphericalKMeans' sphere projection since ISSUE 2), verbose=False
        (per-iteration prints are host-loop-only), single process (the
        decision must not diverge across SPMD processes) — the fit
        switches to the one-dispatch device loop, whose trajectory
        parity with the host loop is pinned to 1e-9
        (tests/test_device_loop.py); otherwise it stays host-side and a
        one-time :class:`DispatchLatencyHint` says where the wall time
        goes.  The 5 ms absolute floor keeps low-latency platforms
        (local CPU/TPU, µs dispatch) deterministically on the host path.

        POLICY TWIN: ``MiniBatchKMeans._resolve_host_loop_mb`` applies
        the same explicit-pass-through / process-count / RTT-floor /
        hook-guard policy to the mini-batch engine (no step measurement
        — its batch step is sub-ms by construction).  A change to the
        policy here almost certainly belongs there too.
        """
        if getattr(self, "_force_host_loop", False):
            # A large-k step is swapped in (ISSUE 16): both the
            # k-sharded and two-level steps exist only as per-iteration
            # host-loop programs (explicit host_loop=False was already
            # rejected in _route_large_k, with the reason).
            return True
        if self.host_loop is True or self.host_loop is False:
            return self.host_loop
        if jax.process_count() > 1:
            return True
        # RTT first: on fast platforms (µs dispatch) the 5 ms floor
        # decides alone, and no step is ever timed — a default-config fit
        # there pays only one cached trivial-op round trip (review r5).
        rtt = _dispatch_rtt(mesh)
        self.auto_rtt_ = rtt        # observability: the dry-run artifact
        if rtt <= 5e-3:
            return True
        key = (mesh, self._eff_chunk(ds), self._mode(ds.n, ds.d),
               self.k, np.dtype(self.dtype).str, tuple(ds.points.shape),
               "autoloop")

        def measure_step():
            cents = self._put_centroids(
                np.zeros((self.k, ds.d), self.dtype), mesh, model_shards)
            stats = step_fn(ds.points, ds.weights, cents)
            float(stats.sse)                        # compile + warm
            t0 = time.perf_counter()
            float(step_fn(ds.points, ds.weights, cents).sse)
            return time.perf_counter() - t0

        # The wasted-work accounting of this measurement: step_fn is the
        # program the HOST loop runs, so on the stay-host outcomes the
        # compile+2 dispatches are pure warmup; only a switch discards
        # them (once per shape key) — accepted, the 25% rule needs a
        # measured denominator.
        # lint: ok(cache-key) — measurement cache: a miss only re-measures
        # one step, it can never serve a wrong compiled program (the key
        # spans every static the probe reads).
        step_total = _AUTO_CACHE.get_or_create(key, measure_step)
        frac = rtt / max(step_total, 1e-12)
        if frac <= 0.25:
            return True
        # A postprocess hook blocks the switch UNLESS the class declares
        # (and the hook is tagged with) an exact device equivalent — how
        # SphericalKMeans' sphere projection rides the one-dispatch loop
        # (parallel.distributed._project_centroids); a further override
        # in a user subclass loses the tag and stays host-side.
        pp = type(self)._postprocess_centroids
        pp_device_ok = (
            pp is KMeans._postprocess_centroids
            or (self._device_project is not None
                and getattr(pp, "_device_equivalent", None)
                == self._device_project))
        base_hooks = (
            pp_device_ok
            and type(self)._handle_empty is KMeans._handle_empty
            and type(self)._finish_lloyd_iteration
            is KMeans._finish_lloyd_iteration)
        # 'resample' with a host-resident dataset draws replacements with
        # the HOST rng (bit-identical to r1); the device loop draws with
        # the on-device Gumbel engine.  Both are uniform, but switching
        # would make results platform-dependent — only hostless datasets
        # (where BOTH loops use the Gumbel engine, parity pinned by
        # tests/test_device_loop.py) may switch under 'resample'.
        resample_safe = (self.empty_cluster != "resample"
                         or getattr(ds, "host", None) is None)
        if base_hooks and resample_safe and not self.verbose:
            _hint_once(
                "auto_switched",
                f"host_loop='auto': dispatch RTT {rtt*1e3:.0f} ms is "
                f"{frac:.0%} of a measured step on this platform — running "
                f"the whole fit as one device dispatch (host_loop=False "
                f"semantics; pass host_loop=True to force the per-iteration "
                f"host loop)")
            return False
        if not base_hooks:
            _hint_once(
                "auto_hint_hooks",
                f"host_loop='auto': dispatch RTT {rtt*1e3:.0f} ms is "
                f"{frac:.0%} of a measured step on this platform, but "
                f"{type(self).__name__}'s host-side hooks require the "
                f"per-iteration host loop — that latency is unavoidable "
                f"for this estimator here")
        elif not resample_safe:
            _hint_once(
                "auto_hint_resample",
                f"host_loop='auto': dispatch RTT {rtt*1e3:.0f} ms is "
                f"{frac:.0%} of a measured step on this platform, but "
                f"empty_cluster='resample' on a host-resident dataset "
                f"draws replacements host-side, so 'auto' stays on the "
                f"host loop; empty_cluster='keep'/'farthest' lets it "
                f"switch, and explicit host_loop=False switches too but "
                f"moves the resample draw to the on-device engine "
                f"(documented divergence)")
        else:
            _hint_once(
                "auto_hint",
                f"host_loop='auto': dispatch RTT {rtt*1e3:.0f} ms is "
                f"{frac:.0%} of a measured step on this platform, so most "
                f"of each iteration's wall time is host dispatch; set "
                f"host_loop=False (one-dispatch fit) or verbose=False "
                f"(lets 'auto' switch itself) to reclaim it")
        return True

    # ------------------------------------------------------------ massive-k

    def _resolve_large_k(self, ds, data_shards, model_shards, chunk):
        """Resolve the ``k_shard``/``assign`` knobs for this fit's shape
        (ISSUE 16).  Returns ``(k_shard, assign)`` as concrete values.

        'auto' consults the r16 HBM planner: the DENSE plan at this
        (n, d, k, mesh, chunk) is compared against the device's free
        bytes (80% headroom — staging buffers and allocator
        fragmentation share the arena).  A backend that reports no
        allocator stats (CPU) resolves both knobs to their bit-exact
        dense oracles, so every committed parity shape keeps the dense
        trajectory.  Sharding the table is the first resort past the
        wall (exact assignment, no routing error surface); two-level
        only engages when the mesh has no TP axis to shard over.
        Explicit values force the path and are validated here, before
        any data-dependent work."""
        ks, asg = self.k_shard, self.assign
        if ks == "auto" or asg == "auto":
            from kmeans_tpu.obs import memory as _mem
            info = _mem.device_memory_info()
            fits = True
            if info.get("available"):
                plan = _mem.plan_fit(
                    "kmeans", ds.n, ds.d, self.k,
                    data_shards=data_shards, model_shards=model_shards,
                    dtype=str(self.dtype), chunk=chunk,
                    pipeline=self._resolve_pipeline(
                        self._mode(ds.n, ds.d)), k_shard=0)
                fits = (plan["predicted_peak_bytes"]
                        <= 0.8 * info["bytes_free"])
            if ks == "auto":
                ks = 0 if (fits or model_shards <= 1) else model_shards
            if asg == "auto":
                asg = "dense" if (fits or model_shards > 1) \
                    else "two_level"
        ks = int(ks)
        if ks:
            if model_shards <= 1:
                raise ValueError(
                    f"k_shard={ks} requires a model-sharded mesh "
                    f"(model_shards > 1); this mesh has no TP axis — "
                    f"use k_shard=0, or build the mesh with model= "
                    f"shards")
            if ks != model_shards:
                raise ValueError(
                    f"k_shard={ks} does not match the mesh's "
                    f"model_shards={model_shards}: the table shards on "
                    f"the EXISTING TP axis, so the only supported "
                    f"values are 0 (the dense oracle) and "
                    f"{model_shards}")
        if asg == "two_level" and model_shards != 1:
            raise ValueError(
                "assign='two_level' composes with data parallelism "
                "only (model_shards == 1); on a TP mesh use k_shard "
                "instead — the two tiers address the same memory wall "
                "and do not stack")
        return ks, asg

    def _route_large_k(self, ds, mesh, model_shards, step_fn):
        """Swap the dense step for the k-sharded or two-level one per
        the resolved knobs; returns the step function the fit loops on
        (the dense ``step_fn`` untouched on the oracle path).

        Both large-k steps are per-iteration host-loop programs (the
        two-level member tables rebuild host-side each iteration; the
        sharded step's stats gather transparently into the host
        M-step's ``np.asarray``), so the swap pins the host loop —
        explicit ``host_loop=False`` on a large-k path is rejected
        with the reason rather than silently overridden."""
        self._force_host_loop = False
        self._two_level_route_ = None
        data_shards, _ = mesh_shape(mesh)
        chunk = self._eff_chunk(ds)
        ks, asg = self._resolve_large_k(ds, data_shards, model_shards,
                                        chunk)
        self.k_shard_resolved_, self.assign_resolved_ = ks, asg
        if not ks and asg == "dense":
            return step_fn
        if self.host_loop is False:
            raise ValueError(
                f"host_loop=False cannot run the large-k paths "
                f"(resolved k_shard={ks}, assign={asg!r}): they are "
                f"per-iteration host-loop programs; drop "
                f"host_loop=False, or force the dense oracle "
                f"(k_shard=0, assign='dense')")
        self._force_host_loop = True
        mode = self._mode(ds.n, ds.d)
        if ks:
            pipeline = self._resolve_pipeline(mode)
            return _STEP_CACHE.get_or_create(
                (mesh, chunk, mode, pipeline, "kshard"),
                lambda: dist.make_kshard_step_fn(
                    mesh, chunk_size=chunk, mode=mode,
                    pipeline=pipeline))
        return self._two_level_step(ds, mesh, chunk, mode)

    def _two_level_params(self):
        """(coarse cell count C, probes-per-row nprobe) for this k —
        √k-ish cells by default (the tentpole's sizing), an eighth of
        the cells probed.  ``nprobe >= C`` probes every cell: exact
        dense coverage, the parity-oracle configuration."""
        C = self.coarse_cells or max(2, int(round(np.sqrt(self.k))))
        C = min(int(C), self.k)
        npb = self.nprobe or max(1, -(-C // 8))
        return C, min(int(npb), C)

    def _train_coarse(self, cents: np.ndarray, C: int) -> np.ndarray:
        """Coarse quantizer: dense k-means over the FINE TABLE (k rows)
        — the existing dense path at √k scale, exactly as the tentpole
        specifies.  IVF discipline: trained once per fit from the
        initial fine table, then FIXED; only the member lists refresh
        per iteration (``_build_members``)."""
        km = KMeans(k=C, max_iter=25, tolerance=1e-4, seed=self.seed,
                    compute_sse=False, init="k-means++",
                    compute_labels=False, empty_cluster="keep",
                    dtype=self.dtype, mesh=self.mesh, host_loop=True,
                    assign="dense", k_shard=0, verbose=False)
        km._eager_labels = False
        km._validate_init = False
        km.fit(np.asarray(cents, np.float64).astype(self.dtype))
        return np.asarray(km.centroids, np.float64)

    def _build_members(self, cents: np.ndarray,
                       coarse: np.ndarray) -> np.ndarray:
        """(C, L) member lists: fine centroid j files under its nearest
        coarse cell.  L is the LARGEST cell size bucketed on the
        candidate ladder (``parallel.sharding.bucket_candidates`` — the
        r19 rung geometry at a 32-row floor), so cell-size drift across
        iterations lands on a handful of compiled widths instead of
        one per iteration; ``k`` (the sentinel row index) pads the
        tails.  Member lists are sorted ascending, which makes the
        device kernel's lexicographic (distance, index) tie-break
        reproduce dense argmin's first-lowest-index rule.  An empty
        cell carries its nearest fine centroid in slot 0, so a probe
        routed there still returns a valid candidate."""
        from kmeans_tpu.parallel.sharding import bucket_candidates
        k, C = cents.shape[0], coarse.shape[0]
        d2 = (np.sum(cents ** 2, axis=1)[:, None]
              - 2.0 * cents @ coarse.T
              + np.sum(coarse ** 2, axis=1)[None, :])
        owner = np.argmin(d2, axis=1)
        lists = [np.flatnonzero(owner == c) for c in range(C)]
        for c in range(C):
            if lists[c].size == 0:
                lists[c] = np.array([int(np.argmin(d2[:, c]))])
        L = bucket_candidates(max(lst.size for lst in lists))
        members = np.full((C, L), k, np.int32)
        for c, lst in enumerate(lists):
            members[c, : lst.size] = np.sort(lst).astype(np.int32)
        return members

    def _two_level_step(self, ds, mesh, chunk, mode):
        """Host wrapper with the dense step's calling convention
        (``step(points, weights, cents_dev) -> StepStats``): trains the
        coarse quantizer on first call, rebuilds the member lists from
        the CURRENT fine table each iteration, and dispatches the
        compiled two-level step for the bucketed member width.  SSE
        stays exact by construction — the fine search recomputes exact
        distances over the candidate set (parallel.distributed.
        make_two_level_step_fn)."""
        C, npb = self._two_level_params()
        state = {"coarse": None}

        def step(points, weights, cents_dev):
            cents = np.asarray(cents_dev, np.float64)[: self.k]
            if state["coarse"] is None:
                state["coarse"] = self._train_coarse(cents, C)
            coarse = state["coarse"]
            members = self._build_members(cents, coarse)
            self._two_level_route_ = (coarse, members)
            fn = _STEP_CACHE.get_or_create(
                (mesh, chunk, mode, C, members.shape[1], npb,
                 "twolevel"),
                lambda: dist.make_two_level_step_fn(
                    mesh, chunk_size=chunk, nprobe=npb, mode=mode))
            return fn(points, weights, cents_dev,
                      coarse.astype(self.dtype), members)

        return step

    def _two_level_tables(self):
        """(coarse, members) for the CURRENT fitted table, cached by
        centroid-array identity (the ``_cents_dev`` discipline).
        Reuses the fit's coarse cells when this process trained them; a
        model that never ran a two-level fit here (loaded checkpoint,
        knob flipped post fit) trains the coarse quantizer once, now."""
        cache = self._route_cache
        if cache is not None and cache[0] is self.centroids:
            return cache[1], cache[2]
        C, _ = self._two_level_params()
        cents = np.asarray(self.centroids, np.float64)
        route = self._two_level_route_
        coarse = (route[0] if route is not None
                  and route[0].shape[0] == C
                  else self._train_coarse(cents, C))
        members = self._build_members(cents, coarse)
        self._route_cache = (self.centroids, coarse, members)
        return coarse, members

    def _predict_two_level_labels(self, ds, mesh, cents_dev):
        """Two-level assignment pass (explicit ``assign='two_level'``
        predict route): same coarse->candidates->exact-recompute kernel
        as the fit step, labels only."""
        coarse, members = self._two_level_tables()
        C, npb = self._two_level_params()
        chunk, mode = self._eff_chunk(ds), self._mode(ds.n, ds.d)
        fn = _STEP_CACHE.get_or_create(
            (mesh, chunk, mode, C, members.shape[1], npb,
             "twolevel-predict"),
            lambda: dist.make_two_level_predict_fn(
                mesh, chunk_size=chunk, nprobe=npb, mode=mode))
        return fn(ds.points, cents_dev, coarse.astype(self.dtype),
                  members)

    def _fit(self, X, *, sample_weight, resume, checkpoint_every: int = 0,
             checkpoint_path=None) -> "KMeans":
        # Multi-host: only process 0 narrates (every host computes the same
        # replicated statistics, so logs would be identical k-fold spam).
        checkpoint_every = self._check_ckpt(checkpoint_every,
                                            checkpoint_path)
        log = IterationLogger(self.verbose and jax.process_index() == 0)
        X = self._apply_sample_weight(X, sample_weight)
        ds, mesh, model_shards, step_fn, _ = self._prepare(
            X, checkpoint_every=checkpoint_every,
            start_iter=(self.iterations_run
                        if resume and self.centroids is not None else 0))
        self._set_fit_data(ds)                        # feeds lazy labels_
        # Fleet prelude (ISSUE 13): per-host row count for the heartbeat
        # rows_per_sec derivation, and the fit-start clock anchor the
        # merged-timeline alignment keys on (a true no-op when obs=0).
        self._progress_rows = ds.local_rows if ds.local_rows else ds.n
        fleet_barrier("fit-start")
        self.io_retries_used_ = getattr(
            getattr(ds, "io_stats", None), "retries_used", 0)
        log.startup(self.k, self.max_iter, self.tolerance, self.compute_sse)
        self.best_restart_ = 0
        self.restart_inertias_ = None
        self._note_estep_path(self._mode(ds.n, ds.d))
        self.bf16_guard_corrected_rows_ = None
        # Massive-k routing (ISSUE 16): on the resolved large-k paths
        # the dense step is swapped for the k-sharded or two-level one
        # (host-loop programs with the same calling convention); the
        # dense oracle path returns step_fn untouched.
        step_fn = self._route_large_k(ds, mesh, model_shards, step_fn)

        if resume and self.centroids is not None:
            centroids = np.asarray(self.centroids, dtype=self.dtype)
            return self._run_restart(ds, mesh, model_shards, step_fn,
                                     centroids, self.iterations_run,
                                     self.seed, log,
                                     checkpoint_every, checkpoint_path)

        seeds = self._restart_seeds()

        # Batched restarts: one dispatch for the whole n_init sweep
        # (composes with model-axis centroid sharding, r1 VERDICT #3).
        if len(seeds) > 1 and \
                not self._resolve_host_loop(ds, mesh, model_shards, step_fn):
            return self._fit_on_device_multi(ds, seeds, mesh, log)

        best = None
        inertias = []
        for r, seed in enumerate(seeds):
            centroids = self._init_centroids(ds, seed)
            self.sse_history = []
            self.iterations_run = 0
            self.iter_times_ = []
            self._run_restart(ds, mesh, model_shards, step_fn, centroids,
                              0, seed, log, checkpoint_every,
                              checkpoint_path)
            if len(seeds) == 1:
                return self
            inertia = self._final_inertia(ds, mesh, model_shards, step_fn)
            log.restart(r, len(seeds), inertia)
            inertias.append(inertia)
            if best is None or inertia < best["inertia"]:
                best = {"inertia": inertia, "restart": r,
                        "centroids": self.centroids,
                        "sse_history": self.sse_history,
                        "iterations_run": self.iterations_run,
                        "cluster_sizes_": self.cluster_sizes_,
                        "iter_times_": self.iter_times_}
        self.centroids = best["centroids"]
        self.sse_history = best["sse_history"]
        self.iterations_run = best["iterations_run"]
        self.cluster_sizes_ = best["cluster_sizes_"]
        self.iter_times_ = best["iter_times_"]
        self.best_restart_ = best["restart"]
        self.restart_inertias_ = np.asarray(inertias, dtype=np.float64)
        return self

    def fit_stream(self, make_blocks, *, d: Optional[int] = None,
                   resume=False, prefetch: int = 2,
                   checkpoint_every: int = 0, checkpoint_path=None,
                   io_retries: int = 0, io_backoff: float = 0.05,
                   on_nonfinite: str = "error") -> "KMeans":
        """EXACT full-batch Lloyd over data larger than device memory.

        ``make_blocks()`` returns a fresh iterable of (n_i, D) host blocks;
        it is re-invoked every iteration (one epoch of blocks = one Lloyd
        iteration).  Each block streams through the SAME fused SPMD step as
        ``fit`` and the dense (k, D+1) statistics are summed across blocks
        in float64 on the host, so — unlike :class:`MiniBatchKMeans`'s
        sampled approximation — the trajectory is identical (up to fp
        summation order) to an in-memory fit of the concatenated blocks.
        On TPU hardware that comparability needs exact f32 dots
        (``jax_default_matmul_precision='highest'``, the README
        troubleshooting knob): under default bf16-rate products a single
        borderline assignment flip diverges the two trajectories
        chaotically — measured r4, winner selection flipped at default
        precision and matched exactly at 'highest'.
        This is the capability the reference gets from Spark's
        disk-spillable RDDs (``README.md:71`` advises repartitioning under
        memory pressure); here only one block is device-resident at a time.

        Initialization draws over the FULL stream (r3 VERDICT #3 — the
        reference's ``takeSample`` draws over the whole distributed
        dataset, kmeans_spark.py:72, not its first partition):
        ``'forgy'``/``'random'`` run one reservoir pass (a uniform
        seeded k-row sample of the entire stream — exactly the
        takeSample capability); ``'k-means++'``/``'k-means||'`` run a
        streamed kmeans|| (``models.init.streamed_kmeans_parallel_init``
        — exact streaming k-means++ would cost k passes, so the
        O(rounds)-pass scalable variant serves both names, as sklearn's
        large-k paths do).  A CALLABLE init receives a seeded uniform
        reservoir sample of the whole stream (up to ~32k
        positive-weight rows, randomly permuted —
        ``models.init.streamed_init_sample``), so custom inits get the
        same full-stream contract as the built-ins; pass an explicit
        (k, D) array for exact control.

        ``n_init > 1`` runs R restarts INTERLEAVED: every epoch computes
        all R restarts' statistics from one shared pass over the stream
        (R x compute, 1x IO), converged restarts drop out, and the
        winner is the restart whose final centroids score the lowest
        inertia (one extra scoring epoch) — the same selection rule as
        the in-memory ``fit``.  ``resume=True`` continues from the
        current centroids/``iterations_run`` (single-restart only).

        All three ``empty_cluster`` policies work: ``'resample'`` (the
        reference's live policy) draws replacements from a seeded
        per-epoch, per-restart RESERVOIR — a uniform without-replacement
        sample of up to k rows maintained across the epoch's blocks
        (Algorithm R), so no global row access is ever needed (r1
        VERDICT #6).  Divergence bound vs the in-memory fit (r2 VERDICT
        #8): iterations WITHOUT empties match the in-memory trajectory
        exactly (identical statistics, same host finish); an
        empty-cluster refill draws from the reservoir instead of the
        in-memory engine's global row draw — both uniform over the data
        (chi-squared-tested, tests/test_stream.py) but different
        streams, so post-refill trajectories are equal in distribution,
        not bitwise.  ``d`` pre-declares the feature count (otherwise
        peeked from the first block).

        Weighted streams: items may be ``(block, weights)`` pairs —
        weights fold into every statistic exactly like ``fit``'s
        ``sample_weight`` (streamed inits draw uniformly over
        POSITIVE-weight rows, the in-memory rule; the streamed kmeans||
        weights its D² mass).

        ``prefetch`` (default 2): each epoch runs through a bounded
        background producer (``data.prefetch.prefetch_iter``) that
        reads/decodes block i+1 and starts its ``jax.device_put`` onto
        the data-mesh sharding while block i's step computes — on
        IO/transfer-bound streams the epoch cost drops toward
        max(IO, compute) instead of their sum (measured numbers in
        docs/PERFORMANCE.md "Streaming pipeline").  ``prefetch=0`` is
        the synchronous path; the trajectory is BIT-IDENTICAL either
        way (only where the work happens moves, never its order —
        pinned by tests/test_prefetch.py).  Device residency grows from
        1 to at most ``prefetch + 2`` blocks.

        Fault tolerance (ISSUE 4): ``checkpoint_every=N`` (+
        ``checkpoint_path``) writes a rotating atomic checkpoint every N
        epochs (single-restart only), and ``resume`` may be a checkpoint
        path (``.prev`` corrupt fallback included).  ``io_retries``/
        ``io_backoff`` retry transient (``OSError``) block reads with a
        deterministic exponential backoff by re-invoking ``make_blocks``
        and fast-forwarding — the FRESH-iterable contract the streamed
        fit already requires — so a recovered epoch is bit-identical.
        ``on_nonfinite='error'`` (default) raises naming the first
        non-finite streamed block; ``'skip'`` quarantines bad blocks
        (every pass sees the same cleaned stream, so the statistics stay
        consistent).  Observability: ``io_retries_used_``,
        ``blocks_skipped_``, ``checkpoint_segments_``.
        """
        from kmeans_tpu.data.io import IOStats, resilient_blocks
        from kmeans_tpu.data.prefetch import (check_prefetch, close_source,
                                              prefetch_iter)
        from kmeans_tpu.parallel.sharding import shard_points
        from kmeans_tpu.models.init import (STREAM_INITIALIZERS,
                                            _split_block,
                                            streamed_init_sample)
        if self.k_shard not in ("auto", 0) or self.assign == "two_level":
            raise ValueError(
                "fit_stream runs the dense assignment path only (its "
                "per-block statistics already bound device memory by "
                "the block size); drop the explicit k_shard/assign "
                "large-k knobs, or use fit on an in-memory dataset")
        prefetch = check_prefetch(prefetch)
        checkpoint_every = self._check_ckpt(checkpoint_every,
                                            checkpoint_path)
        resume = self._resolve_resume(resume)
        io_stats = IOStats()
        make_blocks = resilient_blocks(
            make_blocks, io_retries=io_retries, io_backoff=io_backoff,
            on_nonfinite=on_nonfinite, stats=io_stats)
        self.checkpoint_segments_ = 0 if checkpoint_every else None
        log = IterationLogger(self.verbose and jax.process_index() == 0)
        muted = IterationLogger(False)
        log.startup(self.k, self.max_iter, self.tolerance, self.compute_sse)
        self._note_estep_path()       # provisional; re-noted with the
        self.bf16_guard_corrected_rows_ = None   # first block's real mode

        explicit_init = not isinstance(self.init, str) \
            and not callable(self.init)
        if d is None:
            # close_source: a prefetching source (e.g.
            # iter_npy_blocks(prefetch=N)) must have its producer
            # thread reaped when the peek abandons it after one item.
            peek_it = iter(make_blocks())
            try:
                item = next(peek_it)
                peek = np.asarray(item[0] if isinstance(item, tuple)
                                  else item, dtype=self.dtype)
            finally:
                close_source(peek_it)
            if peek.ndim != 2:
                raise ValueError(f"blocks must be 2-D (m, D), got shape "
                                 f"{peek.shape}")
            d = peek.shape[1]
            del peek, item

        resume = bool(resume) and self.centroids is not None
        if resume and self.n_init != 1:
            raise ValueError("fit_stream resume requires n_init == 1")

        # ---- per-restart initial centroids (float64 working frame)
        if resume:
            seeds = [self.seed]
            cents_list = [np.asarray(self.centroids, dtype=self.dtype)]
            start_iter = self.iterations_run
        else:
            start_iter = 0
            seeds = self._restart_seeds()
            if explicit_init:
                arr = resolve_init(self.init, np.empty((0, d), self.dtype),
                                   self.k, self.seed)
                raw = [arr]
            elif callable(self.init):
                # Full-stream contract for custom inits (r4 VERDICT #8):
                # each restart's callable receives a seeded uniform
                # reservoir sample of the WHOLE stream (positive-weight
                # rows, randomly permuted) — the same takeSample
                # capability the built-in streamed inits use — instead
                # of just the first block.
                samples, _ = streamed_init_sample(make_blocks, self.k,
                                                  seeds, d, self.dtype)
                raw = [np.asarray(self.init(sample, self.k, s))
                       for sample, s in zip(samples, seeds)]
            else:
                try:
                    stream_fn = STREAM_INITIALIZERS[self.init]
                except KeyError:
                    raise ValueError(
                        f"unknown init strategy: {self.init!r}; options: "
                        f"{sorted(STREAM_INITIALIZERS)}") from None
                raw, _ = stream_fn(make_blocks, self.k, seeds, d,
                                   self.dtype)
            cents_list = [self._postprocess_centroids(
                np.asarray(c, np.float64)).astype(self.dtype)
                for c in raw]

        mesh = self._resolve_mesh()
        _, model_shards = mesh_shape(mesh)
        # Fleet prelude (ISSUE 13): the clock anchor; the per-epoch row
        # count lands once the first epoch has measured the stream.
        self._progress_rows = None
        fleet_barrier("fit-stream-start")

        class _StreamMeta:
            """_handle_empty's dataset view of a stream: replacement rows
            come from the current epoch's seeded reservoir (None under
            'keep'/'farthest', where no sampling can happen)."""
            def __init__(self, d):
                self.d = d
                self.reservoir: Optional[_EpochReservoir] = None

            def sample_positive_rows(self, m, seed_seq):
                if self.reservoir is None:
                    return np.empty((0, self.d))
                return self.reservoir.sample(
                    m, np.random.default_rng(seed_seq))

        class _RestartState:
            def __init__(self, seed, cents):
                self.seed = seed
                self.cents = cents
                self.sse_history = []
                self.iter_times = []
                self.done = False
                self.iters = 0
                self.sizes = None
                self.meta = _StreamMeta(d)

        states = [_RestartState(s, c) for s, c in zip(seeds, cents_list)]
        if resume:
            # Continue the existing histories and bookkeeping (same
            # contract as fit's resume): the restart state adopts the
            # estimator's lists AND its counters, so a resume with an
            # already-exhausted iteration budget is a no-op instead of
            # resetting iterations_run/cluster_sizes_ (review r4).
            states[0].sse_history = self.sse_history
            states[0].iter_times = self.iter_times_
            states[0].iters = self.iterations_run
            states[0].sizes = self.cluster_sizes_
        R = len(states)
        want_reservoir = self.empty_cluster == "resample"
        acc = np.float64
        step_fn = chunk = mode = None              # sized from first block

        def stage(item):
            """Producer-side share of one block (with ``prefetch > 0``
            this runs in the background thread): decode + pad +
            ``device_put`` onto the data-mesh sharding — block i+1's IO
            and transfer overlap block i's step.  Chunk/mode are sized
            from the FIRST real block; only the producer writes them,
            and the queue hand-off publishes them before the staged
            block reaches the consumer."""
            nonlocal chunk, mode
            block, bw = _split_block(item, d, self.dtype)
            if chunk is None:                      # chunk from a REAL block
                chunk = self._chunk_for(block.shape[0], d)
                mode = self._mode(block.shape[0], d)
            pts, w = shard_points(block, mesh, chunk, sample_weight=bw)
            return block, bw, pts, w

        def epoch(active, cents_dev, iteration, score_only=False):
            """One pass over the stream accumulating every active
            restart's dense statistics (shared IO, R x compute)."""
            nonlocal step_fn
            sums = [np.zeros((self.k, d), acc) for _ in active]
            counts = [np.zeros((self.k,), acc) for _ in active]
            sse = [0.0] * len(active)
            far = [(-1.0, None)] * len(active)
            n_seen = 0
            # contextlib.closing: a consumer-side error mid-epoch must
            # join the producer thread deterministically (the thread's
            # target holds a reference cycle to the iterator, so GC
            # alone reaps it too late).
            with contextlib.closing(prefetch_iter(make_blocks(),
                                                  prefetch, stage)) as it:
                for block, bw, pts, w in it:
                    if step_fn is None:
                        step_fn, _ = _get_step_fns(
                            mesh, chunk, mode, self._note_estep_path(mode))
                    if want_reservoir and not score_only:
                        # Uniform over POSITIVE-weight rows — the in-memory
                        # 'resample' engine's rule (zero-weight rows must
                        # never seed a centroid).  Offers stay CONSUMER-side
                        # in block order: the reservoir draw stream (and so
                        # the trajectory) is prefetch-invariant.
                        offer = block if bw is None else block[bw > 0]
                        for st_r in active:
                            st_r.meta.reservoir.offer(offer)
                    n_seen += block.shape[0]
                    # Dispatch every restart's step BEFORE any transfer, then
                    # ONE combined device_get per restart — each separate
                    # np.asarray pays a full host round trip on tunneled
                    # platforms, and an early transfer would also serialize
                    # the remaining restarts' dispatches behind it.
                    # The 'dispatch' span covers dispatch + transfer
                    # (the device_get is the sync point; a span around
                    # the async dispatch alone would time queueing).
                    with obs_trace.span("dispatch", tag="stream/block",
                                        restarts=len(active)):
                        outs = [step_fn(pts, w, cents_dev[i])
                                for i in range(len(active))]
                        for i, st in enumerate(outs):
                            s_h, c_h, sse_h, fd_h, fp_h = jax.device_get(
                                (st.sums, st.counts, st.sse,
                                 st.farthest_dist, st.farthest_point))
                            sums[i] += np.asarray(s_h, dtype=acc)[: self.k]
                            counts[i] += np.asarray(c_h,
                                                    dtype=acc)[: self.k]
                            sse[i] += float(sse_h)
                            if float(fd_h) > far[i][0]:
                                far[i] = (float(fd_h),
                                          np.asarray(fp_h, dtype=acc))
            if n_seen == 0:
                raise ValueError(
                    f"make_blocks() yielded no rows on iteration "
                    f"{iteration + 1} — it must return a FRESH iterable "
                    f"on every call (one epoch per Lloyd iteration)")
            return sums, counts, sse, far, n_seen

        for iteration in range(start_iter, self.max_iter):
            active = [st for st in states if not st.done]
            if not active:
                break
            iter_start = time.perf_counter()
            if want_reservoir:
                for st_r in active:
                    st_r.meta.reservoir = _EpochReservoir(
                        self.k, d, np.random.default_rng(
                            [st_r.seed, iteration, 0x5EED]))
            cents_dev = [self._put_centroids(st_r.cents, mesh, model_shards)
                         for st_r in active]
            sums, counts, sse, far, n_seen = epoch(active, cents_dev,
                                                   iteration)
            self._progress_rows = n_seen      # rows/iteration, measured
            if iteration == start_iter and n_seen < self.k:
                raise ValueError(f"Not enough data points ({n_seen}) to "
                                 f"initialize {self.k} clusters")
            for i, st_r in enumerate(active):
                far_d, far_p = far[i]
                agg = StepStats(sums[i], counts[i], np.float64(sse[i]),
                                np.float64(far_d),
                                far_p if far_p is not None
                                else np.zeros((d,), acc),
                                np.zeros((self.k,), acc))
                # _finish_lloyd_iteration reads/writes the estimator's
                # bookkeeping; point it at THIS restart's lists so the
                # SSE monotonicity warning and history are per-restart.
                self.sse_history = st_r.sse_history
                self.iter_times_ = st_r.iter_times
                st_r.cents, max_shift = self._finish_lloyd_iteration(
                    st_r.cents, sums[i], counts[i],
                    sse[i] if self.compute_sse else 0.0, agg, st_r.meta,
                    iteration, log if st_r is states[0] else muted,
                    st_r.seed, iter_start)
                st_r.iters = self.iterations_run
                st_r.sizes = self.cluster_sizes_
                if max_shift < self.tolerance:     # kmeans_spark.py:310
                    st_r.done = True
                    if st_r is states[0]:
                        log.converged(iteration + 1)
            # Epoch-boundary rotating checkpoint (single-restart only,
            # enforced by _check_ckpt): the estimator attrs already
            # reflect this epoch's finish, and resume at any boundary is
            # bit-exact (empty-cluster reservoirs are re-seeded per
            # ABSOLUTE epoch index, never carried across epochs).
            if checkpoint_every and (iteration + 1) % checkpoint_every == 0:
                self.checkpoint_segments_ += 1
                self._write_autockpt(checkpoint_path, iteration + 1)

        # ---- winner selection (true final inertia, one scoring epoch)
        if R > 1:
            cents_dev = [self._put_centroids(st_r.cents, mesh,
                                             model_shards)
                         for st_r in states]
            _, _, finals, _, _ = epoch(states, cents_dev, self.max_iter,
                                       score_only=True)
            best = int(np.argmin(finals))
            for r, st_r in enumerate(states):
                log.restart(r, R, finals[r], winner=(r == best))
            self.best_restart_ = best
            self.restart_inertias_ = np.asarray(finals, np.float64)
            winner = states[best]
        else:
            self.best_restart_ = 0
            self.restart_inertias_ = None
            winner = states[0]
        self.centroids = np.asarray(winner.cents)
        self.sse_history = winner.sse_history
        self.iter_times_ = winner.iter_times
        self.iterations_run = winner.iters
        self.cluster_sizes_ = winner.sizes
        self.io_retries_used_ = io_stats.retries_used
        self.blocks_skipped_ = io_stats.blocks_skipped
        if checkpoint_every and self.iterations_run % checkpoint_every:
            self.checkpoint_segments_ += 1
            self._write_autockpt(checkpoint_path, self.iterations_run)
        self._fit_ds, self._labels_cache = None, None
        self._labels_error = ("labels_ is not materialized by fit_stream "
                              "(the dataset never resides in memory); call "
                              "predict on each block")
        # Terminal completion beat (ISSUE 19) — see fit().
        obs_note_progress(self, phase="finished")
        return self

    def _run_restart(self, ds, mesh, model_shards, step_fn, centroids,
                     start_iter, seed, log, checkpoint_every: int = 0,
                     checkpoint_path=None) -> "KMeans":
        """One restart: the reference's full fit loop (kmeans_spark.py:
        239-319), host- or device-side per ``host_loop`` (with 'auto'
        resolved against this platform's measured dispatch latency).
        ``checkpoint_every=N`` writes a rotating atomic checkpoint every
        N completed iterations (host loop: in place; device loop: the
        fit becomes segmented, see ``_fit_on_device``)."""
        if not self._resolve_host_loop(ds, mesh, model_shards, step_fn):
            return self._fit_on_device(ds, centroids, start_iter, mesh,
                                       model_shards, log, seed,
                                       checkpoint_every, checkpoint_path)

        self.loop_path_ = "host"
        # None (not a stale count) when this fit writes no checkpoints.
        self.checkpoint_segments_ = 0 if checkpoint_every else None
        cents_dev = self._put_centroids(centroids, mesh, model_shards)
        for iteration in range(start_iter, self.max_iter):
            iter_start = time.perf_counter()
            # The 'dispatch' span covers the dispatch AND the host
            # materialization of its statistics (JAX dispatch is async —
            # a span around the call alone would time µs of queueing,
            # not the step; the np.asarray below is the sync point).
            with obs_trace.span("dispatch", tag="lloyd/step",
                                iteration=iteration):
                stats: StepStats = step_fn(ds.points, ds.weights,
                                           cents_dev)
                # Host does exactly the driver's O(k*D) work
                # (kmeans_spark.py:181-188) — in float64 for stable
                # division.
                sums = np.asarray(stats.sums,
                                  dtype=np.float64)[: self.k]
                counts = np.asarray(stats.counts,
                                    dtype=np.float64)[: self.k]
            centroids, max_shift = self._finish_lloyd_iteration(
                centroids, sums, counts,
                float(stats.sse) if self.compute_sse else 0.0, stats, ds,
                iteration, log, seed, iter_start)
            # The cadence is ABSOLUTE in the iteration index (like the
            # mini-batch reassignment cadence), so a resumed fit keeps
            # the uninterrupted run's checkpoint schedule.
            if checkpoint_every and (iteration + 1) % checkpoint_every == 0:
                self.checkpoint_segments_ += 1
                self._write_autockpt(checkpoint_path, iteration + 1)
            if max_shift < self.tolerance:           # kmeans_spark.py:310-313
                log.converged(iteration + 1)
                break
            cents_dev = self._put_centroids(centroids, mesh, model_shards)
        if checkpoint_every and self.iterations_run % checkpoint_every:
            # Off-cadence tail (convergence or max_iter between
            # boundaries): the final state is still durably on disk.
            self.checkpoint_segments_ += 1
            self._write_autockpt(checkpoint_path, self.iterations_run)
        return self

    def _finish_lloyd_iteration(self, centroids, sums, counts, sse_val,
                                stats, ds_like, iteration, log, seed,
                                iter_start):
        """Shared host-side finish of one Lloyd iteration (the reference
        driver's role, kmeans_spark.py:181-204 + :279-307), used by both
        the in-memory host loop and ``fit_stream``: mean division in
        float64, empty-cluster handling, the subclass postprocess hook, SSE
        bookkeeping + monotonicity warning (:283-286), the NaN/Inf guard
        (:289-290), shift computation, per-iteration logging (:296-304),
        and fitted-state writes.  Returns (new_centroids, max_shift)."""
        nonempty = counts > 0
        new_centroids = np.where(
            nonempty[:, None],
            sums / np.maximum(counts, 1.0)[:, None],
            centroids.astype(np.float64))
        new_centroids = self._handle_empty(
            new_centroids, nonempty, ds_like, stats, iteration, log,
            seed=seed)
        new_centroids = self._postprocess_centroids(
            new_centroids, prev=centroids.astype(np.float64))
        new_centroids = new_centroids.astype(self.dtype)

        if self.compute_sse:              # SSE vs starting centroids (:279)
            self.sse_history.append(sse_val)
            if len(self.sse_history) > 1 and \
                    sse_val > self.sse_history[-2] + 1e-6:
                log.warn_sse_increase(self.sse_history[-2], sse_val)

        # Numerical-stability guard (kmeans_spark.py:289-290), upgraded
        # to the divergence-rollback exit (ISSUE 5): when a checkpoint
        # is active the fitted state rolls back to the last-good one
        # before the error — naming the iteration — propagates.
        if not np.all(np.isfinite(new_centroids)):
            self._raise_divergence("centroids", iteration + 1)

        shifts = np.linalg.norm(
            new_centroids.astype(np.float64) -
            centroids.astype(np.float64), axis=1)
        max_shift = float(np.max(shifts))           # kmeans_spark.py:293-294

        sizes = counts.astype(np.int64)
        log.iteration(iteration, max_shift, sizes,
                      self.sse_history[-1] if
                      (self.compute_sse and self.sse_history) else None)

        self.centroids = np.asarray(new_centroids)   # kmeans_spark.py:307
        self.cluster_sizes_ = sizes
        self.iterations_run = iteration + 1          # fixes SURVEY §2.1 bug
        self.iter_times_.append(time.perf_counter() - iter_start)
        # Heartbeat (ISSUE 11): the host loop already materialized this
        # iteration's state — the progress record reads attrs only,
        # zero extra dispatches (no-op with no heartbeat installed).
        obs_note_progress(self, phase="iteration",
                                    shift=max_shift)
        return new_centroids, max_shift

    def _fit_on_device(self, ds, centroids, start_iter, mesh, model_shards,
                       log, seed=None, checkpoint_every: int = 0,
                       checkpoint_path=None) -> "KMeans":
        """Whole-fit-in-one-dispatch path (``host_loop=False``): every
        iteration runs inside a device-side ``lax.while_loop`` — no
        per-iteration host synchronization.  See
        parallel.distributed.make_fit_fn for semantics and trade-offs.

        ``checkpoint_every=N`` SEGMENTS the dispatch: ceil(iters/N)
        device loops of (up to) N iterations each, with a rotating
        atomic checkpoint — and the fault-injection boundary hook —
        between segments.  The hand-off re-puts the boundary centroids
        through exactly the ``_put_centroids`` path a resumed fit uses,
        so kill-at-any-boundary + resume is bit-identical to running
        through, and (since the loop's accumulation dtype equals the
        compute dtype for f32/f64) the segmented trajectory is
        bit-identical to the ``checkpoint_every=0`` single dispatch —
        the parity oracle pinned by tests/test_faults.py.  Per-iteration
        seed schedules are ABSOLUTE (``_empty_seed_array(seed, it0,
        seg)``), so segment boundaries never re-draw."""
        seed = self.seed if seed is None else seed
        mode = self._mode(ds.n, ds.d)
        chunk = self._eff_chunk(ds)
        pipeline = self._note_estep_path(mode)
        guarded = (mode == dist.GUARDED_MODE)
        if guarded and self.bf16_guard_corrected_rows_ is None:
            self.bf16_guard_corrected_rows_ = 0
        self.loop_path_ = "device"
        self.checkpoint_segments_ = 0 if checkpoint_every else None
        self.effective_chunk_ = chunk
        base_hist = list(self.sse_history)
        cents_dev = self._put_centroids(centroids, mesh, model_shards)
        sse_parts, shift_parts = [], []
        it0 = start_iter
        seg_idx = 0
        fit_start = time.perf_counter()
        while True:
            seg = (min(checkpoint_every, self.max_iter - it0)
                   if checkpoint_every else self.max_iter - it0)
            seg = max(seg, 0)

            # Seeds travel as a traced ARGUMENT (not a baked constant),
            # so fits differing only by seed/start_iter — restarts,
            # bisecting splits, resumes, later segments — reuse one
            # compiled program per segment length.  The chunk is a
            # dispatch PARAMETER so the OOM backoff can rebuild the
            # step fn at a smaller tile and replay the segment from
            # this boundary (== the last checkpoint, ISSUE 5).
            def dispatch(c, _seg=seg, _it0=it0):
                fit_fn = self._get_fit_fn(mesh, c, mode, _seg, pipeline)
                return fit_fn(ds.points, ds.weights, cents_dev,
                              dist._empty_seed_array(seed, _it0, _seg))

            out, chunk = self._dispatch_oom_safe(dispatch, chunk, seg_idx)
            if guarded:
                # Guarded rung: the trailing output is the segment's
                # corrected-row audit (ISSUE 8).
                (cents, n_iters, sse_hist, shift_hist, counts,
                 n_corr) = out
                self.bf16_guard_corrected_rows_ += int(n_corr)
            else:
                cents, n_iters, sse_hist, shift_hist, counts = out
            seg_idx += 1
            n = int(n_iters)
            it0 += n
            sse_parts.append(np.asarray(sse_hist, np.float64)[:n])
            shift_parts.append(np.asarray(shift_hist, np.float64)[:n])
            if not checkpoint_every:
                break
            self.checkpoint_segments_ += 1
            converged = n < seg or (n > 0 and
                                    shift_parts[-1][-1] < self.tolerance)
            cents_host = np.asarray(cents, dtype=self.dtype)
            if not np.all(np.isfinite(cents_host)):  # don't checkpoint NaN
                # The in-loop all-finite flag stopped the dispatch at
                # the diverging iteration; roll back to the last-good
                # checkpoint and name it (ISSUE 5).
                self._raise_divergence("centroids", it0)
            # Publish the boundary state so the checkpoint is a valid
            # resume point, then write + fire the injection hook.
            self.centroids = cents_host
            self.cluster_sizes_ = np.asarray(counts, dtype=np.int64)
            self.iterations_run = it0
            if self.compute_sse:
                self.sse_history = base_hist + [
                    float(s) for part in sse_parts for s in part]
            self._write_autockpt(checkpoint_path, it0)
            if converged or it0 >= self.max_iter:
                break
            cents_dev = self._put_centroids(cents_host, mesh, model_shards)
        self.sse_history = base_hist
        self._finish_device_fit(
            cents, it0 - start_iter, start_iter,
            np.concatenate(sse_parts) if sse_parts else np.zeros(0),
            np.concatenate(shift_parts) if shift_parts else np.zeros(0),
            counts, time.perf_counter() - fit_start, log)
        return self

    def _get_fit_fn(self, mesh, chunk: int, mode: str, seg: int,
                    pipeline: int):
        """The cached one-dispatch training program for one segment
        length — ONE key derivation shared by the dispatch closure
        (``_fit_on_device``) and the prelude AOT warm-up
        (``_warm_aot``), so the two can never drift apart and warm a
        different program than the fit runs (the r14 cache-key
        incident class)."""
        key = (mesh, chunk, mode, self.k, seg,
               float(self.tolerance), self.empty_cluster,
               self.compute_sse, self._device_project, pipeline,
               "fit")
        return _STEP_CACHE.get_or_create(
            key, lambda: dist.make_fit_fn(
                mesh, chunk_size=chunk, mode=mode,
                k_real=self.k, max_iter=seg,
                tolerance=float(self.tolerance),
                empty_policy=self.empty_cluster,
                history_sse=self.compute_sse,
                project=self._device_project,
                pipeline=pipeline))

    def _finish_device_fit(self, cents, n_iters: int, start_iter: int,
                           sse_hist, shift_hist, counts, elapsed: float,
                           log: IterationLogger) -> None:
        """Shared postlude of the one-dispatch fit paths: ingest the
        device-side histories, run the reference's guards/logging
        (kmeans_spark.py:283-313) on the host."""
        # One dispatch for the whole fit: only the mean per-iteration wall
        # time is observable from the host.
        self.iter_times_.extend([elapsed / max(n_iters, 1)] * n_iters)
        self.centroids = np.asarray(cents, dtype=self.dtype)
        if not np.all(np.isfinite(self.centroids)):   # kmeans_spark.py:289
            # The all-finite loop flag stopped the dispatch at the
            # diverging iteration; roll back + name it (ISSUE 5).
            self._raise_divergence("centroids", start_iter + n_iters)
        self.cluster_sizes_ = np.asarray(counts, dtype=np.int64)
        self.iterations_run = start_iter + n_iters
        sse_hist = np.asarray(sse_hist, dtype=np.float64)[:n_iters]
        shift_hist = np.asarray(shift_hist, dtype=np.float64)[:n_iters]
        if self.compute_sse:
            for sse in sse_hist:
                self.sse_history.append(float(sse))
                if len(self.sse_history) > 1 and \
                        self.sse_history[-1] > self.sse_history[-2] + 1e-6:
                    log.warn_sse_increase(self.sse_history[-2],
                                          self.sse_history[-1])
        # Per-iteration prints don't exist in one-dispatch mode; emit the
        # final state in the reference's line format instead.
        log.iteration(self.iterations_run - 1, float(shift_hist[-1])
                      if n_iters else 0.0, list(self.cluster_sizes_),
                      self.sse_history[-1] if
                      (self.compute_sse and self.sse_history) else None)
        # End-of-fit heartbeat (ISSUE 11): a one-dispatch fit has no
        # iteration boundaries (and, unsegmented, no checkpoint ones),
        # so the completion record is its progress channel.
        obs_note_progress(self, phase="fit",
                          shift=float(shift_hist[-1]) if n_iters else 0.0)
        if n_iters and shift_hist[-1] < self.tolerance:
            log.converged(self.iterations_run)

    def _fit_on_device_multi(self, ds, seeds, mesh, log) -> "KMeans":
        """All ``n_init`` restarts in ONE dispatch: the restart axis is
        vmapped through the whole training loop on device
        (parallel.distributed.make_multi_fit_fn) and the winner — lowest
        true final inertia — is selected on device too."""
        R = len(seeds)
        mode = self._mode(ds.n, ds.d)
        chunk = self._eff_chunk(ds)
        pipeline = self._note_estep_path(mode)
        guarded = (mode == dist.GUARDED_MODE)
        key = (mesh, chunk, mode, self.k, self.max_iter,
               float(self.tolerance), self.empty_cluster, R,
               self.compute_sse, self._device_project, pipeline,
               "multifit")
        fit_fn = _STEP_CACHE.get_or_create(
            key, lambda: dist.make_multi_fit_fn(
                mesh, chunk_size=chunk, mode=mode,
                k_real=self.k, max_iter=self.max_iter,
                tolerance=float(self.tolerance),
                empty_policy=self.empty_cluster, n_init=R,
                history_sse=self.compute_sse,
                project=self._device_project, pipeline=pipeline))
        self.loop_path_ = "device-multi"
        _, model_shards = mesh_shape(mesh)
        inits = np.stack([dist.pad_centroids(
            self._init_centroids(ds, s), model_shards) for s in seeds])
        cents_dev = jax.device_put(
            inits, NamedSharding(mesh, P(None, MODEL_AXIS, None)))
        self.sse_history = []
        self.iterations_run = 0
        self.iter_times_ = []
        fit_start = time.perf_counter()
        with obs_trace.span("dispatch", tag="fit/multi", restarts=R):
            out = fit_fn(
                ds.points, ds.weights, cents_dev,
                np.stack([dist._empty_seed_array(s, 0, self.max_iter)
                          for s in seeds]))
            out = jax.block_until_ready(out)
        if guarded:
            *out, n_corr = out
            self.bf16_guard_corrected_rows_ = int(n_corr)
        cents, n_iters, sse_hist, shift_hist, counts, best, finals = out
        self.best_restart_ = int(best)
        self.restart_inertias_ = np.asarray(finals, dtype=np.float64)
        self._finish_device_fit(cents, int(n_iters), 0, sse_hist, shift_hist,
                                counts, time.perf_counter() - fit_start, log)
        log.restart(self.best_restart_, R,
                    float(self.restart_inertias_[self.best_restart_]),
                    winner=True)
        return self

    # ----------------------------------------------------------------- sweep

    # Families whose fit engine is NOT plain batched Lloyd (mini-batch
    # Sculley updates, bisecting splits) opt out of the inherited sweep.
    _sweepable = True

    def _sweep_metric_rows(self, X) -> np.ndarray:
        """Host rows the metric criteria score against — overridden by
        SphericalKMeans to L2-normalize (its labels live on the unit
        sphere, so silhouette/CH/DB must too)."""
        return np.ascontiguousarray(np.asarray(X, dtype=np.float32))

    def sweep(self, X, *, k_range, criterion: str = "inertia",
              sample_weight=None, batched=True):
        """Model selection over k: fit every (k, restart) member, score
        by ``criterion``, return a :class:`~kmeans_tpu.sweep.SweepResult`
        with the per-k curve and the fitted winner (ISSUE 7 tentpole).

        ``k_range`` — a range/iterable of k values (or the CLI grammar
        ``"2:33"``, half-open).  ``criterion`` — ``'inertia'`` (elbow
        rule: kneedle max-distance-below-chord; degenerate ranges
        < 3 points fall back to min inertia), ``'silhouette'`` /
        ``'calinski_harabasz'`` (max) or ``'davies_bouldin'`` (min),
        scored on the fitted labels via the mesh-sharded batched metric
        passes (`metrics.batched_criterion_scores`) — NOT k_max host
        round trips.  Silhouette is the full O(n²D) score (sklearn
        semantics); for large n score the winners yourself via
        ``metrics.batched_criterion_scores(..., sample_size=)`` (the
        seeded subsample every member shares).  A winner whose labels
        collapse below 2 occupied clusters (possible under
        ``empty_cluster='keep'`` at k far above the data's structure)
        scores NaN and can never be selected; it does not abort the
        other k's scores.  Restarts within each k come from this model's
        ``n_init``/``seed`` exactly like ``fit``'s restart sweep, and
        the within-k winner is the lowest-inertia restart (sklearn's
        rule); the criterion then selects ACROSS k on the per-k winners.

        ``batched=True`` (default) pads every member to k_max with
        inert sentinel components and runs the whole sweep as ONE
        vmapped device dispatch (`parallel.distributed.make_multi_fit_fn`
        with a per-member k axis) plus O(1) scoring dispatches.
        ``batched=0`` is the sequential per-member ORACLE — one
        device-loop fit per member on the same cached dataset — whose
        member trajectories the batched path must match (bit-exact for
        the f64 device-loop class, r10 parity table; the padded FLOPs
        economics and when sequential wins are in docs/PERFORMANCE.md).

        Notes: requires a string/callable ``init`` (an explicit (k, D)
        array pins k); metric criteria need host rows (pass an array,
        or a dataset cached from one) and score unweighted (sklearn
        semantics).  The returned ``best_model`` has not materialized
        ``labels_`` — call ``predict``.
        """
        from kmeans_tpu import metrics as metrics_mod
        from kmeans_tpu import sweep as sweep_mod

        if not type(self)._sweepable:
            raise NotImplementedError(
                f"sweep() is defined for the full-batch Lloyd families "
                f"(KMeans, SphericalKMeans), not {type(self).__name__}")
        if not (isinstance(self.init, str) or callable(self.init)):
            raise ValueError(
                "sweep() needs a string or callable init (an explicit "
                "(k, D) init array pins k); got an array init")
        if self.k_shard not in ("auto", 0) or self.assign == "two_level":
            raise ValueError(
                "sweep() runs its members on the dense multi-fit path; "
                "the large-k k_shard/assign routes do not compose with "
                "the padded member axis — sweep with the dense oracle "
                "and fit the winner's k with the large-k knobs")
        ks = sweep_mod.parse_k_range(k_range)
        sweep_mod.check_criterion(criterion, sweep_mod.KMEANS_CRITERIA)
        if criterion != "inertia" and ks[0] < 2:
            raise ValueError(f"criterion {criterion!r} needs k >= 2 "
                             f"(got k range starting at {ks[0]})")
        k_max = ks[-1]

        # The engine clone owns dataset placement and chunk choice at
        # k_max (every member's tiles must fit); members inherit every
        # other knob from self.
        engine = sweep_mod.clone_for(self, k=k_max, verbose=False,
                                     compute_labels=False)
        X2 = engine._apply_sample_weight(X, sample_weight)
        ds, mesh, model_shards, step_fn, predict_fn = engine._prepare(X2)
        if k_max >= ds.n:
            raise ValueError(f"k_max={k_max} must be < n={ds.n}")
        seeds = engine._restart_seeds()
        members = [(k, s) for k in ks for s in seeds]
        R, n_init = len(members), len(seeds)
        n_disp = 0
        # Fresh observability for THIS sweep (a prior fit's values must
        # not leak into the summed sequential audit or best_model).
        self.estep_path_ = None
        self.bf16_guard_corrected_rows_ = None

        if batched:
            states = self._sweep_fit_batched(engine, ds, mesh,
                                             model_shards, members, k_max)
            n_disp += 1
        else:
            states = self._sweep_fit_sequential(engine, ds, mesh,
                                                model_shards, step_fn,
                                                members)
            n_disp += 2 * R              # fit + inertia pass per member
        cents, n_iters, sse_hist, counts, finals = states

        inertias, best_r, win_idx = sweep_mod.within_k_winners(
            finals, len(ks), n_init)

        if criterion == "inertia":
            scores = inertias[np.arange(len(ks)), best_r]
        else:
            labels = self._sweep_labels(engine, ds, mesh, model_shards,
                                        predict_fn,
                                        [cents[m][: ks[i]]
                                         for i, m in enumerate(win_idx)],
                                        k_max, batched)
            n_disp += 1 if (batched and model_shards == 1) else len(ks)
            X_host = (X if not isinstance(X, ShardedDataset)
                      else X.host)
            if X_host is None:
                raise ValueError(
                    f"criterion {criterion!r} scores host rows; pass an "
                    f"array (or a dataset cached from one), or use "
                    f"criterion='inertia' for device-only data")
            X_rows = self._sweep_metric_rows(X_host)
            if batched:
                scores = metrics_mod.batched_criterion_scores(
                    X_rows, labels, criterion, mesh=mesh)
                n_disp += metrics_mod.SWEEP_SCORE_DISPATCHES[criterion]
            else:
                single = {"silhouette": metrics_mod.silhouette_score,
                          "calinski_harabasz":
                              metrics_mod.calinski_harabasz_score,
                          "davies_bouldin":
                              metrics_mod.davies_bouldin_score}[criterion]

                def _score_or_nan(lab):
                    # Match the batched path: a winner whose labels
                    # collapsed below 2 occupied clusters (possible
                    # under empty_cluster='keep' at k far above the
                    # data's structure) scores NaN, it does not abort
                    # the other k's scores.
                    try:
                        return single(X_rows, lab, mesh=mesh)
                    except ValueError:
                        return np.nan
                scores = np.asarray([_score_or_nan(lab)
                                     for lab in labels], np.float64)
                n_disp += len(ks) * metrics_mod.SWEEP_SCORE_DISPATCHES[
                    criterion]

        selected_k, sel, m_sel = sweep_mod.selected_member(
            ks, scores, criterion, win_idx)

        best = sweep_mod.clone_for(self, k=selected_k)
        best.mesh = mesh
        best.centroids = np.asarray(cents[m_sel][:selected_k],
                                    dtype=self.dtype)
        best.iterations_run = int(n_iters[m_sel])
        best.cluster_sizes_ = np.asarray(counts[m_sel][:selected_k],
                                         np.int64)
        if self.compute_sse:
            best.sse_history = [float(s) for s in
                                sse_hist[m_sel][: int(n_iters[m_sel])]]
        best.best_restart_ = int(best_r[sel])
        best.restart_inertias_ = np.asarray(inertias[sel], np.float64)
        best.loop_path_ = "device-sweep" if batched else "sequential-sweep"
        # The selected model carries the sweep fit's schedule/guard
        # observability: the documented reading surface is the model
        # that owns the centroids, not the throwaway sweep engine.
        best.estep_path_ = self.estep_path_
        best.bf16_guard_corrected_rows_ = self.bf16_guard_corrected_rows_
        best._fit_ds, best._labels_cache = None, None
        best._labels_error = ("labels_ is not materialized by sweep(); "
                              "call predict(X) on the selected model")

        return sweep_mod.SweepResult(
            family="kmeans", criterion=criterion, k_range=ks,
            scores=np.asarray(scores, np.float64),
            member_scores=inertias.astype(np.float64),
            selected_k=selected_k, selected_restart=int(best_r[sel]),
            best_model=best, n_dispatches=n_disp, batched=bool(batched),
            n_iters=np.asarray(n_iters).reshape(len(ks), n_init))

    def _sweep_fit_batched(self, engine, ds, mesh, model_shards, members,
                           k_max: int):
        """All sweep members in ONE dispatch: per-member inits padded to
        k_max with inert sentinel rows (the model-axis padding
        discipline), the per-member k axis riding
        ``make_multi_fit_fn(k_reals=...)``."""
        from kmeans_tpu.utils import profiling
        mode = engine._mode(ds.n, ds.d)
        member_ks = tuple(k for k, _ in members)
        R = len(members)
        # The batched scan materializes an (R, chunk, k_max) tile — R
        # times the single-model tile the dataset's chunk was budgeted
        # for.  Clamp by the MEMBER-SCALED tile width (measured 1.9x on
        # the CPU proxy config: the unclamped 32-member tile blew the
        # cache hierarchy).  Explicit user chunks pass through untouched;
        # f64 member parity survives the regrouping (f32-width data sums
        # exactly in f64 — the r10 invariance argument), f32 lands in
        # the documented cross-chunk class.
        chunk = ds.effective_chunk(R * engine._tile_k(ds.n, ds.d))
        pipeline = engine._note_estep_path(mode)
        guarded = (mode == dist.GUARDED_MODE)
        key = (mesh, chunk, mode, k_max, member_ks, self.max_iter,
               float(self.tolerance), self.empty_cluster,
               self.compute_sse, self._device_project, pipeline,
               "sweepfit")
        # n_init is written as len(member_ks) so the key's coverage of
        # every builder knob is self-evident (member_ks is in the key;
        # R is the same value).
        fit_fn = _STEP_CACHE.get_or_create(
            key, lambda: dist.make_multi_fit_fn(
                mesh, chunk_size=chunk, mode=mode, k_real=k_max,
                max_iter=self.max_iter, tolerance=float(self.tolerance),
                empty_policy=self.empty_cluster, n_init=len(member_ks),
                history_sse=self.compute_sse,
                project=self._device_project, k_reals=member_ks,
                return_all=True, pipeline=pipeline))
        inits = np.empty((R, k_max, ds.d), self.dtype)
        for i, (k_m, seed) in enumerate(members):
            inits[i] = dist.PAD_CENTROID_VALUE
            inits[i, :k_m] = engine._init_centroids(ds, seed, k=k_m)
        padded = np.stack([dist.pad_centroids(c, model_shards)
                           for c in inits])
        cents_dev = jax.device_put(
            padded, NamedSharding(mesh, P(None, MODEL_AXIS, None)))
        seeds_arr = np.stack([dist._empty_seed_array(s, 0, self.max_iter)
                              for _, s in members])
        profiling.note_dispatch("sweep/fit")
        out = fit_fn(ds.points, ds.weights, cents_dev, seeds_arr)
        # The sweep's schedule/guard observability reads from the model
        # the user called sweep() on (and is copied onto best_model);
        # the engine clone is a placement vehicle.
        self.estep_path_ = engine.estep_path_
        if guarded:
            *out, n_corr = out
            self.bf16_guard_corrected_rows_ = int(n_corr)
        cents, n_iters, sse_hist, _, counts, finals = out
        return (np.asarray(cents), np.asarray(n_iters),
                np.asarray(sse_hist, np.float64),
                np.asarray(counts), np.asarray(finals, np.float64))

    def _sweep_fit_sequential(self, engine, ds, mesh, model_shards,
                              step_fn, members):
        """The ``batched=0`` oracle: one device-loop fit per member on
        the SAME cached dataset (same chunking/padding — what makes
        batched-vs-sequential member parity exact rather than
        equal-in-distribution), plus one fused inertia pass each."""
        from kmeans_tpu import sweep as sweep_mod
        from kmeans_tpu.utils import profiling
        R = len(members)
        k_max = max(k for k, _ in members)
        cents = np.full((R, k_max, ds.d), dist.PAD_CENTROID_VALUE,
                        np.float64)
        n_iters = np.zeros((R,), np.int64)
        sse_hist = np.zeros((R, self.max_iter), np.float64)
        counts = np.zeros((R, k_max), np.float64)
        finals = np.full((R,), np.inf, np.float64)
        for i, (k_m, s) in enumerate(members):
            m = sweep_mod.clone_for(self, k=k_m, n_init=1, seed=s,
                                    verbose=False, compute_labels=False,
                                    host_loop=False)
            m._eager_labels = False
            profiling.note_dispatch("sweep/member-fit")
            m.fit(ds)
            # Member fits carry the real schedule/guard observability —
            # surface it on the sweep's reading model (the batched
            # path's convention); guard audits sum over members.
            self.estep_path_ = m.estep_path_
            if m.bf16_guard_corrected_rows_ is not None:
                self.bf16_guard_corrected_rows_ = (
                    (self.bf16_guard_corrected_rows_ or 0)
                    + m.bf16_guard_corrected_rows_)
            cents[i, :k_m] = np.asarray(m.centroids, np.float64)
            n_iters[i] = m.iterations_run
            hist = np.asarray(m.sse_history, np.float64)
            sse_hist[i, : hist.size] = hist
            counts[i, :k_m] = np.asarray(m.cluster_sizes_, np.float64)
            profiling.note_dispatch("sweep/member-score")
            finals[i] = float(step_fn(
                ds.points, ds.weights,
                m._put_centroids(np.asarray(m.centroids), mesh,
                                 model_shards)).sse)
        return cents, n_iters, sse_hist, counts, finals

    def _sweep_labels(self, engine, ds, mesh, model_shards, predict_fn,
                      winner_cents, k_max: int, batched) -> np.ndarray:
        """Labels of every per-k winner, (n_k, n): ONE packed-model
        dispatch (`make_multi_predict_fn`, the serving idiom) on
        data-parallel meshes; under TP centroid sharding — or on the
        sequential oracle — per-winner assignment dispatches."""
        from kmeans_tpu.utils import profiling
        n_k = len(winner_cents)
        if batched and model_shards == 1:
            mode = engine._mode(ds.n, ds.d)
            # Same member-scaled tile clamp as _sweep_fit_batched: the
            # packed assignment stages an (n_k, chunk, k_max) tile.
            chunk = ds.effective_chunk(n_k * engine._tile_k(ds.n, ds.d))
            key = (mesh, chunk, mode, n_k, "sweeppredict")
            mp_fn = _STEP_CACHE.get_or_create(
                key, lambda: dist.make_multi_predict_fn(
                    mesh, chunk_size=chunk, mode=mode, n_models=n_k))
            stack = np.full((n_k, k_max, ds.d), dist.PAD_CENTROID_VALUE,
                            self.dtype)
            for i, c in enumerate(winner_cents):
                stack[i, : c.shape[0]] = c
            profiling.note_dispatch("sweep/labels")
            labels = np.asarray(mp_fn(ds.points, jnp.asarray(stack)))
            return labels[:, : ds.n]
        out = []
        for c in winner_cents:
            profiling.note_dispatch("sweep/labels")
            cd = engine._put_centroids(np.asarray(c, self.dtype), mesh,
                                       model_shards)
            out.append(np.asarray(predict_fn(ds.points, cd,
                                             np.int32(ds.n)))[: ds.n])
        return np.stack(out)

    def _postprocess_centroids(self, centroids: np.ndarray,
                               prev: Optional[np.ndarray] = None
                               ) -> np.ndarray:
        """Subclass hook applied to freshly-computed centroids (after init
        and after each mean update + empty-cluster handling, before the
        shift/convergence test).  ``prev`` is the previous iteration's
        centroids (None at init).  SphericalKMeans projects onto the unit
        sphere here; the base model is plain Lloyd's — identity."""
        return centroids

    def _handle_empty(self, new_centroids: np.ndarray, nonempty: np.ndarray,
                      ds: ShardedDataset, stats: StepStats, iteration: int,
                      log: IterationLogger, *,
                      seed: Optional[int] = None) -> np.ndarray:
        """Empty-cluster recovery (kmeans_spark.py:190-204 / :84-129).
        ``seed`` is the active restart's seed (defaults to ``self.seed``) so
        restarts resample independently."""
        if seed is None:
            seed = self.seed
        empty_ids = np.flatnonzero(~nonempty)
        if empty_ids.size == 0:
            return new_centroids
        log.warn_empty(empty_ids.size)               # kmeans_spark.py:192
        if self.empty_cluster == "keep":             # fallback :201-204
            return new_centroids
        filled = list(empty_ids)
        if self.empty_cluster == "farthest":
            # The reference's dead policy (:84-129), fused & live: the point
            # farthest from its nearest centroid replaces the first empty.
            far = np.asarray(stats.farthest_point, dtype=np.float64)
            if float(stats.farthest_dist) >= 0:
                new_centroids[filled[0]] = far[: ds.d]
                filled = filled[1:]
        if filled:
            # Deterministic replacement sampling — the reference's live
            # policy (:191-204) minus its time.time() seed (:195-196).
            # Only positive-weight rows are candidates: a zero-weight
            # replacement would leave the cluster empty forever.  The
            # dataset picks the engine: host rng draw when a host copy
            # exists (bit-identical to r1), seeded on-device Gumbel-argmax
            # otherwise (device-only / multi-host process-local data).
            rows = ds.sample_positive_rows(len(filled),
                                           [seed, iteration + 1])
            for slot, row in zip(filled[: len(rows)], rows):
                new_centroids[slot] = row
            # Under-returned samples keep the old centroid (:201-204),
            # already present in new_centroids.
        return new_centroids

    # --------------------------------------------------------------- predict

    def predict(self, X) -> np.ndarray:
        """Labels for (n, D) array-like -> int32 (n,).

        Guard matches kmeans_spark.py:337-338; computation is the eager
        sharded analogue of the reference's lazy mapPartitions (:343-350).

        Multi-host process-local datasets (``from_process_local``):
        returns THIS process's own rows' labels, int32 (local_rows,) —
        the per-process concatenation, in process order, is the global
        label array (r3 VERDICT #4; previously this raised).  The
        assignment pass itself is the same global SPMD dispatch — only
        the unpadding is per-process.
        """
        if self.centroids is None:
            raise ValueError("Model must be fitted before prediction")
        if isinstance(X, ShardedDataset) and \
                not X.points.is_fully_addressable:
            if not X.labelable:
                raise ValueError(
                    "predict on this multi-host dataset cannot unpad its "
                    "per-process padding (unknown layout — build the "
                    "dataset with from_process_local to get process-"
                    "local labels); call predict on each process's "
                    "local rows instead")
            return self._predict_process_local(X)
        ds, mesh, model_shards, _, predict_fn = self._prepare(X)
        cents_dev = self._cents_dev(mesh, model_shards)
        # Explicit assign='two_level' routes inference through the
        # coarse->candidates->exact-recompute pass (ISSUE 16); 'auto'
        # and 'dense' keep the dense assignment (exact everywhere), and
        # a TP mesh falls back to the dense TP kernel — the two tiers
        # do not stack (see _resolve_large_k).
        if self.assign == "two_level" and model_shards == 1:
            labels = self._predict_two_level_labels(ds, mesh, cents_dev)
        else:
            labels = predict_fn(ds.points, cents_dev, np.int32(ds.n))
        return np.asarray(labels)[: ds.n]

    def _predict_process_local(self, ds: ShardedDataset) -> np.ndarray:
        """Process-local labels for a non-addressable dataset: run the
        global sharded assignment, then assemble THIS process's padded
        block from its addressable output shards (global-offset order;
        model-axis replicas deduped) and drop the per-process padding —
        ``from_process_local`` places each process's real rows FIRST in
        its contiguous block."""
        _, mesh, model_shards, _, predict_fn = self._prepare(ds)
        cents_dev = self._cents_dev(mesh, model_shards)
        # Per-PROCESS padding is interleaved (real rows first per block),
        # not a global tail — pass the padded total so the guard's
        # pad-row mask stays off rather than mis-masking real rows.
        labels = predict_fn(ds.points, cents_dev,
                            np.int32(ds.points.shape[0]))
        blocks = {}
        for sh in labels.addressable_shards:
            start = sh.index[0].start or 0
            if start not in blocks:
                blocks[start] = np.asarray(sh.data)
        local = np.concatenate([blocks[s] for s in sorted(blocks)])
        return local[: ds.local_rows]

    def predict_stream(self, make_blocks, *, prefetch: int = 2):
        """Labels for a bigger-than-HBM dataset, one block at a time.

        The streaming complement of ``fit_stream``: ``make_blocks()``
        yields (m, D) arrays (e.g. ``data.io.iter_npy_blocks``); this
        generator yields one int32 (m,) label array per block, uploading
        only a block at a time.  Blocks may vary in size (each distinct
        padded size compiles once).  ``prefetch`` (default 2) stages the
        next blocks' read + decode + device placement in a background
        thread while the current block's assignment computes
        (``fit_stream``'s knob; 0 = synchronous).  Usage::

            labels = np.concatenate(list(km.predict_stream(blocks)))
        """
        # Eager wrapper: the fitted-guard must fail AT THE CALL SITE like
        # predict's (kmeans_spark.py:337-338), not on first iteration of
        # the returned generator.
        if self.centroids is None:
            raise ValueError("Model must be fitted before prediction")
        return self._predict_stream_blocks(make_blocks, prefetch)

    def _iter_stream_blocks(self, make_blocks, *, with_weights: bool,
                            prefetch: int = 0, stage_extra=None):
        """Shared scaffolding of every streaming inference/scoring
        surface (predict/transform/score streams): decode each item
        ((block, weights) pairs kept or dropped per ``with_weights``),
        validate its shape against the fitted model, lazily upload the
        fitted centroids ONCE, and raise the FRESH-iterable error on an
        empty stream (an exhausted generator must not silently produce
        zero output — review r4).  Yields
        (block, weights_or_None, extra, cents_dev, mesh, model_shards).

        ``prefetch``/``stage_extra``: with ``prefetch > 0`` the decode —
        and ``stage_extra(block, bw)``, the caller's hook for its
        per-block device placement — run in a background producer
        thread ``prefetch`` blocks ahead (``data.prefetch``); ``extra``
        is ``stage_extra``'s return (None without the hook)."""
        from kmeans_tpu.data.prefetch import check_prefetch, prefetch_iter
        from kmeans_tpu.models.init import _block_of, _split_block
        prefetch = check_prefetch(prefetch)
        mesh = self._resolve_mesh()
        _, model_shards = mesh_shape(mesh)
        d = self.centroids.shape[1]
        cents_dev = None
        empty = True

        def stage(item):
            raw = item if with_weights else _block_of(item)
            block, bw = _split_block(raw, d, self.dtype)
            extra = stage_extra(block, bw) if stage_extra is not None \
                else None
            return block, bw, extra

        # closing: a consumer abandoning this generator early (break /
        # close()) must join the producer thread deterministically — the
        # thread target's reference cycle keeps GC from reaping it
        # promptly.
        with contextlib.closing(prefetch_iter(make_blocks(), prefetch,
                                              stage)) as it:
            for block, bw, extra in it:
                empty = False
                if cents_dev is None:
                    cents_dev = self._cents_dev(mesh, model_shards)
                yield block, bw, extra, cents_dev, mesh, model_shards
        if empty:
            raise ValueError(
                "make_blocks() yielded no rows — it must return a FRESH "
                "iterable on every call")

    def _predict_stream_blocks(self, make_blocks, prefetch: int = 0):
        from kmeans_tpu.parallel.sharding import shard_points

        def stage_extra(block, bw):
            # Device placement of the NEXT block overlaps the current
            # block's assignment pass (prefetch > 0).
            chunk = self._chunk_for(*block.shape)
            pts, _ = shard_points(block, self._resolve_mesh(), chunk)
            return chunk, pts

        for block, _, (chunk, pts), cents_dev, mesh, _ in \
                self._iter_stream_blocks(make_blocks, with_weights=False,
                                         prefetch=prefetch,
                                         stage_extra=stage_extra):
            _, predict_fn = _get_step_fns(mesh, chunk,
                                          self._mode(*block.shape))
            yield np.asarray(predict_fn(
                pts, cents_dev, np.int32(block.shape[0])))[: block.shape[0]]

    def fit_predict(self, X, y=None) -> np.ndarray:
        # labels_ is materialized by fit() from the same X — reusing it
        # avoids a second upload + assignment pass.
        return self.fit(X).labels_

    def fit_transform(self, X, y=None) -> np.ndarray:
        return self.fit(X).transform(X)

    def transform(self, X, *, block_rows: Optional[int] = None) -> np.ndarray:
        """Euclidean distances to each centroid, (n, k) — sklearn-style.

        Memory contract: DEVICE memory is bounded regardless of n — rows
        stream through the mesh in host blocks of ``block_rows`` (auto:
        ~2^26 elements of (block, k) tile per step), each block's (m, k)
        tile sharded over BOTH mesh axes (data rows x centroid columns)
        before coming back to the host.  Only the returned (n, k) HOST
        array scales with n — at 10M x 1024 that is 41 GB of host RAM;
        slice or stream via ``transform_stream`` if that is too much.
        (r2 VERDICT weak #5: the old path materialized (n, k) on ONE
        device and OOM'd at exactly the advertised scale.)
        """
        if self.centroids is None:
            raise ValueError("Model must be fitted before prediction")
        X = np.asarray(X, dtype=self.dtype)
        if X.ndim != 2:
            raise ValueError(f"X must be 2-D (n, D), got shape {X.shape}")
        n = X.shape[0]
        out = np.empty((n, self.k), dtype=self.dtype)
        start = 0
        for tile in self.transform_stream(
                lambda: iter([X]), block_rows=block_rows):
            out[start: start + tile.shape[0]] = tile
            start += tile.shape[0]
        return out

    def transform_stream(self, make_blocks, *,
                         block_rows: Optional[int] = None,
                         prefetch: int = 2):
        """Streaming ``transform``: yields (m, k) Euclidean-distance tiles
        for successive row blocks of ``make_blocks()`` (bounded host AND
        device memory — the complement of ``predict_stream``).  Input
        blocks larger than ``block_rows`` are split.  ``prefetch``
        (default 2) reads/decodes input blocks ahead in a background
        thread (the per-tile device placement stays consumer-side —
        tile splitting is row-budgeted, see ``block_rows``); 0 =
        synchronous."""
        if self.centroids is None:
            raise ValueError("Model must be fitted before prediction")
        return self._transform_stream_blocks(make_blocks, block_rows,
                                             prefetch)

    def _transform_stream_blocks(self, make_blocks, block_rows,
                                 prefetch: int = 0):
        from kmeans_tpu.parallel.sharding import shard_points
        data_shards, _ = mesh_shape(self._resolve_mesh())
        # The full (n, k) matrix only exists on the host; pallas/auto map
        # to the equivalent matmul form (the fused kernel never
        # materializes distances), and the guarded rung maps to its
        # f32-class twin (ops.assign.value_mode — the shared rule of
        # every value-surface call site, incl. the serving engine's
        # serve-mode table).
        from kmeans_tpu.ops.assign import value_mode
        mode = value_mode({"auto": "matmul", "pallas": "matmul",
                           "pallas_bf16": "matmul_bf16"}.get(
                               self.distance_mode, self.distance_mode))
        d_model = self.centroids.shape[1]
        # Auto block: ~2^26 elements across BOTH the (block, D) input and
        # the (block, k) output tile — sizing on k alone would let a
        # small-k/large-D transform upload an unbounded input block.
        block = block_rows or max(
            8192 * data_shards, (1 << 26) // max(self.k + d_model, 1))
        for raw, _, _, cents_dev, mesh, _ in self._iter_stream_blocks(
                make_blocks, with_weights=False, prefetch=prefetch):
            for start in range(0, raw.shape[0], block):
                xb = np.ascontiguousarray(raw[start: start + block])
                chunk = self._chunk_for(*xb.shape)
                tfn = _STEP_CACHE.get_or_create(
                    (mesh, chunk, mode, "transform"),
                    lambda: dist.make_transform_fn(
                        mesh, chunk_size=chunk, mode=mode))
                pts, _ = shard_points(xb, mesh, chunk)
                tile = tfn(pts, cents_dev)
                yield np.asarray(tile)[: xb.shape[0], : self.k]

    def score(self, X, y=None) -> float:
        """Negative SSE of X under the fitted centroids (sklearn convention)."""
        if self.centroids is None:
            raise ValueError("Model must be fitted before prediction")
        ds, mesh, model_shards, step_fn, _ = self._prepare(X)
        cents_dev = self._cents_dev(mesh, model_shards)
        stats = step_fn(ds.points, ds.weights, cents_dev)
        return -float(stats.sse)

    def score_stream(self, make_blocks, *, prefetch: int = 2) -> float:
        """Negative SSE of a block stream under the fitted centroids —
        the scoring complement of ``fit_stream``/``predict_stream`` (one
        pass, bounded device memory; items may be (block, weights)
        pairs).  ``prefetch`` (default 2) stages the next blocks' read +
        decode + device placement while the current block's pass
        computes (0 = synchronous).  An empty/exhausted stream raises
        rather than returning a perfect -0.0 score."""
        from kmeans_tpu.parallel.sharding import shard_points
        if self.centroids is None:
            raise ValueError("Model must be fitted before prediction")

        def stage_extra(block, bw):
            chunk = self._chunk_for(*block.shape)
            pts, w = shard_points(block, self._resolve_mesh(), chunk,
                                  sample_weight=bw)
            return chunk, pts, w

        sse = 0.0
        for block, bw, (chunk, pts, w), cents_dev, mesh, _ in \
                self._iter_stream_blocks(make_blocks, with_weights=True,
                                         prefetch=prefetch,
                                         stage_extra=stage_extra):
            step_fn, _ = _get_step_fns(mesh, chunk,
                                       self._mode(*block.shape))
            sse += float(step_fn(pts, w, cents_dev).sse)
        return -sse

    # ---------------------------------------------------- sklearn-style sugar

    _PARAM_NAMES = ("k", "max_iter", "tolerance", "seed", "compute_sse",
                    "init", "n_init", "compute_labels", "empty_cluster",
                    "dtype", "mesh", "model_shards", "chunk_size",
                    "distance_mode", "host_loop", "pipeline", "bucket",
                    "overlap", "ingest", "k_shard", "assign",
                    "coarse_cells", "nprobe", "init_cap", "verbose")

    def get_params(self, deep: bool = True) -> dict:
        """Constructor parameters as a dict (sklearn estimator protocol —
        enables ``sklearn.base.clone`` and pipeline interop)."""
        return {name: getattr(self, name) for name in self._PARAM_NAMES}

    def set_params(self, **params) -> "KMeans":
        for name in params:
            if name not in self._PARAM_NAMES:
                raise ValueError(f"unknown parameter {name!r} for "
                                 f"{type(self).__name__}; valid: "
                                 f"{sorted(self._PARAM_NAMES)}")
        # Route through __init__ so new values get exactly the constructor's
        # validation and normalization (empty_cluster whitelist, n_init >= 1,
        # dtype -> np.dtype, ...), then restore every non-parameter attribute
        # (fitted state) — including subclass state — that __init__ reset.
        merged = self.get_params()
        merged.update(params)
        saved = dict(self.__dict__)
        try:
            self.__init__(**merged)
        except Exception:
            self.__dict__.clear()
            self.__dict__.update(saved)
            raise
        for name, value in saved.items():
            if name not in self._PARAM_NAMES:
                self.__dict__[name] = value
        return self

    def get_feature_names_out(self, input_features=None) -> np.ndarray:
        """Output feature names of ``transform`` (sklearn transformer
        protocol — one distance column per centroid), enabling use as a
        feature-extraction stage in ``sklearn.pipeline.Pipeline``."""
        name = type(self).__name__.lower()
        return np.asarray([f"{name}{i}" for i in range(self.k)], dtype=object)

    @property
    def cluster_centers_(self) -> Optional[np.ndarray]:
        return self.centroids

    @property
    def n_iter_(self) -> int:
        return self.iterations_run

    @property
    def inertia_(self) -> Optional[float]:
        return self.sse_history[-1] if self.sse_history else None

    @property
    def labels_(self) -> np.ndarray:
        """Training-set labels under the fitted centroids (sklearn parity;
        the reference exposes labels only through ``predict``,
        kmeans_spark.py:321-352).  ``fit`` materializes these eagerly with
        one fused assignment pass and then releases its dataset reference,
        so device memory is never pinned past the end of ``fit``.

        Multi-host process-local fits: holds THIS process's own rows'
        labels (length ``local_rows``); concatenating across processes in
        process order yields the global label array."""
        if self._labels_cache is None:
            if getattr(self, "_labels_error", None):
                raise AttributeError(self._labels_error)
            if self.centroids is None or self._fit_ds is None:
                raise AttributeError(
                    "labels_ is only available after fit()")
            self._labels_cache = self.predict(self._fit_ds)
            self._fit_ds = None
        return self._labels_cache

    @labels_.setter
    def labels_(self, value) -> None:
        self._labels_cache = value

    def __getstate__(self) -> dict:
        """Pickle/deepcopy support: device-bound objects (the retained
        dataset and the ``jax.sharding.Mesh`` of Device handles) are
        dropped; an unpickled model lazily rebuilds a mesh on next use via
        ``_resolve_mesh``.  ``labels_`` survives — ``fit`` materializes it
        eagerly."""
        if self._labels_cache is None and self._fit_ds is not None \
                and self.centroids is not None:
            _ = self.labels_      # materialize before dropping the dataset
        state = dict(self.__dict__)
        state["_fit_ds"] = None
        state["mesh"] = None
        state["_cents_cache"] = None      # device arrays don't pickle
        return state

    def __deepcopy__(self, memo):
        """In-process deepcopy keeps the (copyable, user-configured) mesh —
        only cross-process pickling must drop device handles."""
        import copy as _copy
        new = self.__class__.__new__(self.__class__)
        memo[id(self)] = new
        for name, value in self.__dict__.items():
            if name in ("mesh", "_fit_ds", "_cents_cache"):
                new.__dict__[name] = value     # share device-bound objects
            else:
                new.__dict__[name] = _copy.deepcopy(value, memo)
        return new

    # ------------------------------------------------------------ checkpoint

    def _state_dict(self) -> dict:
        """Serializable state: constructor config + fitted attributes.
        ``init`` round-trips as a strategy name or explicit array; a callable
        init is recorded as 'forgy' (irrelevant on resume — centroids are
        restored, so init never re-runs)."""
        state = {
            "model_class": type(self).__name__,
            "centroids": np.asarray(self.centroids)
            if self.centroids is not None else np.zeros((0, 0)),
            "k": self.k, "max_iter": self.max_iter,
            "tolerance": self.tolerance, "seed": self.seed,
            "compute_sse": self.compute_sse,
            "n_init": self.n_init,
            "compute_labels": self.compute_labels,
            "empty_cluster": self.empty_cluster,
            "distance_mode": self.distance_mode,
            "model_shards": self.model_shards,
            "chunk_size": self.chunk_size,
            "host_loop": self.host_loop,
            "pipeline": self.pipeline,
            "bucket": self.bucket,
            "overlap": self.overlap,
            "ingest": self.ingest,
            "k_shard": self.k_shard,
            "assign": self.assign,
            "coarse_cells": self.coarse_cells,
            "nprobe": self.nprobe,
            "init_cap": self.init_cap,
            "verbose": self.verbose,
            "sse_history": list(map(float, self.sse_history)),
            "iterations_run": self.iterations_run,
            "dtype": str(self.dtype),
        }
        # Topology metadata block (ISSUE 5): the mesh shape / TP layout
        # this state was written on, jax version, format version — all
        # informational (state itself is canonical/unsharded; resume
        # re-shards it for whatever topology the resuming model has).
        state.update(self._ckpt_meta())
        # Serving-quality reference profile (ISSUE 14): rides the JSON
        # meta block, so a model loaded into the serving registry
        # carries its own reference window (None on mid-fit segment
        # checkpoints that have no sizes yet — re-stamped complete at
        # the final save).
        state["quality_profile"] = self.quality_profile()
        # Two-level routing is FITTED state (ISSUE 16): the coarse
        # quantizer is trained once per fit and then fixed, so the
        # checkpoint must carry it — retraining from the FINAL table
        # at load time would re-route predict onto different candidate
        # sets than the fit (and its drift profile) assigned with.
        route = self._two_level_route_
        if route is not None:
            state["two_level_coarse"] = np.asarray(route[0], np.float64)
        if isinstance(self.init, str):
            state["init"] = self.init
        elif not callable(self.init):
            state["init_array"] = np.asarray(self.init)
        return state

    def _restore_state(self, state: dict) -> None:
        cents = state["centroids"]
        self.centroids = cents if cents.size else None
        self.sse_history = list(state["sse_history"])
        self.iterations_run = int(state["iterations_run"])
        # Pre-r18 checkpoints carry no profile -> None (reference-free
        # monitoring); npz meta JSON round-trips the dict as-is.
        self._quality_profile = state.get("quality_profile")
        # Restore the two-level route from the saved coarse table
        # (member lists rebuild deterministically from table + coarse).
        # Pre-r20 / dense-fit checkpoints carry no key -> the lazy
        # retrain-from-final-table fallback in _two_level_tables.
        coarse = state.get("two_level_coarse")
        if (coarse is not None and getattr(coarse, "size", 0)
                and self.centroids is not None):
            coarse = np.asarray(coarse, np.float64)
            self._two_level_route_ = (coarse, self._build_members(
                np.asarray(self.centroids, np.float64), coarse))

    def save(self, path) -> None:
        """Checkpoint fitted state (beyond-reference; SURVEY.md §5).

        Multi-host: call on EVERY process (SPMD style); the shared
        primary-gated writer handles the single-writer + barrier
        contract (``checkpoint.save_state_primary``)."""
        ckpt.save_state_primary(path, self._state_dict(),
                                "kmeans_tpu.save")

    @classmethod
    def load(cls, path) -> "KMeans":
        state = ckpt.load_state(path)
        init = state.get("init_array", state.get("init", "forgy"))
        model = cls(k=state["k"], max_iter=state["max_iter"],
                    tolerance=state["tolerance"], seed=state["seed"],
                    compute_sse=state["compute_sse"], init=init,
                    n_init=int(state.get("n_init", 1)),
                    compute_labels=bool(state.get("compute_labels", True)),
                    empty_cluster=state["empty_cluster"],
                    distance_mode=state["distance_mode"],
                    model_shards=state["model_shards"],
                    chunk_size=state["chunk_size"],
                    host_loop=state.get("host_loop", True),
                    # Pre-r13 checkpoints have no pipeline knob ->
                    # 'auto' (the schedule is a per-run resolution, not
                    # fitted state).  npz round-trips ints as 0-d arrays.
                    pipeline=(lambda p: p if isinstance(p, str)
                              else int(p))(state.get("pipeline", "auto")),
                    # Pre-r19 checkpoints have neither knob -> the
                    # exact-shape / platform-resolved defaults.
                    bucket=(lambda b: b if isinstance(b, str)
                            else int(b))(state.get("bucket", 0)),
                    overlap=(lambda o: o if isinstance(o, str)
                             else int(o))(state.get("overlap", "auto")),
                    # Pre-r22 checkpoints have no ingest knob -> the
                    # committed-rule default (a per-run placement
                    # resolution, not fitted state).
                    ingest=str(state.get("ingest", "auto")),
                    # Pre-r20 checkpoints have no massive-k knobs ->
                    # the planner-resolved ('auto') defaults.
                    k_shard=(lambda v: v if isinstance(v, str)
                             else int(v))(state.get("k_shard", "auto")),
                    assign=str(state.get("assign", "auto")),
                    coarse_cells=(lambda v: None if v is None
                                  else int(v))(state.get("coarse_cells")),
                    nprobe=(lambda v: None if v is None
                            else int(v))(state.get("nprobe")),
                    init_cap=(lambda v: None if v is None
                              else int(v))(state.get("init_cap")),
                    verbose=state["verbose"],
                    dtype=np.dtype(state["dtype"]),
                    **cls._load_kwargs(state))
        model._restore_state(state)
        return model

    @classmethod
    def _load_kwargs(cls, state: dict) -> dict:
        """Subclass hook for extra constructor kwargs."""
        return {}
