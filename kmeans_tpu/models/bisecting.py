"""Bisecting (divisive hierarchical) K-Means on a TPU mesh.

A beyond-reference model family (the reference implements flat K-Means only,
``class KMeans``, kmeans_spark.py:19-352): start from one cluster holding all
points and repeatedly split the "worst" cluster with a 2-means fit until k
clusters exist — sklearn's ``BisectingKMeans`` capability, re-designed
TPU-first.

The TPU-native trick is **static-shape subproblems via weight masking**:
each 2-means split runs over the FULL sharded dataset with the non-members'
sample weights set to 0 (``ShardedDataset.with_weights`` — one tiny (n,)
upload; the (n, D) points never move).  Zero-weight rows contribute nothing
to any statistic (ops.assign), the shapes every jitted step was compiled for
never change, and no data-dependent gather/compaction is ever needed — the
exact failure mode a literal port (boolean-mask the member rows) would hit
under XLA.

The split criterion uses the fused per-cluster SSE (``StepStats.
sse_per_cluster``), which the shared assignment pass produces at ~zero
marginal cost — the same "fuse the metric into the pass you already make"
move the flat model uses for total SSE vs the reference's second data pass
(kmeans_spark.py:208-237).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from kmeans_tpu.models.kmeans import KMeans, _get_step_fns
from kmeans_tpu.parallel.multihost import fleet_barrier
from kmeans_tpu.obs import note_progress as obs_note_progress
from kmeans_tpu.utils.logging import IterationLogger

_STRATEGIES = ("biggest_sse", "largest_cluster")


class BisectingKMeans(KMeans):
    """Divisive hierarchical K-Means (sklearn ``BisectingKMeans`` analogue).

    Same constructor surface as :class:`KMeans` plus:

    bisecting_strategy : 'biggest_sse' (split the cluster with the largest
        within-cluster SSE — sklearn's ``biggest_inertia``) |
        'largest_cluster' (split the heaviest cluster).

    ``empty_cluster`` and ``n_init`` are forwarded to the per-split 2-means
    fits (sklearn's ``BisectingKMeans`` applies ``n_init`` per bisection the
    same way; default 'resample' / 1).  ``host_loop`` is forwarded too
    (r3): the split TREE is inherently host-driven, but with
    ``host_loop=False`` each inner 2-means runs as ONE device dispatch
    (``lax.while_loop``) instead of ``max_iter`` round trips — on a
    tunneled chip (~0.2 s dispatch RTT) that turns a k=32 fit from ~13
    minutes of per-iteration latency into seconds of compute.

    Attributes after ``fit``: ``centroids`` (k, D); ``labels_`` (n,) — the
    HIERARCHICAL memberships produced by the successive splits;
    ``cluster_sse_`` (k,) per-leaf SSE; ``cluster_sizes_`` (k,) weighted
    sizes; ``sse_history`` — total SSE after each split (when
    ``compute_sse``); ``iterations_run`` — number of splits performed.

    ``predict`` is inherited flat nearest-centroid assignment over the final
    leaves; for points seen in ``fit`` it can differ from ``labels_`` on
    boundary points, because bisecting membership follows the split tree
    (same caveat as sklearn's tree-walking predict vs its labels_).
    """

    _PARAM_NAMES = KMeans._PARAM_NAMES + ("bisecting_strategy",)
    # The inherited k-sweep engine batches flat Lloyd members; the split
    # tree is a different fit engine — opt out (ISSUE 7).
    _sweepable = False

    def __init__(self, k: int = 3, max_iter: int = 100,
                 tolerance: float = 1e-4, seed: int = 42,
                 compute_sse: bool = False, *,
                 bisecting_strategy: str = "biggest_sse",
                 **kwargs):
        if bisecting_strategy not in _STRATEGIES:
            raise ValueError(f"bisecting_strategy must be one of "
                             f"{_STRATEGIES}, got {bisecting_strategy!r}")
        self.bisecting_strategy = bisecting_strategy
        kwargs.setdefault("empty_cluster", "resample")
        super().__init__(k=k, max_iter=max_iter, tolerance=tolerance,
                         seed=seed, compute_sse=compute_sse, **kwargs)
        self.cluster_sse_: Optional[np.ndarray] = None

    # ------------------------------------------------------------------- fit

    def _inner_init(self):
        """Init strategy for the per-split 2-means (array/callable inits are
        k-specific and cannot seed a k=2 subproblem)."""
        return self.init if isinstance(self.init, str) else "k-means++"

    def _fit(self, X, *, sample_weight, resume, checkpoint_every: int = 0,
             checkpoint_path=None) -> "BisectingKMeans":
        checkpoint_every = self._check_ckpt(checkpoint_every,
                                            checkpoint_path)
        tree = getattr(self, "_tree_state", None)
        if resume and tree is None:
            raise ValueError(
                "BisectingKMeans resume needs a split-boundary "
                "checkpoint: fit with checkpoint_every=N + "
                "checkpoint_path, then fit(X, resume=<path>) — a plain "
                "save() holds no mid-tree state")
        verbose = self.verbose and jax.process_index() == 0
        log = IterationLogger(verbose)
        X = self._apply_sample_weight(X, sample_weight)
        ds, mesh, model_shards, step_fn, predict_fn = self._prepare(X)
        # Fleet prelude (ISSUE 13): rows for heartbeat rows_per_sec +
        # the merged-timeline clock anchor (no-op when obs=0).
        self._progress_rows = ds.local_rows if ds.local_rows else ds.n
        fleet_barrier("fit-start")

        n = ds.n
        # Validate the data ONCE up front (same message as the reference's
        # finite guard, kmeans_spark.py:79-80); the per-split inner fits
        # skip their init-time full-array re-scans.
        if ds.host is not None:
            from kmeans_tpu.utils.validation import check_finite_array
            check_finite_array(ds.host, "Data contains NaN or Inf values")
        base_w = (np.ones(n, dtype=np.float64) if ds.host_weights is None
                  else np.asarray(ds.host_weights, dtype=np.float64))
        if int((base_w > 0).sum()) < self.k:
            raise ValueError(
                f"Not enough data points ({int((base_w > 0).sum())}) to "
                f"initialize {self.k} clusters")

        log.startup(self.k, self.max_iter, self.tolerance, self.compute_sse)
        self.checkpoint_segments_ = 0 if checkpoint_every else None

        if resume:
            # Rebuild the split tree at the checkpointed boundary: every
            # later split is a pure function of (seed, split index) and
            # these arrays, so the continuation is bit-identical to the
            # uninterrupted run (the per-split inner-fit seeds derive
            # from the ABSOLUTE split index).
            if tree["labels"].shape != (n,):
                raise ValueError(
                    f"checkpointed split tree was built on "
                    f"{tree['labels'].shape[0]} rows; resume got {n} — "
                    f"pass the same dataset the fit started on")
            start_split = int(tree["splits_done"])
            labels = np.asarray(tree["labels"], np.int32).copy()
            cents = {i: np.asarray(c, np.float64)
                     for i, c in enumerate(tree["cents"])}
            sse = {i: float(v) for i, v in enumerate(tree["sse"])}
            wsize = {i: float(v) for i, v in enumerate(tree["wsize"])}
            members = {i: int(v) for i, v in enumerate(tree["members"])}
        else:
            start_split = 0
            self.sse_history = []
            self.iter_times_ = []
            self._tree_state = None      # no stale tree in checkpoints
            labels = np.zeros(n, dtype=np.int32)
            # Per-leaf state, keyed by leaf id (ids stay contiguous
            # 0..n_leaves-1: child 0 of a split keeps the parent's id,
            # child 1 takes the next free id).
            cents = {0: None}
            sse = {0: np.inf}      # root is always the first split target
            wsize = {0: float(base_w.sum())}
            members = {0: int((base_w > 0).sum())}

        import time as _time
        for split in range(start_split, self.k - 1):
            t0 = _time.perf_counter()
            splittable = [c for c in cents
                          if members[c] >= 2 and
                          (np.isinf(sse[c]) or sse[c] > 0)]
            if not splittable:
                raise RuntimeError(
                    f"Cannot bisect further: {len(cents)} clusters exist but "
                    f"no cluster has >= 2 distinct members (k={self.k})")
            crit = sse if self.bisecting_strategy == "biggest_sse" else wsize
            target = max(splittable, key=lambda c: crit[c])

            w_child = (base_w * (labels == target)).astype(self.dtype)
            ds_t = ds.with_weights(w_child)
            inner = KMeans(
                k=2, max_iter=self.max_iter, tolerance=self.tolerance,
                seed=int(np.random.SeedSequence(
                    [self.seed, split]).generate_state(1)[0] % (2 ** 31)),
                compute_sse=False, init=self._inner_init(),
                n_init=self.n_init,
                empty_cluster=self.empty_cluster, dtype=self.dtype,
                mesh=mesh, chunk_size=ds.chunk,
                distance_mode=self.distance_mode,
                host_loop=self.host_loop, verbose=False)
            inner._validate_init = False     # X validated once above
            inner._eager_labels = False      # membership computed below
            inner.fit(ds_t)

            two = self._put_centroids(np.asarray(inner.centroids), mesh,
                                      model_shards)
            # Hierarchical membership: every current member goes to its
            # nearest child (consistent tie-breaks with the eval pass below).
            child = np.asarray(predict_fn(ds.points, two,
                                          np.int32(n)))[:n]
            new_id = len(cents)
            mask = labels == target
            labels[mask & (child == 1)] = new_id

            # One fused pass gives both children's exact post-fit SSE and
            # weighted sizes (StepStats.sse_per_cluster) — the split
            # criterion's bookkeeping costs one pass, not two.
            stats = step_fn(ds_t.points, ds_t.weights, two)
            sse_pc = np.asarray(stats.sse_per_cluster, np.float64)[:2]
            counts = np.asarray(stats.counts, np.float64)[:2]
            cents[target] = np.asarray(inner.centroids)[0]
            cents[new_id] = np.asarray(inner.centroids)[1]
            sse[target], sse[new_id] = sse_pc[0], sse_pc[1]
            wsize[target], wsize[new_id] = counts[0], counts[1]
            pos = base_w > 0
            members[target] = int((pos & (labels == target)).sum())
            members[new_id] = int((pos & (labels == new_id)).sum())

            self.iter_times_.append(_time.perf_counter() - t0)
            total = float(sum(v for v in sse.values() if np.isfinite(v)))
            if self.compute_sse:
                self.sse_history.append(total)
            if verbose:
                log._emit(
                    f"Split {split + 1}: cluster {target} -> "
                    f"({target}, {new_id}), sizes = "
                    f"({counts[0]:.0f}, {counts[1]:.0f})"
                    + (f", total SSE = {total:.4f}"
                       if self.compute_sse else ""))
            self.iterations_run = split + 1
            # Heartbeat (ISSUE 11): one progress record per completed
            # split — the tree state is host-side already, zero extra
            # dispatches (no-op with no heartbeat installed).
            obs_note_progress(self, phase="split", segment=split + 1,
                              clusters=len(cents))
            if checkpoint_every and (split + 1) % checkpoint_every == 0:
                self._snapshot_tree(split + 1, labels, cents, sse, wsize,
                                    members)
                self.checkpoint_segments_ += 1
                self._write_autockpt(checkpoint_path, split + 1)

        k_out = len(cents)
        if k_out == 1:
            # k=1: the single "leaf" centroid is the weighted mean — one
            # pass against a zero centroid yields exactly the global sums;
            # a second pass against the mean gives its SSE directly.  Both
            # the variance identity sum(w|x|^2) - |s|^2/W and the matmul
            # distance form cancel catastrophically in f32 for data offset
            # from the origin, so the SSE pass uses the exact 'direct'
            # distance mode (k=1 makes its (chunk, 1, D) tile trivial).
            zero = self._put_centroids(
                np.zeros((1, ds.d), dtype=self.dtype), mesh, model_shards)
            stats = step_fn(ds.points, ds.weights, zero)
            s = np.asarray(stats.sums, np.float64)[0]
            c = float(np.asarray(stats.counts, np.float64)[0])
            cents[0] = (s / max(c, 1.0)).astype(self.dtype)
            mean = self._put_centroids(cents[0][None, :], mesh, model_shards)
            # k=1 'direct' tiles are (chunk, 1, D): clamp by D, not k,
            # so a hint-oversized single chunk can't stage a chunk x D
            # transform tile (ShardedDataset.effective_chunk).
            step_exact, _ = _get_step_fns(mesh, ds.effective_chunk(ds.d),
                                          "direct")
            stats = step_exact(ds.points, ds.weights, mean)
            sse[0] = float(np.asarray(stats.sse_per_cluster, np.float64)[0])
            wsize[0] = c
            if self.compute_sse:
                self.sse_history.append(sse[0])

        self.centroids = np.stack(
            [np.asarray(cents[i], dtype=self.dtype) for i in range(k_out)])
        if not np.all(np.isfinite(self.centroids)):  # kmeans_spark.py:289-290
            # Divergence-rollback exit (ISSUE 5): iteration == splits
            # completed; the last-good split-boundary checkpoint (when
            # one is active) is restored before the error propagates.
            self._raise_divergence("centroids", self.iterations_run)
        self.labels_ = labels
        self.cluster_sse_ = np.array([sse[i] for i in range(k_out)])
        self.cluster_sizes_ = np.array([wsize[i] for i in range(k_out)])
        if checkpoint_every and self.iterations_run % checkpoint_every \
                and self.iterations_run:
            # Off-cadence tail (k-1 not a multiple of N): the finished
            # tree is still durably on disk.
            self._snapshot_tree(self.iterations_run, labels, cents, sse,
                                wsize, members)
            self.checkpoint_segments_ += 1
            self._write_autockpt(checkpoint_path, self.iterations_run)
        return self

    def _snapshot_tree(self, splits_done: int, labels, cents, sse, wsize,
                       members) -> None:
        """Freeze the split tree at a boundary (all leaves have centroids
        once the first split landed) — the arrays a checkpointed resume
        rebuilds the leaf dicts from."""
        L = len(cents)
        self._tree_state = {
            "splits_done": int(splits_done),
            "labels": np.asarray(labels, np.int32).copy(),
            "cents": np.stack([np.asarray(cents[i], np.float64)
                               for i in range(L)]),
            "sse": np.asarray([sse[i] for i in range(L)], np.float64),
            "wsize": np.asarray([wsize[i] for i in range(L)], np.float64),
            "members": np.asarray([members[i] for i in range(L)],
                                  np.int64),
        }

    def fit_stream(self, make_blocks, *, d=None, resume=False,
                   prefetch=2, **kwargs):
        """Blocked: the inherited ``fit_stream`` would run plain flat Lloyd
        — no bisecting tree, stale ``cluster_sse_``/``labels_`` semantics
        (ADVICE r1).  Bisecting needs random row access for its per-split
        2-means fits, which a stream cannot serve."""
        raise NotImplementedError(
            "BisectingKMeans does not support fit_stream (the split tree "
            "needs the full dataset resident); use KMeans.fit_stream for a "
            "flat out-of-core fit")

    # ------------------------------------------------------------ checkpoint

    def _state_dict(self) -> dict:
        state = super()._state_dict()
        state["bisecting_strategy"] = self.bisecting_strategy
        tree = getattr(self, "_tree_state", None)
        if tree is not None:
            # Mid-tree auto-checkpoint state (ISSUE 4): the (n,) label
            # array plus per-leaf tables — what fit(resume=<path>) needs
            # to continue splitting bit-identically.  Only present on
            # fits run with checkpoint_every > 0; plain save() stays
            # O(k*D).
            state["tree_labels"] = tree["labels"]
            state["tree_cents"] = tree["cents"]
            state["tree_sse"] = tree["sse"]
            state["tree_wsize"] = tree["wsize"]
            state["tree_members"] = tree["members"]
            state["tree_splits_done"] = int(tree["splits_done"])
        return state

    def _restore_state(self, state: dict) -> None:
        super()._restore_state(state)
        # Clear-then-restore: a stale in-memory tree must never shadow
        # the checkpoint being restored.
        self._tree_state = None
        if "tree_labels" in state:
            self._tree_state = {
                "splits_done": int(state["tree_splits_done"]),
                "labels": np.asarray(state["tree_labels"], np.int32),
                "cents": np.asarray(state["tree_cents"], np.float64),
                "sse": np.asarray(state["tree_sse"], np.float64),
                "wsize": np.asarray(state["tree_wsize"], np.float64),
                "members": np.asarray(state["tree_members"], np.int64),
            }

    @classmethod
    def _load_kwargs(cls, state: dict) -> dict:
        return {"bisecting_strategy": state.get("bisecting_strategy",
                                                "biggest_sse")}
