"""Centroid initialization strategies.

* ``forgy_init`` — capability parity with the reference's
  ``_initialize_centroids`` (kmeans_spark.py:58-82): sample k distinct points,
  seeded, without replacement (``rdd.takeSample(False, k, seed)``,
  kmeans_spark.py:72); raise if fewer than k points; all-finite validation.
* ``kmeanspp_init`` — beyond-reference superset: D² weighting (Arthur &
  Vassilvitskii 2007), distance updates jit-compiled on device so the O(nkD)
  work runs on the MXU; only the per-step categorical draw happens host-side.

All entry points accept either a host ``(n, D)`` array or a
``parallel.sharding.ShardedDataset`` (row access via ``.take``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from kmeans_tpu.utils.validation import check_finite_array


class _EpochReservoir:
    """Seeded Algorithm-R reservoir over streamed rows: a uniform
    without-replacement sample of up to ``cap`` rows, maintained with
    O(block) vectorized host work per block.  Serves ``fit_stream``'s
    'resample' empty-cluster policy AND the streamed initializers (a
    cap-k reservoir over one full pass IS the reference's
    ``takeSample(False, k, seed)`` over the full distributed dataset,
    kmeans_spark.py:72 — r3 VERDICT #3: first-block-only seeding)."""

    def __init__(self, cap: int, d: int, rng: np.random.Generator):
        self.cap = cap
        self.rng = rng
        self.rows = np.zeros((cap, d), np.float64)
        self.seen = 0

    @property
    def filled(self) -> int:
        return min(self.seen, self.cap)

    def offer(self, block: np.ndarray) -> None:
        b = np.asarray(block, np.float64)
        nfill = max(0, min(self.cap - self.seen, len(b)))
        if nfill:
            self.rows[self.seen: self.seen + nfill] = b[:nfill]
        rest = b[nfill:]
        if len(rest):
            # Vectorized Algorithm R: row with global index t replaces a
            # reservoir slot iff randint(0, t+1) < cap.  NumPy fancy
            # assignment applies duplicates in order (last wins), which
            # reproduces the sequential algorithm exactly.
            t = self.seen + nfill + np.arange(len(rest))
            j = self.rng.integers(0, t + 1)
            hit = j < self.cap
            self.rows[j[hit]] = rest[hit]
        self.seen += len(b)

    def sample(self, m: int, rng: np.random.Generator) -> np.ndarray:
        take = min(m, self.filled)
        if take == 0:
            return np.empty((0, self.rows.shape[1]))
        idx = rng.choice(self.filled, size=take, replace=False)
        return self.rows[idx]


class _ArraySource:
    """Adapter giving a host ndarray the ShardedDataset row-access API.
    Optional ``weights`` make ``positive_rows``/``host_weights`` honor
    per-row sample weights (a zero-weight row must never seed a
    centroid)."""

    def __init__(self, X: np.ndarray, weights: Optional[np.ndarray] = None):
        self._X = np.asarray(X)
        self.n, self.d = self._X.shape
        self.dtype = self._X.dtype
        self._w = None if weights is None else np.asarray(weights)

    def take(self, idx):
        return self._X[idx]

    def positive_rows(self):
        if self._w is None:
            return np.arange(self.n)
        return np.flatnonzero(self._w > 0)

    @property
    def host(self):
        return self._X

    @property
    def host_weights(self):
        return self._w


def as_source(X, weights=None):
    if hasattr(X, "take") and hasattr(X, "n"):
        return X
    return _ArraySource(X, weights)


def forgy_init(X, k: int, seed: int, *, validate: bool = True) -> np.ndarray:
    """Seeded sample of k distinct rows (kmeans_spark.py:58-82 semantics).

    With sample weights present, sampling is uniform over the POSITIVE-
    weight rows only (a zero-weight row must never seed a centroid — it
    would start an empty cluster)."""
    src = as_source(X)
    candidates = src.positive_rows()
    if len(candidates) < k:
        raise ValueError(
            f"Not enough data points ({len(candidates)}) to initialize "
            f"{k} clusters")
    rng = np.random.RandomState(seed)
    idx = candidates[rng.choice(len(candidates), size=k, replace=False)]
    centroids = np.asarray(src.take(idx))
    # Same message as the reference's finite guard (kmeans_spark.py:79-80).
    if validate:
        check_finite_array(centroids, "Data contains NaN or Inf values")
    return centroids


@functools.partial(jax.jit, donate_argnums=(1,))
def _update_mind2(x: jax.Array, mind2: jax.Array, c: jax.Array) -> jax.Array:
    d2 = jnp.sum((x - c[None, :]) ** 2, axis=-1)
    return jnp.minimum(mind2, d2)


def _weighted_kmeanspp_host(X: np.ndarray, w: np.ndarray, k: int,
                            rng: np.random.Generator) -> np.ndarray:
    """Core weighted D²-seeding loop over a host array (device-accelerated
    distance maintenance); also the final reduction step of kmeans||."""
    n = X.shape[0]
    if int((w > 0).sum()) < k:
        raise ValueError(
            f"Not enough data points ({int((w > 0).sum())}) to initialize "
            f"{k} clusters")
    centers = np.empty((k, X.shape[1]), dtype=X.dtype)
    centers[0] = X[rng.choice(n, p=w / w.sum())]   # first draw ~ weights
    # Small arrays (every kmeans|| reduction: ~10k candidate rows) run
    # the distance maintenance in PURE numpy: the device path costs one
    # device->host transfer PER DRAW, and on a tunneled platform that
    # round trip is ~120 ms — 1023 draws made the k=1024 kmeans||
    # reduce take 126 s while the numpy loop is milliseconds (r5,
    # time-to-solution run).  Large arrays keep the device path: there
    # the O(n*d) per-draw distance update dwarfs the transfer.
    on_host = X.size <= (1 << 22)
    x = X.astype(np.float64, copy=False) if on_host else jnp.asarray(X)
    mind2 = (np.full((n,), np.inf) if on_host
             else jnp.full((n,), jnp.inf, dtype=x.dtype))
    for i in range(1, k):
        if on_host:
            diff = x - centers[i - 1].astype(np.float64)
            mind2 = np.minimum(mind2, (diff * diff).sum(axis=1))
            p = w * np.maximum(mind2, 0.0)
        else:
            mind2 = _update_mind2(x, mind2, jnp.asarray(centers[i - 1]))
            # D^2 weighting scaled by sample weights: p ~ w * mind2.
            p = w * np.maximum(np.asarray(mind2, dtype=np.float64), 0.0)
        total = p.sum()
        if not np.isfinite(total) or total <= 0:
            idx = rng.choice(n, p=w / w.sum())  # degenerate: coincident pts
        else:
            idx = rng.choice(n, p=p / total)
        centers[i] = X[idx]
    return centers


def kmeanspp_init(X, k: int, seed: int, *, validate: bool = True
                  ) -> np.ndarray:
    """k-means++ seeding; device-accelerated distance maintenance.

    ``validate=False`` skips the full-array finite scan — for callers that
    already validated the data once and re-seed repeatedly over the same
    array (e.g. BisectingKMeans' per-split 2-means fits)."""
    src = as_source(X)
    host = getattr(src, "host", None)
    if host is None:
        # Pre-sharded device-only data: run the on-device variant.
        return kmeanspp_device_init(src, k, seed)
    X = host
    sw = getattr(src, "host_weights", None)
    w = (np.ones(X.shape[0]) if sw is None
         else np.asarray(sw, dtype=np.float64))
    # Full scan (not just the chosen rows): a NaN anywhere poisons the D^2
    # distance weights, so the guard must cover all of X here.
    if validate:
        check_finite_array(X, "Data contains NaN or Inf values")
    return _weighted_kmeanspp_host(X, w, k, np.random.default_rng(seed))


def _kmeanspp_body(points: jax.Array, weights: jax.Array, k: int,
                   key) -> jax.Array:
    """Traceable core of the one-dispatch weighted k-means++ (see
    ``_kmeanspp_device`` for the seeding semantics).  Shared by the
    standalone device init AND the on-device k-means|| pipeline's final
    recluster (``_build_parallel_pipeline``), so the Gumbel-top-k draw
    machinery exists exactly once."""
    n, d = points.shape
    neg_inf = jnp.array(-jnp.inf, points.dtype)

    w_logits = jnp.where(weights > 0, jnp.log(jnp.maximum(weights, 1e-38)),
                         neg_inf)

    def draw(logits, subkey):
        g = jax.random.gumbel(subkey, (n,), dtype=points.dtype)
        # Degenerate fallback (all remaining mass zero): weight-proportional
        # over the real rows.
        logits = jnp.where(jnp.any(jnp.isfinite(logits)), logits, w_logits)
        return jnp.argmax(logits + g)

    idx0 = draw(w_logits, jax.random.fold_in(key, 0))  # first ~ weights
    centers0 = jnp.zeros((k, d), points.dtype).at[0].set(points[idx0])
    mind20 = jnp.full((n,), jnp.inf, points.dtype)

    def body(i, carry):
        centers, mind2 = carry
        c = centers[i - 1]
        d2 = jnp.sum((points - c[None, :]) ** 2, axis=1)
        mind2 = jnp.minimum(mind2, d2)
        p = weights * mind2                 # D^2 x sample-weight mass
        logits = jnp.where(p > 0, jnp.log(p), neg_inf)
        idx = draw(logits, jax.random.fold_in(key, i))
        return centers.at[i].set(points[idx]), mind2

    centers, _ = jax.lax.fori_loop(1, k, body, (centers0, mind20))
    return centers


@functools.partial(jax.jit, static_argnames=("k",))
def _kmeanspp_device(points: jax.Array, weights: jax.Array, k: int,
                     seed) -> jax.Array:
    """Whole k-means++ seeding in ONE dispatch, GSPMD-parallel over sharded
    points.  The categorical D²-draw uses the Gumbel-max trick — an argmax
    over (log p + gumbel noise), which XLA parallelizes across shards the
    same way every other reduction here is — so no host round-trip and no
    gather of the (n,) distance vector ever happens."""
    return _kmeanspp_body(points, weights, k, jax.random.PRNGKey(seed))


def kmeanspp_device_init(ds, k: int, seed: int) -> np.ndarray:
    """k-means++ on a ShardedDataset — fully on-device (see
    ``_kmeanspp_device``); used automatically when no host copy exists."""
    if ds.n < k:
        raise ValueError(
            f"Not enough data points ({ds.n}) to initialize {k} clusters")
    centers = np.asarray(_kmeanspp_device(ds.points, ds.weights, k, seed))
    check_finite_array(centers, "Data contains NaN or Inf values")
    return centers


@functools.partial(jax.jit, static_argnames=("cap",))
def _parallel_round(weights, mind2, phi, key, ell, cap: int):
    """One kmeans|| oversampling round, fully on device: Bernoulli-sample
    each point with prob min(1, ell*w*d²/phi); returns up to ``cap`` sampled
    indices plus a validity mask.  The caller is responsible for folding the
    returned candidates into ``mind2`` before the next round."""
    p = jnp.minimum(1.0, ell * weights * mind2 /
                    jnp.maximum(phi, jnp.finfo(mind2.dtype).tiny))
    u = jax.random.uniform(key, mind2.shape, dtype=mind2.dtype)
    sampled = (u < p) & (weights > 0)
    # Up to cap winners; among sampled points the u-order is an arbitrary
    # (seed-determined) subset, which is what the cap needs.
    score = jnp.where(sampled, 1.0 + u, 0.0)
    vals, idx = jax.lax.top_k(score, cap)
    return idx, vals > 0


@functools.partial(jax.jit, donate_argnums=(1,))
def _fold_candidates(points, mind2, cands, valid):
    """mind2 <- min(mind2, d²(points, c)) over all valid candidate rows,
    as ONE chunked matmul-form distance pass.

    r5 rewrite: the original scanned candidates one at a time, each step
    broadcasting (points - c)² over the full array — a re-read of the
    whole dataset PER CANDIDATE (10.5 TB of HBM traffic per round at
    10M x 128 with the 2048-candidate cap; measured 348 s of k-means||
    init in the time-to-solution run).  The matmul form reads points
    once per round and puts the distance work on the MXU.  Invalid
    candidate rows get ``+inf`` squared norms, so they can never win the
    min — same semantics as the masked scan."""
    from kmeans_tpu.ops.assign import pairwise_sq_dists

    n, d = points.shape
    cap = cands.shape[0]
    # (chunk, cap) distance tile bounded at 2^23 elems; cap treated as
    # >= 64 so a 1-candidate fold doesn't slice GB-scale windows.
    chunk = int(min(n, max(128, (1 << 23) // max(cap, 64) // 8 * 8)))
    n_chunks = -(-n // chunk)

    def body(i, m):
        # Clamped sliding window: the last window may overlap the
        # previous one — min is idempotent, re-minning rows is free.
        start = jnp.minimum(i * chunk, n - chunk)
        zero = jnp.zeros((), start.dtype)
        xc = jax.lax.dynamic_slice(points, (start, zero), (chunk, d))
        mc = jax.lax.dynamic_slice(m, (start,), (chunk,))
        # HIGHEST cross-term: the fold's answer is the distance VALUE —
        # a covered point must read ~0, and bf16-rounded products would
        # leave it |x||c|*2^-8 of sampling mass (see pairwise_sq_dists).
        d2 = pairwise_sq_dists(xc, cands,
                               precision=jax.lax.Precision.HIGHEST)
        d2 = jnp.where(valid[None, :], d2, jnp.inf)
        # pairwise_sq_dists accumulates in at least f32; cast back so
        # float16 mind2 buffers round-trip (r5 review).
        best = jnp.minimum(mc, jnp.min(d2, axis=1).astype(m.dtype))
        return jax.lax.dynamic_update_slice(m, best, (start,))

    return jax.lax.fori_loop(0, n_chunks, body, mind2)


def _kmeans_parallel_host(src, k: int, seed: int, *, rounds: int = 5,
                          oversampling: Optional[float] = None,
                          cap: Optional[int] = None,
                          return_candidates: bool = False) -> np.ndarray:
    """LEGACY kmeans|| engine (the ``device=False`` path): per-round device
    dispatches with host-side candidate bookkeeping and a host-side final
    weighted k-means++ reduce.  Retained verbatim as the parity oracle for
    the one-dispatch device pipeline — its seeded trajectory is pinned by
    tests, so treat any behavioral change here as a breaking change.  On a
    tunneled platform each round pays a device->host round trip (~70-100 ms)
    plus host numpy; that structural cost is why the DEVICE pipeline is now
    the default (see ``kmeans_parallel_init``)."""
    from kmeans_tpu.ops.assign import assign_reduce
    from kmeans_tpu.utils import profiling

    candidates_idx = src.positive_rows()

    points = getattr(src, "points", None)
    weights = getattr(src, "weights", None)
    if points is None:                   # plain host array source
        points = jnp.asarray(src.host)
        weights = (jnp.ones(src.n, points.dtype)
                   if src.host_weights is None
                   else jnp.asarray(src.host_weights, points.dtype))

    ell = float(oversampling if oversampling is not None else 2 * k)
    # cap may not exceed the (padded) point count — lax.top_k requires it.
    # Default clamp(2k, 256, 2048) unchanged since r5 (the pinned
    # oracle trajectory); an explicit cap (ISSUE 16 — KMeans(init_cap=))
    # overrides the capacity, bounded the same way.
    cap = int(min(max(2 * k, 256), 2048, points.shape[0])) if cap is None \
        else int(min(max(int(cap), 1), points.shape[0]))
    rounds = max(rounds, -(-int(1.5 * k) // cap))  # ensure >= 1.5k samples
    key = jax.random.PRNGKey(seed)
    rng = np.random.default_rng(seed)

    # Seed candidate: one weight-proportional draw (matching the first draw
    # of _weighted_kmeanspp_host / _kmeanspp_device).
    sw = getattr(src, "host_weights", None)
    if sw is None:
        first = int(candidates_idx[rng.integers(len(candidates_idx))])
    else:
        pw = np.asarray(sw, dtype=np.float64)[candidates_idx]
        first = int(candidates_idx[rng.choice(len(candidates_idx),
                                              p=pw / pw.sum())])
    cand_rows = [np.asarray(src.take(np.array([first])))]
    cand_valid = [np.ones(1, bool)]
    mind2 = jnp.full((points.shape[0],), jnp.inf, points.dtype)
    mind2 = _fold_candidates(points, mind2,
                             jnp.asarray(cand_rows[0]),
                             jnp.ones(1, bool))

    for r in range(rounds):
        phi = jnp.sum(jnp.where(weights > 0, mind2 * weights, 0.0))
        idx, valid = _parallel_round(weights, mind2, phi,
                                     jax.random.fold_in(key, r), ell, cap)
        rows_dev = points[idx]                # gather stays on device
        # One device->host round trip PER ROUND — the structural cost the
        # device pipeline exists to remove (ISSUE 2).
        profiling.note_dispatch("kmeans||/round")
        cand_rows.append(np.asarray(rows_dev))
        cand_valid.append(np.asarray(valid))
        mind2 = _fold_candidates(points, mind2, rows_dev, valid)

    cands = np.concatenate(cand_rows)[np.concatenate(cand_valid)]
    cands = np.unique(cands, axis=0)
    if len(cands) < k:                       # tiny data: backfill uniformly
        extra = src.take(candidates_idx[rng.choice(
            len(candidates_idx), size=k - len(cands), replace=False)])
        cands = np.concatenate([cands, np.asarray(extra)])

    # Weight candidates by their nearest-candidate cell mass: one fused
    # pass of the SAME step kernel with candidates as "centroids".
    # Chunk by the shared budget rule — the old hardcoded 512 meant a
    # ~19,500-step scan at the 10M headline (r5).
    from kmeans_tpu.parallel.sharding import choose_chunk_size
    chunk = choose_chunk_size(points.shape[0], len(cands), points.shape[1])
    pad = (-points.shape[0]) % chunk
    pts_pad = jnp.pad(points, ((0, pad), (0, 0)))
    w_pad = jnp.pad(weights, (0, pad))
    stats = assign_reduce(pts_pad, w_pad, jnp.asarray(cands),
                          chunk_size=chunk)
    profiling.note_dispatch("kmeans||/cell-mass")
    cell_mass = np.maximum(np.asarray(stats.counts, np.float64), 1e-12)

    centers = _weighted_kmeanspp_host(cands.astype(np.float64), cell_mass,
                                      k, rng)
    profiling.note_dispatch("kmeans||/host-reduce")
    centers = centers.astype(np.asarray(cands).dtype)
    if return_candidates:
        return centers, np.asarray(cands), cell_mass
    return centers


# ------------------------------------------- one-dispatch kmeans|| (ISSUE 2)
# Coordinates of unused candidate-buffer slots.  Same class of trick as
# distributed.PAD_CENTROID_VALUE: far beyond any real datum, finite in
# float32 even after squaring against real rows, so a sentinel slot can
# never win an argmin/min and earns zero cell mass — which lets every
# fixed-shape pass (fold, cell mass, recluster) run maskless.
_CAND_SENTINEL = 1e12

# Compiled pipeline per (mesh, statics) — the shard_map closure must be
# reused or every init would recompile (same pattern as kmeans._STEP_CACHE).
from kmeans_tpu.utils.cache import LRUCache

_PIPE_CACHE = LRUCache(32, name="init._PIPE_CACHE")

# Module-level (compiled once): the positive-row count for hostless
# datasets — a per-call lambda would re-trace on every init.
_count_positive = jax.jit(lambda w: jnp.sum(w > 0))


def _build_parallel_pipeline(mesh, *, k: int, rounds: int, cap: int,
                             refine: int, chunk_fold: int, chunk_mass: int,
                             use_pallas: bool):
    """Build the ONE-DISPATCH kmeans|| pipeline (Bahmani et al. 2012,
    Arthur & Vassilvitskii 2007 D²-weighting for the final reduce):

    1. weight-proportional first draw (global Gumbel-argmax);
    2. ``rounds`` oversampling rounds inside a single ``lax.fori_loop``:
       Bernoulli draw with prob ``min(1, ell*w*d²/phi)``, per-shard
       ``top_k(cap)`` + exact cross-shard top-k combine, candidate rows
       written into a fixed-capacity ``(1 + rounds*cap, D)`` buffer
       (unused slots carry ``_CAND_SENTINEL`` coordinates), and the
       mind2 table folded against only the round's NEW candidates;
    3. one chunked cell-mass pass (nearest-candidate weighted counts);
    4. on-device weighted k-means++ over the candidate buffer
       (``_kmeanspp_body`` — the Gumbel-top-k machinery from the device
       forgy/k-means++ rewrite) + ``refine`` weighted Lloyd steps on the
       (cap_total, D) table.

    Everything runs in ONE host dispatch — O(1) in ``rounds`` — under a
    ``data``-axis ``shard_map`` when a mesh exists, so multi-chip inits
    never gather the dataset: the only cross-shard traffic is the scalar
    phi psum, the (S, cap) candidate-score/row gathers, and the (cap_total,)
    cell-mass psum.  Every random draw is a function of the GLOBAL row
    index (each shard generates the full (n_glob,) stream and slices its
    segment — the ``_refill_empty_slots`` pattern), so results are
    invariant to the shard count.

    ``use_pallas`` routes the O(n·cap·D) mind2 maintenance and the cell-
    mass assignment through the fused Pallas kernel's mind2/labels outputs
    (``pallas_assign``) with ``prep_points`` hoisted ONCE per init —
    only chosen inside the kernel's measured win region
    (``pallas_preferred`` at k=cap).  Trade documented in
    ``kmeans_parallel_init``: the kernel's bf16-rate products leave
    covered rows ~|x||c|·2⁻⁸ of spurious sampling mass where the XLA
    route's HIGHEST-precision fold reads ~0 — harmless for Bernoulli
    OVERSAMPLING (kmeans|| is robust to the oversampling factor; the
    final recluster re-weighs candidates by exact cell mass), unlike the
    assignment-value uses that forced HIGHEST elsewhere.

    NOT done: threading the final mind2 into the fit.  The fit's first
    pass assigns against the k REDUCED centers, not the candidate set,
    and mind2-vs-candidates is not mind2-vs-centers — there is nothing
    sound for the training loop to reuse.
    """
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from kmeans_tpu.ops.assign import pairwise_sq_dists
    from kmeans_tpu.parallel.mesh import (DATA_AXIS, mesh_shape, shard_map)

    data_shards, _ = mesh_shape(mesh)
    cap_total = 1 + rounds * cap
    interpret = jax.default_backend() != "tpu"

    def pipeline(points, weights, seed, ell):
        n_local, d = points.shape
        acc = jnp.promote_types(points.dtype, jnp.float32)
        w = weights.astype(acc)
        n_glob = n_local * data_shards
        d_idx = lax.axis_index(DATA_AXIS) if data_shards > 1 else 0
        key = jax.random.PRNGKey(seed)
        neg_inf = jnp.array(-jnp.inf, acc)
        ell_a = jnp.asarray(ell, acc)
        sentinel = jnp.asarray(_CAND_SENTINEL, points.dtype)

        if use_pallas:
            from kmeans_tpu.ops.pallas_kernels import (pallas_assign,
                                                       prep_points)
            # Hoisted ONCE per init: the kernel's row/lane padding + fold
            # column (XLA does not hoist these full-array writes itself).
            xp, _, _ = prep_points(points, w)

        def fold(mind2, cands):
            """mind2 <- min(mind2, d²(points, cands)).  Sentinel slots
            lose every min by construction, so no validity mask is
            needed.  XLA route: chunked matmul-form distances at HIGHEST
            cross-term precision (the VALUE is sampling mass — a covered
            point must read ~0, see _fold_candidates)."""
            if use_pallas:
                _, m_new = pallas_assign(xp, cands, interpret=interpret)
                return jnp.minimum(mind2, m_new[:n_local].astype(acc))
            n_chunks = -(-n_local // chunk_fold)

            def body(i, m):
                # Clamped sliding window (re-minning overlap rows is free).
                start = jnp.minimum(i * chunk_fold, n_local - chunk_fold)
                xc = lax.dynamic_slice(
                    points, (start, jnp.zeros((), start.dtype)),
                    (chunk_fold, d))
                mc = lax.dynamic_slice(m, (start,), (chunk_fold,))
                d2 = pairwise_sq_dists(xc, cands,
                                       precision=jax.lax.Precision.HIGHEST)
                best = jnp.minimum(mc, jnp.min(d2, axis=1).astype(m.dtype))
                return lax.dynamic_update_slice(m, best, (start,))

            return lax.fori_loop(0, n_chunks, body, mind2)

        # ---- weight-proportional first draw (global Gumbel-argmax).
        w_logits = jnp.where(w > 0, jnp.log(jnp.maximum(w, 1e-38)), neg_inf)
        g = jax.random.gumbel(jax.random.fold_in(key, 0), (n_glob,), acc)
        g_loc = lax.dynamic_slice(g, (d_idx * n_local,), (n_local,))
        s0 = w_logits + g_loc
        j0 = jnp.argmax(s0)
        if data_shards > 1:
            s_all = lax.all_gather(s0[j0], DATA_AXIS)         # (S,)
            r_all = lax.all_gather(points[j0], DATA_AXIS)     # (S, d)
            c0 = r_all[jnp.argmax(s_all)]
        else:
            c0 = points[j0]

        buf = jnp.full((cap_total, d), sentinel,
                       points.dtype).at[0].set(c0.astype(points.dtype))
        valid = jnp.zeros((cap_total,), bool).at[0].set(True)
        mind2 = fold(jnp.full((n_local,), jnp.inf, acc), buf[:1])

        # ---- all oversampling rounds in ONE fori_loop (zero host syncs).
        def round_body(r, carry):
            buf, valid, mind2 = carry
            phi_loc = jnp.sum(w * mind2)
            phi = lax.psum(phi_loc, DATA_AXIS) if data_shards > 1 \
                else phi_loc
            p = jnp.minimum(1.0, ell_a * w * mind2 /
                            jnp.maximum(phi, jnp.finfo(acc).tiny))
            u = jax.random.uniform(jax.random.fold_in(key, 1 + r),
                                   (n_glob,), acc)
            u_loc = lax.dynamic_slice(u, (d_idx * n_local,), (n_local,))
            # Among sampled points the u-order is an arbitrary (seed-
            # determined) subset — the same cap rule as _parallel_round.
            score = jnp.where((u_loc < p) & (w > 0), 1.0 + u_loc, 0.0)
            vals, idx = lax.top_k(score, cap)
            rows = points[idx]
            if data_shards > 1:
                # Exact distributed top-k: any global top-cap element is
                # inside its own shard's top-cap.
                v_all = lax.all_gather(vals, DATA_AXIS).reshape(-1)
                r_all = lax.all_gather(rows, DATA_AXIS).reshape(-1, d)
                vals, j = lax.top_k(v_all, cap)
                rows = r_all[j]
            ok = vals > 0
            rows = jnp.where(ok[:, None], rows, sentinel)
            mind2 = fold(mind2, rows)
            # Explicit common index dtype: under x64 the loop counter is
            # int64 while jnp.int32(0) is not — dynamic_update_slice
            # rejects mixed index dtypes.
            off = jnp.asarray(1 + r * cap, jnp.int32)
            buf = lax.dynamic_update_slice(buf, rows, (off, jnp.int32(0)))
            valid = lax.dynamic_update_slice(valid, ok, (off,))
            return buf, valid, mind2

        buf, valid, mind2 = lax.fori_loop(0, rounds, round_body,
                                          (buf, valid, mind2))

        # ---- cell mass: nearest-candidate weighted counts, one chunked
        # pass (assignment only — default matmul precision suffices; only
        # boundary ties could flip, exactly like the training step).
        if use_pallas:
            labels, _ = pallas_assign(xp, buf, interpret=interpret)
            mass = jax.ops.segment_sum(w, labels[:n_local],
                                       num_segments=cap_total)
        else:
            pad = (-n_local) % chunk_mass
            pts_p = jnp.pad(points, ((0, pad), (0, 0)))
            w_p = jnp.pad(w, (0, pad))
            xs = (pts_p.reshape(-1, chunk_mass, d),
                  w_p.reshape(-1, chunk_mass))

            def mass_body(m, ch):
                xc, wc = ch
                best = jnp.argmin(pairwise_sq_dists(xc, buf), axis=1)
                return m + jax.ops.segment_sum(
                    wc, best, num_segments=cap_total), None

            mass, _ = lax.scan(mass_body, jnp.zeros((cap_total,), acc), xs)
        if data_shards > 1:
            mass = lax.psum(mass, DATA_AXIS)

        # ---- final reduce ON DEVICE: weighted k-means++ over the buffer
        # (replicated O(cap_total·k·D) work per shard) + a few weighted
        # Lloyd steps on the candidate table.
        mass_pos = jnp.where(valid, jnp.maximum(mass, 1e-12), 0.0)
        centers = _kmeanspp_body(buf, mass_pos.astype(buf.dtype), k,
                                 jax.random.fold_in(key, rounds + 1))

        ids = jnp.arange(k, dtype=jnp.int32)

        def refine_body(i, c):
            d2 = pairwise_sq_dists(buf.astype(acc), c.astype(acc))
            best = jnp.argmin(d2, axis=1).astype(jnp.int32)
            oh = (best[:, None] == ids[None, :]).astype(acc) \
                * mass_pos[:, None]
            sums = lax.dot_general(oh, buf.astype(acc),
                                   (((0,), (0,)), ((), ())),
                                   preferred_element_type=acc)
            counts = jnp.sum(oh, axis=0)
            return jnp.where((counts > 0)[:, None],
                             (sums / jnp.maximum(counts, 1.0)[:, None]
                              ).astype(c.dtype), c)

        centers = lax.fori_loop(0, refine, refine_body, centers)
        return centers, buf, valid, mass

    if mesh is None:
        return jax.jit(pipeline)
    mapped = shard_map(
        pipeline, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(), P()),
        out_specs=(P(None, None), P(None, None), P(None), P(None)),
        check_vma=False)
    return jax.jit(mapped)


def _distinct_backfill(centers: np.ndarray, src, k: int, seed: int
                       ) -> np.ndarray:
    """Replace duplicate rows of a (k, D) center table with seeded uniform
    positive-weight rows — the device pipeline's analogue of the legacy
    path's host-side candidate backfill (only reachable on tiny/degenerate
    data where the Bernoulli rounds cannot produce k distinct candidates).
    Skipped (centers returned as-is) when the source has no host row
    access (multi-host process-local data)."""
    _, first = np.unique(centers, axis=0, return_index=True)
    if len(first) >= k:
        return centers
    try:
        cand_idx = src.positive_rows()
    except ValueError:
        return centers
    keep = np.zeros(k, bool)
    keep[first] = True
    dup = np.flatnonzero(~keep)
    rng = np.random.default_rng([seed, 0xBF11])
    take = cand_idx[rng.choice(len(cand_idx),
                               size=min(len(dup), len(cand_idx)),
                               replace=False)]
    rows = np.asarray(src.take(take))
    centers[dup[: len(rows)]] = rows
    return centers


def kmeans_parallel_init(X, k: int, seed: int, *, rounds: int = 5,
                         oversampling: Optional[float] = None,
                         validate: bool = True, device: bool = True,
                         cap: Optional[int] = None, refine: int = 4,
                         return_candidates: bool = False) -> np.ndarray:
    """kmeans|| seeding (Bahmani et al. 2012) — the distributed-scale
    initializer.  Each round Bernoulli-samples ~l = oversampling*k
    candidates proportional to current D² cost; candidates are weighted by
    their nearest-candidate cell mass and reduced to k seeds with weighted
    k-means++ (Arthur & Vassilvitskii 2007 D² semantics).  O(rounds)
    passes over the data instead of k-means++'s O(k).

    ``device=True`` (the DEFAULT since ISSUE 2): the whole init — all
    oversampling rounds, the cell-mass pass, and the final weighted
    k-means++ reduce plus ``refine`` weighted Lloyd steps on the candidate
    table — runs as ONE device dispatch (``_build_parallel_pipeline``),
    under a ``data``-axis ``shard_map`` when the dataset is mesh-sharded
    (multi-chip inits never gather the dataset).  At the 2M×128 k=1024
    headline shape the legacy engine paid ~5 device→host round trips
    (~70–100 ms each on the tunneled platform) plus a host-side
    k-means++ over ~10k candidates — 7.4 s warm while the entire
    20-iteration training loop computes in 0.77 s; the pipeline removes
    every per-round sync (dispatch count O(1) in ``rounds``, pinned by
    tests/test_init_device.py).

    RNG-stream divergence (documented exactly like the r5 device forgy):
    the device pipeline draws from different seeded streams than the
    legacy engine — per-seed results differ from ``device=False`` but are
    deterministic, drawn from the same distributions, and the final
    refine step only tightens the Bahmani reduction.  ``device=False``
    keeps the legacy per-round host engine bit-for-bit
    (``_kmeans_parallel_host``) as the parity/trajectory oracle.

    ``cap`` overrides the per-round candidate capacity (default
    ``clamp(2k, 256, 2048)``, bounded by the per-shard row count) —
    promoted from an r5 internal constant to a real keyword, threaded
    from the estimator as ``KMeans(init_cap=...)`` (ISSUE 16: the
    two-level assignment tier reuses this candidate-buffer discipline
    and needs it sizeable per workload; both the device pipeline and
    the ``device=False`` host oracle honor it).  ``refine`` sets the
    on-device weighted Lloyd polish steps (device path only).  ``return_candidates=True`` additionally returns the
    (valid) candidate rows and their cell masses — the hook the candidate-
    set parity tests use."""
    from kmeans_tpu.utils import profiling

    src = as_source(X)
    # Positive-weight n >= k guard, without forcing host access for
    # device-only datasets (one tiny reduce there, not per-round).
    try:
        n_pos = len(src.positive_rows())
    except ValueError:
        n_pos = int(_count_positive(src.weights))
    if n_pos < k:
        raise ValueError(
            f"Not enough data points ({n_pos}) to initialize "
            f"{k} clusters")
    if validate and getattr(src, "host", None) is not None:
        check_finite_array(src.host, "Data contains NaN or Inf values")

    if not device:
        return _kmeans_parallel_host(
            src, k, seed, rounds=rounds, oversampling=oversampling,
            cap=cap, return_candidates=return_candidates)

    points = getattr(src, "points", None)
    weights = getattr(src, "weights", None)
    mesh = getattr(src, "mesh", None)
    if points is None:                   # plain host array source
        points = jnp.asarray(src.host)
        weights = (jnp.ones(src.n, points.dtype)
                   if src.host_weights is None
                   else jnp.asarray(src.host_weights, points.dtype))
        mesh = None

    from kmeans_tpu.parallel.mesh import mesh_shape
    data_shards, _ = mesh_shape(mesh)
    n_pad, d = points.shape
    n_local = n_pad // data_shards
    ell = float(oversampling if oversampling is not None else 2 * k)
    # cap may not exceed the per-shard row count — lax.top_k requires it.
    cap = int(min(max(2 * k, 256), 2048, n_local)) if cap is None \
        else int(min(max(int(cap), 1), n_local))
    rounds = max(rounds, -(-int(1.5 * k) // cap))  # ensure >= 1.5k samples
    cap_total = 1 + rounds * cap
    # Fold/mass chunks under the same tile budget as _fold_candidates.
    chunk_fold = int(min(n_local, max(128, (1 << 23) // max(cap, 64)
                                      // 8 * 8)))
    chunk_mass = int(min(n_local, max(128, (1 << 23) // max(cap_total, 64)
                                      // 8 * 8)))
    from kmeans_tpu.ops.pallas_kernels import pallas_preferred
    use_pallas = pallas_preferred(n_local, d, cap)

    fn = _PIPE_CACHE.get_or_create(
        (mesh, k, rounds, cap, refine, chunk_fold, chunk_mass, use_pallas),
        lambda: _build_parallel_pipeline(
            mesh, k=k, rounds=rounds, cap=cap, refine=refine,
            chunk_fold=chunk_fold, chunk_mass=chunk_mass,
            use_pallas=use_pallas))
    centers_d, buf_d, valid_d, mass_d = fn(
        points, weights.astype(points.dtype),
        np.uint32(seed % (2 ** 31)), np.asarray(ell, np.float64))
    profiling.note_dispatch("kmeans||/device-pipeline")
    # np.array, not np.asarray: jax returns its cached buffer view with
    # writeable=False, and _distinct_backfill writes duplicate slots.
    centers = np.array(centers_d)
    centers = _distinct_backfill(centers, src, k, seed)
    if validate:
        check_finite_array(centers, "Data contains NaN or Inf values")
    if return_candidates:
        v = np.asarray(valid_d)
        return centers, np.asarray(buf_d)[v], np.asarray(mass_d)[v]
    return centers


# ------------------------------------------------------------- streaming
# fit_stream initializers: the dataset is only ever seen block-at-a-time,
# so named strategies get streamed equivalents that draw over the FULL
# stream instead of its first block (r3 VERDICT #3; the reference's
# takeSample draws over the whole distributed dataset, kmeans_spark.py:72).
# All take a ``seeds`` LIST and share each data pass across restarts, so
# n_init=R costs R x compute but only 1x IO per pass.  Stream items may
# be bare (m, D) blocks or (block, weights) tuples (r4: weighted
# streams) — ``_split_block`` is the single decoder.


def _block_of(item):
    """Block part of a stream item, for inference paths that don't
    consume weights (predict/transform/score streams) — validates the
    tuple arity like ``_split_block`` but drops the weights."""
    if isinstance(item, tuple):
        if len(item) != 2:
            raise ValueError(
                f"stream items must be (m, D) blocks or (block, weights) "
                f"pairs, got a {len(item)}-tuple")
        return item[0]
    return item


def _split_block(item, d: int, dtype):
    """Decode one stream item: a bare (m, D) array or a (block, weights)
    tuple.  Returns (block contiguous in ``dtype``, weights (m,) in the
    block dtype or None), with the same validation every consumer needs."""
    if isinstance(item, tuple):
        if len(item) != 2:
            raise ValueError(
                f"stream items must be (m, D) blocks or (block, weights) "
                f"pairs, got a {len(item)}-tuple")
        block, w = item
    else:
        block, w = item, None
    block = np.ascontiguousarray(np.asarray(block, dtype=dtype))
    if block.ndim != 2 or block.shape[1] != d:
        raise ValueError(f"block shape {block.shape} != (*, {d})")
    if w is not None:
        # The SAME validation the in-memory sample_weight path applies
        # (shape, finiteness, non-negativity) — one rule, two engines.
        from kmeans_tpu.parallel.sharding import _validate_sample_weight
        w = _validate_sample_weight(w, block.shape[0], block.dtype)
    return block, w


def _reservoir_pass(make_blocks, cap: int, k: int, d: int, seeds,
                    salt: int):
    """Shared single-pass scaffold of the streamed samplers: one seeded
    cap-row Algorithm-R reservoir per restart over the POSITIVE-weight
    rows of the whole stream (the in-memory ``forgy_init`` weight rule).
    Raises the standard n<k error.  Returns (reservoirs, n_rows)."""
    from kmeans_tpu.data.prefetch import close_source
    res = [_EpochReservoir(cap, d, np.random.default_rng([s, salt]))
           for s in seeds]
    n = 0
    # close_source in finally: a decode error mid-pass must reap a
    # prefetching source's producer thread, not leave it to cyclic GC.
    it = iter(make_blocks())
    try:
        for item in it:
            block, bw = _split_block(item, d, np.float64)
            b = block if bw is None else block[bw > 0]
            n += len(b)
            for r in res:
                r.offer(b)
    finally:
        close_source(it)
    if n < k:
        raise ValueError(
            f"Not enough data points ({n}) to initialize {k} clusters")
    return res, n


def streamed_forgy_init(make_blocks, k: int, seeds, d: int, dtype):
    """ONE pass: per-seed cap-k Algorithm-R reservoirs — each result is a
    uniform without-replacement k-row sample of the whole stream, the
    exact capability of ``rdd.takeSample(False, k, seed)``
    (kmeans_spark.py:72).  Weighted streams draw uniformly over the
    POSITIVE-weight rows, the in-memory ``forgy_init`` rule.  Returns
    (list of (k, d) arrays, n_total)."""
    res, n = _reservoir_pass(make_blocks, k, k, d, seeds, 0xF0261)
    outs = []
    for r in res:
        c = r.rows[: r.filled].astype(dtype)
        check_finite_array(c, "Data contains NaN or Inf values")
        outs.append(c)
    return outs, n


def streamed_init_sample(make_blocks, k: int, seeds, d: int, dtype, *,
                         cap: Optional[int] = None):
    """ONE pass: per-seed uniform reservoir samples of the WHOLE stream
    for CALLABLE inits (r4 VERDICT #8 — callables previously saw only
    the first block, while every built-in streamed init draws over the
    full stream like the reference's ``takeSample`` over the whole
    distributed dataset, kmeans_spark.py:72).

    Each result is a uniform without-replacement sample of up to ``cap``
    positive-weight rows (Algorithm R), in randomly-permuted order —
    enough for a D²-weighting or subsample-then-solve callable to be
    meaningful, while bounding host memory (``cap`` defaults to
    ``clamp(16*k, 2048, 32768)`` and is floored to ``k`` so the sample
    can always seed k centroids).  Returns (list of (m, d) ``dtype``
    arrays, n_total)."""
    cap = int(cap if cap is not None else min(max(16 * k, 2048), 32768))
    cap = max(cap, k)
    res, n = _reservoir_pass(make_blocks, cap, k, d, seeds, 0xCA11AB1E)
    outs = []
    for r, s in zip(res, seeds):
        # The reservoir's slot order is fill-order-biased (early rows sit
        # in early slots); permute so positional callables (e.g.
        # ``lambda X, k, seed: X[:k]``) still get a uniform draw.
        rows = r.rows[: r.filled]
        perm = np.random.default_rng([s, 0x5EED]).permutation(len(rows))
        c = rows[perm].astype(dtype)
        check_finite_array(c, "Data contains NaN or Inf values")
        outs.append(c)
    return outs, n


@functools.partial(jax.jit, static_argnames=("cap",))
def _stream_round_block(x, w, cands, phi_prev, ell, key, cap: int):
    """One block's contribution to one streamed kmeans|| round: min
    squared distance to the CURRENT candidate set (matmul form on the
    MXU), Bernoulli-sample rows w.p. ``min(1, ell*w*d2/phi_prev)``,
    return up to ``cap`` sampled rows + validity + this block's weighted
    cost (which accumulates into the NEXT round's phi).  ``w`` carries
    the per-row sample weights folded into the 0/1 padding mask —
    blocks arrive padded to a fixed row multiple so ragged streams
    compile once per round, not once per block length; unweighted
    streams pass the bare mask (w=1 on real rows)."""
    from kmeans_tpu.ops.assign import pairwise_sq_dists
    # HIGHEST cross-term for the same reason as _fold_candidates: the
    # D^2 VALUE is the sampling mass, and bf16 products would leave
    # covered rows |x||c|*2^-8 instead of ~0.
    d2 = jnp.maximum(
        jnp.min(pairwise_sq_dists(x, cands, mode="matmul",
                                  precision=jax.lax.Precision.HIGHEST),
                axis=1), 0.0)
    d2w = d2 * w                                   # weighted D^2 mass;
    phi_b = jnp.sum(d2w)                           # padding rows: 0
    p = jnp.minimum(1.0, ell * d2w /
                    jnp.maximum(phi_prev, jnp.finfo(d2w.dtype).tiny))
    u = jax.random.uniform(key, d2w.shape, d2w.dtype)
    score = jnp.where((u < p) & (w > 0), 1.0 + u, 0.0)
    vals, idx = jax.lax.top_k(score, cap)
    return x[idx], vals > 0, phi_b


def streamed_kmeans_parallel_init(make_blocks, k: int, seeds, d: int,
                                  dtype, *, rounds: int = 5,
                                  oversampling: Optional[float] = None):
    """Streamed kmeans|| (Bahmani et al. 2012) over a block stream.

    Differences from the in-memory ``kmeans_parallel_init``, forced by
    the one-block-at-a-time access pattern and documented here:

    * ``phi`` for round r's sampling is the cost accumulated during
      round r-1's pass (one candidate-set stale — the true phi would
      need an extra pass per round).  A stale phi only LOWERS sampling
      probability slightly; kmeans|| is robust to the oversampling
      factor.
    * The first candidate comes from a cap-1 reservoir pass (uniform
      over the stream), and backfill rows (when dedup'd candidates < k)
      from a cap-k reservoir maintained during the cell-mass pass.

    Passes over the stream: 1 (reservoir) + 1 (initial phi) + rounds
    (sampling) + 1 (cell mass) — one-time init cost comparable to
    ``rounds + 3`` Lloyd iterations.  Returns (list of (k, d) arrays,
    n_total)."""
    from kmeans_tpu.ops.assign import assign_reduce

    R = len(seeds)
    ell = float(oversampling if oversampling is not None else 2 * k)
    cap = int(min(max(2 * k, 256), 2048))
    res = [_EpochReservoir(1, d, np.random.default_rng([s, 0xF1257]))
           for s in seeds]
    from kmeans_tpu.data.prefetch import close_source
    n = 0
    it = iter(make_blocks())                         # pass: first cand + n
    try:
        for item in it:
            block, bw = _split_block(item, d, np.float64)
            b = block if bw is None else block[bw > 0]
            n += len(b)
            for r in res:
                r.offer(b)
    finally:
        close_source(it)
    if n < k:
        raise ValueError(
            f"Not enough data points ({n}) to initialize {k} clusters")
    cands = [r.rows[:1].copy() for r in res]         # per-seed candidates

    def epoch_blocks():
        """Blocks padded to a fixed row multiple (>= cap, so top_k's
        static argument is always just ``cap``): ragged streams compile
        one program per round instead of one per block length.  Sample
        weights fold into the padding mask, making every downstream
        reduction weighted."""
        from kmeans_tpu.parallel.sharding import pad_points
        mult = -(-cap // 512) * 512      # >= cap AND a 512-chunk multiple
        it = iter(make_blocks())
        try:
            for item in it:
                block, bw = _split_block(item, d, dtype)
                x, w = pad_points(block, mult)
                if bw is not None:
                    w[: block.shape[0]] *= bw.astype(w.dtype)
                yield x, w
        finally:
            close_source(it)

    phi = np.zeros(R)
    for x, w in epoch_blocks():                      # pass: initial phi
        xd, wd = jnp.asarray(x), jnp.asarray(w)
        for r in range(R):
            _, _, phi_b = _stream_round_block(
                xd, wd, jnp.asarray(cands[r].astype(dtype)), jnp.inf,
                0.0, jax.random.PRNGKey(0), cap)
            phi[r] += float(phi_b)

    keys = [jax.random.PRNGKey(
        int(np.random.SeedSequence([s, 0xF1258]).generate_state(1)[0]
            % (2 ** 31))) for s in seeds]
    for rd in range(rounds):                         # sampling passes
        new = [[] for _ in range(R)]
        phi_next = np.zeros(R)
        for bi, (x, w) in enumerate(epoch_blocks()):
            xd, wd = jnp.asarray(x), jnp.asarray(w)
            for r in range(R):
                rows, valid, phi_b = _stream_round_block(
                    xd, wd, jnp.asarray(cands[r].astype(dtype)),
                    float(phi[r]), ell,
                    jax.random.fold_in(
                        jax.random.fold_in(keys[r], rd), bi), cap)
                rows, valid = np.asarray(rows), np.asarray(valid)
                if valid.any():
                    new[r].append(rows[valid].astype(np.float64))
                phi_next[r] += float(phi_b)
        for r in range(R):
            if new[r]:
                cands[r] = np.concatenate([cands[r]] + new[r])
        phi = phi_next

    for r in range(R):
        cands[r] = np.unique(cands[r], axis=0)

    # Cell-mass pass (+ cap-k backfill reservoirs, maintained only for
    # restarts that actually came up short — review r4).
    masses = [np.zeros(len(c)) for c in cands]
    short = [r for r in range(R) if len(cands[r]) < k]
    back = {r: _EpochReservoir(k, d,
                               np.random.default_rng([seeds[r], 0xF1259]))
            for r in short}
    chunk = 512
    for x, w in epoch_blocks():
        xp, wp = jnp.asarray(x), jnp.asarray(w)
        for r in range(R):
            st = assign_reduce(xp, wp, jnp.asarray(cands[r].astype(dtype)),
                               chunk_size=chunk)
            masses[r] += np.asarray(st.counts, np.float64)
        if short:
            real = x[np.asarray(w) > 0]
            for r in short:
                back[r].offer(real)

    outs = []
    for r in range(R):
        c = cands[r]
        if len(c) < k:
            extra = back[r].sample(
                k - len(c), np.random.default_rng([seeds[r], 0xF1260]))
            c = np.concatenate([c, extra])
            masses[r] = np.concatenate(
                [masses[r], np.ones(len(extra))])
        centers = _weighted_kmeanspp_host(
            c.astype(np.float64), np.maximum(masses[r][: len(c)], 1e-12),
            k, np.random.default_rng(seeds[r]))
        centers = centers.astype(dtype)
        check_finite_array(centers, "Data contains NaN or Inf values")
        outs.append(centers)
    return outs, n


STREAM_INITIALIZERS = {"forgy": streamed_forgy_init,
                       "random": streamed_forgy_init,
                       "k-means++": streamed_kmeans_parallel_init,
                       "kmeans++": streamed_kmeans_parallel_init,
                       "k-means||": streamed_kmeans_parallel_init,
                       "kmeans||": streamed_kmeans_parallel_init}


INITIALIZERS = {"forgy": forgy_init, "random": forgy_init,
                "k-means++": kmeanspp_init, "kmeans++": kmeanspp_init,
                "k-means||": kmeans_parallel_init,
                "kmeans||": kmeans_parallel_init}


def resolve_init(init, X, k: int, seed: int, *,
                 validate: bool = True,
                 cap: Optional[int] = None) -> np.ndarray:
    """Dispatch: strategy name, callable, or an explicit (k, D) array.

    ``validate=False`` skips redundant full-array finite scans in the named
    strategies (data already validated by the caller); custom callables
    manage their own validation.  A named or callable strategy runs
    under a ``seed`` span (ISSUE 11: the seeding share of
    time-to-first-iteration; explicit arrays cost nothing and are not
    spanned).  ``cap`` (ISSUE 16 — ``KMeans(init_cap=...)``) sets the
    k-means|| per-round candidate capacity; it is a property of that
    buffer discipline specifically, so a non-|| strategy rejects it
    rather than silently ignoring the knob."""
    from kmeans_tpu.obs import trace as _obs_trace
    src = as_source(X)
    dtype = np.dtype(str(src.dtype))
    if cap is not None and not (
            isinstance(init, str)
            and INITIALIZERS.get(init) is kmeans_parallel_init):
        raise ValueError(
            "init_cap sizes the k-means|| candidate buffer and only "
            "applies to init='k-means||'; got init="
            + (repr(init) if isinstance(init, str) else "a non-strategy "
               "init (array/callable)"))
    if callable(init):
        host = getattr(src, "host", None)
        with _obs_trace.span("seed", strategy="callable", k=k):
            return np.asarray(
                init(host if host is not None else src, k, seed),
                dtype=dtype)
    if isinstance(init, str):
        try:
            fn = INITIALIZERS[init]
        except KeyError:
            raise ValueError(f"unknown init strategy: {init!r}; "
                             f"options: {sorted(INITIALIZERS)}") from None
        kw = {"cap": cap} if cap is not None else {}
        with _obs_trace.span("seed", strategy=init, k=k):
            return np.asarray(fn(src, k, seed, validate=validate, **kw),
                              dtype=dtype)
    arr = np.asarray(init, dtype=dtype)
    if arr.shape != (k, src.d):
        raise ValueError(f"explicit init must have shape ({k}, "
                         f"{src.d}), got {arr.shape}")
    check_finite_array(arr, "Data contains NaN or Inf values")
    return arr
